"""Driver benchmark: north-star metric as ONE JSON line.

Metric (BASELINE.json): encode+decode MiB/s at k=8, m=4, 1 MiB stripes,
device-resident buffers.

Methodology: `block_until_ready`/dispatch timing is unreliable over the
axon dev tunnel (async RPC completes early), so each kernel is timed as a
jitted fori_loop chain of R dependent applications ending in a scalar
reduction (4-byte fetch forces real completion); per-op time is the
difference between an R-rep and a 2-rep chain divided by R-2.  The chain
XORs the output back into the carry, so no iteration can be elided.

vs_baseline: ratio against the native SIMD CPU codec (cpp_rs,
gf8_simd.cc: GFNI/AVX-512 where the host supports it, AVX2 pshufb
otherwise — the same kernel families the reference's isa-l uses, so the
denominator is an honest AVX2-class number, not numpy).  Falls back to
the numpy codec only if the native build is unavailable.

Outage hardening (round 5): the tunneled TPU backend can be DOWN or can
HANG during init (observed: `Unable to initialize backend 'axon':
UNAVAILABLE` and >240s wedges).  The backend is therefore probed in a
SUBPROCESS with a per-attempt timeout and retried on a bounded deadline;
if no TPU appears, the script still emits ONE parsable JSON line carrying
the native SIMD CPU number, clearly marked "device": "cpu" — a failed
tunnel must never turn into rc=1 / parsed=null (BENCH_r04 regression).
An overall SIGALRM watchdog bounds the whole run the same way.

The JSON line also reports pct_hbm_roofline: the combined number as a
percentage of what v5e HBM bandwidth (819 GB/s) allows for this op's
mandatory traffic (in + out bytes) — MFU-style context the driver can
record directly.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from contextlib import contextmanager
from pathlib import Path

import numpy as np

# stdlib-only imports (no jax): safe before any backend probe
from ceph_tpu.common.device_telemetry import jax_version
from ceph_tpu.common.tracer import default_tracer

HBM_BYTES_PER_S = 819e9          # TPU v5e HBM bandwidth (public spec)
# env-overridable so CI / smoke tests can shrink the retry budget
PROBE_DEADLINE_S = float(os.environ.get("BENCH_PROBE_DEADLINE_S", 600))
PROBE_STEP_S = float(os.environ.get("BENCH_PROBE_STEP_S", 30))
PROBE_ATTEMPT_TIMEOUT_S = float(   # a single init probe may WEDGE, not fail
    os.environ.get("BENCH_PROBE_ATTEMPT_TIMEOUT_S", 90))
# worst honest path: probe deadline (600) + compile (~40) + two bounded
# measurement passes (~240) + cpu baseline (~60) ≈ 950s; the watchdog
# leaves headroom above that while still emitting the fallback line
# before any plausible driver timeout
WATCHDOG_S = int(os.environ.get("BENCH_WATCHDOG_S", 1200))


_chain_cache: dict = {}


class PlatformMismatchError(RuntimeError):
    """The measured JAX platform is not the one the run requested — the
    r05 failure mode (a silent CPU fallback recorded as if it were a
    slower TPU number).  Raised BEFORE the suite runs so the artifact
    names the abort instead of carrying a different experiment's data."""


def requested_platform() -> str | None:
    """The platform this run was ASKED to measure on: the explicit
    ``BENCH_EXPECT_PLATFORM`` override, else ``JAX_PLATFORMS`` when it
    names exactly one platform (a comma list is jax's own documented
    fallback chain — the operator opted into degradation there)."""
    expect = os.environ.get("BENCH_EXPECT_PLATFORM", "").strip().lower()
    if expect:
        return expect
    env = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if env and "," not in env:
        return env
    return None


def preflight_platform(measured: str | None) -> None:
    """Abort the suite with a NAMED error when the measured platform is
    not the requested one (kills the silent-fallback mode at the source;
    tools/perf_gate.py still gates it after the fact)."""
    requested = requested_platform()
    if requested is not None and measured != requested:
        raise PlatformMismatchError(
            f"requested platform {requested!r} but measured "
            f"{measured or 'none'} — refusing to run the suite on the "
            f"wrong device (set BENCH_EXPECT_PLATFORM/JAX_PLATFORMS to "
            f"what you mean, or unset them to accept fallback)")

# Hardware attribution for EVERY emitted line (including watchdog and
# fallback paths): jax version is readable without importing jax; the
# platform/device fields fill in from whatever the subprocess probe saw.
# Without this block the BENCH trajectory is unattributable — a regression
# could be a slower kernel or a different device and the artifact alone
# could not tell.
_DEVICE_INFO: dict = {"platform": None, "device_kind": None,
                      "num_devices": 0, "jax_version": jax_version()}

# -- per-phase accounting -----------------------------------------------------
# Every phase lands in the bench JSON (`phases`: name -> seconds) AND on the
# process span tracer, so a wedged run is diagnosable from the artifact alone
# (the BENCH_r05 lesson: 570s of probe with no per-attempt record).
_PHASES: dict[str, float] = {}
_OPEN_PHASES: dict[str, float] = {}    # in-flight: name -> start perf_counter
_PROBE_HISTORY: list[dict] = []
_RUN_T0 = time.monotonic()


@contextmanager
def phase(name):
    with default_tracer().span(f"bench.{name}"):
        _OPEN_PHASES[name] = time.perf_counter()
        try:
            yield
        finally:
            t0 = _OPEN_PHASES.pop(name)
            _PHASES[name] = round(
                _PHASES.get(name, 0.0) + time.perf_counter() - t0, 3)


def chain_fn(apply_fn, mat, data, reps):
    """The cached jitted chain of `reps` applications (build only; the
    first execution compiles)."""
    import jax
    import jax.numpy as jnp

    # On TPU the kernel is an opaque pallas call, so a 2-row tap is enough
    # to chain iterations — XLA cannot slice an opaque call down to the
    # used rows, and the glue adds only ~2 rows of extra HBM traffic.  On
    # the XLA fallback path (plain dot_general) a narrow tap WOULD let the
    # compiler elide most of the matmul, so consume every output row there.
    on_tpu = jax.devices()[0].platform == "tpu"

    key = (id(apply_fn), reps, mat.shape, data.shape)
    run = _chain_cache.get(key)
    if run is None:
        @jax.jit
        def run(M, D):
            def body(i, carry):
                out = apply_fn(M, carry)                   # [R, N]
                dep_rows = min(2, out.shape[0]) if on_tpu else out.shape[0]
                head = jax.lax.dynamic_slice(
                    carry, (0, 0), (dep_rows, carry.shape[1]))
                tap = jax.lax.dynamic_slice(
                    out, (0, 0), (dep_rows, out.shape[1]))
                return jax.lax.dynamic_update_slice(
                    carry, jax.lax.bitwise_xor(head, tap), (0, 0))
            final = jax.lax.fori_loop(0, reps, body, D)
            return final.astype(jnp.int32).sum()
        _chain_cache[key] = run
    return run


def chain_timer(apply_fn, mat, data, reps, rounds=5):
    """Best-of-rounds wall time of a jitted chain of `reps` applications."""
    run = chain_fn(apply_fn, mat, data, reps)
    _ = int(run(mat, data))                                # compile+sync
    best = 1e9
    for _ in range(rounds):
        t0 = time.perf_counter()
        _ = int(run(mat, data))                            # 4-byte fetch
        best = min(best, time.perf_counter() - t0)
    return best


def per_op_seconds(apply_fn, mat, data, lo=4, hi=52):
    """Per-op seconds from the (hi-reps − lo-reps) chain difference.

    The tunnel adds latency noise comparable to small kernels; a wide rep
    spread plus best-of-rounds keeps the difference positive.  If jitter
    still swallows it, retry once, then fall back to the hi-chain mean
    (conservative: includes the fixed dispatch overhead, so it can only
    understate throughput).
    """
    for _ in range(2):
        t_lo = chain_timer(apply_fn, mat, data, lo, rounds=7)
        t_hi = chain_timer(apply_fn, mat, data, hi, rounds=7)
        if t_hi > t_lo * 1.05:
            return (t_hi - t_lo) / (hi - lo)
    return t_hi / hi


def measure_cpu(fn, iters=3, warmup=1):
    for _ in range(warmup):
        fn()
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    return (time.perf_counter() - t0) / iters


def probe_backend() -> str | None:
    """Initialize the JAX backend in a SUBPROCESS, retrying on a bounded
    deadline.  Returns the platform string ('tpu', 'cpu', ...) or None if
    nothing initialized before the deadline.  Subprocess isolation matters
    twice over: a wedged tunnel can hang init forever (per-attempt
    timeout kills it), and a failed init poisons the in-process backend
    cache (each retry gets a fresh process).  Each attempt — including the
    successful one — is recorded in the JSON's `probe_history` (start,
    duration, error), so a wedged init is diagnosable from the artifact."""
    t0 = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        a0 = time.monotonic()
        platform = None
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax, json; ds = jax.devices(); "
                 "print(json.dumps({'platform': ds[0].platform, "
                 "'device_kind': getattr(ds[0], 'device_kind', None), "
                 "'num_devices': len(ds)}))"],
                capture_output=True, text=True,
                timeout=PROBE_ATTEMPT_TIMEOUT_S)
            if r.returncode == 0 and r.stdout.strip():
                last = r.stdout.strip().splitlines()[-1]
                try:
                    info = json.loads(last)
                    # validate BEFORE mutating: a stray final stdout line
                    # like 'null' parses as non-dict JSON, and a partial
                    # update would leave _DEVICE_INFO half-written
                    if not isinstance(info, dict) or "platform" not in info:
                        raise ValueError(last)
                    _DEVICE_INFO.update(info)
                    platform = info["platform"]
                except ValueError:                 # plain-string fallback
                    platform = last
                    _DEVICE_INFO["platform"] = platform
                reason = None
            else:
                reason = (r.stderr or "").strip().splitlines()[-1:] \
                    or ["rc!=0"]
                reason = reason[0][-120:]
        except subprocess.TimeoutExpired:
            reason = f"init wedged > {PROBE_ATTEMPT_TIMEOUT_S}s"
        _PROBE_HISTORY.append({
            "attempt": attempt,
            "start_s": round(a0 - _RUN_T0, 3),
            "duration_s": round(time.monotonic() - a0, 3),
            "platform": platform,
            "error": reason,
        })
        default_tracer().instant("bench.probe_attempt", attempt=attempt,
                                 platform=platform, error=reason)
        if platform is not None:
            return platform
        elapsed = time.monotonic() - t0
        if elapsed + PROBE_STEP_S > PROBE_DEADLINE_S:
            print(f"# backend probe gave up after {elapsed:.0f}s "
                  f"({attempt} attempts): {reason}", file=sys.stderr)
            return None
        print(f"# backend probe attempt {attempt} failed ({reason}); "
              f"retrying in {PROBE_STEP_S}s", file=sys.stderr)
        time.sleep(PROBE_STEP_S)


def cpu_baseline(data, k, m, erasures):
    """(combined MiB/s, kind, encode MiB/s, decode MiB/s) for the host
    codec: native SIMD if the toolchain built, else the numpy path."""
    from ceph_tpu.ops import RSCodec

    stripe_bytes = data.shape[1] * k
    cdata = np.ascontiguousarray(data[:k])
    kind = "numpy"
    try:
        from ceph_tpu.native import NativeRegistry
        native = NativeRegistry().factory(
            "cpp_rs", {"k": str(k), "m": str(m), "technique": "cauchy"})
        enc_t = measure_cpu(lambda: native.encode(cdata), iters=20)
        parity = native.encode(cdata)
        avail = {i: cdata[i] for i in range(k) if i not in erasures}
        avail |= {k + j: parity[j] for j in range(m)
                  if k + j not in erasures}
        dec_t = measure_cpu(
            lambda: native.decode(avail, erasures, data.shape[1]), iters=20)
        kind = "simd"                          # only after timings succeed
    except Exception as e:                     # no native toolchain
        print(f"# native baseline unavailable ({e}); using numpy",
              file=sys.stderr)
        from ceph_tpu.gf import ref
        cpu = RSCodec(k, m, technique="cauchy", device="numpy")
        D, src = cpu.decode_matrix(erasures)
        enc_t = measure_cpu(lambda: cpu.encode(cdata))
        csurv = np.concatenate([cdata, cpu.encode(cdata)], axis=0)[src]
        dec_t = measure_cpu(lambda: ref.apply_matrix(D, csurv))
    enc = (stripe_bytes / 2**20) / enc_t
    dec = (stripe_bytes / 2**20) / dec_t
    return 2.0 / (1.0 / enc + 1.0 / dec), kind, enc, dec


_emit_lock = threading.Lock()
_emitted = False
_SERVING: dict | None = None     # the serving-engine comparison block
_OBSERVABILITY: dict | None = None  # instruments on/off overhead block
_RECOVERY: dict | None = None    # the repair-throughput comparison block
_PIPELINE: dict | None = None    # the async-pipeline comparison block
_EFFICIENCY: dict | None = None  # the roofline device-efficiency block
_RESILIENCE: dict | None = None  # goodput under faults + breaker fallback
_SLO: dict | None = None         # critical-path attribution + budget block
_LINT: dict | None = None        # ceph-lint static-analysis summary block
_TIERING: dict | None = None     # hot-tier cold/warm flash-crowd block


def _pipeline_pass(sinfo, ec, batches, degraded, depth: int,
                   mesh_devices: int = 0, rounds: int = 3) -> dict:
    """One sync-vs-async measurement arm: encode then decode every batch
    through the codec pipeline at ``depth`` (0 = the synchronous
    per-batch path: every submit completes before returning — exactly
    the pre-pipeline coalescer dispatch).  Best-of-rounds MiB/s over the
    logical payload, encode/decode combined harmonically."""
    from ceph_tpu.backend import ecutil
    from ceph_tpu.ops.pipeline import CodecPipeline

    total = sum(len(b) for bb in batches for b in bb)
    pipe = CodecPipeline(depth=depth, name=f"bench.pipe.d{depth}",
                         mesh_devices=mesh_devices)
    try:
        # warm the jit shape caches out of the timed region
        ecutil.encode_many_pipelined(sinfo, ec, batches[0], pipe).result()
        for _i, f in ecutil.decode_many_pipelined(
                sinfo, ec, degraded[0], pipe,
                chunk_size=sinfo.chunk_size):
            f.result()
        enc_t = dec_t = 1e9
        for _ in range(rounds):
            t0 = time.perf_counter()
            futs = [ecutil.encode_many_pipelined(sinfo, ec, bb, pipe)
                    for bb in batches]
            pipe.flush()
            for f in futs:
                f.result()
            enc_t = min(enc_t, time.perf_counter() - t0)
            t0 = time.perf_counter()
            pend = [ecutil.decode_many_pipelined(
                sinfo, ec, bb, pipe, chunk_size=sinfo.chunk_size)
                for bb in degraded]
            pipe.flush()
            for groups in pend:
                for _i, f in groups:
                    f.result()
            dec_t = min(dec_t, time.perf_counter() - t0)
        mesh_hits = int(pipe.perf.get("mesh_dispatches"))
    finally:
        pipe.close()
    enc = total / 2**20 / enc_t
    dec = total / 2**20 / dec_t
    out = {"depth": depth,
           "encode_mibs": round(enc, 1), "decode_mibs": round(dec, 1),
           "mib_s": round(2.0 / (1.0 / enc + 1.0 / dec), 1)}
    if mesh_devices:
        out["mesh_devices"] = mesh_devices
        out["mesh_dispatches"] = mesh_hits
    return out


def pipeline_section(platform: str | None) -> dict:
    """Codec-pipeline comparison for the JSON artifact's `pipeline`
    block: synchronous per-batch dispatch (depth 0: pack | compute |
    fetch serial, the pre-pipeline serving path) vs async depth-4
    (batch N+1's host pack overlaps batch N's in-flight device compute),
    plus a mesh-sharded arm when >1 device is up.  Degrades to a
    clearly-marked CPU line — and names the single-core case, where no
    concurrency exists for the overlap to exploit — rather than failing
    the bench."""
    try:
        import jax
        from ceph_tpu.backend.ecutil import StripeInfo
        from ceph_tpu.plugins.registry import ErasureCodePluginRegistry
        if platform is None:
            return {"device": "none",
                    "error": "no jax backend initialized"}
        k, m, chunk = 8, 4, 16384           # 128 KiB stripes
        n_batches, ops_per_batch = 12, 8    # 1 MiB coalesced batches
        ec = ErasureCodePluginRegistry.instance().factory(
            "jax_rs", "", {"plugin": "jax_rs", "k": str(k), "m": str(m),
                           "technique": "reed_sol_van", "device": "jax"})
        sinfo = StripeInfo(k, chunk)
        rng = np.random.default_rng(2)
        with phase("pipeline"):
            batches = [[rng.integers(0, 256, sinfo.stripe_width,
                                     np.uint8).tobytes()
                        for _ in range(ops_per_batch)]
                       for _ in range(n_batches)]
            from ceph_tpu.backend import ecutil
            degraded = [[{c: v for c, v in chunks.items() if c != 0}
                         for chunks in ecutil.encode_many(sinfo, ec, bb)]
                        for bb in batches]
            sync = _pipeline_pass(sinfo, ec, batches, degraded, depth=0)
            asynch = _pipeline_pass(sinfo, ec, batches, degraded, depth=4)
            n_dev = len(jax.devices())
            mesh = None
            if n_dev > 1:
                mesh = _pipeline_pass(sinfo, ec, batches, degraded,
                                      depth=4, mesh_devices=n_dev)
        res = {
            "device": "tpu" if platform == "tpu" else "cpu",
            "host_cpus": os.cpu_count(),
            "sync": sync,
            "async": asynch,
            "speedup": round(asynch["mib_s"] / max(sync["mib_s"], 1e-9),
                             2),
        }
        if mesh is not None:
            res["mesh"] = mesh
        if res["device"] == "cpu":
            res["note"] = (
                "no tpu: overlap measured on the jax-cpu path"
                + ("; single-core host — pack and compute share one "
                   "core, so no concurrency exists for the async depth "
                   "to exploit" if (os.cpu_count() or 1) < 2 else ""))
        print(f"# pipeline: async depth-4 {asynch['mib_s']:.1f} MiB/s vs "
              f"sync {sync['mib_s']:.1f} MiB/s -> {res['speedup']}x on "
              f"{res['device']} ({res['host_cpus']} cpus)",
              file=sys.stderr)
        return res
    except Exception as e:                 # never fail the artifact
        print(f"# pipeline bench failed: {e!r}", file=sys.stderr)
        return {"device": "none", "error": repr(e)[:200]}


def _recovery_repair_pass(device: str, batched: bool, n_objects: int,
                          obj_bytes: int, chain: bool = False) -> dict:
    """One degraded-cluster repair: write, kill a shard, overwrite
    everything while it is down, revive, and time the drain to clean.
    ``batched`` routes repair through the recovery scheduler (waves
    fused into decode_shards_many dispatches); otherwise the per-object
    inline path runs.  ``chain`` (batched only) lets the scheduler plan
    partial-sum chains over the survivors instead of centralizing k
    chunks at the primary.  Returns MiB/s over the chunk bytes pushed
    plus the wire decomposition (total / coordinator-ingress /
    newcomer-ingress per repaired byte)."""
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.common import Context
    # fresh Context: the conf knobs below must not leak into the rest
    # of the bench through the process-global default context
    c = MiniCluster(n_osds=8, osds_per_host=2, chunk_size=4096,
                    cct=Context())
    try:
        # chains default ON cluster-wide, so the CENTRALIZED arms must
        # pin them off explicitly to measure what they claim to measure
        c.cct.conf.set("osd_recovery_chain_enable", bool(chain))
        if batched:
            c.cct.conf.set("osd_recovery_max_active", 16)
            c.enable_recovery_scheduler()
        pid = c.create_ec_pool(
            "r", {"k": "4", "m": "2", "device": device,
                  "technique": "reed_sol_van"}, pg_num=1)
        g = c.pools[pid]["pgs"][0]
        victim = g.acting[1]
        rng = np.random.default_rng(0)
        objs = {f"o{i}": rng.integers(0, 256, obj_bytes,
                                      np.uint8).tobytes()
                for i in range(n_objects)}
        for oid, d in objs.items():
            c.put(pid, oid, d)
        # two kill-overwrite-revive cycles: the first warms the jit
        # shape caches (both paths pay a cold compile on their decode
        # shapes), the second is the steady-state measurement — same
        # warm-vs-cold discipline as the chain timer above
        dt = pushed = wire = 0
        tdelta: dict = {}
        chain_objects = chain_fallbacks = 0
        for payload in (b"\x01", b"\x02"):
            g.bus.mark_down(victim)
            for oid in objs:              # the writes the victim misses
                c.put(pid, oid, payload + objs[oid][1:])
            before = g.backend.perf.get("recovery_bytes")
            co_before = g.backend.perf.get("chain_objects")
            cf_before = g.backend.perf.get("chain_fallbacks")
            wire_before = c.wire.class_bytes()["recovery"]
            types_before = {t: v["tx_bytes"]
                            for t, v in c.wire.per_type().items()}
            t0 = time.perf_counter()
            g.bus.mark_up(victim)
            c.deliver_all()
            dt = time.perf_counter() - t0
            pushed = g.backend.perf.get("recovery_bytes") - before
            chain_objects = g.backend.perf.get("chain_objects") - co_before
            chain_fallbacks = (g.backend.perf.get("chain_fallbacks")
                               - cf_before)
            wire = c.wire.class_bytes()["recovery"] - wire_before
            tdelta = {t: v["tx_bytes"] - types_before.get(t, 0)
                      for t, v in c.wire.per_type().items()}
            assert not g.backend.stale, "repair did not drain"
        report = c.scrub_pool(pid, repair=False)
        assert report == {}, f"repair left scrub findings: {report}"
        # wire decomposition from per-type deltas: the message types
        # below flow to exactly one role in a repair (read replies +
        # chain acks/aborts land on the coordinating primary; pushes +
        # chain applies land on the repair target)
        coord_in = sum(tdelta.get(t, 0) for t in
                       ("ECSubReadReply", "ECPartialSumApplied",
                        "ECPartialSumAbort"))
        newcomer_in = sum(tdelta.get(t, 0) for t in
                          ("PushOp", "ECPartialSumApply"))
        return {"mib_s": round(pushed / 2**20 / dt, 2),
                "objects": n_objects, "pushed_bytes": pushed,
                "elapsed_s": round(dt, 3),
                # bytes-on-wire per byte repaired (ROADMAP item 3's
                # success metric): recovery-class wire traffic of the
                # measured cycle over the chunk bytes pushed — ~k for
                # centralized repair.  The k-transfer information floor
                # means NO repair scheme gets total wire below ~k-1;
                # what chains eliminate is the COORDINATOR ingress
                # (~k+m-1 chunks per object centralized, ~0 chained)
                # while the newcomer keeps receiving ~1 byte per byte
                # repaired
                "wire_bytes": int(wire),
                "wire_per_byte": round(wire / max(pushed, 1), 3),
                "coordinator_ingress_per_byte": round(
                    coord_in / max(pushed, 1), 3),
                "newcomer_ingress_per_byte": round(
                    newcomer_in / max(pushed, 1), 3),
                "chain_objects": int(chain_objects),
                "chain_fallbacks": int(chain_fallbacks)}
    finally:
        c.shutdown()


def _recovery_regen_pass(device: str, mode: str, k: int, m: int, d: int,
                         chunk: int, n_objects: int, stripes: int,
                         regen: bool = True) -> dict:
    """One degraded repair on a REGENERATING pool (pm_regen MSR/MBR):
    write, kill a shard, overwrite while down, revive, time the drain.
    ``regen=False`` pins the option off so the same pool repairs through
    the centralized verified wave — the comparison arm.  Repaired bytes
    are counted in STORED units (MBR chunks are expanded alpha*k/B on
    disk); wire is the recovery-class delta over the measured cycle."""
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.common import Context
    c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=chunk,
                    cct=Context())
    try:
        c.cct.conf.set("osd_recovery_regen_enable", bool(regen))
        c.cct.conf.set("osd_recovery_max_active", 16)
        c.enable_recovery_scheduler()
        pid = c.create_ec_pool(
            "rg", {"plugin": "pm_regen", "k": str(k), "m": str(m),
                   "d": str(d), "mode": mode, "device": device},
            pg_num=1)
        g = c.pools[pid]["pgs"][0]
        victim = g.acting[1]
        obj_bytes = stripes * chunk * k
        rng = np.random.default_rng(0)
        objs = {f"o{i}": rng.integers(0, 256, obj_bytes,
                                      np.uint8).tobytes()
                for i in range(n_objects)}
        for oid, data in objs.items():
            c.put(pid, oid, data)
        stored = g.backend.ec_impl.get_stored_chunk_size(chunk)
        repaired = stripes * stored * n_objects
        dt = wire = helper_tx = 0
        ro = rf = 0
        # warm cycle then measured cycle (same discipline as the chain
        # pass: both arms pay their cold jit/compile in cycle one)
        for payload in (b"\x01", b"\x02"):
            g.bus.mark_down(victim)
            for oid in objs:
                c.put(pid, oid, payload + objs[oid][1:])
            ro_before = g.backend.perf.get("regen_objects")
            rf_before = g.backend.perf.get("regen_fallbacks")
            wire_before = c.wire.class_bytes()["recovery"]
            helper_before = c.wire.per_type().get(
                "ECRegenHelper", {}).get("tx_bytes", 0)
            t0 = time.perf_counter()
            g.bus.mark_up(victim)
            c.deliver_all()
            dt = time.perf_counter() - t0
            ro = g.backend.perf.get("regen_objects") - ro_before
            rf = g.backend.perf.get("regen_fallbacks") - rf_before
            wire = c.wire.class_bytes()["recovery"] - wire_before
            helper_tx = c.wire.per_type().get(
                "ECRegenHelper", {}).get("tx_bytes", 0) - helper_before
            assert not g.backend.stale, "regen repair did not drain"
        report = c.scrub_pool(pid, repair=False)
        assert report == {}, f"repair left scrub findings: {report}"
        return {"mib_s": round(repaired / 2**20 / dt, 2),
                "objects": n_objects, "repaired_bytes": repaired,
                "stored_chunk": stored, "elapsed_s": round(dt, 3),
                "wire_bytes": int(wire),
                # total recovery wire per STORED byte repaired — the
                # ROADMAP item-3 metric on the regenerating pool.  The
                # beta-stream floor is 1.0 B/B at the MBR point and
                # d/alpha at MSR; control legs (plan + acks) amortize
                # over payload
                "wire_per_byte": round(wire / max(repaired, 1), 3),
                # the helper beta-streams alone: what the newcomer
                # ingests beyond its own combine matrix
                "helper_stream_per_byte": round(
                    helper_tx / max(repaired, 1), 3),
                "regen_objects": int(ro),
                "regen_fallbacks": int(rf)}
    finally:
        c.shutdown()


def recovery_section(platform: str | None) -> dict:
    """Degraded-cluster repair throughput for the JSON artifact's
    `recovery` block: kill-one-shard repair MiB/s, batch-fused
    (scheduler waves through decode_shards_many) vs per-object, on the
    SAME device.  Degrades to a cpu-marked line / error marker rather
    than failing the bench."""
    try:
        device = "jax" if platform is not None else "numpy"
        with phase("recovery"):
            per_object = _recovery_repair_pass(device, batched=False,
                                               n_objects=48,
                                               obj_bytes=64 * 1024)
            batched = _recovery_repair_pass(device, batched=True,
                                            n_objects=48,
                                            obj_bytes=64 * 1024)
            chained = _recovery_repair_pass(device, batched=True,
                                            n_objects=48,
                                            obj_bytes=64 * 1024,
                                            chain=True)
            # regenerating-code repair (pm_regen): MBR at the ~1 B/B
            # repair-bandwidth point, MSR at d/alpha, vs the same pool
            # repaired through the centralized wave
            regen_mbr = _recovery_regen_pass(device, "mbr", 3, 2, 4,
                                             chunk=1536, n_objects=24,
                                             stripes=8)
            regen_mbr_cent = _recovery_regen_pass(device, "mbr", 3, 2,
                                                  4, chunk=1536,
                                                  n_objects=24,
                                                  stripes=8,
                                                  regen=False)
            regen_msr = _recovery_regen_pass(device, "msr", 3, 2, 4,
                                             chunk=4096, n_objects=24,
                                             stripes=8)
        res = {
            "device": "tpu" if platform == "tpu" else "cpu",
            "codec": device,
            "per_object": per_object,
            "batched": batched,
            "speedup": round(batched["mib_s"] /
                             max(per_object["mib_s"], 1e-9), 2),
            # the wire sub-block tools/perf_gate.py gates on: repair
            # efficiency regresses when this number rises
            "wire": {"per_byte_repaired": batched["wire_per_byte"],
                     "per_object_arm": per_object["wire_per_byte"]},
            # chained streaming repair vs the centralized wave on the
            # SAME cluster shape (k=4/m=2, one victim).  Total wire
            # cannot beat the k-transfer information floor; the honest
            # wins the gate holds are (a) total wire well under the
            # centralized arm, (b) coordinator ingress ~0, (c) newcomer
            # ingress ~1x bytes repaired (<= 1.5 gated absolutely in
            # tools/perf_gate.py)
            "chain": {
                "mib_s": chained["mib_s"],
                "pushed_bytes": chained["pushed_bytes"],
                "wire_per_byte": chained["wire_per_byte"],
                "centralized_wire_per_byte": batched["wire_per_byte"],
                "wire_reduction": round(
                    batched["wire_per_byte"] /
                    max(chained["wire_per_byte"], 1e-9), 2),
                "speedup_vs_centralized": round(
                    chained["mib_s"] / max(batched["mib_s"], 1e-9), 2),
                "coordinator_ingress_per_byte":
                    chained["coordinator_ingress_per_byte"],
                "centralized_coordinator_ingress_per_byte":
                    batched["coordinator_ingress_per_byte"],
                "newcomer_ingress_per_byte":
                    chained["newcomer_ingress_per_byte"],
                "chain_objects": chained["chain_objects"],
                "chain_fallbacks": chained["chain_fallbacks"],
            },
            # regenerating repair vs centralized on the SAME pm_regen
            # pool.  MBR's total wire is gated absolutely at 1.5 B/B
            # (tools/perf_gate.py) — below the k-transfer floor any
            # decode-based repair pays; MSR sits at d/alpha and is
            # gated under the 4.0 regenerating-pool ceiling
            "regen": {
                "mbr": {
                    **regen_mbr,
                    "centralized_wire_per_byte":
                        regen_mbr_cent["wire_per_byte"],
                    "wire_reduction": round(
                        regen_mbr_cent["wire_per_byte"] /
                        max(regen_mbr["wire_per_byte"], 1e-9), 2),
                },
                "msr": regen_msr,
            },
        }
        if res["device"] == "cpu":
            res["note"] = ("no tpu: repair dispatch overhead measured "
                           f"on the {'jax-cpu' if platform else 'numpy'}"
                           " path")
        print(f"# recovery: batched {batched['mib_s']:.1f} MiB/s vs "
              f"per-object {per_object['mib_s']:.1f} MiB/s -> "
              f"{res['speedup']}x on {res['device']}; chain wire "
              f"{chained['wire_per_byte']:.2f}/B vs centralized "
              f"{batched['wire_per_byte']:.2f}/B, newcomer ingress "
              f"{chained['newcomer_ingress_per_byte']:.2f}/B",
              file=sys.stderr)
        print(f"# recovery.regen: mbr {regen_mbr['wire_per_byte']:.2f}/B"
              f" (centralized {regen_mbr_cent['wire_per_byte']:.2f}/B, "
              f"{res['regen']['mbr']['wire_reduction']}x less wire) at "
              f"{regen_mbr['mib_s']:.1f} MiB/s; msr "
              f"{regen_msr['wire_per_byte']:.2f}/B at "
              f"{regen_msr['mib_s']:.1f} MiB/s",
              file=sys.stderr)
        return res
    except Exception as e:                 # never fail the artifact
        print(f"# recovery bench failed: {e!r}", file=sys.stderr)
        return {"device": "none", "error": repr(e)[:200]}


def _serving_wire_pass(device: str, n_ops: int = 64) -> dict:
    """Bytes-on-wire per client op over a short cluster pass (put+get
    through the PG fan-out).  compare_batched_unbatched drives the
    ServingEngine directly — no bus — so the wire cost of a served op
    is measured here, on the path that actually frames messages."""
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.common import Context
    c = MiniCluster(n_osds=6, chunk_size=1024, cct=Context())
    try:
        pid = c.create_ec_pool(
            "sw", {"k": "4", "m": "2", "device": device,
                   "technique": "reed_sol_van"}, pg_num=4)
        rng = np.random.default_rng(1)
        payload = rng.integers(0, 256, 4096, np.uint8).tobytes()
        before = c.wire.class_bytes()
        for i in range(n_ops // 2):
            c.put(pid, f"w{i}", payload)
        for i in range(n_ops // 2):
            c.get(pid, f"w{i}", len(payload))
        after = c.wire.class_bytes()
        moved = sum(after[k] - before[k] for k in ("client", "serving"))
        return {"per_op": round(moved / n_ops, 1), "ops": n_ops,
                "bytes": int(moved), "op_bytes": len(payload)}
    finally:
        c.shutdown()


def _serving_async_pass() -> dict:
    """The async-messenger block (`serving.async`): 10k logical
    closed-loop clients multiplexed over 8 TCP connections to an async
    ClusterServer (tools/rados_bench.run_mux_bench) — goodput + p99 at
    clean capacity, and goodput + shed-rate with the dispatch queue
    pinned tiny (the overload arm: the shed ladder must refuse work by
    class while completed work keeps flowing)."""
    from tools.rados_bench import run_mux_overload_pair
    return run_mux_overload_pair(n_clients=10000, ops_per_client=2,
                                 n_conns=8)


def serving_section(platform: str | None) -> dict:
    """Closed-loop serving comparison (coalesced vs op-at-a-time on the
    SAME device) for the JSON artifact's `serving` block: throughput +
    p50/p99 at fixed concurrency through ceph_tpu.exec.ServingEngine.
    Degrades to a clearly-marked CPU line (numpy codec) when no backend
    initialized, and to an error marker rather than failing the bench."""
    try:
        from ceph_tpu.backend import StripeInfo
        from ceph_tpu.exec.workload import compare_batched_unbatched
        from ceph_tpu.plugins.registry import ErasureCodePluginRegistry
        device = "jax" if platform is not None else "numpy"
        ec = ErasureCodePluginRegistry.instance().factory(
            "jax_rs", "", {"plugin": "jax_rs", "k": "4", "m": "2",
                           "technique": "reed_sol_van", "device": device})
        with phase("serving"):
            res = compare_batched_unbatched(
                ec, StripeInfo(4, 1024), n_ops=256, concurrency=64,
                op_bytes=4096, warmup_ops=64, timeout=240.0)
        res["device"] = "tpu" if platform == "tpu" else "cpu"
        res["wire"] = _serving_wire_pass(device)
        if res["device"] == "cpu":
            res["note"] = ("no tpu: dispatch overhead measured on the "
                           f"{'jax-cpu' if platform else 'numpy'} path")
        print(f"# serving: batched {res['batched']['ops_s']:.0f} ops/s "
              f"(p99 {res['batched']['p99_ms']:.2f} ms) vs unbatched "
              f"{res['unbatched']['ops_s']:.0f} ops/s (p99 "
              f"{res['unbatched']['p99_ms']:.2f} ms) -> "
              f"{res['speedup']}x on {res['device']}", file=sys.stderr)
        try:                               # async-messenger concurrency
            with phase("serving.async"):
                res["async"] = _serving_async_pass()
            a = res["async"]
            print(f"# serving.async: {a['clients']} clients "
                  f"{a['ops_s']:.0f} ops/s p99 {a['p99_ms']:.1f} ms "
                  f"({a['threads']} threads); overload shed-rate "
                  f"{a['overload']['shed_rate']:.0%} with "
                  f"{a['overload']['ops_s']:.0f} ops/s goodput",
                  file=sys.stderr)
        except Exception as e:             # never fail the artifact
            print(f"# serving.async bench failed: {e!r}", file=sys.stderr)
            res["async"] = {"error": repr(e)[:200]}
        try:                               # zero-copy data-path arms
            from tools.rados_bench import run_zero_copy_pair
            with phase("serving.zero_copy"):
                res["zero_copy"] = run_zero_copy_pair()
            z = res["zero_copy"]
            print(f"# serving.zero_copy: fused "
                  f"{z['copies_per_byte']:.2f} copies/B at "
                  f"{z['fused']['ops_s']:.0f} ops/s (p99 "
                  f"{z['fused']['p99_ms']:.1f} ms) vs legacy "
                  f"{z['legacy_copies_per_byte']:.2f} copies/B at "
                  f"{z['legacy']['ops_s']:.0f} ops/s — "
                  f"{z['goodput_ratio']}x goodput on "
                  f"{z['payload_bytes']}B payloads", file=sys.stderr)
        except Exception as e:             # never fail the artifact
            print(f"# serving.zero_copy bench failed: {e!r}",
                  file=sys.stderr)
            res["zero_copy"] = {"error": repr(e)[:200]}
        return res
    except Exception as e:                 # never fail the artifact
        print(f"# serving bench failed: {e!r}", file=sys.stderr)
        return {"device": "none", "error": repr(e)[:200]}


def observability_section(platform: str | None) -> dict:
    """The instrumentation-tax block (`observability`): the serving.async
    mux workload with full instruments vs the ``instruments_enabled``
    kill-switch — reporting both arms' goodput/p99 and the overhead
    percentage the perf gate caps absolutely (ISSUE 18).  The A/B runs
    as paired on/off CPU-time segments against ONE warmed server
    (tools.rados_bench.run_mux_overhead_bench), overhead = median of the
    per-round paired deltas: wall-clock goodput on a shared host swings
    2x run-to-run from scheduler noise and per-process setup, and that
    noise must not masquerade as instrument tax."""
    try:
        from ceph_tpu.common.tracer import default_tracer
        from tools.rados_bench import run_mux_overhead_bench
        with phase("observability"):
            ab = run_mux_overhead_bench()
        on = ab["instruments_on"]
        off = ab["instruments_off"]
        res = {
            "device": "tpu" if platform == "tpu" else "cpu",
            "sample_rate": default_tracer().sample_rate,
            "overhead_pct": ab["overhead_pct"],
            "rounds": ab["rounds"],
            "deltas_pct": ab["deltas_pct"],
            "instruments_on": dict(on),
            "instruments_off": dict(off),
            "p99_delta_ms": round(on["p99_ms"] - off["p99_ms"], 3),
        }
        print(f"# observability: instruments on {on['cpu_us_per_op']:.1f} "
              f"us/op CPU ({on['ops_s']:.0f} ops/s) vs off "
              f"{off['cpu_us_per_op']:.1f} us/op ({off['ops_s']:.0f} ops/s)"
              f" -> {res['overhead_pct']:.1f}% overhead at sample_rate "
              f"{res['sample_rate']}", file=sys.stderr)
        return res
    except Exception as e:                 # never fail the artifact
        print(f"# observability bench failed: {e!r}", file=sys.stderr)
        return {"device": "none", "error": repr(e)[:200]}


def _resilience_cluster_pass(device: str, faulted: bool,
                             n_objects: int = 24) -> dict:
    """One put+verify-get pass over a MiniCluster — clean, or under a
    FIXED seeded fault schedule (bus reorder+dup, slow store reads) —
    returning latency percentiles and acked-goodput MiB/s."""
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.common import Context
    c = MiniCluster(n_osds=6, chunk_size=1024, cct=Context())
    try:
        pid = c.create_ec_pool(
            "rz", {"k": "4", "m": "2", "device": device,
                   "technique": "reed_sol_van"}, pg_num=4)
        rng = np.random.default_rng(5)
        payload = rng.integers(0, 256, 8192, np.uint8).tobytes()
        if faulted:
            from ceph_tpu.failure import (FaultConfig, FaultPlan,
                                          StoreFaults)
            c.inject_faults(FaultPlan(
                seed=23, bus=FaultConfig(reorder=True, dup_prob=0.2),
                store=StoreFaults(slow_read_prob=0.10,
                                  slow_read_ms=0.5)))
        for i in range(2):            # codec warmup outside the window
            c.put(pid, f"warm{i}", payload)
            c.get(pid, f"warm{i}", len(payload))
        lat: list[float] = []
        t_all = time.perf_counter()
        for i in range(n_objects):
            t0 = time.perf_counter()
            c.put(pid, f"r{i}", payload)
            lat.append(time.perf_counter() - t0)
        for i in range(n_objects):
            t0 = time.perf_counter()
            got = c.get(pid, f"r{i}", len(payload))
            lat.append(time.perf_counter() - t0)
            assert got == payload, f"read diverged under faults: r{i}"
        wall = time.perf_counter() - t_all
        moved = 2 * n_objects * len(payload)
        lat_ms = sorted(x * 1e3 for x in lat)
        return {"ops": 2 * n_objects,
                "goodput_mib_s": round(moved / 2**20 / wall, 2),
                "p50_ms": round(lat_ms[len(lat_ms) // 2], 3),
                "p99_ms": round(lat_ms[int(len(lat_ms) * 0.99)], 3)}
    finally:
        c.shutdown()


def _breaker_fallback_pass(n_batches: int = 12) -> dict:
    """Encode throughput with the device path FORCED open (dispatch
    failures at probability 1): every batch serves through the breaker's
    sync host fallback — the floor the cluster keeps serving at when the
    device dies."""
    from ceph_tpu.backend import StripeInfo, ecutil
    from ceph_tpu.common import Context
    from ceph_tpu.failure import DeviceFaults, FaultInjector, FaultPlan
    from ceph_tpu.ops.pipeline import CodecPipeline
    from ceph_tpu.plugins.registry import ErasureCodePluginRegistry
    ec = ErasureCodePluginRegistry.instance().factory(
        "jax_rs", "", {"plugin": "jax_rs", "k": "4", "m": "2",
                       "technique": "reed_sol_van", "device": "jax"})
    sinfo = StripeInfo(4, 1024)
    cct = Context(overrides={"pipeline_breaker_threshold": 2,
                             "pipeline_breaker_cooldown": 60.0})
    pl = CodecPipeline(depth=2, name="bench.resilience", cct=cct)
    try:
        pl.inject_faults(FaultInjector(FaultPlan(
            seed=31, device=DeviceFaults(dispatch_fail_prob=1.0))))
        rng = np.random.default_rng(7)
        bufs = [rng.integers(0, 256, 64 * 4096, np.uint8).tobytes()
                for _ in range(n_batches)]
        t0 = time.perf_counter()
        futs = [ecutil.encode_many_pipelined(sinfo, ec, [b], pl)
                for b in bufs]
        pl.flush()
        for f in futs:
            f.result(120)
        wall = time.perf_counter() - t0
        moved = sum(len(b) for b in bufs)
        return {"fallback_mib_s": round(moved / 2**20 / wall, 2),
                "batches": n_batches,
                "opens": pl.breaker.opens if pl.breaker else 0,
                "fallbacks": pl.perf.get("host_fallbacks")}
    finally:
        pl.close()


def resilience_section(platform: str | None) -> dict:
    """The `resilience` block (ISSUE 9): p99 + goodput with a fixed
    seeded fault schedule vs a clean run (the self-healing tax), and
    breaker-fallback throughput (the floor when the device path dies).
    Gated by tools/perf_gate.py: a goodput-ratio or fallback-throughput
    drop past threshold fails the round."""
    try:
        device = "jax" if platform is not None else "numpy"
        with phase("resilience"):
            clean = _resilience_cluster_pass(device, faulted=False)
            faulted = _resilience_cluster_pass(device, faulted=True)
            res = {
                "device": "tpu" if platform == "tpu" else "cpu",
                "clean": clean, "faulted": faulted,
                "goodput_ratio": round(
                    faulted["goodput_mib_s"]
                    / max(clean["goodput_mib_s"], 1e-9), 3),
            }
            if platform is not None:
                res["breaker"] = _breaker_fallback_pass()
        if res["device"] == "cpu":
            res["note"] = ("no tpu: host-codec cluster pass — the fault "
                           "tax, not device throughput")
        brk = res.get("breaker", {})
        print(f"# resilience: goodput x{res['goodput_ratio']} under "
              f"faults (clean {clean['goodput_mib_s']} -> faulted "
              f"{faulted['goodput_mib_s']} MiB/s, p99 "
              f"{clean['p99_ms']} -> {faulted['p99_ms']} ms)"
              + (f"; breaker fallback {brk['fallback_mib_s']} MiB/s"
                 if brk else ""), file=sys.stderr)
        return res
    except Exception as e:                 # never fail the artifact
        print(f"# resilience bench failed: {e!r}", file=sys.stderr)
        return {"device": "none", "error": repr(e)[:200]}


def slo_section(platform: str | None) -> dict:
    """The `slo` block (ISSUE 10): a short loaded MiniCluster pass whose
    completed traces fold through the critical-path ledger into
    per-class p99 + phase attribution, judged against a generous bench
    objective so the artifact carries budget state too.
    tools/perf_gate.py gates `slo.client_p99_ms` (regression = p99 rise)
    and `slo.budget_remaining` (regression = budget burned);
    tools/slo_report.py reproduces the attribution table from the block
    alone."""
    try:
        from ceph_tpu.cluster import MiniCluster
        from ceph_tpu.common import Context
        device = "jax" if platform is not None else "numpy"
        cct = Context(overrides={
            # a generous objective: steady-state ops pass it easily, so
            # budget_remaining ~1.0 and any real latency cliff shows as
            # a burned budget in the gate
            "slo_client_p99_ms": 250.0,
            "slo_client_target": 0.9,
            "slo_min_ops": 4,
        })
        with phase("slo"):
            # the ledger folds the PROCESS tracer ring: drop the traces
            # the earlier sections left there (resilience deliberately
            # ran faulted traffic) so the gated p99/budget numbers
            # measure THIS pass, not the chaos before it
            from ceph_tpu.common.tracer import default_tracer
            default_tracer().reset()
            c = MiniCluster(n_osds=6, chunk_size=1024, cct=cct)
            try:
                pid = c.create_ec_pool(
                    "slo", {"k": "4", "m": "2", "device": device,
                            "technique": "reed_sol_van"}, pg_num=4)
                rng = np.random.default_rng(11)
                payload = rng.integers(0, 256, 8192, np.uint8).tobytes()
                for i in range(24):
                    c.put(pid, f"s{i}", payload)
                for i in range(24):
                    c.get(pid, f"s{i}", len(payload))
                c.status()                      # fold + tick
                c.critpath.refresh()
                res = c.slo.bench_block(
                    "tpu" if platform == "tpu" else "cpu")
            finally:
                c.shutdown()
        cl = res.get("client") or {}
        if cl:
            from ceph_tpu.common.critpath import format_phase_mix
            print(f"# slo: client p99 {cl['p99_ms']:.2f} ms over "
                  f"{cl['ops']} ops ({format_phase_mix(cl['phases'])}); "
                  f"budget {100 * cl.get('budget_remaining', 0):.0f}% "
                  f"left", file=sys.stderr)
        return res
    except Exception as e:                 # never fail the artifact
        print(f"# slo bench failed: {e!r}", file=sys.stderr)
        return {"device": "none", "error": repr(e)[:200]}


def tiering_section(platform: str | None) -> dict:
    """The `tiering` block (ROADMAP 7): a flash crowd — 90% of arrivals
    collapsing onto 0.1% of the keyspace — of mixed reads/writes from
    10k mux clients, served cold (straight off the EC base pool) and
    then warm (through a writeback cache tier, after one warmup pass of
    the identical stream).  tools/perf_gate.py gates the warm hit rate
    (>= 0.8), warm-over-cold p99 (<= 1.0) and warm-over-cold
    device-time-per-op: the tier must actually absorb the crowd, not
    just sit in the path."""
    try:
        from tools.rados_bench import run_tier_mux_bench
        device = "jax" if platform is not None else "numpy"
        with phase("tiering"):
            # the run resets the process tracer ring (its device
            # seconds are per-segment critpath deltas) — safe here:
            # slo_section already folded and captured its own block
            res = run_tier_mux_bench(
                n_clients=int(os.environ.get("BENCH_TIER_CLIENTS",
                                             10000)),
                ops_per_client=1, n_objects=1000, object_bytes=2048,
                device=device, timeout_s=240.0)
        # the gate compares like-for-like devices across artifacts:
        # carry the codec arg separately and mark the block with the
        # platform vocabulary every other block uses
        res["codec_device"] = res.pop("device")
        res["device"] = "tpu" if platform == "tpu" else "cpu"
        return res
    except Exception as e:                 # never fail the artifact
        print(f"# tiering bench failed: {e!r}", file=sys.stderr)
        return {"device": "none", "error": repr(e)[:200]}


def efficiency_section(platform: str | None) -> dict:
    """The roofline ledger the sections above populated (every
    traced_jit dispatch recorded its measured seconds next to its
    XLA-modeled FLOPs/bytes), rendered as the JSON artifact's
    `efficiency` block: aggregate %-of-peak + the per-executable table
    tools/roofline_report.py renders.  tools/perf_gate.py gates
    `efficiency.pct_of_peak` regressions against the BENCH history."""
    try:
        from ceph_tpu.common import roofline
        if platform is None:
            return {"device": "none",
                    "error": "no jax backend initialized"}
        block = roofline.bench_block(platform)
        if "error" not in block:
            print(f"# efficiency: {block['pct_of_peak']:.2f}% of "
                  f"{block['peaks']['source']} peak "
                  f"({block['bound']}-bound aggregate, "
                  f"{len(block['executables'])} executables)",
                  file=sys.stderr)
        return block
    except Exception as e:                 # never fail the artifact
        print(f"# efficiency section failed: {e!r}", file=sys.stderr)
        return {"device": "none", "error": repr(e)[:200]}


def lint_section() -> dict:
    """ceph-lint over the tree with the committed baseline applied
    (ISSUE 15): carried in the artifact so the perf-gate history tracks
    the finding trajectory — ``lint.new`` must stay 0, and a growing
    ``lint.baselined`` count shows debt accumulating even while the
    gate is green."""
    try:
        from tools.ceph_lint import lint_summary
        block = lint_summary(Path(__file__).resolve().parent
                             / ".ceph_lint_baseline.json")
        print(f"# lint: {block['new']} new, {block['baselined']} "
              f"baselined, {block['rules_run']} rules",
              file=sys.stderr)
        return block
    except Exception as e:                 # never fail the artifact
        print(f"# lint section failed: {e!r}", file=sys.stderr)
        return {"error": repr(e)[:200]}


def emit(value, vs_baseline, extra):
    """Print the one driver JSON line — at most once per process (the
    watchdog thread and the main path can race to it)."""
    global _emitted
    with _emit_lock:
        if _emitted:
            return
        _emitted = True
    line = {
        "metric": "rs_k8m4_1MiB_encode_decode_device_resident",
        "value": round(value, 1),
        "unit": "MiB/s",
        "vs_baseline": round(vs_baseline, 3),
        # hardware attribution (common/device_telemetry): platform +
        # device kind/count from the subprocess probe, jax version from
        # package metadata — present on every path, watchdog included
        "device_info": dict(_DEVICE_INFO),
    }
    line.update(extra)
    if _SERVING is not None:
        line.setdefault("serving", _SERVING)
    if _OBSERVABILITY is not None:
        line.setdefault("observability", _OBSERVABILITY)
    if _RECOVERY is not None:
        line.setdefault("recovery", _RECOVERY)
    if _PIPELINE is not None:
        line.setdefault("pipeline", _PIPELINE)
    if _EFFICIENCY is not None:
        line.setdefault("efficiency", _EFFICIENCY)
    if _RESILIENCE is not None:
        line.setdefault("resilience", _RESILIENCE)
    if _SLO is not None:
        line.setdefault("slo", _SLO)
    if _LINT is not None:
        line.setdefault("lint", _LINT)
    if _TIERING is not None:
        line.setdefault("tiering", _TIERING)
    # always carried, even on the watchdog/fallback paths: the per-phase
    # breakdown and the per-attempt probe record accumulated so far.  A
    # phase still OPEN when the watchdog fires is exactly the one that
    # wedged: include its elapsed-so-far and name it explicitly.
    phases = dict(_PHASES)
    now = time.perf_counter()
    for name, t0 in list(_OPEN_PHASES.items()):
        phases[name] = round(phases.get(name, 0.0) + now - t0, 3)
        line["phase_in_flight"] = name
    line["phases"] = phases
    line["probe_history"] = list(_PROBE_HISTORY)
    # perf-regression gate (tools/perf_gate.py): every artifact carries
    # its own verdict vs the repo's BENCH history — a >threshold drop or
    # a TPU->CPU platform fallback lands as gate.ok=false in the very
    # JSON the driver records, instead of a silently degraded number
    # (the r05 lesson).  Best-effort: the gate must never block the line.
    try:
        gate = _run_perf_gate(line)
        if gate is not None:
            line["gate"] = gate
            print(f"# {gate['verdict']}", file=sys.stderr)
    except Exception as e:                      # noqa: BLE001 — telemetry
        print(f"# perf gate skipped: {e!r}", file=sys.stderr)
    print(json.dumps(line), flush=True)


def _run_perf_gate(line: dict) -> dict | None:
    """Load tools/perf_gate.py (stdlib-only, not a package) and evaluate
    this line against the BENCH_r history next to this script."""
    import importlib.util
    repo_dir = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(repo_dir, "tools", "perf_gate.py")
    if not os.path.exists(path):
        return None
    spec = importlib.util.spec_from_file_location("ceph_tpu_perf_gate",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.gate_for_bench(line, repo_dir)


def arm_watchdog(seconds, value, vs_baseline, extra):
    """A THREAD watchdog (not SIGALRM: a native-code backend-init wedge in
    the main thread never returns to the interpreter, so a signal handler
    would never run; a waiting thread still gets the GIL because the
    wedge blocks in a syscall).  On expiry it emits the fallback line and
    hard-exits 0 so the driver always gets parsable output."""
    def fire():
        print(f"# watchdog fired after {seconds:.0f}s", file=sys.stderr)
        emit(value, vs_baseline, extra)
        sys.stderr.flush()
        os._exit(0)
    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


def measure_device(data, k, m, erasures, batch):
    """The TPU measurement proper: (combined MiB/s, extra-keys dict)."""
    import jax
    import jax.numpy as jnp
    from ceph_tpu.ops import RSCodec, rs_kernels

    stripe_bytes = data.shape[1] * k
    codec = RSCodec(k, m, technique="cauchy", device="jax")
    with phase("table_upload"):
        dev = jax.device_put(jnp.asarray(data))
        pmat = jax.device_put(jnp.asarray(codec.parity_mat))
        D, _src = codec.decode_matrix(erasures)
        dmat = jax.device_put(jnp.asarray(D))
        jax.block_until_ready(dev)

    def apply_auto(M, Dd):
        return rs_kernels.gf_apply_stripes(M, Dd, batch)

    # the chains per_op_seconds will time (lo=4, hi=52 reps over the
    # encode and decode matrices): compile them all first, then warm them
    # once more, so the measure phase is pure steady-state dispatch
    with phase("compile"):
        for mt in (pmat, dmat):
            for reps in (4, 52):
                _ = int(chain_fn(apply_auto, mt, dev, reps)(mt, dev))
    with phase("warmup"):
        for mt in (pmat, dmat):
            _ = int(chain_fn(apply_auto, mt, dev, 4)(mt, dev))

    # Best of two full passes: the shared tunnel has multi-second slow
    # periods that depress encode and decode uniformly; peak-of-passes is
    # the honest capability number (standard throughput methodology).
    # When the tunnel is so degraded that one pass already took minutes,
    # the second pass cannot help — skip it instead of timing out.
    t_start = time.perf_counter()
    enc_mibs = dec_mibs = 0.0
    with phase("measure"):
        for _pass in range(2):
            enc_t = per_op_seconds(apply_auto, pmat, dev)   # [B*k]->[B*m]
            enc_mibs = max(enc_mibs, batch * (stripe_bytes / 2**20) / enc_t)
            # decode: 2 erasures (1 data + 1 parity) — the same apply
            # primitive over the decode matrix; the chain keeps the
            # [B*k, N] carry so per-op traffic matches a real reconstruct
            # over k survivors
            dec_t = per_op_seconds(apply_auto, dmat, dev)
            dec_mibs = max(dec_mibs, batch * (stripe_bytes / 2**20) / dec_t)
            if time.perf_counter() - t_start > 240:
                print("# degraded tunnel: single measurement pass",
                      file=sys.stderr)
                break

    combined = 2.0 / (1.0 / enc_mibs + 1.0 / dec_mibs)

    # HBM roofline for the measured ops: mandatory traffic per op is the
    # uint8 input block plus the uint8 output block (the fused kernel's
    # whole point is that bit-plane inflation never touches HBM).  Convert
    # the roofline to "stripe-payload MiB/s" so it is directly comparable
    # to enc/dec_mibs, then take the combined-metric ratio.
    n = data.shape[1]
    payload = batch * stripe_bytes
    roof_enc = HBM_BYTES_PER_S * payload / (batch * (k + m) * n) / 2**20
    r_dec = int(D.shape[0])
    roof_dec = HBM_BYTES_PER_S * payload / (batch * (k + r_dec) * n) / 2**20
    roof_combined = 2.0 / (1.0 / roof_enc + 1.0 / roof_dec)

    return combined, {
        "device": "tpu",
        "encode_mibs": round(enc_mibs, 1),
        "decode_mibs": round(dec_mibs, 1),
        "pct_hbm_roofline": round(100.0 * combined / roof_combined, 1),
    }


def smoke_device_phases() -> None:
    """Tiny jitted encode on whatever backend DID initialize: keeps the
    compile/warmup/measure phase breakdown present in the artifact even
    when the TPU is away (the device numbers themselves stay cpu-marked)."""
    from ceph_tpu.gf import cauchy1
    from ceph_tpu.ops import rs_kernels

    rng = np.random.default_rng(1)
    mat = cauchy1(8, 4)
    small = rng.integers(0, 256, size=(8, 4096), dtype=np.uint8)
    with phase("compile"):
        np.asarray(rs_kernels.gf_apply(mat, small, variant="bitslice"))
    with phase("warmup"):
        np.asarray(rs_kernels.gf_apply(mat, small, variant="bitslice"))
    with phase("measure"):
        for _ in range(3):
            np.asarray(rs_kernels.gf_apply(mat, small, variant="bitslice"))


def main() -> int:
    k, m = 8, 4
    stripe_bytes = 1024 * 1024
    n = stripe_bytes // k                      # 128 KiB chunks
    batch = 64                                 # stripes per dispatch
    rng = np.random.default_rng(0)
    # device-native VERTICAL batch layout: stripe s = rows [s*k, (s+1)*k)
    # (tall blocks feed full MXU tiles; see rs_kernels.gf_apply_stripes)
    data = rng.integers(0, 256, size=(batch * k, n), dtype=np.uint8)
    erasures = [0, 9]

    wd = arm_watchdog(WATCHDOG_S, 0.0, 0.0, {
        "device": "none", "error": "watchdog: wedged before cpu baseline"})

    # CPU baseline first: jax-free, so it lands even when the tunnel is
    # down, and the fallback JSON can carry a real measured value
    with phase("cpu_baseline"):
        cpu_combined, cpu_kind, cpu_enc, cpu_dec = cpu_baseline(
            data, k, m, erasures)
    print(f"# cpu-{cpu_kind} encode {cpu_enc:.0f} decode {cpu_dec:.0f} "
          f"MiB/s", file=sys.stderr)
    # re-arm with a real fallback value now that one exists: if the
    # device path wedges in native init (where SIGALRM could never run),
    # the driver still records the clearly-marked CPU number
    wd.cancel()
    wd = arm_watchdog(WATCHDOG_S, cpu_combined, 1.0, {
        "device": "cpu", "cpu_kind": cpu_kind,
        "error": "watchdog: device measurement wedged"})

    with phase("probe"):
        platform = probe_backend()
    # preflight (ISSUE 8): the measured platform must BE the requested
    # one before any suite runs — a silent fallback aborts loudly here
    # with a named error in the artifact AND a nonzero exit, instead of
    # recording a different experiment's numbers (the r05 mode)
    try:
        preflight_platform(platform)
    except PlatformMismatchError as e:
        print(f"# {e}", file=sys.stderr)
        emit(cpu_combined, 1.0, {
            "device": platform or "none", "cpu_kind": cpu_kind,
            "error": f"PlatformMismatchError: {e}"[:300]})
        return 1
    # serving comparison (coalesced vs op-at-a-time) on whatever device
    # is up — its own subsystem, measured before the device codec pass so
    # a tunnel death mid-codec still leaves the serving block in the line
    global _SERVING, _OBSERVABILITY, _RECOVERY, _PIPELINE, _EFFICIENCY, \
        _RESILIENCE, _SLO, _LINT, _TIERING
    # static-analysis trajectory first: pure AST work, no device needed,
    # so even a probe/tunnel death right after still carries the block
    _LINT = lint_section()
    _SERVING = serving_section(platform)
    # instrumentation tax (instruments on vs off over the same mux
    # workload) right after the serving block it compares against
    _OBSERVABILITY = observability_section(platform)
    # repair-throughput comparison (batched waves vs per-object) on the
    # same device — like serving, measured before the codec pass so a
    # tunnel death mid-codec still leaves the block in the line
    _RECOVERY = recovery_section(platform)
    # codec-pipeline comparison (sync per-batch vs async depth-4, mesh
    # when >1 device) — same placement rationale
    _PIPELINE = pipeline_section(platform)
    # goodput under a fixed fault schedule + breaker-fallback floor
    _RESILIENCE = resilience_section(platform)
    # critical-path attribution + SLO budget over a loaded cluster pass
    _SLO = slo_section(platform)
    # hot-tier flash crowd, cold vs warm, at mux-client scale (after
    # slo: the run resets the tracer ring slo folds from)
    _TIERING = tiering_section(platform)
    # the roofline efficiency block reads the ledger the sections above
    # populated — computed here so a codec-pass death still carries it
    _EFFICIENCY = efficiency_section(platform)
    if platform == "tpu":
        try:
            combined, extra = measure_device(data, k, m, erasures, batch)
            print(f"# encode {extra['encode_mibs']:.0f} MiB/s, decode "
                  f"{extra['decode_mibs']:.0f} MiB/s "
                  f"({extra['pct_hbm_roofline']:.0f}% of HBM roofline)",
                  file=sys.stderr)
            emit(combined, combined / cpu_combined, extra)
            return 0
        except Exception as e:                 # tunnel died mid-run
            print(f"# device measurement failed: {e!r}", file=sys.stderr)
            emit(cpu_combined, 1.0, {
                "device": "cpu", "cpu_kind": cpu_kind,
                "error": f"device measurement failed: {e!r}"[:200]})
            return 0
    # no TPU: still one parsable line, clearly marked.  When SOME backend
    # initialized (cpu), run a tiny jitted encode on it so the phases
    # section still carries real compile/warmup/measure durations and the
    # jit telemetry is exercised end to end.
    if platform is not None:
        try:
            smoke_device_phases()
        except Exception as e:
            print(f"# device smoke failed: {e!r}", file=sys.stderr)
    emit(cpu_combined, 1.0, {
        "device": "cpu", "cpu_kind": cpu_kind,
        "error": "tpu backend unavailable after bounded init retries"
                 if platform is None else
                 f"no tpu device (platform={platform})"})
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BaseException as e:                 # noqa: BLE001 — last resort
        if isinstance(e, (SystemExit, KeyboardInterrupt)):
            raise                              # a human abort must keep rc!=0
        print(f"# bench aborted: {e!r}", file=sys.stderr)
        emit(0.0, 0.0, {"device": "none", "error": repr(e)[:200]})
        sys.exit(0)
