"""Driver benchmark: north-star metric as ONE JSON line.

Metric (BASELINE.json): encode+decode MiB/s at k=8, m=4, 1 MiB stripes,
device-resident buffers.

Methodology: `block_until_ready`/dispatch timing is unreliable over the
axon dev tunnel (async RPC completes early), so each kernel is timed as a
jitted fori_loop chain of R dependent applications ending in a scalar
reduction (4-byte fetch forces real completion); per-op time is the
difference between an R-rep and a 2-rep chain divided by R-2.  The chain
XORs the output back into the carry, so no iteration can be elided.

vs_baseline: ratio against the native SIMD CPU codec (cpp_rs,
gf8_simd.cc: GFNI/AVX-512 where the host supports it, AVX2 pshufb
otherwise — the same kernel families the reference's isa-l uses, so the
denominator is an honest AVX2-class number, not numpy).  Falls back to
the numpy codec only if the native build is unavailable.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


_chain_cache: dict = {}


def chain_timer(apply_fn, mat, data, reps, rounds=5):
    """Best-of-rounds wall time of a jitted chain of `reps` applications."""
    import jax
    import jax.numpy as jnp

    # On TPU the kernel is an opaque pallas call, so a 2-row tap is enough
    # to chain iterations — XLA cannot slice an opaque call down to the
    # used rows, and the glue adds only ~2 rows of extra HBM traffic.  On
    # the XLA fallback path (plain dot_general) a narrow tap WOULD let the
    # compiler elide most of the matmul, so consume every output row there.
    on_tpu = jax.devices()[0].platform == "tpu"

    key = (id(apply_fn), reps, mat.shape, data.shape)
    run = _chain_cache.get(key)
    if run is None:
        @jax.jit
        def run(M, D):
            def body(i, carry):
                out = apply_fn(M, carry)                   # [R, N]
                dep_rows = min(2, out.shape[0]) if on_tpu else out.shape[0]
                head = jax.lax.dynamic_slice(
                    carry, (0, 0), (dep_rows, carry.shape[1]))
                tap = jax.lax.dynamic_slice(
                    out, (0, 0), (dep_rows, out.shape[1]))
                return jax.lax.dynamic_update_slice(
                    carry, jax.lax.bitwise_xor(head, tap), (0, 0))
            final = jax.lax.fori_loop(0, reps, body, D)
            return final.astype(jnp.int32).sum()
        _chain_cache[key] = run
    _ = int(run(mat, data))                                # compile+sync
    best = 1e9
    for _ in range(rounds):
        t0 = time.perf_counter()
        _ = int(run(mat, data))                            # 4-byte fetch
        best = min(best, time.perf_counter() - t0)
    return best


def per_op_seconds(apply_fn, mat, data, lo=4, hi=52):
    """Per-op seconds from the (hi-reps − lo-reps) chain difference.

    The tunnel adds latency noise comparable to small kernels; a wide rep
    spread plus best-of-rounds keeps the difference positive.  If jitter
    still swallows it, retry once, then fall back to the hi-chain mean
    (conservative: includes the fixed dispatch overhead, so it can only
    understate throughput).
    """
    for _ in range(2):
        t_lo = chain_timer(apply_fn, mat, data, lo, rounds=7)
        t_hi = chain_timer(apply_fn, mat, data, hi, rounds=7)
        if t_hi > t_lo * 1.05:
            return (t_hi - t_lo) / (hi - lo)
    return t_hi / hi


def measure_cpu(fn, iters=3, warmup=1):
    for _ in range(warmup):
        fn()
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    return (time.perf_counter() - t0) / iters


def main() -> int:
    import jax
    import jax.numpy as jnp
    from ceph_tpu.ops import RSCodec, rs_kernels

    k, m = 8, 4
    stripe_bytes = 1024 * 1024
    n = stripe_bytes // k                      # 128 KiB chunks
    batch = 64                                 # stripes per dispatch
    rng = np.random.default_rng(0)
    # device-native VERTICAL batch layout: stripe s = rows [s*k, (s+1)*k)
    # (tall blocks feed full MXU tiles; see rs_kernels.gf_apply_stripes)
    data = rng.integers(0, 256, size=(batch * k, n), dtype=np.uint8)

    codec = RSCodec(k, m, technique="cauchy", device="jax")
    dev = jax.device_put(jnp.asarray(data))
    pmat = jax.device_put(jnp.asarray(codec.parity_mat))

    def apply_auto(M, D):
        return rs_kernels.gf_apply_stripes(M, D, batch)

    erasures = [0, 9]
    D, src = codec.decode_matrix(erasures)
    dmat = jax.device_put(jnp.asarray(D))

    # Best of two full passes: the shared tunnel has multi-second slow
    # periods that depress encode and decode uniformly; peak-of-passes is
    # the honest capability number (standard throughput methodology).
    # When the tunnel is so degraded that one pass already took minutes,
    # the second pass cannot help — skip it instead of timing out.
    t_start = time.perf_counter()
    enc_mibs = dec_mibs = 0.0
    for _pass in range(2):
        # encode: [B*k, N] -> [B*m, N]
        enc_t = per_op_seconds(apply_auto, pmat, dev)
        enc_mibs = max(enc_mibs, batch * (stripe_bytes / 2**20) / enc_t)
        # decode: 2 erasures (1 data + 1 parity) — the same apply primitive
        # over the decode matrix; the chain keeps the [B*k, N] carry so
        # per-op traffic matches a real reconstruct over k survivors
        dec_t = per_op_seconds(apply_auto, dmat, dev)
        dec_mibs = max(dec_mibs, batch * (stripe_bytes / 2**20) / dec_t)
        if time.perf_counter() - t_start > 240:
            print("# degraded tunnel: single measurement pass",
                  file=sys.stderr)
            break

    combined = 2.0 / (1.0 / enc_mibs + 1.0 / dec_mibs)

    # CPU baseline: the native SIMD codec (GFNI/AVX-512 or AVX2 pshufb),
    # same 1 MiB stripe through the plugin path like the reference's
    # ceph_erasure_code_benchmark measures its isa/jerasure plugins
    cdata = np.ascontiguousarray(data[:k, :n])
    cpu_kind = "numpy"
    try:
        from ceph_tpu.native import NativeRegistry
        native = NativeRegistry().factory(
            "cpp_rs", {"k": str(k), "m": str(m), "technique": "cauchy"})
        cpu_enc_t = measure_cpu(lambda: native.encode(cdata), iters=20)
        parity = native.encode(cdata)
        avail = {i: cdata[i] for i in range(k) if i not in erasures}
        avail |= {k + j: parity[j] for j in range(m) if k + j not in erasures}
        cpu_dec_t = measure_cpu(
            lambda: native.decode(avail, erasures, n), iters=20)
        cpu_kind = "simd"                      # only after timings succeed
    except Exception as e:                     # no native toolchain
        print(f"# native baseline unavailable ({e}); using numpy",
              file=sys.stderr)
        from ceph_tpu.gf import ref
        cpu = RSCodec(k, m, technique="cauchy", device="numpy")
        cpu_enc_t = measure_cpu(lambda: cpu.encode(cdata))
        csurv = np.concatenate([cdata, cpu.encode(cdata)], axis=0)[src]
        cpu_dec_t = measure_cpu(lambda: ref.apply_matrix(D, csurv))
    cpu_enc = (stripe_bytes / 2**20) / cpu_enc_t
    cpu_dec = (stripe_bytes / 2**20) / cpu_dec_t
    cpu_combined = 2.0 / (1.0 / cpu_enc + 1.0 / cpu_dec)

    print(f"# encode {enc_mibs:.0f} MiB/s, decode {dec_mibs:.0f} MiB/s, "
          f"cpu-{cpu_kind} encode {cpu_enc:.0f} decode {cpu_dec:.0f} MiB/s "
          f"(device={jax.devices()[0].platform})", file=sys.stderr)
    print(json.dumps({
        "metric": "rs_k8m4_1MiB_encode_decode_device_resident",
        "value": round(combined, 1),
        "unit": "MiB/s",
        "vs_baseline": round(combined / cpu_combined, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
