"""Deterministic schedule exploration: the race detector for this design.

The reference hunts data races with TSAN builds, lockdep lock-order
tracking, and valgrind suites (reference: CMakeLists.txt:585-607,
src/common/lockdep.h, qa/suites/rados/verify/validater/) — tools for
shared-memory threads.  This framework is deterministic message-passing:
its races are cross-sender DELIVERY ORDERS, so the equivalent tool
controls the nondeterminism directly.  A ``ScheduledBus`` turns every
"which message next?" decision into an explicit choice point, and the
explorer drives a scenario through many distinct schedules — randomly
sampled or exhaustively (bounded DFS over the choice tree) — asserting
the scenario's invariants after each.  A schedule that breaks an
invariant is returned as a replayable choice list (the trace IS the
reproducer, which TSAN can never give you).

Scenario contract:
    def scenario(bus: ScheduledBus) -> None:
        ... build state over the bus, call bus.run_to_quiescence(),
        assert invariants (raise AssertionError on violation) ...
Each schedule runs a FRESH scenario instance; determinism of everything
except delivery order is what makes replay exact.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

from ..backend.messages import MessageBus, _WireEnvelope


class _Controller:
    """Replays a choice prefix, then takes branch 0; records the trace
    and each point's branching factor for DFS frontier expansion."""

    def __init__(self, prefix: list[int]):
        self.prefix = deque(prefix)
        self.trace: list[int] = []
        self.widths: list[int] = []

    def choose(self, n: int) -> int:
        i = self.prefix.popleft() if self.prefix else 0
        if i >= n:
            i = n - 1
        self.trace.append(i)
        self.widths.append(n)
        return i


class _RandomController:
    def __init__(self, rng):
        self.rng = rng
        self.trace: list[int] = []
        self.widths: list[int] = []

    def choose(self, n: int) -> int:
        i = self.rng.randrange(n)
        self.trace.append(i)
        self.widths.append(n)
        return i


class ScheduledBus(MessageBus):
    """MessageBus whose delivery order is an explicit choice sequence.

    A choice point offers every (destination, sender) pair with a
    pending head message — per-sender FIFO stays intact (the messenger's
    per-connection ordering guarantee) while cross-sender and
    cross-destination order is fully controlled."""

    def __init__(self, controller):
        super().__init__()
        self.controller = controller

    def _options(self):
        opts = []
        for shard in sorted(self.queues):
            if shard in self.down:
                continue
            q = self.queues[shard]
            seen = set()
            for m in q:
                s = getattr(m, "from_shard", None)
                if s not in seen:
                    seen.add(s)
                    opts.append((shard, s))
        return opts

    def _deliver_from(self, shard: int, sender) -> None:
        q = self.queues[shard]
        for i, m in enumerate(q):
            if getattr(m, "from_shard", None) == sender:
                del q[i]
                if isinstance(m, _WireEnvelope):
                    from ..backend.wire import FrameParser, message_decode
                    [(tag, segs)] = FrameParser(
                        self.wire_secret).feed(m.frame)
                    m = message_decode(tag, segs)
                self.handlers[shard].handle_message(m)
                self.delivered += 1
                return
        raise AssertionError("option vanished")

    def run_to_quiescence(self, max_steps: int = 100000) -> int:
        n = 0
        while n < max_steps:
            opts = self._options()
            if not opts:
                return n
            pick = self.controller.choose(len(opts))
            shard, sender = opts[pick]
            self._deliver_from(shard, sender)
            n += 1
        raise RuntimeError("schedule did not quiesce")

    # deliver_all must also go through choice points: scenario code (and
    # framework code it calls) pumps the bus with deliver_all
    def deliver_all(self, max_rounds: int = 10000) -> int:
        return self.run_to_quiescence()


@dataclass
class ExplorationResult:
    schedules_run: int
    choice_points: int
    failure_trace: list[int] | None = None
    failure: str | None = None
    traces_seen: set = field(default_factory=set)

    @property
    def ok(self) -> bool:
        return self.failure_trace is None


def explore_random(scenario, schedules: int = 50,
                   seed: int = 0) -> ExplorationResult:
    """Sample ``schedules`` random delivery orders; stop at the first
    invariant violation (its trace replays it exactly)."""
    import random
    res = ExplorationResult(0, 0)
    for s in range(schedules):
        ctl = _RandomController(random.Random(seed + s))
        bus = ScheduledBus(ctl)
        try:
            scenario(bus)
        except AssertionError as e:
            res.failure_trace = list(ctl.trace)
            res.failure = str(e)
            return res
        finally:
            res.schedules_run += 1
            res.choice_points += len(ctl.trace)
            res.traces_seen.add(tuple(ctl.trace))
    return res


def explore_dfs(scenario, max_runs: int = 200) -> ExplorationResult:
    """Bounded-exhaustive: depth-first over the choice tree (stateless
    model checking — each run replays a prefix then defaults to 0)."""
    res = ExplorationResult(0, 0)
    stack: list[list[int]] = [[]]
    while stack and res.schedules_run < max_runs:
        prefix = stack.pop()
        ctl = _Controller(prefix)
        bus = ScheduledBus(ctl)
        try:
            scenario(bus)
        except AssertionError as e:
            res.failure_trace = list(ctl.trace)
            res.failure = str(e)
            return res
        finally:
            res.schedules_run += 1
            res.choice_points += len(ctl.trace)
            res.traces_seen.add(tuple(ctl.trace))
        # expand: for the deepest new choice points, queue sibling branches
        base = len(prefix)
        for pos in range(len(ctl.trace) - 1, base - 1, -1):
            for alt in range(ctl.trace[pos] + 1, ctl.widths[pos]):
                stack.append(ctl.trace[:pos] + [alt])
    return res


def replay(scenario, trace: list[int]) -> None:
    """Re-run a failing schedule exactly (raises its AssertionError)."""
    scenario(ScheduledBus(_Controller(list(trace))))
