"""Honour JAX_PLATFORMS in environments whose sitecustomize overrides it.

The axon dev environment installs a sitecustomize that forces
``jax.config.jax_platforms = "axon,cpu"`` — overriding the caller's
``JAX_PLATFORMS=cpu`` env var — so any tool that merely imports jax will
dial the TPU tunnel on first backend init.  The tunnel has multi-hour
outages where init HANGS (not fails), turning every CLI invocation into a
wedge.  Call :func:`honour_jax_platforms_env` before first device use in
every entry point (the test conftest and ``__graft_entry__`` already do
the equivalent inline).
"""
from __future__ import annotations

import os


def honour_jax_platforms_env() -> None:
    """If JAX_PLATFORMS is set, force jax.config to agree with it."""
    want = os.environ.get("JAX_PLATFORMS", "")
    if not want:
        return
    import jax
    if jax.config.jax_platforms != want:
        jax.config.update("jax_platforms", want)
