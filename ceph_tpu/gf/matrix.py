"""Reed-Solomon generator-matrix construction and GF(2^8) linear algebra.

Host-side (numpy, exact integer math). Three matrix families, matching the
semantics of the reference's plugins:

- ``rs_vandermonde_isa``: Intel ISA-L ``gf_gen_rs_matrix`` semantics
  (reference: src/erasure-code/isa/ErasureCodeIsa.cc:384-387): parity row r
  is the geometric row (2^r)^j.  Only guaranteed MDS inside ISA-L's safe
  envelope k<=32, m<=4 (m=4 => k<=21), enforced by callers
  (reference: src/erasure-code/isa/ErasureCodeIsa.cc:323-364).
- ``cauchy1``: ISA-L ``gf_gen_cauchy1_matrix`` semantics: parity row i
  (absolute row index i >= k) entry j = inverse(i ^ j).  MDS for all k+m<=256.
- ``rs_vandermonde_jerasure``: jerasure ``reed_sol_vandermonde_coding_matrix``
  semantics (Plank & Ding 2003 "Note: Correction to the 1997 Tutorial on
  Reed-Solomon Coding"): extended-Vandermonde matrix made systematic by
  elementary column operations, then normalised so the first parity row is
  all ones.  (The jerasure/gf-complete submodules are empty in the reference
  checkout, so this construction follows the published algorithm; MDS and
  structural properties are property-tested in tests/test_gf_matrix.py.)

Decode matrices are built exactly the way the isa plugin does
(reference: src/erasure-code/isa/ErasureCodeIsa.cc:151-311): take the k
generator rows of k surviving chunks, invert, and multiply back through the
generator rows of the lost chunks.
"""
from __future__ import annotations

import numpy as np

from .tables import gf_inv, gf_mul, gf_pow, gf_mul_vec, MUL_TABLE


def rs_vandermonde_isa(k: int, m: int) -> np.ndarray:
    """Parity matrix [m, k]: row r, col j = 2^(r*j) (ISA-L gf_gen_rs_matrix)."""
    a = np.zeros((m, k), dtype=np.uint8)
    gen = 1
    for r in range(m):
        p = 1
        for j in range(k):
            a[r, j] = p
            p = gf_mul(p, gen)
        gen = gf_mul(gen, 2)
    return a


def cauchy1(k: int, m: int) -> np.ndarray:
    """Parity matrix [m, k]: row i+k, col j = inv((i+k) ^ j) (gf_gen_cauchy1)."""
    a = np.zeros((m, k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            a[i, j] = gf_inv((i + k) ^ j)
    return a


def rs_vandermonde_jerasure(k: int, m: int) -> np.ndarray:
    """Parity matrix [m, k]: systematic EXTENDED Vandermonde exactly as
    jerasure's ``reed_sol_vandermonde_coding_matrix`` builds it (Plank &
    Ding 2003 "Note: Correction to the 1997 Tutorial on Reed-Solomon
    Coding"; jerasure manual: "its first row is all 1s").

    Construction:

    1. extended Vandermonde over rows 0..k+m-1: natural rows
       V[i, j] = i^j (with 0^0 = 1, so row 0 is e_0) for all but the LAST
       row, which is the extension row e_{k-1};
    2. systematize: elementary column ops turning the top k x k block into
       the identity right-multiply V by inv(V_top), so the parity block is
       uniquely ``V_bottom @ inv(V_top)``;
    3. column normalisation (divide every column by the first coding
       row's entry, then rescale the data rows to restore the identity):
       the first parity row becomes ALL ONES — plain XOR, which is also
       why the RAID-6 P drive under ``reed_sol_r6_op`` is an XOR
       (reference: src/erasure-code/jerasure/ErasureCodeJerasure.h:111);
    4. row normalisation of the remaining coding rows (each divided by its
       first element) so the first COLUMN of the parity block is all ones
       too — reed_sol.c's final "first column of each row" step.

    Validated against an independent longhand-field re-derivation of the
    published algorithm in tests/test_ec_external_vectors.py.
    """
    rows, cols = k + m, k
    vdm = np.zeros((rows, cols), dtype=np.uint8)
    for i in range(rows - 1):
        vdm[i, 0] = 1
        for j in range(1, cols):
            vdm[i, j] = gf_mul(int(vdm[i, j - 1]), i)
    vdm[rows - 1, cols - 1] = 1          # the extension row e_{k-1}

    top_inv = gf_invert(vdm[:k, :])
    parity = gf_matmul(vdm[k:, :], top_inv)

    for j in range(cols):
        c = int(parity[0, j])
        if c == 0:
            raise ValueError(f"degenerate vandermonde col k={k} m={m} j={j}")
        if c != 1:
            parity[:, j] = gf_mul_vec(parity[:, j], gf_inv(c))
    for r in range(1, m):
        c = int(parity[r, 0])
        if c == 0:
            raise ValueError(f"degenerate vandermonde row k={k} m={m} r={r}")
        if c != 1:
            parity[r, :] = gf_mul_vec(parity[r, :], gf_inv(c))
    return parity


def generator_matrix(parity: np.ndarray) -> np.ndarray:
    """Full systematic generator [k+m, k] = [I_k ; parity]."""
    m, k = parity.shape
    return np.concatenate([np.eye(k, dtype=np.uint8), parity], axis=0)


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix product (XOR-accumulated) of uint8 matrices."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    assert a.shape[1] == b.shape[0]
    prod = MUL_TABLE[a[:, :, None].astype(np.intp), b[None, :, :].astype(np.intp)]
    return np.bitwise_xor.reduce(prod, axis=1)


def gf_invert(mat: np.ndarray) -> np.ndarray:
    """Invert a square GF(2^8) matrix by Gauss-Jordan elimination."""
    mat = np.asarray(mat, dtype=np.uint8)
    n = mat.shape[0]
    assert mat.shape == (n, n)
    aug = np.concatenate([mat.copy(), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        piv = col
        while piv < n and aug[piv, col] == 0:
            piv += 1
        if piv == n:
            raise np.linalg.LinAlgError("singular GF(2^8) matrix")
        if piv != col:
            aug[[col, piv], :] = aug[[piv, col], :]
        v = int(aug[col, col])
        if v != 1:
            aug[col, :] = gf_mul_vec(aug[col, :], gf_inv(v))
        for r in range(n):
            t = int(aug[r, col])
            if r != col and t != 0:
                aug[r, :] ^= gf_mul_vec(aug[col, :], t)
    return aug[:, n:].copy()


def decode_matrix(parity: np.ndarray, erasures: list[int],
                  available: list[int] | None = None) -> tuple[np.ndarray, list[int]]:
    """Build the decode matrix for a set of erased chunk indices.

    Returns ``(D, src)`` where ``src`` lists the k surviving chunk indices
    used as decode input and ``D`` is [len(erasures), k] with
    ``lost[e] = XOR_j D[e, j] * chunk[src[j]]``.

    Mirrors the isa plugin's decode-table construction
    (reference: src/erasure-code/isa/ErasureCodeIsa.cc:227-307): pick the
    first k surviving rows of the generator, invert, and for lost parity rows
    multiply the parity row back through the inverse.
    """
    m, k = parity.shape
    n = k + m
    erased = set(int(e) for e in erasures)
    if available is None:
        available = [i for i in range(n) if i not in erased]
    else:
        available = [int(a) for a in available if int(a) not in erased]
    if len(available) < k:
        raise ValueError(f"need {k} chunks, only {len(available)} available")
    src = sorted(available)[:k]

    gen = generator_matrix(parity)
    sub = gen[src, :]                    # [k, k]
    inv = gf_invert(sub)                 # data[j] = XOR inv[j, :] @ chunks[src]
    rows = []
    for e in sorted(erased):
        if e < k:
            rows.append(inv[e, :])
        else:
            rows.append(gf_matmul(parity[e - k:e - k + 1, :], inv)[0])
    return np.stack(rows, axis=0).astype(np.uint8), src
