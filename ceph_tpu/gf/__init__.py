from .tables import (GF_POLY, EXP_TABLE, LOG_TABLE, MUL_TABLE, gf_mul, gf_div,
                     gf_inv, gf_pow, gf_mul_vec, mul_bitmatrix, expand_bitmatrix)
from .matrix import (rs_vandermonde_isa, rs_vandermonde_jerasure, cauchy1,
                     generator_matrix, gf_matmul, gf_invert, decode_matrix)
from . import ref

__all__ = [
    "GF_POLY", "EXP_TABLE", "LOG_TABLE", "MUL_TABLE", "gf_mul", "gf_div",
    "gf_inv", "gf_pow", "gf_mul_vec", "mul_bitmatrix", "expand_bitmatrix",
    "rs_vandermonde_isa", "rs_vandermonde_jerasure", "cauchy1",
    "generator_matrix", "gf_matmul", "gf_invert", "decode_matrix", "ref",
]
