"""GF(2^w) for w in {16, 32}: matrix construction for wide-word codes.

The reference's jerasure plugin accepts w in {8, 16, 32}
(reference: src/erasure-code/jerasure/ErasureCodeJerasure.cc:191-197);
GF(2^8) lives in gf/tables.py.  This module supplies the WIDE fields —
only for building coding matrices and decode inversions (k*m scalars):
the DATA path never does wide-field arithmetic, because a GF(2^w)
matrix expands to a [w*m, w*k] GF(2) bitmatrix (column j of entry a =
bits of a*x^j) and the apply is then the SAME packet-layout XOR-matmul
the bitmatrix techniques run on the MXU (gf/bitmatrix.py,
ops.rs_kernels.xor_apply).  Word-size never touches the kernel: it just
changes how many packets a chunk splits into.

Primitive polynomials match gf-complete's defaults so the constructions
line up with the published jerasure semantics: w=16 -> 0x1100B,
w=32 -> 0x400007.
"""
from __future__ import annotations

import numpy as np

POLY = {16: 0x1100B, 32: 0x400007}


class GFW:
    """Scalar GF(2^w) arithmetic (log/exp tables for w=16; carryless
    multiply + reduction for w=32, where tables don't fit)."""

    def __init__(self, w: int):
        if w not in POLY:
            raise ValueError(f"w={w} must be 16 or 32")
        self.w = w
        self.poly = POLY[w]
        self.mask = (1 << w) - 1
        self._log = self._exp = None
        if w == 16:
            exp = np.zeros(1 << 16, dtype=np.uint32)
            log = np.zeros(1 << 16, dtype=np.uint32)
            x = 1
            for i in range((1 << 16) - 1):
                exp[i] = x
                log[x] = i
                x <<= 1
                if x & (1 << 16):
                    x = (x ^ self.poly) & 0xFFFF
            self._exp, self._log = exp, log

    def mul(self, a: int, b: int) -> int:
        if a == 0 or b == 0:
            return 0
        if self.w == 16:
            return int(self._exp[(int(self._log[a]) + int(self._log[b]))
                                 % 0xFFFF])
        # carryless multiply then reduce (w=32)
        r = 0
        x, y = int(a), int(b)
        while y:
            if y & 1:
                r ^= x
            y >>= 1
            x <<= 1
        for bit in range(63, self.w - 1, -1):
            if r & (1 << bit):
                r ^= self.poly << (bit - self.w) | (1 << bit)
        return r & self.mask

    def pow(self, a: int, n: int) -> int:
        r = 1
        while n:
            if n & 1:
                r = self.mul(r, a)
            a = self.mul(a, a)
            n >>= 1
        return r

    def inv(self, a: int) -> int:
        if a == 0:
            raise ZeroDivisionError("GF inverse of 0")
        return self.pow(a, (1 << self.w) - 2)

    # -- coding matrices ----------------------------------------------------

    def vandermonde(self, k: int, m: int) -> np.ndarray:
        """Systematic extended-Vandermonde parity matrix [m, k] (the
        reed_sol_van construction, Plank & Ding 2003, generalized to
        this field).  object dtype: w=32 values exceed int64-safe ops."""
        rows, cols = k + m, k
        V = [[self.pow(r, c) for c in range(cols)] for r in range(rows)]
        # Gaussian elimination to make the top k x k identity (column ops)
        for i in range(k):
            if V[i][i] == 0:
                for j in range(i + 1, cols):
                    if V[i][j] != 0:
                        for r in range(rows):
                            V[r][i], V[r][j] = V[r][j], V[r][i]
                        break
            inv = self.inv(V[i][i])
            if V[i][i] != 1:
                for r in range(rows):
                    V[r][i] = self.mul(V[r][i], inv)
            for j in range(cols):
                if j != i and V[i][j] != 0:
                    c = V[i][j]
                    for r in range(rows):
                        V[r][j] ^= self.mul(c, V[r][i])
        out = np.empty((m, k), dtype=object)
        for r in range(m):
            for c in range(k):
                out[r, c] = V[k + r][c]
        return out

    def cauchy(self, k: int, m: int) -> np.ndarray:
        """gf_gen_cauchy1-style matrix [m, k]: entry = inv((k+i) ^ j)."""
        out = np.empty((m, k), dtype=object)
        for i in range(m):
            for j in range(k):
                out[i, j] = self.inv((k + i) ^ j)
        return out

    # -- GF(2) expansion (the data-path bridge) ------------------------------

    def mul_bitmatrix(self, a: int) -> np.ndarray:
        """[w, w] GF(2) matrix of multiply-by-a: column j = bits of
        a * x^j (the jerasure_matrix_to_bitmatrix cell)."""
        w = self.w
        out = np.zeros((w, w), dtype=np.uint8)
        v = int(a)
        for j in range(w):
            for i in range(w):
                out[i, j] = (v >> i) & 1
            v = self.mul(v, 2)
        return out

    def expand_bitmatrix(self, A: np.ndarray) -> np.ndarray:
        """GF(2^w) matrix [r, c] -> GF(2) bitmatrix [w*r, w*c]."""
        r, c = A.shape
        w = self.w
        out = np.zeros((w * r, w * c), dtype=np.uint8)
        for i in range(r):
            for j in range(c):
                out[w * i:w * i + w, w * j:w * j + w] = \
                    self.mul_bitmatrix(int(A[i, j]))
        return out
