"""GF(2^8) arithmetic tables, generated — not stored — at import time.

Field: GF(2^8) with primitive polynomial 0x11D (x^8+x^4+x^3+x^2+1), the
polynomial used by both gf-complete (jerasure w=8 default) and Intel ISA-L,
i.e. the field behind the reference's `jerasure` and `isa` erasure-code
plugins (reference: src/erasure-code/jerasure/, src/erasure-code/isa/).

Everything here is numpy (host side); the JAX kernels in ceph_tpu.ops pull
these tables onto the device as constants.
"""
from __future__ import annotations

import numpy as np

GF_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1, primitive over GF(2)
GF_ORDER = 256


def _gen_exp_log() -> tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GF_POLY
    exp[255:510] = exp[0:255]
    # log[0] is mathematically undefined; callers must special-case 0.
    log[0] = 0
    return exp, log


EXP_TABLE, LOG_TABLE = _gen_exp_log()


def gf_mul(a: int, b: int) -> int:
    """Scalar GF(2^8) multiply."""
    if a == 0 or b == 0:
        return 0
    return int(EXP_TABLE[int(LOG_TABLE[a]) + int(LOG_TABLE[b])])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    if a == 0:
        return 0
    return int(EXP_TABLE[(int(LOG_TABLE[a]) - int(LOG_TABLE[b])) % 255])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(2^8) inverse of zero")
    return int(EXP_TABLE[(255 - int(LOG_TABLE[a])) % 255])


def gf_pow(a: int, n: int) -> int:
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(EXP_TABLE[(int(LOG_TABLE[a]) * n) % 255])


def _gen_mul_table() -> np.ndarray:
    """Full 256x256 multiplication table, MUL[a, b] = a*b in GF(2^8)."""
    a = np.arange(256)
    la = LOG_TABLE[a]
    # sum of logs mod 255, exp; zero rows/cols handled by mask
    s = (la[:, None] + la[None, :]) % 255
    t = EXP_TABLE[s].astype(np.uint8)
    t[0, :] = 0
    t[:, 0] = 0
    return t


MUL_TABLE = _gen_mul_table()


def gf_mul_vec(a, b):
    """Elementwise GF(2^8) multiply of uint8 numpy arrays (broadcasting)."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    return MUL_TABLE[a.astype(np.intp), b.astype(np.intp)]


def mul_bitmatrix(c: int) -> np.ndarray:
    """The 8x8 GF(2) matrix of 'multiply by constant c'.

    Column j holds the bits (little-endian: row i = bit i) of c * 2^j, so for
    a byte d with bit vector x, (M @ x) mod 2 is the bit vector of c*d.
    This is the bit-matrix representation jerasure's cauchy/bitmatrix
    techniques use (reference: src/erasure-code/jerasure/ErasureCodeJerasure.h:142-171);
    here it is the bridge from GF(2^8) matmul to an MXU-friendly GF(2) matmul.
    """
    m = np.zeros((8, 8), dtype=np.uint8)
    for j in range(8):
        v = gf_mul(c, 1 << j)
        for i in range(8):
            m[i, j] = (v >> i) & 1
    return m


def expand_bitmatrix(A: np.ndarray) -> np.ndarray:
    """Expand a GF(2^8) matrix [r, c] into its GF(2) bit-matrix [8r, 8c]."""
    A = np.asarray(A, dtype=np.uint8)
    r, c = A.shape
    out = np.zeros((8 * r, 8 * c), dtype=np.uint8)
    for i in range(r):
        for j in range(c):
            out[8 * i:8 * i + 8, 8 * j:8 * j + 8] = mul_bitmatrix(int(A[i, j]))
    return out
