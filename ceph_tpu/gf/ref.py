"""Pure-numpy Reed-Solomon codec: the exact host-side reference.

Used (a) as the oracle the JAX/TPU kernels are tested against and (b) as the
low-latency CPU fallback for single small stripes, where a device round-trip
is not worth it (the "dispatch economics" concern from SURVEY.md §7).
"""
from __future__ import annotations

import numpy as np

from .tables import MUL_TABLE
from .matrix import decode_matrix


def apply_matrix(mat: np.ndarray, data: np.ndarray) -> np.ndarray:
    """out[i] = XOR_j mat[i, j] * data[j] over GF(2^8).

    mat: [r, k] uint8, data: [k, N] uint8 -> [r, N] uint8.
    """
    mat = np.asarray(mat, dtype=np.uint8)
    data = np.asarray(data, dtype=np.uint8)
    r, k = mat.shape
    out = np.zeros((r, data.shape[1]), dtype=np.uint8)
    for i in range(r):
        acc = None
        for j in range(k):
            c = int(mat[i, j])
            if c == 0:
                continue
            term = data[j] if c == 1 else MUL_TABLE[c][data[j].astype(np.intp)]
            acc = term.copy() if acc is None else np.bitwise_xor(acc, term)
        if acc is not None:
            out[i] = acc
    return out


_native_apply = None


def _load_native():
    """ctypes handle to the SIMD region kernel (gf8_simd.cc), or None.

    The pure-numpy ``apply_matrix`` above stays untouched — it is the
    oracle the JAX kernels AND the native kernels are tested against;
    only ``apply_matrix_fast`` (the production CPU path) dispatches here.
    """
    global _native_apply
    if _native_apply is not None:
        return _native_apply or None
    try:
        from ..native import registry_lib
        _native_apply = registry_lib().ec_apply_matrix
    except Exception:
        _native_apply = False
    return _native_apply or None


def apply_matrix_fast(mat: np.ndarray, data: np.ndarray) -> np.ndarray:
    """Production CPU path: SIMD (GFNI/AVX-512 or AVX2) region kernel when
    the native build is available, exact numpy otherwise.  Bit-identical
    to ``apply_matrix`` either way."""
    fn = _load_native()
    if fn is None:
        return apply_matrix(mat, data)
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    r, k = mat.shape
    out = np.empty((r, data.shape[1]), dtype=np.uint8)
    fn(mat.ctypes.data, r, k, data.ctypes.data, out.ctypes.data,
       data.shape[1])
    return out


def encode(parity_mat: np.ndarray, data: np.ndarray) -> np.ndarray:
    """data: [k, N] -> parity [m, N]."""
    return apply_matrix(parity_mat, data)


def decode(parity_mat: np.ndarray, chunks: dict[int, np.ndarray],
           erasures: list[int]) -> dict[int, np.ndarray]:
    """Recover erased chunks from surviving ones.

    chunks: {index: [N] uint8} of surviving chunks, erasures: lost indices.
    """
    D, src = decode_matrix(parity_mat, erasures, available=list(chunks))
    stack = np.stack([chunks[i] for i in src], axis=0)
    rec = apply_matrix(D, stack)
    return {e: rec[i] for i, e in enumerate(sorted(erasures))}
