"""GF(2) bitmatrix codes: liberation, blaum_roth, liber8tion.

The reference's jerasure plugin exposes three bitmatrix-only RAID-6
techniques (reference: src/erasure-code/jerasure/ErasureCodeJerasure.h:191-252)
whose CPU implementations compile the bitmatrix into a word-XOR schedule
(`jerasure_smart_bitmatrix_to_schedule`,
reference: src/erasure-code/jerasure/ErasureCodeJerasure.cc:453-509).  On
TPU no schedule is needed: a bitmatrix apply IS a GF(2) matmul, which is
exactly what the MXU runs natively (int8 matmul, mod 2) — the same primitive
the GF(2^8) codec already uses, with packets instead of bit-planes as rows.

Data layout (jerasure packet semantics): a chunk of B bytes is processed in
groups of w*packetsize bytes; within a group, packet p is bytes
[p*ps, (p+1)*ps).  Bitmatrix row/column index i corresponds to packet i of
each group.  Encode: parity_packets = W_coding @ data_packets over GF(2),
XOR acting bytewise.

Matrix constructions (the jerasure/gf-complete submodules are empty in the
reference checkout, so these follow the published algorithms; validity as
RAID-6 codes — every single and double erasure decodable — is property-
tested in tests/test_bitmatrix.py):

- liberation (Plank, "The RAID-6 Liberation Codes", FAST 2008): w prime,
  k <= w.  P block: identities.  Q block column j: the cyclic shift by j,
  plus for j > 0 one extra bit at row (j*(w-1)/2) mod w, column offset
  (row + j - 1) mod w — the published minimal-density construction.
- blaum_roth (Blaum & Roth array codes): w+1 prime.  Q block column j is
  multiplication by x^j in the ring GF(2)[x]/(1 + x + ... + x^w)
  (powers of the companion matrix).
- liber8tion: w = 8, m = 2, k <= 8.  Plank's published liber8tion matrices
  were found by search to minimise XOR count; XOR count is irrelevant to a
  dense MXU matmul, so this implementation uses the geometric RAID-6
  bitmatrix over GF(2^8) (X_j = mul-by-2^j), which has the identical
  parameter envelope and fault tolerance.  NOT bit-identical to CPU
  jerasure's liber8tion output (nothing can be: the submodule implementing
  it is absent from the reference checkout).
"""
from __future__ import annotations

import numpy as np

from .tables import gf_pow, mul_bitmatrix


def is_prime(n: int) -> bool:
    if n < 2:
        return False
    p = 2
    while p * p <= n:
        if n % p == 0:
            return False
        p += 1
    return True


def liberation_bitmatrix(k: int, w: int) -> np.ndarray:
    """Coding bitmatrix [2w, kw] of the liberation code (w prime, k <= w)."""
    if w <= 2 or not is_prime(w):
        raise ValueError(f"w={w} must be greater than two and be prime")
    if k > w:
        raise ValueError(f"k={k} must be less than or equal to w={w}")
    M = np.zeros((2 * w, k * w), dtype=np.uint8)
    for j in range(k):
        for i in range(w):
            M[i, j * w + i] = 1                        # P: identity
            M[w + i, j * w + (j + i) % w] = 1          # Q: cyclic shift by j
        if j > 0:
            i = (j * ((w - 1) // 2)) % w
            M[w + i, j * w + (i + j - 1) % w] = 1      # the extra liberty bit
    return M


def blaum_roth_bitmatrix(k: int, w: int) -> np.ndarray:
    """Coding bitmatrix [2w, kw] of the Blaum-Roth code (w+1 prime, k <= w).

    w == 7 is tolerated without the primality check for backward
    compatibility, exactly like the reference
    (ErasureCodeJerasure.cc:461-471: "back in Firefly, w = 7 was the
    default and produced usable chunks").  WARNING: w=7 is NOT MDS —
    1+x+...+x^7 = (1+x)^7 over GF(2), so x^i + x^j is a zero divisor and
    every (data, data) double erasure is undecodable; single erasures and
    data+parity pairs still decode ("usable", not safe).  The plugin never
    defaults to it.
    """
    if w != 7 and (w <= 2 or not is_prime(w + 1)):
        raise ValueError(f"w={w} must be greater than two and w+1 prime")
    if k > w:
        raise ValueError(f"k={k} must be less than or equal to w={w}")
    # companion matrix of multiply-by-x in GF(2)[x]/(1 + x + ... + x^w):
    # x * x^j = x^(j+1) for j < w-1; x * x^(w-1) = x^w = 1 + x + ... + x^(w-1)
    C = np.zeros((w, w), dtype=np.uint8)
    for j in range(w - 1):
        C[j + 1, j] = 1
    C[:, w - 1] = 1
    M = np.zeros((2 * w, k * w), dtype=np.uint8)
    X = np.eye(w, dtype=np.uint8)
    for j in range(k):
        M[:w, j * w:(j + 1) * w] = np.eye(w, dtype=np.uint8)
        M[w:, j * w:(j + 1) * w] = X
        X = (C @ X) % 2
    return M


def liber8tion_bitmatrix(k: int) -> np.ndarray:
    """Coding bitmatrix [16, 8k] of the w=8 RAID-6 code (k <= 8).

    Geometric construction X_j = mul_bitmatrix(2^j): X_i + X_j =
    M(2^i XOR 2^j) is invertible for i != j because 2^i != 2^j in GF(2^8),
    so every double erasure decodes (see module docstring re Plank's
    hand-searched minimal-density table).
    """
    if k > 8:
        raise ValueError(f"k={k} must be less than or equal to 8")
    M = np.zeros((16, 8 * k), dtype=np.uint8)
    for j in range(k):
        M[:8, 8 * j:8 * j + 8] = np.eye(8, dtype=np.uint8)
        M[8:, 8 * j:8 * j + 8] = mul_bitmatrix(gf_pow(2, j))
    return M


def gf2_invert(M: np.ndarray) -> np.ndarray:
    """Invert a square 0/1 matrix over GF(2) by Gauss-Jordan."""
    M = np.asarray(M, dtype=np.uint8) & 1
    n, n2 = M.shape
    if n != n2:
        raise ValueError(f"matrix {M.shape} is not square")
    aug = np.concatenate([M.copy(), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        pivot = col + int(np.argmax(aug[col:, col]))
        if aug[pivot, col] == 0:
            raise np.linalg.LinAlgError(f"singular over GF(2) at column {col}")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        rows = np.flatnonzero(aug[:, col])
        rows = rows[rows != col]
        aug[rows] ^= aug[col]
    return aug[:, n:]


def decode_bitmatrix(coding: np.ndarray, k: int, w: int,
                     erasures: list[int],
                     available: list[int] | None = None
                     ) -> tuple[np.ndarray, list[int]]:
    """Decode matrix for a bitmatrix code.

    coding: [m*w, k*w] coding part; returns (D, src) where src lists the k
    survivor chunk ids used (first k available, like the interface default
    _minimum_to_decode) and D [len(erasures)*w, k*w] maps their packets to
    the erased chunks' packets: erased = D @ survivors over GF(2).
    """
    m = coding.shape[0] // w
    n = k + m
    R = np.zeros((n * w, k * w), dtype=np.uint8)
    for i in range(k):
        R[i * w:(i + 1) * w, i * w:(i + 1) * w] = np.eye(w, dtype=np.uint8)
    R[k * w:] = coding
    erasures = sorted(int(e) for e in erasures)
    pool = (sorted(set(range(n)) - set(erasures)) if available is None
            else sorted(set(available) - set(erasures)))
    if len(pool) < k:
        raise ValueError(
            f"{len(pool)} survivors cannot decode a k={k} bitmatrix code")
    src = pool[:k]
    S = np.concatenate([R[c * w:(c + 1) * w] for c in src])
    Sinv = gf2_invert(S)
    D = np.concatenate(
        [(R[e * w:(e + 1) * w].astype(np.int64) @ Sinv.astype(np.int64)) % 2
         for e in erasures]).astype(np.uint8)
    return D, src


# -- packet layout + host apply --------------------------------------------

def to_packets(chunks: np.ndarray, w: int, ps: int) -> np.ndarray:
    """[c, B] chunk bytes -> [c*w, B/w] packet rows.

    jerasure group layout: a chunk is processed in groups of w*ps bytes;
    within a group, packet p is bytes [p*ps, (p+1)*ps).  Bitmatrix row i of
    chunk c gathers packet i of every group:
    row[c*w + i] = concat over groups g of chunk[g*w*ps + i*ps : ... + ps].
    """
    c, B = chunks.shape
    if B % (w * ps):
        raise ValueError(
            f"chunk size {B} not a multiple of w*packetsize={w * ps}")
    return np.ascontiguousarray(
        chunks.reshape(c, -1, w, ps).swapaxes(1, 2).reshape(c * w, -1))


def from_packets(packets: np.ndarray, w: int, ps: int) -> np.ndarray:
    """[c*w, P] packet rows -> [c, P*w] chunk bytes (inverse of to_packets)."""
    cw, P = packets.shape
    c = cw // w
    return np.ascontiguousarray(
        packets.reshape(c, w, -1, ps).swapaxes(1, 2).reshape(c, -1))


def xor_apply_host(W: np.ndarray, packets: np.ndarray) -> np.ndarray:
    """out[r] = XOR of packets[i] where W[r, i] == 1 (numpy host path)."""
    W = np.asarray(W, dtype=bool)
    out = np.zeros((W.shape[0], packets.shape[1]), dtype=np.uint8)
    for r in range(W.shape[0]):
        sel = packets[W[r]]
        if len(sel):
            out[r] = np.bitwise_xor.reduce(sel, axis=0)
    return out
