"""Manager modules (SURVEY.md §2.4 mgr): balancer + pg_autoscaler analogs.

The reference runs these as Python modules inside ceph-mgr
(src/pybind/mgr/{balancer,pg_autoscaler}); here they are library functions
over OSDMap — same decision logic, emitted as OSDMap incrementals."""
from .balancer import calc_pg_upmaps, calc_weight_set, osd_deviation
from .health import (CheckResult, HEALTH_ERR, HEALTH_OK, HEALTH_WARN,
                     HealthCheckEngine, iter_throttles,
                     live_health_engines, recompile_storm_check,
                     slow_ops_check, throttle_saturated_check)
from .pg_autoscaler import autoscale_recommendations, nearest_power_of_two
from .stats import StatsAggregator, live_aggregators

__all__ = ["calc_pg_upmaps", "calc_weight_set", "osd_deviation",
           "autoscale_recommendations", "nearest_power_of_two",
           "CheckResult", "HEALTH_OK", "HEALTH_WARN", "HEALTH_ERR",
           "HealthCheckEngine", "iter_throttles", "live_health_engines",
           "slow_ops_check", "throttle_saturated_check",
           "recompile_storm_check",
           "StatsAggregator", "live_aggregators"]
