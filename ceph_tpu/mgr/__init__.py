"""Manager modules (SURVEY.md §2.4 mgr): balancer + pg_autoscaler analogs.

The reference runs these as Python modules inside ceph-mgr
(src/pybind/mgr/{balancer,pg_autoscaler}); here they are library functions
over OSDMap — same decision logic, emitted as OSDMap incrementals."""
from .balancer import calc_pg_upmaps, calc_weight_set, osd_deviation
from .pg_autoscaler import autoscale_recommendations, nearest_power_of_two

__all__ = ["calc_pg_upmaps", "calc_weight_set", "osd_deviation",
           "autoscale_recommendations", "nearest_power_of_two"]
