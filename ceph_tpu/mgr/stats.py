"""StatsAggregator: rolling perf-counter windows -> PGMap-style rates.

Analog of the reference's MgrStatMonitor/PGMap digest (reference:
src/mon/MgrStatMonitor.cc + src/mon/PGMap.cc ``overall_recovery_summary``
/ ``overall_client_io_rate_summary`` — the 'client: 12 MiB/s wr, 3 op/s'
lines in ``ceph -s``): daemons report counters, the mgr differentiates
them over time, and status renders RATES, not lifetime totals.

Here the source is the process-wide :class:`PerfCountersCollection`: each
``sample()`` flattens every registered collection into a
``(collection, key) -> value`` snapshot appended to a bounded window;
rates are computed between the window's endpoints, summed across the
collections that carry a key (one ``ec_backend.<pg>`` collection per PG —
the cluster rate is their sum, exactly how PGMap sums per-PG deltas).
Counter resets (a collection removed and re-registered) clamp to zero
rather than going negative.

Driving: ``sample()`` is explicit (``Cluster.status()`` ticks it — the
deterministic single-thread design), the prometheus exporter ticks it on
scrape, and ``start()`` runs an optional background sampler at
``mgr_stats_period`` for live `top` output.
"""
from __future__ import annotations

import threading
import time
import weakref
from collections import deque

from ..common import default_context

# live aggregators, for the prometheus rate-gauge export
_AGGREGATORS: "weakref.WeakSet[StatsAggregator]" = weakref.WeakSet()

# collection prefixes whose counters are CLIENT/RECOVERY io (the PG
# backends; one collection per PG instance)
PG_PREFIXES = ("ec_backend.", "replicated_backend.", "pg_backend.")

# collection prefix of the wire accountants (common/wire_accounting.py):
# bus + TCP messenger byte/op counters, per-op-class rollups
WIRE_PREFIXES = ("wire.",)


def live_aggregators() -> list["StatsAggregator"]:
    return list(_AGGREGATORS)


def _flatten(perf_dump: dict) -> dict[tuple[str, str], float]:
    """One numeric value per (collection, key): counters/gauges as-is,
    averages and histograms as ``key:count``/``key:sum`` pairs (their
    monotone components — rates over them are ops/s and seconds/s)."""
    flat: dict[tuple[str, str], float] = {}
    for coll, metrics in perf_dump.items():
        for key, v in metrics.items():
            if isinstance(v, dict):
                if "avgcount" in v:                  # avg / time_avg
                    flat[(coll, f"{key}:count")] = float(v["avgcount"])
                    flat[(coll, f"{key}:sum")] = float(v["sum"])
                elif "buckets" in v:                 # histogram
                    flat[(coll, f"{key}:count")] = float(v["count"])
                    flat[(coll, f"{key}:sum")] = float(v["sum"])
            else:
                flat[(coll, key)] = float(v)
    return flat


class StatsAggregator:
    """Bounded time-series of perf snapshots + rate/digest math."""

    def __init__(self, cct=None, name: str = "stats",
                 window: int | None = None, clock=time.monotonic):
        self.cct = cct if cct is not None else default_context()
        self.name = name
        self.clock = clock
        n = int(self.cct.conf.get("mgr_stats_window")
                if window is None else window)
        self._samples: deque[tuple[float, dict]] = deque(maxlen=max(2, n))
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        _AGGREGATORS.add(self)

    # -- sampling ----------------------------------------------------------

    def sample(self, now: float | None = None) -> dict:
        """Scrape every registered collection into the window."""
        flat = _flatten(self.cct.perf.perf_dump())
        t = self.clock() if now is None else now
        with self._lock:
            self._samples.append((t, flat))
        return flat

    def start(self, period: float | None = None) -> "StatsAggregator":
        """Background sampler (live ``ceph_tpu top``); bounded by the
        window deque.  Explicit ``sample()`` calls still work alongside."""
        if self._thread is None:
            p = float(self.cct.conf.get("mgr_stats_period")
                      if period is None else period)
            self._stop.clear()

            def loop():
                while not self._stop.wait(p):
                    self.sample()
            self._thread = threading.Thread(
                target=loop, name=f"stats-{self.name}", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join()
            self._thread = None

    def close(self) -> None:
        self.stop()
        _AGGREGATORS.discard(self)

    # -- window math -------------------------------------------------------

    def _ends(self) -> tuple[tuple[float, dict], tuple[float, dict]] | None:
        with self._lock:
            if len(self._samples) < 2:
                return None
            return self._samples[0], self._samples[-1]

    def span(self) -> float:
        """Seconds covered by the window (0.0 below two samples)."""
        ends = self._ends()
        return ends[1][0] - ends[0][0] if ends else 0.0

    def counter_delta(self, key: str,
                      coll_prefix: tuple[str, ...] | None = None) -> float:
        """Summed increase of counter ``key`` across matching collections
        between the window's endpoints.  A collection that appeared
        mid-window contributes its full value (its counters started at
        zero inside the window); a reset clamps to zero."""
        ends = self._ends()
        if ends is None:
            return 0.0
        (_, first), (_, last) = ends
        total = 0.0
        for (coll, k), v in last.items():
            if k != key:
                continue
            if coll_prefix is not None and \
                    not any(coll.startswith(p) for p in coll_prefix):
                continue
            total += max(0.0, v - first.get((coll, k), 0.0))
        return total

    def rate(self, key: str,
             coll_prefix: tuple[str, ...] | None = None) -> float:
        """``counter_delta / span`` — per-second rate over the window."""
        dt = self.span()
        return self.counter_delta(key, coll_prefix) / dt if dt > 0 else 0.0

    def per_collection_delta(self, key: str,
                             coll_prefix: tuple[str, ...] | None = None
                             ) -> dict[str, float]:
        """Window increase of counter ``key`` PER collection (the heat
        tracker's input: one PG backend collection per PG, so per-
        collection deltas ARE per-PG deltas).  Same born-mid-window and
        reset-clamp semantics as :meth:`counter_delta`."""
        ends = self._ends()
        if ends is None:
            return {}
        (_, first), (_, last) = ends
        out: dict[str, float] = {}
        for (coll, k), v in last.items():
            if k != key:
                continue
            if coll_prefix is not None and \
                    not any(coll.startswith(p) for p in coll_prefix):
                continue
            out[coll] = max(0.0, v - first.get((coll, k), 0.0))
        return out

    def gauge_sum(self, key: str,
                  coll_prefix: tuple[str, ...] | None = None) -> float:
        """Summed CURRENT value across matching collections (for gauges
        and lifetime totals)."""
        with self._lock:
            if not self._samples:
                return 0.0
            last = self._samples[-1][1]
        return sum(v for (coll, k), v in last.items()
                   if k == key and (coll_prefix is None or
                                    any(coll.startswith(p)
                                        for p in coll_prefix)))

    # -- the PGMap-style digest --------------------------------------------

    def _wire_class_delta(self, cls: str) -> float:
        return self.counter_delta(f"class_bytes:{cls}", WIRE_PREFIXES)

    def wire_bytes_per_byte_repaired(self) -> float:
        """ROADMAP item 3's success metric: wire bytes attributed to
        recovery-class ops over the window, per byte of repaired data
        pushed — ~k for centralized repair (k-1 survivor chunk reads +
        one reconstructed chunk push per chunk repaired), ~1 for a
        pipelined repair chain.  0.0 while nothing repaired."""
        repaired = self.counter_delta("recovery_bytes", PG_PREFIXES)
        if repaired <= 0:
            return 0.0
        return self._wire_class_delta("recovery") / repaired

    def wire_bytes_per_op(self) -> float:
        """ROADMAP item 4's companion metric: wire bytes of client- and
        serving-class traffic per completed client op over the window."""
        ops = (self.counter_delta("writes", PG_PREFIXES)
               + self.counter_delta("reads", PG_PREFIXES))
        if ops <= 0:
            return 0.0
        return (self._wire_class_delta("client")
                + self._wire_class_delta("serving")) / ops

    @staticmethod
    def bytes_copied_per_byte_served() -> float:
        """ROADMAP item 2's success metric: host payload copies per
        payload byte consumed, from the process-global copy ledger —
        ~3 on the legacy pickle path, ~1 on the sideband path.  0.0
        while nothing served (or the ledger is unavailable)."""
        try:
            from ..common.copy_ledger import ledger
        except Exception:                   # pragma: no cover
            return 0.0
        return ledger().copies_per_byte()

    def digest(self) -> dict:
        """The rate digest ``Cluster.status()`` / `ceph_tpu top` render:
        client IO, recovery, serving-batch throughput, wire traffic,
        jit churn."""
        return {
            "window_s": round(self.span(), 3),
            "samples": len(self._samples),
            "client_io": {
                "wr_bytes_s": self.rate("write_bytes", PG_PREFIXES),
                "rd_bytes_s": self.rate("read_bytes", PG_PREFIXES),
                "wr_op_s": self.rate("writes", PG_PREFIXES),
                "rd_op_s": self.rate("reads", PG_PREFIXES),
            },
            "recovery": {
                "bytes_s": self.rate("recovery_bytes", PG_PREFIXES),
                # objects-recovered/s: batched waves and the per-object
                # machine both land on the backends' `recoveries` counter
                "op_s": self.rate("recoveries", PG_PREFIXES),
                # scheduler occupancy (0 when no scheduler is attached):
                # queued/active PG jobs from the live recovery schedulers
                "queued_pgs": self.gauge_sum("jobs_queued",
                                             ("recovery.",)),
                "active_pgs": self.gauge_sum("jobs_active",
                                             ("recovery.",)),
                # bytes-on-wire per byte repaired (ROADMAP item 3's
                # success metric — ~k centralized, ~1 pipelined)
                "wire_bytes_per_byte_repaired":
                    self.wire_bytes_per_byte_repaired(),
            },
            "serving": {
                "batch_s": self.rate("batches"),
                "op_s": self.rate("ops_completed"),
                "bytes_s": self.rate("bytes_in"),
                # client+serving wire bytes per completed client op
                "wire_bytes_per_op": self.wire_bytes_per_op(),
                # host copies per payload byte consumed — the zero-copy
                # data path's success metric (common/copy_ledger.py);
                # cumulative since process start, not windowed
                "bytes_copied_per_byte_served":
                    self.bytes_copied_per_byte_served(),
            },
            "wire": {
                "tx_bytes_s": self.rate("tx_bytes", WIRE_PREFIXES),
                "tx_msgs_s": self.rate("tx_msgs", WIRE_PREFIXES),
                "class_bytes_s": {
                    cls: (self._wire_class_delta(cls) / self.span()
                          if self.span() > 0 else 0.0)
                    for cls in ("client", "serving", "recovery",
                                "scrub", "rebalance", "other")},
            },
            "jit": {
                "compiles": self.counter_delta("compilations", ("jit",)),
                "cache_hits": self.counter_delta("cache_hits", ("jit",)),
            },
        }

    def digest_flat(self) -> dict[str, float]:
        """The digest flattened to ``stat -> value`` (the prometheus
        ``ceph_tpu_stats_rate`` gauge label set)."""
        d = self.digest()
        return {
            "client_wr_bytes_s": d["client_io"]["wr_bytes_s"],
            "client_rd_bytes_s": d["client_io"]["rd_bytes_s"],
            "client_wr_op_s": d["client_io"]["wr_op_s"],
            "client_rd_op_s": d["client_io"]["rd_op_s"],
            "recovery_bytes_s": d["recovery"]["bytes_s"],
            "recovery_op_s": d["recovery"]["op_s"],
            "recovery_queued_pgs": d["recovery"]["queued_pgs"],
            "recovery_active_pgs": d["recovery"]["active_pgs"],
            "recovery_wire_per_byte":
                d["recovery"]["wire_bytes_per_byte_repaired"],
            "serving_batch_s": d["serving"]["batch_s"],
            "serving_op_s": d["serving"]["op_s"],
            "serving_bytes_s": d["serving"]["bytes_s"],
            "serving_wire_per_op": d["serving"]["wire_bytes_per_op"],
            "serving_copies_per_byte":
                d["serving"]["bytes_copied_per_byte_served"],
            "wire_tx_bytes_s": d["wire"]["tx_bytes_s"],
            "wire_tx_msgs_s": d["wire"]["tx_msgs_s"],
            "jit_compiles": d["jit"]["compiles"],
            "jit_cache_hits": d["jit"]["cache_hits"],
        }
