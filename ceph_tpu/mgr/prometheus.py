"""Prometheus exporter: perf counters + span latencies in the text format.

Analog of the reference mgr's prometheus module (reference:
src/pybind/mgr/prometheus/module.py — walks every daemon's perf counter
schema and renders `ceph_<subsystem>_<counter>` metrics).  Here the
process-wide PerfCounters registry renders to the same text format:
counters as `ceph_tpu_<collection>_<name>`, averages as `_sum`/`_count`
pairs, histograms as cumulative `_bucket{le=...}` series **plus the
`_sum` series real scrapers require for histogram types** — and the span
tracer's per-name latency distributions as
`ceph_tpu_span_latency_seconds` histograms.  `# HELP`/`# TYPE` are
emitted exactly once per metric name (several collections share counter
names, e.g. one `ec_backend.<pg>` per PG) and the `collection` label is
identical across a histogram's `_bucket`/`_count`/`_sum` series.
"""
from __future__ import annotations

from ..common import default_context
from ..common.perf_counters import (
    PERFCOUNTER_AVG, PERFCOUNTER_HISTOGRAM, PERFCOUNTER_TIME_AVG,
)
from ..common.tracer import default_tracer


def _sanitize(name: str) -> str:
    return "".join(ch if ch.isalnum() or ch == "_" else "_"
                   for ch in name)


class _MetricFamily:
    """One exposition block: HELP + TYPE once, then every series."""

    def __init__(self, name: str, kind: str, help_text: str):
        self.name, self.kind = name, kind
        self.help = help_text or name
        self.lines: list[str] = []

    def render(self) -> list[str]:
        return [f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} {self.kind}"] + self.lines


def _histogram_series(fam: _MetricFamily, label: str, bounds, counts,
                      total_sum: float) -> None:
    """Cumulative buckets + the +Inf bucket + _sum/_count, all under ONE
    label set (the satellite contract: consistent `collection`/`span`
    labels across the three series)."""
    cum = 0
    for bound, n in zip(bounds, counts):
        cum += n
        fam.lines.append(f'{fam.name}_bucket{{{label},le="{bound}"}} {cum}')
    total = cum + (counts[len(bounds)] if len(counts) > len(bounds) else 0)
    fam.lines.append(f'{fam.name}_bucket{{{label},le="+Inf"}} {total}')
    fam.lines.append(f'{fam.name}_sum{{{label}}} {total_sum}')
    fam.lines.append(f'{fam.name}_count{{{label}}} {total}')


def render(cct=None, prefix: str = "ceph_tpu") -> str:
    """The /metrics payload: every registered collection's metrics plus
    the tracer's span-latency histograms."""
    cct = cct if cct is not None else default_context()
    families: dict[str, _MetricFamily] = {}

    def family(metric: str, kind: str, help_text: str) -> _MetricFamily:
        fam = families.get(metric)
        if fam is None:
            fam = families[metric] = _MetricFamily(metric, kind, help_text)
        return fam

    for coll_name, pc in sorted(cct.perf._loggers.items()):
        label = f'collection="{coll_name}"'
        for key, m in sorted(pc._metrics.items()):
            metric = f"{prefix}_{_sanitize(key)}"
            if m.kind in (PERFCOUNTER_AVG, PERFCOUNTER_TIME_AVG):
                fam = family(metric, "summary", m.description)
                fam.lines.append(f"{metric}_sum{{{label}}} {m.sum}")
                fam.lines.append(f"{metric}_count{{{label}}} {m.count}")
            elif m.kind == PERFCOUNTER_HISTOGRAM:
                fam = family(metric, "histogram", m.description)
                _histogram_series(fam, label, m.buckets, m.bucket_counts,
                                  m.sum)
            else:
                fam = family(metric, "counter", m.description)
                fam.lines.append(f"{metric}{{{label}}} {m.value}")

    span_metric = f"{prefix}_span_latency_seconds"
    hists = default_tracer().histograms()
    if hists:
        fam = family(span_metric, "histogram",
                     "span wall time by span name (common/tracer.py)")
        for name in sorted(hists):
            h = hists[name]
            _histogram_series(fam, f'span="{name}"', h["buckets"],
                              h["counts"], h["sum"])

    lines: list[str] = []
    for metric in sorted(families):
        lines.extend(families[metric].render())
    return "\n".join(lines) + "\n"
