"""Prometheus exporter: perf counters + span latencies in the text format.

Analog of the reference mgr's prometheus module (reference:
src/pybind/mgr/prometheus/module.py — walks every daemon's perf counter
schema and renders `ceph_<subsystem>_<counter>` metrics).  Here the
process-wide PerfCounters registry renders to the same text format:
counters as `ceph_tpu_<collection>_<name>`, averages as `_sum`/`_count`
pairs, histograms as cumulative `_bucket{le=...}` series **plus the
`_sum` series real scrapers require for histogram types** — and the span
tracer's per-name latency distributions as
`ceph_tpu_span_latency_seconds` histograms.  `# HELP`/`# TYPE` are
emitted exactly once per metric name (several collections share counter
names, e.g. one `ec_backend.<pg>` per PG) and the `collection` label is
identical across a histogram's `_bucket`/`_count`/`_sum` series.
"""
from __future__ import annotations

import time

from ..common import default_context
from ..common.perf_counters import (
    PERFCOUNTER_AVG, PERFCOUNTER_HISTOGRAM, PERFCOUNTER_TIME_AVG,
)
from ..common.tracer import default_tracer


def _sanitize(name: str) -> str:
    return "".join(ch if ch.isalnum() or ch == "_" else "_"
                   for ch in name)


class _MetricFamily:
    """One exposition block: HELP + TYPE once, then every series."""

    def __init__(self, name: str, kind: str, help_text: str):
        self.name, self.kind = name, kind
        self.help = help_text or name
        self.lines: list[str] = []

    def render(self) -> list[str]:
        return [f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} {self.kind}"] + self.lines


def _histogram_series(fam: _MetricFamily, label: str, bounds, counts,
                      total_sum: float) -> None:
    """Cumulative buckets + the +Inf bucket + _sum/_count, all under ONE
    label set (the satellite contract: consistent `collection`/`span`
    labels across the three series)."""
    cum = 0
    for bound, n in zip(bounds, counts):
        cum += n
        fam.lines.append(f'{fam.name}_bucket{{{label},le="{bound}"}} {cum}')
    total = cum + (counts[len(bounds)] if len(counts) > len(bounds) else 0)
    fam.lines.append(f'{fam.name}_bucket{{{label},le="+Inf"}} {total}')
    fam.lines.append(f'{fam.name}_sum{{{label}}} {total_sum}')
    fam.lines.append(f'{fam.name}_count{{{label}}} {total}')


def _mclock_depth_gauges(family, prefix: str) -> None:
    """Queue depths of every live mClock queue — the OSD daemons' sharded
    op queues and the serving engines' admission queues — as one gauge
    family (`ceph_tpu_mclock_queue_depth`), labelled by owner.  Lazy
    imports keep the exporter loadable in partial environments."""
    metric = f"{prefix}_mclock_queue_depth"
    fam = None
    try:
        from ..osd.osd_daemon import live_daemons
    except Exception:                       # pragma: no cover
        live_daemons = list
    try:
        from ..exec.engine import live_engines
    except Exception:                       # pragma: no cover
        live_engines = list
    for d in sorted(live_daemons(), key=lambda d: d.whoami):
        for shard, depths in sorted(d.queue_depths().items()):
            for op_class, depth in sorted(depths.items()):
                if fam is None:
                    fam = family(metric, "gauge",
                                 "queued items per mClock class")
                fam.lines.append(
                    f'{metric}{{owner="osd.{d.whoami}",shard="{shard}",'
                    f'op_class="{_sanitize(op_class)}"}} {depth}')
    for e in sorted(live_engines(), key=lambda e: e.name):
        for op_class, depth in sorted(e.depths().items()):
            if op_class.startswith("_"):
                continue                    # the _total/_bytes extras
            if fam is None:
                fam = family(metric, "gauge",
                             "queued items per mClock class")
            fam.lines.append(
                f'{metric}{{owner="serving.{_sanitize(e.name)}",'
                f'shard="0",op_class="{_sanitize(op_class)}"}} {depth}')


def _recovery_reserver_gauges(family, prefix: str) -> None:
    """``ceph_tpu_recovery_reserver_queued`` /
    ``ceph_tpu_recovery_reserver_granted`` — per-OSD local/remote
    reservation queue depth and in-flight grants of every live
    RecoveryScheduler (the AsyncReserver occupancy an operator watches
    to tell 'repair is pacing' from 'repair is wedged')."""
    try:
        from ..recovery.scheduler import live_schedulers
    except Exception:                       # pragma: no cover
        return
    fams = {}
    for sched in sorted(live_schedulers(), key=lambda s: s.name):
        for kind, osd, depth, granted in sched.reserver_gauges():
            for suffix, v, help_text in (
                    ("queued", depth,
                     "recovery reservations waiting per OSD reserver"),
                    ("granted", granted,
                     "recovery reservations in flight per OSD reserver")):
                metric = f"{prefix}_recovery_reserver_{suffix}"
                fam = fams.get(metric)
                if fam is None:
                    fam = fams[metric] = family(metric, "gauge",
                                                help_text)
                fam.lines.append(
                    f'{metric}{{owner="{_sanitize(sched.name)}",'
                    f'kind="{kind}",osd="{osd}"}} {v}')


def _health_gauges(family, prefix: str) -> None:
    """``ceph_tpu_health_status{owner=...,check=...}`` — one gauge per
    REGISTERED check per live engine (0=ok, 1=warn, 2=err).  Evaluated
    live at scrape time, so a scrape that catches a fresh WARN/ERR also
    trips the owner's flight recorder — by design."""
    try:
        from .health import live_health_engines
    except Exception:                       # pragma: no cover
        return
    metric = f"{prefix}_health_status"
    fam = None
    for e in sorted(live_health_engines(), key=lambda e: e.name):
        for key, rank in sorted(e.severity_gauges().items()):
            if fam is None:
                fam = family(metric, "gauge",
                             "health check severity "
                             "(0=ok/muted 1=warn 2=err)")
            fam.lines.append(
                f'{metric}{{owner="{_sanitize(e.name)}",'
                f'check="{_sanitize(key)}"}} {rank}')


def _device_time_gauges(family, prefix: str) -> None:
    """``ceph_tpu_device_time_seconds{class=...}`` — cumulative device
    occupancy by owner class from the attribution ledger
    (common/device_attribution), plus the busy-time total as
    ``class="_busy"`` so dashboards can plot shares without summing."""
    try:
        from ..common import device_attribution
        snap = device_attribution.snapshot()
    except Exception:                       # pragma: no cover
        return
    if not snap["classes"] and not snap["busy_s"]:
        return
    metric = f"{prefix}_device_time_seconds"
    fam = family(metric, "counter",
                 "device busy seconds attributed per owner class "
                 "(common/device_attribution)")
    for cls, rec in sorted(snap["classes"].items()):
        fam.lines.append(
            f'{metric}{{class="{_sanitize(cls)}"}} '
            f'{round(rec["device_s"], 6)}')
    fam.lines.append(
        f'{metric}{{class="_busy"}} {round(snap["busy_s"], 6)}')


def _device_efficiency_gauges(family, prefix: str, snap: dict | None
                              ) -> None:
    """``ceph_tpu_device_efficiency{executable,stat}`` — the roofline
    ledger's per-executable achieved rates, arithmetic intensity and
    %-of-peak (common/roofline.py).  ``stat="memory_bound"`` encodes the
    classification (1 = under the ridge point).  The aggregate view
    exports through the ordinary ``device_efficiency`` collection walk;
    this family adds the per-executable breakdown the perf schema cannot
    hold (open-ended executable set).  ``snap`` is the ONE snapshot
    ``render()`` took via ``roofline.refresh(cct)`` — sharing it keeps
    the per-executable rows on the same (config-overridable) peaks as
    the aggregate gauges in the same scrape."""
    if not snap or not snap["executables"]:
        return
    metric = f"{prefix}_device_efficiency"
    fam = family(metric, "gauge",
                 "per-executable roofline efficiency "
                 "(common/roofline.py)")
    for eid, rec in sorted(snap["executables"].items()):
        stats = (("calls", rec["calls"]),
                 ("seconds", rec["seconds"]),
                 ("achieved_flops_s", rec["achieved_flops_s"]),
                 ("achieved_bytes_s", rec["achieved_bytes_s"]),
                 ("arithmetic_intensity", rec["arithmetic_intensity"]),
                 ("pct_of_peak", rec["pct_of_peak"]),
                 ("memory_bound",
                  1 if rec["bound"] == "memory" else 0))
        for stat, v in stats:
            fam.lines.append(
                f'{metric}{{executable="{_sanitize(eid)}",'
                f'stat="{stat}"}} {round(float(v), 6)}')


def _wire_gauges(family, prefix: str) -> None:
    """``ceph_tpu_wire_bytes`` / ``ceph_tpu_wire_msgs``
    ``{owner,msg_type,dir}`` — per-message-type wire traffic of every
    live WireAccounting (bus + TCP messenger).  The totals and per-class
    rollups already export through the ordinary ``wire.<name>``
    collection walk; this family adds the per-TYPE breakdown the perf
    schema cannot hold (open-ended type set)."""
    try:
        from ..common.wire_accounting import live_wire_accountants
    except Exception:                       # pragma: no cover
        return
    fams = {}
    for acct in sorted(live_wire_accountants(), key=lambda a: a.name):
        for mtype, rec in acct.per_type().items():
            for direction in ("tx", "rx"):
                for unit, help_text in (
                        ("bytes", "wire bytes per message type"),
                        ("msgs", "wire messages per message type")):
                    v = rec[f"{direction}_{unit}"]
                    if not v:
                        continue
                    metric = f"{prefix}_wire_{unit}"
                    fam = fams.get(metric)
                    if fam is None:
                        fam = fams[metric] = family(metric, "counter",
                                                    help_text)
                    fam.lines.append(
                        f'{metric}{{owner="{_sanitize(acct.name)}",'
                        f'msg_type="{_sanitize(mtype)}",'
                        f'dir="{direction}"}} {v}')


def _heat_gauges(family, prefix: str) -> None:
    """``ceph_tpu_osd_heat{owner,osd,stat}`` /
    ``ceph_tpu_pg_heat{owner,pg,stat}`` — the workload heat maps of
    every live HeatTracker (mgr/heat.py): primary-op and byte rates over
    the stats window, rolled per PG and per primary OSD.  The
    before/after instrument for the balancer loop (ROADMAP item 5)."""
    try:
        from .heat import live_heat_trackers
    except Exception:                       # pragma: no cover
        return
    fams = {}
    for tracker in sorted(live_heat_trackers(), key=lambda t: t.name):
        owner = _sanitize(tracker.name)
        snap = tracker.snapshot()
        for metric_key, label, rows, help_text in (
                ("osd_heat", "osd", snap["osds"],
                 "per-OSD primary-op load over the stats window"),
                ("pg_heat", "pg", snap["pgs"],
                 "per-PG primary-op load over the stats window")):
            metric = f"{prefix}_{metric_key}"
            for key, rec in sorted(rows.items(), key=lambda kv:
                                   str(kv[0])):
                for stat in ("op_s", "bytes_s"):
                    fam = fams.get(metric)
                    if fam is None:
                        fam = fams[metric] = family(metric, "gauge",
                                                    help_text)
                    # pg ids ("1.0") and osd ids are clean label VALUES
                    # as-is; only metric names need sanitizing
                    fam.lines.append(
                        f'{metric}{{owner="{owner}",'
                        f'{label}="{key}",'
                        f'stat="{stat}"}} {rec[stat]}')


def _tier_gauges(family, prefix: str) -> None:
    """``ceph_tpu_tier_ops{owner,op}`` /
    ``ceph_tpu_tier_state{owner,stat}`` — every live cache tier's
    promotion/flush/evict counters plus residency, dirtiness, and hit
    rate (tier/service.py): the before/after instrument for the
    hot-tier loop (ROADMAP item 7)."""
    try:
        from ..tier import live_tier_services
    except Exception:                       # pragma: no cover
        return
    ops_fam = state_fam = None
    for svc in sorted(live_tier_services(), key=lambda s: s.name):
        owner = _sanitize(svc.name)
        for op in ("hit", "miss", "proxy_read", "proxy_write", "promote",
                   "promote_skip", "writeback", "flush", "evict",
                   "invalidate"):
            if ops_fam is None:
                ops_fam = family(f"{prefix}_tier_ops", "counter",
                                 "cache-tier operations by kind "
                                 "(tier/service.py)")
            ops_fam.lines.append(
                f'{prefix}_tier_ops{{owner="{owner}",op="{op}"}} '
                f'{int(svc.perf.get(op))}')
        st = svc.stats()
        for stat, v in (("objects", st["objects"]),
                        ("dirty", svc.perf.get("dirty")),
                        ("hit_rate", round(st["hit_rate"], 6))):
            if state_fam is None:
                state_fam = family(f"{prefix}_tier_state", "gauge",
                                   "cache-tier residency, dirtiness, "
                                   "and hit rate")
            state_fam.lines.append(
                f'{prefix}_tier_state{{owner="{owner}",'
                f'stat="{stat}"}} {v}')


def _copy_gauges(family, prefix: str) -> None:
    """``ceph_tpu_copy_bytes{source}`` / ``ceph_tpu_copy_state{stat}``
    — the payload copy ledger (common/copy_ledger.py): bytes copied per
    surviving host-copy source, bytes served to consumers, and the
    ``copies_per_byte`` quotient the zero-copy data path is gated on
    (ROADMAP item 2)."""
    try:
        from ..common.copy_ledger import ledger
    except Exception:                       # pragma: no cover
        return
    snap = ledger().snapshot()
    copied_fam = family(f"{prefix}_copy_bytes", "counter",
                        "payload bytes copied, by copy source "
                        "(common/copy_ledger.py)")
    for source, v in sorted(snap["copied"].items()):
        copied_fam.lines.append(
            f'{prefix}_copy_bytes{{source="{_sanitize(source)}"}} {v}')
    state_fam = family(f"{prefix}_copy_state", "gauge",
                       "payload bytes served and copies per served byte")
    for stat, v in (("served_bytes", snap["served"]),
                    ("copied_total", snap["copied_total"]),
                    ("copies_per_byte",
                     round(snap["copies_per_byte"], 6))):
        state_fam.lines.append(
            f'{prefix}_copy_state{{stat="{stat}"}} {v}')


def _slo_gauges(family, prefix: str) -> None:
    """``ceph_tpu_slo_budget{owner,class,stat}`` — every live
    SLOTracker's per-class objective state: the configured p99 bound,
    both windows' burn rates, and the remaining error budget (mgr/slo.py
    multi-window burn engine)."""
    try:
        from .slo import live_slo_trackers
    except Exception:                       # pragma: no cover
        return
    metric = f"{prefix}_slo_budget"
    fam = None
    for tracker in sorted(live_slo_trackers(), key=lambda t: t.name):
        # objectives only: the full status() would also compute the
        # per-class attribution summaries this family never renders
        for cls, s in sorted(tracker.objectives_status().items()):
            stats = (("objective_p99_ms", s["objective_p99_ms"]),
                     ("target", s["target"]),
                     ("burn_fast", s["fast"]["burn"]),
                     ("burn_slow", s["slow"]["burn"]),
                     ("budget_remaining", s["budget_remaining"]),
                     ("ops_slow_window", s["slow"]["ops"]),
                     ("bad_slow_window", s["slow"]["bad"]))
            for stat, v in stats:
                if fam is None:
                    fam = family(metric, "gauge",
                                 "per-class latency SLO state "
                                 "(mgr/slo.py burn-rate engine)")
                fam.lines.append(
                    f'{metric}{{owner="{_sanitize(tracker.name)}",'
                    f'class="{_sanitize(cls)}",stat="{stat}"}} '
                    f'{round(float(v), 6)}')


def _latency_phase_gauges(family, prefix: str) -> None:
    """``ceph_tpu_latency_phase_seconds{owner,class,phase}`` — the
    critical-path ledgers' cumulative per-(class, phase) seconds
    (common/critpath.py).  Each scrape folds newly-completed traces
    first, the StatsAggregator idiom: scrape cadence IS fold cadence."""
    try:
        from ..common.critpath import live_ledgers
    except Exception:                       # pragma: no cover
        return
    metric = f"{prefix}_latency_phase_seconds"
    fam = None
    for ledger in sorted(live_ledgers(), key=lambda led: led.name):
        try:
            ledger.refresh()
        except Exception:                   # pragma: no cover
            pass
        for cls, acc in ledger.phase_seconds().items():
            for phase, secs in sorted(acc.items()):
                if not secs:
                    continue
                if fam is None:
                    fam = family(metric, "counter",
                                 "critical-path latency attributed per "
                                 "op class and phase "
                                 "(common/critpath.py)")
                fam.lines.append(
                    f'{metric}{{owner="{_sanitize(ledger.name)}",'
                    f'class="{_sanitize(cls)}",'
                    f'phase="{_sanitize(phase)}"}} {round(secs, 6)}')


def _stats_rate_gauges(family, prefix: str) -> None:
    """``ceph_tpu_stats_rate{owner=...,stat=...}`` — the PGMap-style
    digest (client IO B/s and op/s, recovery B/s, serving batch
    throughput, jit churn) of every live StatsAggregator.  Each scrape
    ticks the aggregator, so scrape cadence IS the rate window cadence
    (how the reference mgr's prometheus module drives PGMap deltas)."""
    try:
        from .stats import live_aggregators
    except Exception:                       # pragma: no cover
        return
    metric = f"{prefix}_stats_rate"
    fam = None
    for agg in sorted(live_aggregators(), key=lambda a: a.name):
        agg.sample()
        for stat, v in sorted(agg.digest_flat().items()):
            if fam is None:
                fam = family(metric, "gauge",
                             "rolling-window rate digest "
                             "(mgr/stats.py StatsAggregator)")
            fam.lines.append(
                f'{metric}{{owner="{_sanitize(agg.name)}",'
                f'stat="{stat}"}} {round(v, 3)}')


def _device_refresh_due(cct, now: float) -> bool:
    """TTL gate on the per-scrape device-telemetry refresh
    (``mgr_device_refresh_ttl``): a tight scrape loop re-renders the
    LAST snapshot's gauges instead of re-snapshotting JAX backend state
    every render.  ``ttl=0`` restores refresh-every-scrape.  The stamp
    lives ON the context — a fresh context's first scrape must refresh
    its own gauges regardless of when another context last scraped."""
    try:
        ttl = float(cct.conf.get("mgr_device_refresh_ttl"))
    except Exception:
        ttl = 0.0
    last = getattr(cct, "_prom_device_refresh", float("-inf"))
    if ttl > 0.0 and now - last < ttl:
        return False
    cct._prom_device_refresh = now
    return True


def render(cct=None, prefix: str = "ceph_tpu") -> str:
    """The /metrics payload: every registered collection's metrics plus
    the tracer's span-latency histograms."""
    cct = cct if cct is not None else default_context()
    # refresh the device gauges BEFORE the collection walk renders them
    # (never initializes a backend: scrape must not be the thing that
    # dials a wedged tunnel), at most once per mgr_device_refresh_ttl
    try:
        if _device_refresh_due(cct, time.monotonic()):
            from ..common import device_telemetry
            device_telemetry.refresh(cct)
    except Exception:                       # pragma: no cover
        pass
    # same for the roofline ledger's aggregate device_efficiency gauges;
    # the returned snapshot also feeds the per-executable family below
    # (one ledger join per scrape, same peaks for both surfaces)
    eff_snap = None
    try:
        from ..common import roofline
        eff_snap = roofline.refresh(cct)
    except Exception:                       # pragma: no cover
        pass
    families: dict[str, _MetricFamily] = {}

    def family(metric: str, kind: str, help_text: str) -> _MetricFamily:
        fam = families.get(metric)
        if fam is None:
            fam = families[metric] = _MetricFamily(metric, kind, help_text)
        return fam

    for coll_name, pc in sorted(cct.perf.snapshot().items()):
        label = f'collection="{coll_name}"'
        # fold the per-thread counter cells: hot-path inc/tinc/hinc land
        # in thread-local shards, and a scrape must see them
        with pc._lock:
            folded = {key: pc._folded_locked(m, key)
                      for key, m in pc._metrics.items()}
        for key, m in sorted(pc._metrics.items()):
            metric = f"{prefix}_{_sanitize(key)}"
            value, total, count, bc = folded[key]
            if m.kind in (PERFCOUNTER_AVG, PERFCOUNTER_TIME_AVG):
                fam = family(metric, "summary", m.description)
                fam.lines.append(f"{metric}_sum{{{label}}} {total}")
                fam.lines.append(f"{metric}_count{{{label}}} {count}")
            elif m.kind == PERFCOUNTER_HISTOGRAM:
                fam = family(metric, "histogram", m.description)
                _histogram_series(fam, label, m.buckets, bc, total)
            else:
                fam = family(metric, "counter", m.description)
                fam.lines.append(f"{metric}{{{label}}} {value}")

    _mclock_depth_gauges(family, prefix)
    _recovery_reserver_gauges(family, prefix)
    _health_gauges(family, prefix)
    _stats_rate_gauges(family, prefix)
    # latency-phase first: it FOLDS every live ledger, so the slo
    # budget gauges in the same scrape judge the freshly-folded records
    # instead of lagging one scrape behind the attribution data
    _latency_phase_gauges(family, prefix)
    _slo_gauges(family, prefix)
    _device_time_gauges(family, prefix)
    _device_efficiency_gauges(family, prefix, eff_snap)
    _wire_gauges(family, prefix)
    _heat_gauges(family, prefix)
    _tier_gauges(family, prefix)
    _copy_gauges(family, prefix)

    span_metric = f"{prefix}_span_latency_seconds"
    hists = default_tracer().histograms()
    if hists:
        fam = family(span_metric, "histogram",
                     "span wall time by span name (common/tracer.py)")
        for name in sorted(hists):
            h = hists[name]
            _histogram_series(fam, f'span="{name}"', h["buckets"],
                              h["counts"], h["sum"])

    lines: list[str] = []
    for metric in sorted(families):
        lines.extend(families[metric].render())
    return "\n".join(lines) + "\n"
