"""Prometheus exporter: perf counters in the text exposition format.

Analog of the reference mgr's prometheus module (reference:
src/pybind/mgr/prometheus/module.py — walks every daemon's perf counter
schema and renders `ceph_<subsystem>_<counter>` metrics).  Here the
process-wide PerfCounters registry renders to the same text format:
counters as `ceph_tpu_<collection>_<name>`, averages as `_sum`/`_count`
pairs, histograms as cumulative `_bucket{le=...}` series — scrapeable by
an actual Prometheus, or by the tests that pin the format.
"""
from __future__ import annotations

from ..common import default_context
from ..common.perf_counters import (
    PERFCOUNTER_AVG, PERFCOUNTER_HISTOGRAM, PERFCOUNTER_TIME_AVG,
)


def _sanitize(name: str) -> str:
    return "".join(ch if ch.isalnum() or ch == "_" else "_"
                   for ch in name)


def render(cct=None, prefix: str = "ceph_tpu") -> str:
    """The /metrics payload: every registered collection's metrics."""
    cct = cct if cct is not None else default_context()
    lines: list[str] = []
    for coll_name, pc in sorted(cct.perf._loggers.items()):
        label = f'{{collection="{coll_name}"}}'
        for key, m in sorted(pc._metrics.items()):
            metric = f"{prefix}_{_sanitize(key)}"
            if m.kind in (PERFCOUNTER_AVG, PERFCOUNTER_TIME_AVG):
                lines.append(f"# TYPE {metric} summary")
                lines.append(f"{metric}_sum{label} {m.sum}")
                lines.append(f"{metric}_count{label} {m.count}")
            elif m.kind == PERFCOUNTER_HISTOGRAM:
                lines.append(f"# TYPE {metric} histogram")
                cum = 0
                for bound, n in zip(m.buckets, m.bucket_counts):
                    cum += n
                    lines.append(
                        f'{metric}_bucket{{collection="{coll_name}",'
                        f'le="{bound}"}} {cum}')
                total = sum(m.bucket_counts)
                lines.append(
                    f'{metric}_bucket{{collection="{coll_name}",'
                    f'le="+Inf"}} {total}')
                lines.append(f"{metric}_count{label} {total}")
            else:
                lines.append(f"# TYPE {metric} counter")
                lines.append(f"{metric}{label} {m.value}")
    return "\n".join(lines) + "\n"
