"""Health check engine: named, registered checks with severities + mute.

Mirror of the reference's health-check registry (reference:
src/mon/health_check.h — ``health_check_map_t`` keyed by check name, each
carrying a severity, a summary and detail lines; src/mon/Monitor.cc
``handle_command`` 'health mute <code>').  PR 0-2 hard-coded three checks
inside ``Cluster.health()``; this engine makes the check set EXTENSIBLE so
any subsystem (optracker slow ops, exec throttles, the traced_jit
registry, scrub) can register a named check without the cluster layer
knowing about it, and so operators can mute a known-noisy key without
losing the rest of the surface.

A check is a callable returning:

- ``None``/falsy — healthy;
- a ``str`` — raised at the registered default severity with that summary;
- a :class:`CheckResult` — summary + detail lines + optional severity
  override (e.g. PG_AVAILABILITY escalating WARN->ERR past ``m`` lost
  shards).

``evaluate()`` runs every check, computes the aggregate status over the
UNMUTED raised checks, and fires ``on_transition(key, info, evaluation)``
for every check that newly raised or escalated — the anomaly
flight-recorder hook (``common/flight_recorder.py``): state is captured
at the moment something goes wrong, not when an operator gets around to
asking.
"""
from __future__ import annotations

import threading
import weakref
from dataclasses import dataclass, field

HEALTH_OK = "HEALTH_OK"
HEALTH_WARN = "HEALTH_WARN"
HEALTH_ERR = "HEALTH_ERR"

SEVERITY_RANK = {HEALTH_OK: 0, HEALTH_WARN: 1, HEALTH_ERR: 2}

# live engines, for the prometheus health-status gauge export (the same
# weakref pattern as osd_daemon.live_daemons / engine.live_engines)
_ENGINES: "weakref.WeakSet[HealthCheckEngine]" = weakref.WeakSet()


def live_health_engines() -> list["HealthCheckEngine"]:
    return list(_ENGINES)


@dataclass
class CheckResult:
    """What a raised check reports (health_check_t analog)."""
    summary: str
    detail: list[str] = field(default_factory=list)
    severity: str | None = None          # None -> the registered default
    count: int = 0                       # affected entities (mon's count)


class HealthCheckEngine:
    """Registry of named health checks; ``Cluster.health()`` is a thin
    view over ``evaluate()``."""

    def __init__(self, name: str = "health", cct=None, on_transition=None,
                 on_clear=None):
        self.name = name
        self.cct = cct
        # key -> (fn, default severity, description of the trigger)
        self._checks: dict[str, tuple] = {}
        self._muted: set[str] = set()
        # key -> severity rank currently raised (transition detection)
        self._raised: dict[str, int] = {}
        self._lock = threading.Lock()
        self.on_transition = on_transition
        # fired (key, evaluation) when a previously-raised check stops
        # reporting — the cluster-log "cleared" line's source
        self.on_clear = on_clear
        # the most recent evaluation: flight-recorder sources read THIS
        # instead of re-evaluating (which would recurse through the
        # transition hook mid-dump)
        self.last_evaluation: dict | None = None
        # bumped at the start of every evaluate(): checks that share an
        # expensive scan (e.g. the cluster's per-PG state walk) key a
        # memo on it so one evaluation pays the scan once
        self.eval_seq = 0
        _ENGINES.add(self)

    # -- registry ----------------------------------------------------------

    def register(self, key: str, fn, severity: str = HEALTH_WARN,
                 description: str = "") -> None:
        if severity not in SEVERITY_RANK or severity == HEALTH_OK:
            raise ValueError(f"check {key!r}: severity must be "
                             f"{HEALTH_WARN} or {HEALTH_ERR}")
        with self._lock:
            self._checks[key] = (fn, severity, description)

    def unregister(self, key: str) -> None:
        with self._lock:
            self._checks.pop(key, None)
            self._raised.pop(key, None)

    def registered(self) -> dict[str, dict]:
        """Check metadata (key -> severity/description), for docs/top."""
        with self._lock:
            return {k: {"severity": sev, "description": desc}
                    for k, (_, sev, desc) in sorted(self._checks.items())}

    # -- mute ('ceph health mute <code>') ----------------------------------

    def mute(self, key: str) -> None:
        """Muting is lenient about unknown keys (a persisted mute must
        survive a check that is registered later in boot)."""
        with self._lock:
            self._muted.add(key)

    def unmute(self, key: str) -> None:
        with self._lock:
            self._muted.discard(key)

    @property
    def muted(self) -> set[str]:
        with self._lock:
            return set(self._muted)

    # -- evaluation --------------------------------------------------------

    def _run_check(self, key: str, fn, default_sev: str) -> dict | None:
        try:
            res = fn()
        except Exception as e:           # a broken check must not crash
            res = CheckResult(           # health itself — it IS a finding
                f"health check {key!r} raised: {e!r}"[:200])
        if not res:
            return None
        if isinstance(res, str):
            res = CheckResult(res)
        sev = res.severity or default_sev
        return {"severity": sev, "summary": res.summary,
                "detail": list(res.detail), "count": res.count,
                "muted": key in self._muted}

    def evaluate(self, fire_transitions: bool = True) -> dict:
        """Run every registered check.  Returns the health_check_map_t
        shape: ``{"status", "checks": {key: {...}}, "muted": [...]}``.
        Aggregate status ignores muted checks; transitions (new raise or
        severity escalation) fire ``on_transition`` AFTER the full
        evaluation is cached, so hooks can snapshot it re-entrantly.
        ``fire_transitions=False`` is a read-only snapshot: no hooks, no
        raised-state bookkeeping — for callers INSIDE a transition hook
        (e.g. a flight-recorder source) where firing again would recurse
        or steal the real transition from the next live evaluation."""
        with self._lock:
            checks = dict(self._checks)
            self.eval_seq += 1
        results: dict[str, dict] = {}
        for key, (fn, sev, _desc) in sorted(checks.items()):
            info = self._run_check(key, fn, sev)
            if info is not None:
                results[key] = info
        worst = max((SEVERITY_RANK[c["severity"]]
                     for k, c in results.items() if not c["muted"]),
                    default=0)
        evaluation = {
            "status": {0: HEALTH_OK, 1: HEALTH_WARN, 2: HEALTH_ERR}[worst],
            "checks": results,
            "muted": sorted(self.muted),
        }
        if not fire_transitions:
            with self._lock:
                self.last_evaluation = evaluation
            return evaluation
        transitions: list[tuple[str, dict]] = []
        with self._lock:
            for key, info in results.items():
                rank = SEVERITY_RANK[info["severity"]]
                # muted checks never fire the transition hook: mute
                # exists for known-noisy keys, and a flapping muted
                # check must not evict real incidents from the
                # flight-recorder ring (raised-state is still tracked,
                # so unmuting mid-raise does not retro-fire either)
                if rank > self._raised.get(key, 0) and not info["muted"]:
                    transitions.append((key, info))
                self._raised[key] = rank
            cleared: list[str] = []
            for key in list(self._raised):
                if key not in results:
                    del self._raised[key]        # cleared: re-raise fires
                    cleared.append(key)
            self.last_evaluation = evaluation
        if self.on_transition is not None:
            for key, info in transitions:
                self.on_transition(key, info, evaluation)
        if self.on_clear is not None:
            for key in cleared:
                self.on_clear(key, evaluation)
        return evaluation

    def severity_gauges(self) -> dict[str, int]:
        """One gauge per REGISTERED check (0=ok/muted, 1=warn, 2=err) —
        the ``ceph_tpu_health_status`` prometheus surface.  Evaluates
        live so a scrape sees current state (and trips the flight
        recorder on a fresh transition, which is the point of scraping).
        MUTED checks export 0: mute must silence alert rules the same
        way it silences the status line, or the two surfaces disagree
        and the pager defeats the mute."""
        ev = self.evaluate()
        with self._lock:
            keys = list(self._checks)
        return {key: SEVERITY_RANK[ev["checks"][key]["severity"]]
                if key in ev["checks"] and not ev["checks"][key]["muted"]
                else 0
                for key in sorted(keys)}

    def close(self) -> None:
        """Drop out of the live-engine registry (a shut-down cluster must
        not keep exporting health gauges — the ServingEngine.stop
        discipline)."""
        _ENGINES.discard(self)
        with self._lock:
            self._checks.clear()
            self._raised.clear()
        self.last_evaluation = None


def thin_view(evaluation: dict) -> dict:
    """The 'ceph health' wire shape from a full evaluation:
    {"status", "checks": {key: summary}} with muted checks split out
    under "muted" only when any exist (so the healthy shape stays
    exactly {"status", "checks"} — pinned by the rados API tests).
    Shared by ``Cluster.health()`` and the CLI so one evaluation serves
    both the status line and the detail listing."""
    out = {"status": evaluation["status"],
           "checks": {k: c["summary"]
                      for k, c in evaluation["checks"].items()
                      if not c["muted"]}}
    if evaluation["muted"]:
        out["muted"] = {k: evaluation["checks"][k]["summary"]
                        if k in evaluation["checks"] else "(not raised)"
                        for k in evaluation["muted"]}
    return out


# -- generic check factories (subsystem-agnostic: they read only the perf
#    and stats surfaces, so any owner — MiniCluster, a standalone serving
#    process — can register them) ------------------------------------------

def slow_ops_check(stats):
    """SLOW_OPS: ops exceeded ``osd_op_complaint_time`` within the stats
    window (reference: the mon's SLOW_OPS from per-OSD complaints).  The
    cumulative ``slow_ops`` counters alone cannot clear; the WINDOW delta
    is what distinguishes 'slow right now' from 'was slow last week'."""
    def check():
        delta = stats.counter_delta("slow_ops")
        if delta > 0:
            total = int(stats.gauge_sum("slow_ops"))
            return CheckResult(
                f"{int(delta)} slow ops in the last "
                f"{stats.span():.0f}s ({total} total)",
                count=int(delta))
        return None
    return check


def iter_throttles(cct):
    """Yield ``(name, val, max)`` for every registered throttle perf
    collection — ONE walk of the schema shared by THROTTLE_SATURATED
    and `ceph_tpu top` (two hand-rolled walks would drift apart the
    first time the val/max keys move)."""
    for name, pc in sorted(cct.perf.snapshot().items()):
        if not name.startswith("throttle."):
            continue
        try:
            yield name, pc.get("val"), pc.get("max")
        except KeyError:
            continue


def throttle_saturated_check(cct, ratio: float | None = None):
    """THROTTLE_SATURATED: an admission throttle is pinned near its limit
    (queue saturation — the arXiv:1709.05365 signal: sustained
    backpressure means demand is outrunning the device)."""
    def check():
        r = ratio if ratio is not None else \
            float(cct.conf.get("mgr_throttle_saturation_ratio"))
        hot: list[str] = []
        for name, val, mx in iter_throttles(cct):
            if mx and val / mx >= r:
                hot.append(f"{name}: {int(val)}/{int(mx)} units in use")
        if hot:
            return CheckResult(
                f"{len(hot)} throttle(s) >= {r:.0%} of limit",
                detail=hot, count=len(hot))
        return None
    return check


def pg_recovery_stalled_check(stats, scheduler_getter):
    """PG_RECOVERY_STALLED: degraded PGs sit in the recovery scheduler
    but NOTHING progresses over the stats window — no reservation is
    active (``osd_max_backfills`` exhausted or zeroed, a wedged grant
    holder), or jobs hold grants yet zero objects recovered/replayed.
    The queue-depth alone cannot distinguish 'busy' from 'stuck'; the
    window delta of actual repair work is what does."""
    def check():
        sched = scheduler_getter()
        if sched is None:
            return None
        queued, active = sched.job_counts()
        if queued + active == 0:
            return None
        if stats.span() < 1.0:
            # a sub-second window (or a single sample) holds no evidence
            # of a stall — back-to-back scrapes must not page anyone
            return None
        from .stats import PG_PREFIXES
        progress = (
            stats.counter_delta("recoveries", PG_PREFIXES) +
            stats.counter_delta("recovery_failures", PG_PREFIXES) +
            stats.counter_delta("log_repairs_clean", PG_PREFIXES) +
            stats.counter_delta("log_repair_objects", PG_PREFIXES) +
            stats.counter_delta("backfill_objects", PG_PREFIXES) +
            stats.counter_delta("wave_objects", ("recovery.",)))
        if progress > 0:
            return None
        return CheckResult(
            f"{queued + active} recovery job(s) "
            f"({queued} queued, {active} active) with no repair "
            f"progress in the last {stats.span():.0f}s",
            detail=[f"job {key}: state={j.state.value} "
                    f"priority={j.priority} targets={list(j.targets)}"
                    for key, j in sorted(sched.jobs.items())],
            count=queued + active)
    return check


def hbm_pressure_check(cct, ratio: float | None = None, sampler=None):
    """HBM_PRESSURE: a device's session high-water memory mark is pinned
    near its capacity (``mgr_hbm_pressure_ratio`` of ``bytes_limit``) —
    the working set is one allocation away from an OOM that would take a
    serving dispatch down with it.  Reads the guarded watermark sampler
    (``device_telemetry.hbm_watermarks``): platforms whose backend lacks
    memory stats (CPU) report nothing and the check stays silent."""
    def check():
        r = ratio if ratio is not None else \
            float(cct.conf.get("mgr_hbm_pressure_ratio"))
        if sampler is not None:
            marks = sampler()
        else:
            from ..common import device_telemetry
            marks = device_telemetry.hbm_watermarks()
        hot: list[str] = []
        for dev, rec in sorted(marks.items()):
            limit = rec.get("bytes_limit", 0)
            hw = rec.get("high_water_bytes", 0)
            if limit and hw / limit >= r:
                hot.append(f"{dev}: high-water {hw}/{limit} bytes "
                           f"({100.0 * hw / limit:.0f}% of capacity)")
        if hot:
            return CheckResult(
                f"{len(hot)} device(s) >= {r:.0%} of memory capacity",
                detail=hot, count=len(hot))
        return None
    return check


def device_degraded_check():
    """DEVICE_DEGRADED: one or more codec pipelines have circuit-broken
    their device path — N consecutive device failures opened the breaker
    and fallback-capable batches are running the SYNC HOST codec
    (``ops/pipeline.py``).  Clears when half-open probes re-close every
    breaker.  Reads the live-breaker registry (``failure/breaker.py``),
    so any pipeline in the process — serving engine, recovery scheduler,
    standalone — reports without wiring."""
    def check():
        from ..failure.breaker import CLOSED, live_breakers
        rows: list[str] = []
        for b in live_breakers():
            d = b.dump()
            if d["state"] == CLOSED:
                continue
            rows.append(
                f"{d['name']}: {d['state']} after "
                f"{d['consecutive_failures']} consecutive device "
                f"failures ({d['opens']} opens, {d['fallbacks']} "
                f"host-fallback batches)")
        if rows:
            return CheckResult(
                f"{len(rows)} device codec path(s) degraded to host "
                f"fallback", detail=rows, count=len(rows))
        return None
    return check


def osd_flapping_check(limiter_getter):
    """OSD_FLAPPING: the monitor's mark-down limiter has damped one or
    more OSDs — marked down too often inside ``osd_markdown_window``,
    they stay down (boots refused) until the operator clears the record
    (``Monitor.clear_markdown``).  The osd_markdown_log health surface."""
    def check():
        lim = limiter_getter()
        if lim is None:
            return None
        damped = sorted(lim.damped)
        if damped:
            return CheckResult(
                f"{len(damped)} osd(s) flapping: boots damped until "
                f"operator clear",
                detail=[f"osd.{o} marked down >= {lim.count} times in "
                        f"{lim.window:.0f}s; down until cleared"
                        for o in damped],
                count=len(damped))
        return None
    return check


def recompile_storm_check(cct, stats, threshold: float | None = None):
    """RECOMPILE_STORM: the traced_jit registry is compiling at more
    than ``mgr_recompile_storm_compiles`` per MINUTE over the stats
    window — the shape-churn failure mode where every batch recompiles
    instead of hitting the size buckets (each compile is ~ms-to-s of
    stall on the dispatch path).  Time-normalized: the window is bounded
    by sample COUNT, so on a rarely-polled cluster it can span hours —
    an absolute count would flag ordinary warmup as a storm."""
    def check():
        limit = threshold if threshold is not None else \
            float(cct.conf.get("mgr_recompile_storm_compiles"))
        dt = stats.span()
        if dt <= 0:
            return None
        compiles = stats.counter_delta("compilations", coll_prefix=("jit",))
        # a window shorter than a minute still needs `limit` ABSOLUTE
        # compiles to fire: two warmup compiles 100ms apart are a 1200/min
        # instantaneous rate but not a storm
        per_min = compiles / max(dt, 60.0) * 60.0
        if compiles >= limit and per_min >= limit:
            hits = stats.counter_delta("cache_hits", coll_prefix=("jit",))
            return CheckResult(
                f"{int(compiles)} jit compilations in the last "
                f"{dt:.0f}s (~{per_min:.0f}/min, cache hits: "
                f"{int(hits)}) — check shape bucketing",
                count=int(compiles))
        return None
    return check


def tier_full_check(tiers_getter):
    """TIER_FULL: a cache tier's residency is at or past its
    ``tier_full_ratio`` watermark — promotions and absorbed writes are
    about to be paid for with synchronous evictions (or refused), the
    tier-equivalent of a full OSD.  ``tiers_getter`` returns the live
    ``{cache_pool: (service, agent)}`` map; residency is counted from
    object bookkeeping (no I/O on the health path)."""
    def check():
        hot: list[str] = []
        for pid, (svc, agent) in sorted(tiers_getter().items()):
            full = svc.cct.conf.get("tier_full_ratio")
            f = agent.fullness()
            if f >= full:
                hot.append(f"tier pool {pid} ({svc.name}): "
                           f"{len(svc.resident())} objects = "
                           f"{100.0 * f:.0f}% of target "
                           f"(tier_full_ratio {100.0 * full:.0f}%)")
        if hot:
            return CheckResult(
                f"{len(hot)} cache tier(s) at/over the full watermark",
                detail=hot, count=len(hot))
        return None
    return check


def tier_flush_backlog_check(tiers_getter, min_ticks: int = 2):
    """TIER_FLUSH_BACKLOG: an agent finished ``min_ticks`` consecutive
    passes still above ``tier_dirty_ratio_high`` — the EC base pool is
    not absorbing flushes as fast as writeback absorbs writes (base
    inactive, flush budget too small, or genuine overload).  One
    over-watermark pass is normal burst behavior; a STREAK is the
    backlog.  Reads the agent's own tick accounting: no I/O here."""
    def check():
        stuck: list[str] = []
        for pid, (svc, agent) in sorted(tiers_getter().items()):
            if agent.backlog_ticks >= min_ticks:
                stuck.append(
                    f"tier pool {pid} ({svc.name}): dirty ratio "
                    f"{agent.last.get('dirty_ratio', 0.0):.2f} still "
                    f"over tier_dirty_ratio_high after "
                    f"{agent.backlog_ticks} agent passes")
        if stuck:
            return CheckResult(
                f"{len(stuck)} cache tier(s) cannot flush fast enough",
                detail=stuck, count=len(stuck))
        return None
    return check
