"""Workload heat maps: rolling per-PG / per-OSD load + tail digest.

The spatial half of the PGMap digest (reference: src/mon/PGMap.cc keeps
per-PG/per-OSD stat deltas; the balancer module and `ceph osd perf`
read them): mgr/stats.py already windows every PG backend's perf
collection, so the per-collection deltas ARE per-PG deltas — this
module projects them onto the placement topology (pg -> primary OSD)
and answers the question ROADMAP item 5's balancer loop needs answered
before and after it engages: *which OSDs are hot, and how bad is the
tail?*

Surfaces:

- :meth:`HeatTracker.pg_heat` — per-PG primary-op and byte rates over
  the stats window;
- :meth:`HeatTracker.osd_heat` — the same rolled onto each PG's primary
  OSD (primary-op heat: the serving cost lands on the primary);
- :meth:`HeatTracker.tail_digest` — max/median/mean OSD load and the
  max/median skew ratio (the tail-utilization number item 5 gates on);
- :func:`hot_shard_check` — the ``HOT_SHARD`` health check: a sustained,
  skewed load concentration fires WARN with the offending OSDs listed;
- ``ceph_tpu_osd_heat{osd=...}`` / ``ceph_tpu_pg_heat{pg=...}``
  prometheus families via :func:`live_heat_trackers`
  (mgr/prometheus.py renders them).

Collections are matched to PGs by the backend naming convention
(``<prefix>.<tag>[e<epoch>].pg<pgid>`` — the epoch suffix appears on
backfilled incarnations); the ``tag`` scopes a tracker to its own
cluster when several share one Context.
"""
from __future__ import annotations

import re
import statistics
import weakref

from .stats import PG_PREFIXES

_TRACKERS: "weakref.WeakSet[HeatTracker]" = weakref.WeakSet()

# the windowed counters that make up "load": primary ops and bytes
_OP_KEYS = ("reads", "writes")
_BYTE_KEYS = ("read_bytes", "write_bytes")


def live_heat_trackers() -> list["HeatTracker"]:
    return list(_TRACKERS)


class HeatTracker:
    """Project the stats window's per-collection deltas onto the PG/OSD
    topology.  ``topology`` is a callable returning
    ``{pg: {"primary": osd, "acting": [osds]}}`` (the cluster's live
    placement); ``tag`` scopes collection matching to one cluster."""

    def __init__(self, stats, topology, name: str = "heat",
                 tag: str | None = None):
        self.stats = stats
        self.topology = topology
        self.name = name
        # "<prefix>.<tag>[e<epoch>].pg<pgid>" -> pgid; no tag matches any
        self._pg_re = re.compile(
            (rf"\.{re.escape(tag)}(?:e\d+)?" if tag else r"(?:\.[^.]+?)?")
            + r"\.pg(?P<pg>.+)$")
        _TRACKERS.add(self)

    def _pg_of(self, coll: str) -> str | None:
        if not any(coll.startswith(p) for p in PG_PREFIXES):
            return None
        m = self._pg_re.search(coll)
        return m.group("pg") if m else None

    # -- heat surfaces -----------------------------------------------------

    def pg_heat(self, topo: dict | None = None) -> dict[str, dict]:
        """``{pg: {op_s, bytes_s, primary}}`` over the stats window.
        Every topology PG appears (cold PGs at 0.0), so the heat map's
        SHAPE is the placement, not just the traffic."""
        dt = self.stats.span()
        if topo is None:
            topo = self.topology() or {}
        out = {pg: {"op_s": 0.0, "bytes_s": 0.0,
                    "primary": info.get("primary")}
               for pg, info in topo.items()}
        if dt <= 0:
            return out
        for key, bucket in (list(zip(_OP_KEYS, ["op_s"] * 2))
                            + list(zip(_BYTE_KEYS, ["bytes_s"] * 2))):
            for coll, delta in self.stats.per_collection_delta(
                    key, PG_PREFIXES).items():
                pg = self._pg_of(coll)
                if pg in out:
                    out[pg][bucket] += delta / dt
        for rec in out.values():
            rec["op_s"] = round(rec["op_s"], 3)
            rec["bytes_s"] = round(rec["bytes_s"], 3)
        return out

    def osd_heat(self, topo: dict | None = None,
                 pgs: dict | None = None) -> dict[int, dict]:
        """``{osd: {op_s, bytes_s, primary_pgs}}`` — per-PG heat rolled
        onto each PG's primary.  Every OSD appearing in any acting set
        is present (a spare OSD's 0.0 row IS the imbalance signal)."""
        if topo is None:
            topo = self.topology() or {}
        if pgs is None:
            pgs = self.pg_heat(topo)
        out: dict[int, dict] = {}
        for info in topo.values():
            for osd in info.get("acting", ()):
                out.setdefault(int(osd), {"op_s": 0.0, "bytes_s": 0.0,
                                          "primary_pgs": 0})
        for pg, rec in pgs.items():
            osd = rec.get("primary")
            if osd is None:
                continue
            row = out.setdefault(int(osd), {"op_s": 0.0, "bytes_s": 0.0,
                                            "primary_pgs": 0})
            row["op_s"] = round(row["op_s"] + rec["op_s"], 3)
            row["bytes_s"] = round(row["bytes_s"] + rec["bytes_s"], 3)
            row["primary_pgs"] += 1
        return out

    def tail_digest(self, heat: dict | None = None) -> dict:
        """The tail-utilization digest (ROADMAP item 5's before/after
        instrument): max/median/mean primary-op load across OSDs and the
        max/median skew ratio.  ``ratio`` is 0.0 when nothing moves and
        ``inf``-free: a hot OSD over an otherwise idle cluster reports
        the max against a zero median via ``median == 0``."""
        if heat is None:
            heat = self.osd_heat()
        loads = sorted(r["op_s"] for r in heat.values())
        if not loads:
            return {"osds": 0, "max_op_s": 0.0, "median_op_s": 0.0,
                    "mean_op_s": 0.0, "ratio": 0.0, "hot_osds": []}
        mx = loads[-1]
        med = statistics.median(loads)
        mean = sum(loads) / len(loads)
        ratio = (mx / med) if med > 0 else (0.0 if mx <= 0 else mx)
        hot = sorted((osd for osd, r in heat.items()
                      if med > 0 and r["op_s"] >= med * 2
                      or med <= 0 and r["op_s"] > 0),
                     key=lambda o: -heat[o]["op_s"])
        return {"osds": len(loads), "max_op_s": round(mx, 3),
                "median_op_s": round(med, 3),
                "mean_op_s": round(mean, 3),
                "ratio": round(ratio, 3), "hot_osds": hot[:8]}

    def snapshot(self) -> dict:
        """ONE coherent heat computation — the stats window is walked
        and the topology queried once, and every derived surface (osd
        rollup, tail digest) comes from that same per-PG pass.  The
        multi-surface consumers (time-series tick, flight dump, health
        check, prometheus scrape) read this instead of recomputing
        pg_heat per surface."""
        topo = self.topology() or {}
        pgs = self.pg_heat(topo)
        osds = self.osd_heat(topo, pgs)
        return {"tail": self.tail_digest(osds), "osds": osds,
                "pgs": pgs}

    def flat_series(self) -> dict[str, float]:
        """The time-series-ring source: tail digest + per-OSD op rates
        as flat ``name -> value`` series."""
        snap = self.snapshot()
        d = snap["tail"]
        out = {"tail_max_op_s": d["max_op_s"],
               "tail_median_op_s": d["median_op_s"],
               "tail_ratio": d["ratio"]}
        for osd, rec in sorted(snap["osds"].items()):
            out[f"osd.{osd}.op_s"] = rec["op_s"]
        return out

    def dump(self) -> dict:
        """The flight-recorder source: the full spatial picture."""
        return self.snapshot()

    def close(self) -> None:
        _TRACKERS.discard(self)


def hot_shard_check(tracker: HeatTracker, cct):
    """HOT_SHARD: one OSD's primary-op load is a sustained multiple of
    the median (``mgr_hot_shard_ratio``) while carrying real traffic
    (``mgr_hot_shard_min_ops`` op/s) over a window of at least a second
    — the hot-shard workload ROADMAP item 5's balancer must flatten.
    Sub-second windows and idle clusters never fire (the
    pg_recovery_stalled_check discipline: no paging without evidence)."""
    def check():
        from .health import CheckResult
        if tracker.stats.span() < 1.0:
            return None
        ratio = float(cct.conf.get("mgr_hot_shard_ratio"))
        min_ops = float(cct.conf.get("mgr_hot_shard_min_ops"))
        snap = tracker.snapshot()
        d = snap["tail"]
        if d["max_op_s"] < min_ops:
            return None
        med = d["median_op_s"]
        if med > 0 and d["max_op_s"] / med < ratio:
            return None
        heat = snap["osds"]
        # offenders at the CONFIGURED ratio (tail_digest's hot_osds uses
        # a fixed 2x digest convention — the check must not claim ">= Nx"
        # for OSDs that only cleared 2x)
        hot = sorted((osd for osd, r in heat.items()
                      if (med > 0 and r["op_s"] >= med * ratio)
                      or (med <= 0 and r["op_s"] >= min_ops)),
                     key=lambda o: -heat[o]["op_s"])[:8]
        detail = [f"osd.{osd}: {heat[osd]['op_s']:.0f} op/s over "
                  f"{heat[osd]['primary_pgs']} primary pgs"
                  for osd in hot]
        return CheckResult(
            f"{len(hot)} osd(s) serving >= {ratio:.0f}x the "
            f"median primary-op load (max {d['max_op_s']:.0f} op/s, "
            f"median {med:.0f})",
            detail=detail, count=len(hot))
    return check


def top_objects(cluster, n: int = 20) -> list[dict]:
    """Bounded top-N hot-OBJECT digest folded from the per-PG hit sets
    — object granularity under the PG/OSD heat maps above, and the
    tier agent's promotion-evidence surface (`heat top`).

    Bloom hit sets cannot enumerate their members, so candidates come
    from the cluster's object bookkeeping and each is membership-tested
    against its PG's current + archived sets
    (``object_temperature``).  Only pools with hit sets armed
    contribute; the result is bounded by a heap, never by truncating a
    sort of the whole namespace."""
    import heapq
    from ..osd.hit_set import is_hit_set_oid
    scored = []
    for pid, oids in sorted(cluster.objects.items()):
        engines = {}          # pg ps -> engine (one hit-set probe setup)
        for oid in sorted(oids):
            if is_hit_set_oid(oid):
                continue
            ps = cluster.object_pg(pid, oid)
            eng = engines.get(ps)
            if eng is None:
                eng = engines[ps] = \
                    cluster.pools[pid]["pgs"][ps].engine
            if eng.hit_set_params is None:
                continue
            t = eng.object_temperature(oid)
            if t > 0:
                scored.append((t, f"{pid}/{oid}", pid, oid))
    # nlargest == sorted(..., reverse=True)[:n] and is STABLE: equal
    # temperatures keep the pool/oid iteration order (alphabetical)
    top = heapq.nlargest(int(n), scored, key=lambda rec: rec[0])
    return [{"pool": pid, "oid": oid, "temperature": t}
            for t, _, pid, oid in top]
