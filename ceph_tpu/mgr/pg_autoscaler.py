"""pg_autoscaler: recommend pg_num per pool.

Mirror of the reference's autoscaler math (reference:
src/pybind/mgr/pg_autoscaler/module.py:270-330): the root's PG allowance is
``osd_count * mon_target_pg_per_osd``; each pool gets the share of that
allowance matching its capacity ratio (actual usage vs target_size take the
max), scaled by ``raw_used_rate`` (replica count / EC (k+m)/k overhead) and
``pg_autoscale_bias``, quantized to the nearest power of two, floored at
``pg_num_min``; an adjustment is recommended only when the target is off by
more than the 3x threshold.
"""
from __future__ import annotations

from ..osdmap import OSDMap, POOL_TYPE_ERASURE

PG_NUM_MIN = 4
THRESHOLD = 3.0
MON_TARGET_PG_PER_OSD = 100


def nearest_power_of_two(n: float) -> int:
    if n <= 1:
        return 1
    v = int(n)
    next_p = 1 << v.bit_length()
    prev_p = next_p >> 1
    return prev_p if (n - prev_p) < (next_p - n) else next_p


def raw_used_rate(m: OSDMap, pool_id: int, k: int | None = None) -> float:
    """Storage amplification: size for replicated, (k+m)/k for EC
    (OSDMap::pool_raw_used_rate)."""
    pool = m.pools[pool_id]
    if pool.type == POOL_TYPE_ERASURE:
        if k is None and pool.params:
            kv = pool.params.get("k")
            k = int(kv) if kv is not None else None
        if k is None:
            # pools rebuilt from a serialized map only carry the profile
            # string ("k=4 m=2 ..."); parse k from there
            for kv in (pool.erasure_code_profile or "").split():
                key, _, val = kv.partition("=")
                if key == "k" and val.isdigit():
                    k = int(val)
                    break
        if k:
            return pool.size / float(k)
        return float(pool.size)
    return float(pool.size)


def autoscale_recommendations(
        m: OSDMap, pool_bytes_used: dict[int, int],
        capacity_bytes: int,
        target_pg_per_osd: int = MON_TARGET_PG_PER_OSD,
        options: dict[int, dict] | None = None) -> list[dict]:
    """Per-pool recommendation dicts (module.py:310-330 shape)."""
    options = options or {}
    n_osds = sum(1 for o in range(m.max_osd) if m.is_in(o))
    root_pg_target = n_osds * target_pg_per_osd
    out = []
    for pid in sorted(m.pools):
        pool = m.pools[pid]
        opt = options.get(pid, {})
        bias = opt.get("pg_autoscale_bias", 1.0)
        target_bytes = opt.get("target_size_bytes", 0)
        k = opt.get("k")
        rate = raw_used_rate(m, pid, k)
        used = pool_bytes_used.get(pid, 0)
        actual_ratio = (used * rate) / capacity_bytes if capacity_bytes else 0
        capacity_ratio = (max(used, target_bytes) * rate / capacity_bytes
                          if capacity_bytes else 0.0)
        target_ratio = opt.get("target_size_ratio", 0.0)
        final_ratio = max(capacity_ratio, target_ratio)
        pool_pg_target = (final_ratio * root_pg_target) / rate * bias
        final_pg = max(opt.get("pg_num_min", PG_NUM_MIN),
                       nearest_power_of_two(pool_pg_target))
        would_adjust = (0.0 <= final_ratio <= 1.0 and
                        (final_pg > pool.pg_num * THRESHOLD or
                         final_pg <= pool.pg_num / THRESHOLD))
        out.append({
            "pool_id": pid, "pool_name": pool.name,
            "pg_num_target": pool.pg_num,
            "raw_used_rate": rate,
            "actual_capacity_ratio": actual_ratio,
            "capacity_ratio": capacity_ratio,
            "final_ratio": final_ratio,
            "pg_num_ideal": int(pool_pg_target),
            "pg_num_final": final_pg,
            "would_adjust": would_adjust,
            "bias": bias,
        })
    return out
