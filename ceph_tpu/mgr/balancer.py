"""Balancer: even out PG counts with pg_upmap_items.

Mirror of the reference's upmap balancer (reference:
src/pybind/mgr/balancer/module.py upmap mode driving
``OSDMap::calc_pg_upmaps``, src/osd/OSDMap.h:1439 — iterate: find the most
overfull OSD vs its weight-proportional target, move one of its PGs to the
most underfull OSD via a ``pg_upmap_items`` entry, re-check).  Like the
reference, moves operate on the **up mapping** (raw CRUSH + upmap, no
pg_temp — temp mappings are transient recovery state) and every candidate
is applied speculatively and re-verified through the real mapping chain
before being kept: the item must actually remove ``over``, land ``under``,
keep all OSDs distinct, and preserve host-separation where the layout had
it.

Placement counting runs through the vmapped bulk mapper, one device
dispatch per pool per iteration (the reference walks PGs on CPU threads).
"""
from __future__ import annotations

import numpy as np

from ..crush.map import CRUSH_ITEM_NONE
from ..osdmap import Incremental, OSDMap, PG
from ..osdmap.bulk import BulkPGMapper


def osd_deviation(m: OSDMap, pools: list[int] | None = None,
                  mapper: BulkPGMapper | None = None):
    """Per-OSD (count, target) from the **up** sets; target is
    weight-proportional.  Returns (counts, targets, mappings) where
    mappings is {pool_id: PoolMapping} for reuse by the move search."""
    counts = np.zeros(m.max_osd, dtype=np.int64)
    total_slots = 0
    if mapper is None:
        mapper = BulkPGMapper(m)
    mappings = {}
    for pid in (pools if pools is not None else sorted(m.pools)):
        pm = mapper.map_pool(pid)
        mappings[pid] = pm
        for row in pm.up:
            for o in row:
                if o != CRUSH_ITEM_NONE:
                    counts[o] += 1
                    total_slots += 1
    cw = m.crush.device_weights()
    eff = np.zeros(m.max_osd)
    for o in range(m.max_osd):
        if m.is_in(o):
            eff[o] = cw.get(o, 0) * (m.osd_weight[o] / 0x10000)
    tw = eff.sum()
    targets = (eff / tw * total_slots) if tw else eff
    return counts, targets, mappings


def _host_of(m: OSDMap) -> dict[int, int]:
    host = {}
    for bid, b in m.crush.buckets.items():
        # shadow (per-class clone) hosts must not register as separate
        # physical hosts, or the upmap host-separation check would let
        # two replicas share one real host
        if m.crush.is_shadow(bid):
            continue
        if m.crush.type_names.get(b.type) == "host":
            for item in b.items:
                if item >= 0:
                    host[item] = b.id
    return host


def _try_move(work: OSDMap, pg: PG, over: int, under: int,
              host_of: dict[int, int]) -> list[tuple[int, int]] | None:
    """Build the pg_upmap_items list that moves `over` -> `under` for this
    PG, apply it speculatively, and verify through the real chain
    (the reference's try_pg_upmap + re-check).  Returns the verified items
    list, or None."""
    up_before, *_ = work.pg_to_raw_up(pg)
    real_before = [o for o in up_before if o != CRUSH_ITEM_NONE]
    if over not in real_before or under in real_before:
        return None

    raw, _ = work.pg_to_raw_osds(pg)
    items = list(work.pg_upmap_items.get(pg, []))
    if over in raw:
        # raw slot maps to `over` directly: add a fresh item
        items = [(f, t) for f, t in items if f != over] + [(over, under)]
    else:
        # `over` only appears via an existing item (f -> over): rewrite it
        rewritten = False
        for i, (f, t) in enumerate(items):
            if t == over:
                items[i] = (f, under)
                rewritten = True
                break
        if not rewritten:
            return None

    saved = work.pg_upmap_items.get(pg)
    work.pg_upmap_items[pg] = items
    up_after, *_ = work.pg_to_raw_up(pg)
    real_after = [o for o in up_after if o != CRUSH_ITEM_NONE]

    ok = (over not in real_after and under in real_after and
          len(real_after) == len(set(real_after)) and
          len(real_after) == len(real_before))
    if ok and host_of:
        hosts_before = [host_of.get(o) for o in real_before]
        if len(set(hosts_before)) == len(hosts_before):  # was host-separated
            hosts_after = [host_of.get(o) for o in real_after]
            ok = len(set(hosts_after)) == len(hosts_after)
    if not ok:
        if saved is None:
            del work.pg_upmap_items[pg]
        else:
            work.pg_upmap_items[pg] = saved
        return None
    return items


def _subtree_devices(m: OSDMap) -> dict[int, list[int]]:
    """bucket/device id -> devices under it (memoized DFS)."""
    out: dict[int, list[int]] = {}

    def walk(item: int) -> list[int]:
        if item in out:
            return out[item]
        if item >= 0:
            out[item] = [item]
        else:
            devs: list[int] = []
            for child in m.crush.buckets[item].items:
                devs.extend(walk(child))
            out[item] = devs
        return out[item]

    for bid in m.crush.buckets:
        walk(bid)
    return out


def calc_weight_set(m: OSDMap, max_iterations: int = 16, step: float = 0.4,
                    pools: list[int] | None = None) -> dict | None:
    """The balancer's crush-compat mode: build the COMPAT weight-set
    (choose_args key -1, one position) nudging every bucket item's straw2
    weight toward its subtree's PG-load target — the role
    ``do_crush_compat`` plays in the reference's balancer module
    (src/pybind/mgr/balancer/module.py) over CrushWrapper's
    ``choose_args``.  Works where upmap can't be used (pre-luminous
    clients), evaluated through the vmapped bulk mapper each iteration.

    Returns the choose_args set ({bucket_id: {"weight_set": [[...]]}}) to
    install as ``m.crush.choose_args[-1]``, or None if no improvement was
    found.
    """
    work = m.clone()
    subtree = _subtree_devices(work)
    # candidate: start from the buckets' own weights (single position)
    cand = {bid: {"weight_set": [list(b.item_weights)]}
            for bid, b in work.crush.buckets.items()}

    mapper = BulkPGMapper(work)     # kernels depend only on the crush tree

    def evaluate():
        counts, targets, _ = osd_deviation(work, pools, mapper=mapper)
        mask = np.array([work.is_in(o) for o in range(work.max_osd)])
        dev = np.where(mask, counts - targets, 0.0)
        return counts, targets, float(np.sqrt((dev ** 2).mean()))

    work.crush.choose_args[-1] = cand
    counts, targets, best = evaluate()
    best_cand = {bid: {"weight_set": [list(a["weight_set"][0])]}
                 for bid, a in cand.items()}
    improved = False

    for _ in range(max_iterations):
        # nudge each bucket item by its subtree's load ratio
        for bid, b in work.crush.buckets.items():
            ws = cand[bid]["weight_set"][0]
            for i, item in enumerate(b.items):
                devs = subtree[item]
                c = sum(counts[d] for d in devs if d < len(counts))
                t = sum(targets[d] for d in devs if d < len(targets))
                if t <= 0 or ws[i] <= 0:
                    continue
                ratio = max(0.5, min(2.0, (t / max(c, 0.5)) ** step))
                ws[i] = max(1, int(ws[i] * ratio))
        counts, targets, rms = evaluate()
        if rms < best - 1e-9:
            best = rms
            best_cand = {bid: {"weight_set": [list(a["weight_set"][0])]}
                         for bid, a in cand.items()}
            improved = True
        else:
            break
    return best_cand if improved else None


def calc_pg_upmaps(m: OSDMap, max_iterations: int = 32,
                   max_deviation: float = 1.0,
                   pools: list[int] | None = None) -> Incremental:
    """Propose pg_upmap_items to bring every OSD within ``max_deviation``
    PGs of its target.  Returns an Incremental (possibly empty); apply with
    ``apply_incremental`` or feed to Monitor.pending."""
    work = m.clone()
    inc = Incremental()
    host_of = _host_of(work)
    pool_ids = pools if pools is not None else sorted(work.pools)
    mapper = BulkPGMapper(work)     # kernels depend only on the crush tree

    for _ in range(max_iterations):
        counts, targets, mappings = osd_deviation(work, pool_ids,
                                                  mapper=mapper)
        dev = counts - targets
        mask = np.array([work.is_in(o) and work.is_up(o)
                         for o in range(work.max_osd)])
        dev_masked = np.where(mask, dev, 0.0)
        over = int(dev_masked.argmax())
        under = int(np.where(mask, dev, np.inf).argmin())
        if dev_masked[over] <= max_deviation:
            break
        moved = False
        for pid in pool_ids:
            pm = mappings[pid]
            for ps in range(work.pools[pid].pg_num):
                row = [int(o) for o in pm.up[ps] if o != CRUSH_ITEM_NONE]
                if over not in row or under in row:
                    continue
                pg = PG(pid, ps)
                items = _try_move(work, pg, over, under, host_of)
                if items is not None:
                    inc.new_pg_upmap_items[pg] = list(items)
                    moved = True
                    break
            if moved:
                break
        if not moved:
            break
    return inc
