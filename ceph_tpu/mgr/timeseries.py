"""Embedded time-series ring: fixed-interval round-robin archives.

The RRDtool idea embedded in the process (the reference ships this as
the mgr's ``prometheus``+external-scraper pairing; ``ceph -s`` history
otherwise dies with the terminal): every ``mgr_ts_interval`` seconds a
POINT is recorded — the stats digest, the heat tail, the wire rollup,
whatever sources are attached — into a bounded FINE ring, and every
``mgr_ts_coarse_every`` fine points are folded (mean + max per series)
into a bounded COARSE ring.  Total memory is fixed; history depth is
``capacity * (1 + coarse_every)`` intervals — classic round-robin
archive eviction, oldest first.

The ring rides every flight-recorder bundle (``timeseries`` source), so
post-hoc analysis of a soak or bench run — `tools/ts_report.py`'s
sparkline/percentile tables — needs the artifact alone, no external
scraper running at incident time.
"""
from __future__ import annotations

import threading
import time


class TimeSeriesRing:
    """Bounded two-resolution archive of flat ``name -> value`` series."""

    def __init__(self, cct=None, interval: float | None = None,
                 capacity: int | None = None,
                 coarse_every: int | None = None, clock=time.monotonic):
        from ..common import default_context
        self.cct = cct if cct is not None else default_context()
        conf = self.cct.conf
        self.interval = float(conf.get("mgr_ts_interval")
                              if interval is None else interval)
        self.capacity = max(2, int(conf.get("mgr_ts_capacity")
                                   if capacity is None else capacity))
        self.coarse_every = max(1, int(conf.get("mgr_ts_coarse_every")
                                       if coarse_every is None
                                       else coarse_every))
        self.clock = clock
        from collections import deque
        self.fine: "deque[dict]" = deque(maxlen=self.capacity)
        self.coarse: "deque[dict]" = deque(maxlen=self.capacity)
        self._pending: list[dict] = []      # fine points awaiting fold
        self._sources: dict[str, object] = {}
        self._last_t: float | None = None
        self._lock = threading.Lock()
        self.points_recorded = 0
        self.points_skipped = 0

    def add_source(self, name: str, fn) -> None:
        """Attach a flat-series provider: ``fn() -> {key: float}``;
        series land namespaced ``<name>.<key>``."""
        with self._lock:
            self._sources[name] = fn

    # -- recording ---------------------------------------------------------

    def record(self, now: float | None = None, force: bool = False
               ) -> dict | None:
        """Record one point if at least ``interval`` has passed since the
        last one (``force`` overrides — phase boundaries in tests and
        benches).  Sources are exception-guarded: a broken provider
        zeroes its series, never the tick."""
        t = self.clock() if now is None else now
        with self._lock:
            if not force and self._last_t is not None and \
                    t - self._last_t < self.interval:
                self.points_skipped += 1
                return None
            self._last_t = t
            sources = dict(self._sources)
        point: dict = {"t": t, "wall": time.time()}
        for name, fn in sources.items():
            try:
                for k, v in (fn() or {}).items():
                    if isinstance(v, (int, float)):
                        point[f"{name}.{k}"] = round(float(v), 4)
            except Exception:            # the ring records THROUGH faults
                point[f"{name}.error"] = 1.0
        with self._lock:
            self.fine.append(point)
            self.points_recorded += 1
            self._pending.append(point)
            if len(self._pending) >= self.coarse_every:
                self.coarse.append(self._fold(self._pending))
                self._pending = []
        return point

    @staticmethod
    def _fold(points: list[dict]) -> dict:
        """mean + max per series over one coarse bucket (the RRD
        consolidation functions that matter for capacity questions)."""
        keys = {k for p in points for k in p if k not in ("t", "wall")}
        out = {"t": points[0]["t"], "wall": points[0]["wall"],
               "n": len(points)}
        for k in keys:
            vals = [p[k] for p in points if k in p]
            out[f"{k}:avg"] = round(sum(vals) / len(vals), 4)
            out[f"{k}:max"] = round(max(vals), 4)
        return out

    # -- read --------------------------------------------------------------

    def series_names(self) -> list[str]:
        with self._lock:
            return sorted({k for p in self.fine
                           for k in p if k not in ("t", "wall")})

    def series(self, name: str) -> list[tuple[float, float]]:
        """``[(t, value)]`` for one fine series (missing points skipped)."""
        with self._lock:
            return [(p["t"], p[name]) for p in self.fine if name in p]

    def dump(self) -> dict:
        """The flight-recorder source / ts_report input."""
        with self._lock:
            return {"interval_s": self.interval,
                    "capacity": self.capacity,
                    "coarse_every": self.coarse_every,
                    "recorded": self.points_recorded,
                    "fine": list(self.fine),
                    "coarse": list(self.coarse)}
