"""SLO engine: per-class latency objectives, error budgets, burn rates.

The health engine (PR 3) knows point thresholds; ROADMAP items 3 and 4
are judged on p99 and goodput-under-overload, which need an OBJECTIVE:
"99.9% of client ops complete under 40 ms" — and an alert policy that
pages on a sustained budget burn, not on one slow op.  This module is
the SRE-workbook multi-window burn-rate engine over the critical-path
ledger (``common/critpath.py``):

- **objectives** come from config: ``slo_<class>_p99_ms`` (the latency
  bound; 0 = no objective for that class) and ``slo_<class>_target``
  (the fraction of ops that must meet it, default 0.999 — the error
  budget is ``1 - target``);
- **burn rate** over a window = (fraction of ops over the bound) /
  budget: 1.0 means spending exactly the sustainable rate, 2.0 means
  the budget dies in half its period;
- **multi-window agreement**: ``SLO_BURN`` raises only when BOTH the
  fast window (``slo_fast_window``) and the slow window
  (``slo_slow_window``) burn past ``slo_burn_rate_threshold`` — a blip
  trips the fast window alone and stays silent; a sustained burn trips
  both and pages.  ``SLO_EXHAUSTED`` (HEALTH_ERR) raises when the slow
  window burns past ``slo_exhausted_burn_rate`` — the budget is not
  merely burning, it is gone at any plausible compliance period;
- windows below ``slo_min_ops`` ops never page (an idle class has no
  evidence either way).

Surfaces: the ``SLO_BURN``/``SLO_EXHAUSTED`` health checks (every
MiniCluster registers them; transitions ride the clusterlog + flight
recorder like any other check), ``slo status``/``slo dump`` admin
commands, ``ceph_tpu_slo_budget{class,stat}`` prometheus gauges, the
``slo`` series in the time-series ring, and the ``slo`` block in
bench.py artifacts gated by ``tools/perf_gate.py``.
"""
from __future__ import annotations

import threading
import time
import weakref

from ..common import default_context
from ..common.critpath import PHASES, render_attribution
from ..common.device_attribution import OWNER_CLASSES
from .health import HEALTH_ERR, CheckResult

_TRACKERS: "weakref.WeakSet[SLOTracker]" = weakref.WeakSet()


def live_slo_trackers() -> list["SLOTracker"]:
    return list(_TRACKERS)


def slo_objectives(conf) -> dict[str, dict]:
    """{class: {"p99_ms", "target", "budget"}} for every class with a
    configured objective (``slo_<class>_p99_ms`` > 0)."""
    out: dict[str, dict] = {}
    for cls in OWNER_CLASSES:
        p99 = float(conf.get(f"slo_{cls}_p99_ms"))
        if p99 <= 0:
            continue
        target = min(0.999999, max(0.0, float(
            conf.get(f"slo_{cls}_target"))))
        out[cls] = {"p99_ms": p99, "target": target,
                    "budget": max(1e-9, 1.0 - target)}
    return out


class SLOTracker:
    """Error-budget accounting over the critical-path ledger's per-op
    records (each record: completion time on the perf_counter clock,
    total seconds, per-phase seconds)."""

    def __init__(self, ledger, cct=None, name: str = "slo",
                 clock=time.perf_counter):
        self.cct = cct if cct is not None else default_context()
        self.ledger = ledger
        self.name = name
        self.clock = clock
        self._lock = threading.Lock()
        _TRACKERS.add(self)

    # windows/thresholds read LIVE, like the objectives: `config set
    # slo_fast_window 5` on a running cluster must take effect the same
    # way `config set slo_client_p99_ms 40` does
    @property
    def fast_window(self) -> float:
        return float(self.cct.conf.get("slo_fast_window"))

    @property
    def slow_window(self) -> float:
        return float(self.cct.conf.get("slo_slow_window"))

    @property
    def burn_threshold(self) -> float:
        return float(self.cct.conf.get("slo_burn_rate_threshold"))

    @property
    def exhausted_burn(self) -> float:
        return float(self.cct.conf.get("slo_exhausted_burn_rate"))

    @property
    def min_ops(self) -> int:
        return int(self.cct.conf.get("slo_min_ops"))

    # -- window math -------------------------------------------------------

    @staticmethod
    def _window(records: list[dict], window_s: float, bound_ms: float,
                budget: float, now: float) -> dict:
        recs = [r for r in records if now - r["t"] <= window_s]
        # weighted by each record's sample weight (1/rate for head-
        # sampled traces, 1.0 otherwise): bad_frac stays an unbiased
        # estimate of the true bad-op RATE under sampling.  `ops` stays
        # the observed record count — it feeds the min_ops significance
        # floor, which is about how much EVIDENCE we have, not how many
        # ops the evidence represents.
        bad = sum(r.get("w", 1.0) for r in recs
                  if r["total_s"] * 1e3 > bound_ms)
        wsum = sum(r.get("w", 1.0) for r in recs)
        n = len(recs)
        bad_frac = bad / wsum if wsum else 0.0
        return {"window_s": window_s, "ops": n,
                "weighted_ops": round(wsum, 1), "bad": round(bad, 1),
                "bad_frac": round(bad_frac, 6),
                "burn": round(bad_frac / budget, 3)}

    def class_status(self, cls: str, objective: dict,
                     now: float | None = None) -> dict:
        now = self.clock() if now is None else now
        # ONE copy of the class's record window serves both burn
        # windows (records() copies the bounded deque under the ledger
        # lock — doing it per window doubled the hold for nothing)
        records = self.ledger.records(cls)
        fast = self._window(records, self.fast_window,
                            objective["p99_ms"], objective["budget"],
                            now)
        slow = self._window(records, self.slow_window,
                            objective["p99_ms"], objective["budget"],
                            now)
        enough = fast["ops"] >= self.min_ops and \
            slow["ops"] >= self.min_ops
        burning = enough and fast["burn"] >= self.burn_threshold \
            and slow["burn"] >= self.burn_threshold
        exhausted = enough and slow["burn"] >= self.exhausted_burn
        return {
            "objective_p99_ms": objective["p99_ms"],
            "target": objective["target"],
            "budget": round(objective["budget"], 6),
            "fast": fast,
            "slow": slow,
            # budget left over the slow window: 1.0 = untouched,
            # 0.0 = fully consumed (burn >= 1/budget would be needed
            # only for bad_frac = 1; the remaining fraction is the
            # honest operator number)
            "budget_remaining": round(
                max(0.0, 1.0 - slow["bad_frac"] / objective["budget"]),
                4),
            "burning": burning,
            "exhausted": exhausted,
        }

    # -- surfaces ----------------------------------------------------------

    def objectives_status(self, now: float | None = None
                          ) -> dict[str, dict]:
        """Just the per-class objective/burn state — what the two
        health checks read every evaluation (computing the full
        attribution summaries there would deep-copy and sort every
        class's record window once per check per tick for data the
        checks never look at)."""
        objectives = slo_objectives(self.cct.conf)
        now = self.clock() if now is None else now
        return {cls: self.class_status(cls, obj, now)
                for cls, obj in sorted(objectives.items())}

    def status(self, now: float | None = None) -> dict:
        """The `slo status` shape: per-class objective/burn state plus
        the ledger's attribution summaries (classes WITHOUT an
        objective still show attribution — the p99 table is useful
        before anyone commits to a number)."""
        return {
            "windows": {"fast_s": self.fast_window,
                        "slow_s": self.slow_window,
                        "burn_threshold": self.burn_threshold,
                        "exhausted_burn": self.exhausted_burn,
                        "min_ops": self.min_ops},
            "objectives": self.objectives_status(now),
            "attribution": {cls: self.ledger.class_summary(cls)
                            for cls in self.ledger.classes()},
        }

    def dump(self) -> dict:
        """`slo dump` / the flight-recorder source: status + the full
        ledger snapshot, so a WARN/ERR bundle answers 'which phase blew
        the budget' without a live cluster."""
        return {"slo": self.status(), "critpath": self.ledger.snapshot()}

    def flat_series(self) -> dict[str, float]:
        """The time-series-ring source (`slo.<class>_<stat>`)."""
        out: dict[str, float] = {}
        st = self.status()
        for cls, s in st["objectives"].items():
            out[f"{cls}_burn_fast"] = s["fast"]["burn"]
            out[f"{cls}_burn_slow"] = s["slow"]["burn"]
            out[f"{cls}_budget_remaining"] = s["budget_remaining"]
        for cls, summary in st["attribution"].items():
            if summary:
                out[f"{cls}_p99_ms"] = summary["p99_ms"]
        return out

    def bench_block(self, device: str) -> dict:
        """The bench.py `slo` block: per-class p99 + phase fractions +
        budget state — everything tools/slo_report.py needs to
        reproduce the attribution table from the artifact alone, and
        tools/perf_gate.py gates (`slo.client_p99_ms`,
        `slo.budget_remaining`)."""
        st = self.status()
        block: dict = {"device": device,
                       "windows": st["windows"]}
        for cls, summary in st["attribution"].items():
            if not summary:
                continue
            entry = {"p99_ms": summary["p99_ms"],
                     "mean_ms": summary["mean_ms"],
                     "ops": summary["ops"],
                     "phases": summary["phases"]}
            obj = st["objectives"].get(cls)
            if obj:
                entry["objective_p99_ms"] = obj["objective_p99_ms"]
                entry["budget_remaining"] = obj["budget_remaining"]
                entry["burn_fast"] = obj["fast"]["burn"]
                entry["burn_slow"] = obj["slow"]["burn"]
            block[cls] = entry
        return block

    def close(self) -> None:
        _TRACKERS.discard(self)


# -- health checks -----------------------------------------------------------

def slo_burn_check(tracker: SLOTracker):
    """SLO_BURN: fast AND slow windows agree the error budget is
    burning past threshold — a blip trips the fast window alone and
    stays silent; a sustained burn pages."""
    def check():
        hot: list[str] = []
        for cls, s in tracker.objectives_status().items():
            if s["burning"] and not s["exhausted"]:
                hot.append(
                    f"{cls}: burn x{s['fast']['burn']:.1f} fast / "
                    f"x{s['slow']['burn']:.1f} slow (p99 objective "
                    f"{s['objective_p99_ms']:.1f} ms, "
                    f"{s['slow']['bad']}/{s['slow']['ops']} ops over, "
                    f"{100 * s['budget_remaining']:.0f}% budget left)")
        if hot:
            return CheckResult(
                f"{len(hot)} class(es) burning latency error budget "
                f"(fast+slow window agreement)",
                detail=hot, count=len(hot))
        return None
    return check


def slo_exhausted_check(tracker: SLOTracker):
    """SLO_EXHAUSTED: the slow window's burn rate says the budget is
    gone at any plausible compliance period — HEALTH_ERR."""
    def check():
        hot: list[str] = []
        for cls, s in tracker.objectives_status().items():
            if s["exhausted"]:
                hot.append(
                    f"{cls}: burn x{s['slow']['burn']:.1f} over "
                    f"{s['slow']['window_s']:.0f}s "
                    f"({s['slow']['bad']}/{s['slow']['ops']} ops past "
                    f"the {s['objective_p99_ms']:.1f} ms objective)")
        if hot:
            return CheckResult(
                f"{len(hot)} class(es) exhausted their latency error "
                f"budget", detail=hot, severity=HEALTH_ERR,
                count=len(hot))
        return None
    return check


# -- rendering ---------------------------------------------------------------

def render_status(status: dict, ledger_snapshot: dict | None = None
                  ) -> str:
    """The `ceph slo status` text: per-class p99 attribution table plus
    the budget table for classes with objectives."""
    lines = ["latency attribution (critical-path ledger):"]
    snap = ledger_snapshot or {"classes": status.get("attribution", {})}
    lines += [f"  {line}" for line in render_attribution(snap)]
    objectives = status.get("objectives") or {}
    if objectives:
        lines.append("objectives:")
        lines.append(f"  {'class':<10} {'p99 obj':>9} {'p99 now':>9} "
                     f"{'burn(fast)':>10} {'burn(slow)':>10} "
                     f"{'budget left':>11}  state")
        for cls, s in sorted(objectives.items()):
            summary = (status.get("attribution") or {}).get(cls)
            now_ms = f"{summary['p99_ms']:.1f}" if summary else "-"
            state = "EXHAUSTED" if s["exhausted"] else \
                "BURNING" if s["burning"] else "ok"
            lines.append(
                f"  {cls:<10} {s['objective_p99_ms']:>7.1f}ms "
                f"{now_ms:>7}ms {s['fast']['burn']:>9.1f}x "
                f"{s['slow']['burn']:>9.1f}x "
                f"{100 * s['budget_remaining']:>10.0f}%  {state}")
    else:
        lines.append("objectives: none configured "
                     "(set slo_<class>_p99_ms)")
    return "\n".join(lines)


def render_phase_table(phases: dict[str, float]) -> str:
    """One class's phase-fraction row set (slo_report's table body)."""
    rows = [f"  {p:<12} {100 * phases.get(p, 0.0):>6.1f}%"
            for p in PHASES if phases.get(p, 0.0) > 0]
    return "\n".join(rows) if rows else "  (no attributed time)"
