"""Wire protocol: v2-style framing with CRC and secure modes.

Analog of the reference messenger's on-wire layer (reference:
src/msg/async/ProtocolV2.cc, 2905 LoC; frame layout in
src/msg/async/frames_v2.h; AEAD in src/msg/async/crypto_onwire.cc):
banner exchange, a hello handshake, then length-prefixed frames of up to
4 segments, each integrity-protected — crc32c per segment in ``crc``
mode, HMAC-SHA256 with a session key (e.g. a cephx session key,
ceph_tpu/auth) in ``secure`` mode.

The deterministic in-process MessageBus stays the DELIVERY substrate
(SURVEY §5's comm-backend note: ICI collectives carry the math; the bus
carries control) — this module makes the bus's payloads REAL bytes:
``MessageBus(wire=...)`` serializes every message through a frame on
send and parses it back on delivery, so type registration, segment
integrity, and codec roundtripping are exercised on every message, and
corruption faults become *detected* frame errors instead of silent
state divergence.

Frame layout (little-endian):

    preamble:  tag u8 | num_segments u8 | flags u16 | seg_len u32 x4 |
               crc32c(preamble) u32
    segments:  bytes  (per segment)
    epilogue:  crc mode: crc32c u32 per segment
               secure mode: HMAC-SHA256[:16] over preamble+segments

Like frames_v2.h, the preamble CRC covers lengths before any payload is
trusted, and a parser never yields a partially-validated frame.
"""
from __future__ import annotations

import hmac
import pickle
import struct
from dataclasses import dataclass
from hashlib import sha256

import numpy as np

from .ecutil import crc32c

BANNER = b"ceph_tpu msgr v2\n"
MAX_SEGMENTS = 4                        # frames_v2.h MAX_NUM_SEGMENTS
_PREAMBLE = struct.Struct("<BBH4I")
_CRC = struct.Struct("<I")
_MAC_LEN = 16                           # truncated HMAC-SHA256

# frame tags (ProtocolV2 Tag enum shape)
TAG_HELLO = 1
TAG_AUTH = 2
TAG_MESSAGE = 17


class WireError(Exception):
    """Framing/integrity violation (the reference drops the connection)."""


def _crc(data: bytes) -> int:
    return crc32c(0xFFFFFFFF, data) ^ 0xFFFFFFFF


def frame_encode(tag: int, segments: list[bytes], *,
                 secret: bytes | None = None) -> bytes:
    """One frame; ``secret`` switches crc mode -> secure (HMAC) mode."""
    if not 1 <= len(segments) <= MAX_SEGMENTS:
        raise WireError(f"{len(segments)} segments (1..{MAX_SEGMENTS})")
    lens = [len(s) for s in segments] + [0] * (MAX_SEGMENTS - len(segments))
    pre = _PREAMBLE.pack(tag, len(segments), 0, *lens)
    out = [pre, _CRC.pack(_crc(pre))]
    out += segments
    if secret is None:
        out += [_CRC.pack(_crc(s)) for s in segments]
    else:
        mac = hmac.new(secret, pre + b"".join(segments), sha256).digest()
        out.append(mac[:_MAC_LEN])
    return b"".join(out)


def frame_encode_parts(tag: int, segments: list, *,
                       secret: bytes | None = None) -> list:
    """:func:`frame_encode` without the payload join: returns the frame
    as an ordered list of buffers for a gather-write path (the async
    connection splices them into its write queue unjoined — ISSUE 20's
    device->wire leg).

    Each entry of ``segments`` is either a bytes-like segment or a LIST
    of bytes-like pieces forming one scattered segment (the sideband's
    length table + spliced payload views).  Byte-for-byte identical on
    the wire to ``frame_encode(tag, [b"".join(...), ...])``: the
    preamble lengths sum the pieces, the HMAC updates incrementally in
    piece order (exactly how :class:`~ceph_tpu.msg.parser.StreamParser`
    verifies), and the crc-mode epilogue seed-chains across pieces.
    Small control pieces coalesce into the head/tail buffers; only the
    large scattered pieces stay unjoined, so queue entries stay O(payloads).
    """
    if not 1 <= len(segments) <= MAX_SEGMENTS:
        raise WireError(f"{len(segments)} segments (1..{MAX_SEGMENTS})")
    flat = [s if isinstance(s, list) else [s] for s in segments]
    lens = [sum(len(p) for p in seg) for seg in flat]
    pre = _PREAMBLE.pack(tag, len(segments), 0,
                         *(lens + [0] * (MAX_SEGMENTS - len(segments))))
    parts: list = []
    head = [pre, _CRC.pack(_crc(pre))]

    def _flush_head():
        if head:
            parts.append(b"".join(head) if len(head) > 1 else head[0])
            head.clear()

    if secret is not None:
        h = hmac.new(secret, pre, sha256)
        for seg in flat:
            for p in seg:
                h.update(p)
                if isinstance(p, memoryview) and len(p) >= 1024:
                    _flush_head()
                    parts.append(p)
                else:
                    head.append(bytes(p) if isinstance(p, memoryview)
                                else p)
        head.append(h.digest()[:_MAC_LEN])
    else:
        tail = []
        for seg in flat:
            c = 0xFFFFFFFF
            for p in seg:
                c = crc32c(c, p if isinstance(p, bytes)
                           else np.frombuffer(p, dtype=np.uint8))
                if isinstance(p, memoryview) and len(p) >= 1024:
                    _flush_head()
                    parts.append(p)
                else:
                    head.append(bytes(p) if isinstance(p, memoryview)
                                else p)
            tail.append(_CRC.pack(c ^ 0xFFFFFFFF))
        head.extend(tail)
    _flush_head()
    return parts


class FrameParser:
    """Incremental parser: feed bytes, yields (tag, segments) frames.
    Partial input yields nothing until the full frame (and its
    integrity data) arrives — no partially-validated output."""

    def __init__(self, secret: bytes | None = None):
        self.secret = secret
        self._buf = bytearray()
        # opt-in (wire accounting): when True, each parsed frame's REAL
        # on-wire length (preamble + crcs/mac + body) is appended here in
        # frame order; the consumer drains the list after every feed()
        self.track_sizes = False
        self.frame_sizes: list[int] = []

    def feed(self, data: bytes) -> list[tuple[int, list[bytes]]]:
        self._buf += data
        frames = []
        while True:
            f = self._try_parse()
            if f is None:
                return frames
            frames.append(f)

    def _try_parse(self):
        head = _PREAMBLE.size + _CRC.size
        if len(self._buf) < head:
            return None
        pre = bytes(self._buf[:_PREAMBLE.size])
        (want_crc,) = _CRC.unpack_from(self._buf, _PREAMBLE.size)
        if _crc(pre) != want_crc:
            raise WireError("preamble crc mismatch")
        tag, nseg, flags, *lens = _PREAMBLE.unpack(pre)
        if not 1 <= nseg <= MAX_SEGMENTS:
            raise WireError(f"bad segment count {nseg}")
        seg_lens = lens[:nseg]
        body = sum(seg_lens)
        tail = (_MAC_LEN if self.secret is not None
                else _CRC.size * nseg)
        total = head + body + tail
        if len(self._buf) < total:
            return None
        segs, off = [], head
        for ln in seg_lens:
            segs.append(bytes(self._buf[off:off + ln]))
            off += ln
        if self.secret is None:
            for i, s in enumerate(segs):
                (want,) = _CRC.unpack_from(self._buf, off + i * _CRC.size)
                if _crc(s) != want:
                    raise WireError(f"segment {i} crc mismatch")
        else:
            want = bytes(self._buf[off:off + _MAC_LEN])
            mac = hmac.new(self.secret, pre + b"".join(segs),
                           sha256).digest()[:_MAC_LEN]
            if not hmac.compare_digest(want, mac):
                raise WireError("frame MAC mismatch")
        del self._buf[:total]
        if self.track_sizes:
            self.frame_sizes.append(total)
        return tag, segs


# -- message codec ----------------------------------------------------------

def message_encode(msg, *, secret: bytes | None = None) -> bytes:
    """A bus message as one MESSAGE frame: segment 0 = type name,
    segment 1 = payload (the reference's header/payload segment split)."""
    return frame_encode(
        TAG_MESSAGE,
        [type(msg).__name__.encode(), pickle.dumps(msg)],
        secret=secret)


def message_decode(tag: int, segs: list[bytes]):
    if tag != TAG_MESSAGE or len(segs) != 2:
        raise WireError(f"not a message frame: tag {tag}")
    from . import messages as m
    name = segs[0].decode()
    klass = getattr(m, name, None)
    if klass is None or not hasattr(klass, "__dataclass_fields__"):
        raise WireError(f"unknown message type {name!r}")
    msg = pickle.loads(segs[1])
    if type(msg).__name__ != name:
        raise WireError("segment type name mismatch")
    return msg


# -- connection handshake ---------------------------------------------------

@dataclass
class Hello:
    """TAG_HELLO payload (ProtocolV2 HelloFrame shape)."""
    entity: str
    features: int = 1


class FramedConnection:
    """One endpoint of a framed byte stream.  Deterministic and
    in-process: ``out`` accumulates bytes for the peer; ``receive``
    consumes peer bytes, returning decoded messages after the handshake
    completes.  Banner first, then HELLO frames, then messages."""

    def __init__(self, entity: str, secret: bytes | None = None):
        self.entity = entity
        self.secret = secret
        self.parser = FrameParser(secret)
        self.out = bytearray()
        self.peer_hello: Hello | None = None
        self._banner_buf = bytearray()
        self._banner_seen = False
        self.out += BANNER
        self.out += frame_encode(
            TAG_HELLO, [pickle.dumps(Hello(entity))], secret=secret)

    @property
    def ready(self) -> bool:
        return self.peer_hello is not None

    def send(self, msg) -> None:
        if not self.ready:
            raise WireError("handshake incomplete")
        self.out += message_encode(msg, secret=self.secret)

    def receive(self, data: bytes) -> list:
        msgs = []
        if not self._banner_seen:
            # buffer like the frame parser: a banner split across reads
            # is normal stream behavior, not an error
            self._banner_buf += data
            if len(self._banner_buf) < len(BANNER):
                return msgs
            if self._banner_buf[:len(BANNER)] != BANNER:
                raise WireError(
                    f"banner mismatch: "
                    f"{bytes(self._banner_buf[:len(BANNER)])!r}")
            self._banner_seen = True
            data = bytes(self._banner_buf[len(BANNER):])
            self._banner_buf.clear()
        for tag, segs in self.parser.feed(data):
            if tag == TAG_HELLO:
                self.peer_hello = pickle.loads(segs[0])
            else:
                msgs.append(message_decode(tag, segs))
        return msgs
