"""EC sub-op wire payloads and the in-process message bus.

Analog of the reference's ``ECSubWrite``/``ECSubRead``(+replies) payloads
(reference: src/osd/ECMsgTypes.h:23-129) carried by
``MOSDECSubOpWrite/Read`` messages, and of the messenger fan-out that moves
them between shards (reference: src/osd/ECBackend.cc:2036-2070).  The bus is
deterministic: sends enqueue, ``deliver_all`` drains — tests step it to
exercise pipeline orderings; a down shard silently drops its queue the way a
dead OSD would.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

from .extent import ExtentSet
from .memstore import Transaction
from ..common import wire_accounting
from ..common.tracer import default_tracer
# the bus fault plane now lives in the unified failure/ schema (one
# schema, one seed across bus/transport/store/device); re-exported here
# so every existing `from ceph_tpu.backend.messages import FaultConfig`
# keeps working
from ..failure.config import FaultConfig  # noqa: F401  (re-export)


@dataclass
class ECSubWrite:
    """Primary -> shard: apply this shard-local transaction (ECMsgTypes.h:23-38).

    ``log_entries`` ride along exactly like the reference's (ECSubWrite
    carries the op's pg_log entries so every shard's log advances with the
    write); ``at_version``/``trim_to`` are the eversion bump and the
    piggybacked trim point."""
    from_shard: int
    tid: int
    t: Transaction
    at_version: int = 0
    trim_to: int = 0
    log_entries: list = field(default_factory=list)
    backfill_or_async_recovery: bool = False
    # two-phase commit: entries <= this are stable cluster-wide, the shard
    # may drop their rollback data (the reference piggybacks
    # roll_forward_to on every sub-write, ECMsgTypes.h:23-38)
    roll_forward_to: int = 0
    # dispatch generation: a rolled-back-and-reissued op bumps this so the
    # primary can tell fresh acks from stale ones (the role op reqids and
    # the osdmap epoch stamp play in the reference)
    gen: int = 0
    # distributed-trace context (stamped by PGChannel.send from the
    # sender's active trace): the receiving shard's spans stitch under
    # the originating client op
    trace: object = None


@dataclass
class ECSubWriteReply:
    """Shard -> primary: committed/applied acks (ECMsgTypes.h:91-102)."""
    from_shard: int
    tid: int
    committed: bool = True
    applied: bool = True
    gen: int = 0


@dataclass
class RollForward:
    """Primary -> shard: entries <= ``to`` are committed on min_size shards;
    drop their rollback data.  The standalone kick the reference sends as a
    dummy transaction when the pipeline drains (ECBackend.cc:2106-2120)."""
    from_shard: int
    to: int


@dataclass
class Rollback:
    """Primary -> shard: undo every logged entry with version > ``to`` using
    the rollback info captured at apply time, and rewind your log.  The
    divergent-entry rollback of the reference's peering
    (doc/dev/osd_internals/erasure_coding/ecbackend.rst:149-174)."""
    from_shard: int
    to: int


@dataclass
class ECSubRead:
    """Primary -> shard: read chunk extents, optionally sub-chunk runs
    (ECMsgTypes.h:105-116; sub-chunks serve clay, ECBackend.cc:985-1031)."""
    from_shard: int
    tid: int
    # oid -> list of (chunk-space offset, length, subchunk_runs|None)
    to_read: dict[str, list[tuple]] = field(default_factory=dict)
    attrs_to_read: set[str] = field(default_factory=set)
    include_omap: bool = False     # replicated recovery moves omap too
    # denominator for subchunk_runs (codec's get_sub_chunk_count(); the
    # reference ships it inside the run offsets, ECMsgTypes.h:105-116)
    sub_chunk_count: int = 1
    # distributed-trace context (see ECSubWrite.trace)
    trace: object = None


@dataclass
class ECSubReadReply:
    """Shard -> primary (ECMsgTypes.h:118-129)."""
    from_shard: int
    tid: int
    buffers_read: dict[str, list[tuple[int, bytes]]] = field(default_factory=dict)
    attrs_read: dict[str, dict] = field(default_factory=dict)
    # oid -> (omap kvs, omap header) when include_omap was set
    omap_read: dict[str, tuple] = field(default_factory=dict)
    errors: dict[str, int] = field(default_factory=dict)


@dataclass
class PushOp:
    """Recovery payload: reconstructed chunk data for a missing shard
    (reference: src/osd/ECBackend.cc:284-360 shape)."""
    from_shard: int
    oid: str
    data: bytes
    attrs: dict = field(default_factory=dict)
    version: int = 0
    # None = leave omap alone (EC chunks have none); dict = replace
    omap: dict | None = None
    omap_header: bytes = b""


@dataclass
class PushReply:
    from_shard: int
    oid: str


@dataclass
class PGLogQuery:
    """Primary -> shard: report your log state (the pg_query_t/pg_info_t
    exchange peering opens with, reference: src/osd/PeeringState.cc
    GetInfo; ``since`` bounds the entry payload of the reply)."""
    from_shard: int
    since: int = 0


@dataclass
class PGLogInfo:
    """Shard -> primary: last_update + entries after ``since`` (pg_info_t
    plus the log segment merge_log would examine)."""
    from_shard: int
    last_update: int
    tail: int
    entries: list = field(default_factory=list)


@dataclass
class PGScan:
    """Primary -> shard: list your objects (the backfill scan,
    reference: MOSDPGScan / PrimaryLogPG::do_scan)."""
    from_shard: int


@dataclass
class PGScanReply:
    from_shard: int
    oids: list = field(default_factory=list)


@dataclass
class PGLogUpdate:
    """Primary -> shard: adopt this authoritative log segment (the rewind/
    catch-up half of merge_log).  Entries replace everything the shard has
    past ``rewind_to``; last_update becomes ``last_update``."""
    from_shard: int
    entries: list = field(default_factory=list)
    last_update: int = 0
    rewind_to: int = 0
    trim_to: int = 0


@dataclass
class PGActivate:
    """Primary -> replica: peering is done, serve at this epoch (the
    MOSDPGLog-with-activation the Activating state fans out,
    reference: PeeringState::Active constructor / activate())."""
    from_shard: int
    epoch: int
    head: int = 0                 # authority log head at activation


@dataclass
class PGActivateAck:
    """Replica -> primary: activated (reference: the peer_activated set
    PeeringState::Active collects before pg goes clean)."""
    from_shard: int
    epoch: int


@dataclass
class ECPartialSum:
    """Hop -> next hop: chained streaming repair leg (RapidRAID-style
    pipelined partial sums, PAPERS.md arXiv:1207.6744).  Each survivor
    GF-scales its local chunk by its decode coefficients and XORs the
    result into ``acc`` before forwarding, so the newcomer receives ~1x
    the lost bytes instead of the primary pulling k full shards."""
    from_shard: int
    tid: int
    coordinator: int              # shard Applied/Abort replies go to
    oids: list = field(default_factory=list)       # plan order
    lengths: list = field(default_factory=list)    # per-oid chunk bytes
    versions: list = field(default_factory=list)   # per-oid pg_log version
    rows: list = field(default_factory=list)       # erased chunks, acc order
    targets: list = field(default_factory=list)    # target shard per row
    # remaining legs: [(shard, chunk, ((coeff per row)...)), ...]
    hops: list = field(default_factory=list)
    attrs: dict = field(default_factory=dict)      # oid -> replicated attrs
    # one running partial-sum buffer per erased row (concatenation over
    # plan oids; sliced apart by ``lengths`` at the final hop)
    acc: list | None = None
    use_device: bool = False
    trace: object = None


@dataclass
class ECPartialSumApply:
    """Final hop -> repair target: one reconstructed chunk, applied like
    a PushOp (stale-version guard included) but scoped to the chain tid."""
    from_shard: int
    tid: int
    coordinator: int
    oid: str
    data: bytes
    attrs: dict = field(default_factory=dict)
    trace: object = None


@dataclass
class ECPartialSumApplied:
    """Repair target -> coordinator: chunk for ``oid`` is durable."""
    from_shard: int
    tid: int
    oid: str


@dataclass
class ECPartialSumAbort:
    """Any hop -> coordinator: the chain cannot complete (missing or
    rotten local chunk, version skew, misroute); coordinator falls back
    to centralized verified repair for the unfinished objects."""
    from_shard: int
    tid: int
    reason: str = ""
    trace: object = None


@dataclass
class ECRegenRead:
    """Coordinator -> one leg of a regenerating repair (product-matrix
    MSR/MBR, arXiv:1412.3022).  The same message serves both legs:

    - **helper leg** (``proj`` set): project your stored chunk's
      ``sub_count`` symbol rows by the 1 x sub_count coefficient row and
      ship the beta-stream to ``target`` via :class:`ECRegenHelper`;
    - **newcomer leg** (``combine`` set): expect ``len(helpers)``
      beta-streams per oid, combine them by the sub_count x d matrix
      into the lost chunk, verify, apply, ack the coordinator.

    Validation mirrors the chain hops (PR 12's verification-first rule):
    any mismatch aborts to the coordinator, which falls back to
    centralized waves."""
    from_shard: int
    tid: int
    coordinator: int              # shard Applied/Abort replies go to
    target: int                   # newcomer shard the beta-streams converge on
    chunk: int                    # receiver's chunk id (helper: its own; newcomer: the lost one)
    sub_count: int = 1            # alpha symbol rows per stored chunk
    proj: bytes = b""             # helper leg: 1 x alpha projection row
    combine: bytes = b""          # newcomer leg: alpha x d combine matrix (row-major)
    helpers: list = field(default_factory=list)   # newcomer leg: helper chunks, stream order
    oids: list = field(default_factory=list)      # plan order
    lengths: list = field(default_factory=list)   # per-oid STORED chunk bytes
    versions: list = field(default_factory=list)  # per-oid pg_log version
    attrs: dict = field(default_factory=dict)     # oid -> replicated attrs
    use_device: bool = False
    trace: object = None


@dataclass
class ECRegenHelper:
    """Helper -> newcomer: the beta-byte inner-product streams — the d
    small shipments that replace k full-chunk reads (MBR: d*beta equals
    ONE chunk; MSR: d/alpha chunks)."""
    from_shard: int
    tid: int
    coordinator: int
    chunk: int                    # helper's chunk id (stream-order key)
    streams: dict = field(default_factory=dict)   # oid -> beta bytes
    trace: object = None


# -- wire accounting (common/wire_accounting.py) -----------------------------
#
# Every PG message type registers its payload sizer here, next to its
# definition, so the non-framed in-process bus can charge honest byte
# counts (the wire-mode bus and net.py use real frame lengths).  The
# sizers weigh the fields that dominate on a real wire — chunk buffers,
# transactions, log entries; fixed-size headers ride the shared
# MSG_OVERHEAD.  tests/test_wire_guard.py fails the build if a message
# class lands here without one: no unmetered message types.

_blob = wire_accounting.blob_size

wire_accounting.register_wire_sizes({
    ECSubWrite: lambda m: _blob(m.t.ops) + _blob(m.log_entries),
    ECSubWriteReply: lambda m: 16,
    RollForward: lambda m: 8,
    Rollback: lambda m: 8,
    ECSubRead: lambda m: _blob(m.to_read) + _blob(m.attrs_to_read),
    ECSubReadReply: lambda m: (_blob(m.buffers_read) + _blob(m.attrs_read)
                               + _blob(m.omap_read)),
    PushOp: lambda m: (len(m.data) + _blob(m.attrs) + _blob(m.omap)
                       + len(m.omap_header)),
    PushReply: lambda m: len(m.oid),
    PGLogQuery: lambda m: 8,
    PGLogInfo: lambda m: 16 + _blob(m.entries),
    PGScan: lambda m: 8,
    PGScanReply: lambda m: _blob(m.oids),
    PGLogUpdate: lambda m: 24 + _blob(m.entries),
    PGActivate: lambda m: 16,
    PGActivateAck: lambda m: 16,
    ECPartialSum: lambda m: (_blob(m.acc) + _blob(m.hops) + _blob(m.attrs)
                             + _blob(m.oids) + _blob(m.rows)
                             + _blob(m.targets) + 8 * len(m.lengths)
                             + 8 * len(m.versions)),
    ECPartialSumApply: lambda m: (len(m.data) + _blob(m.attrs)
                                  + len(m.oid) + 16),
    ECPartialSumApplied: lambda m: 16 + len(m.oid),
    ECPartialSumAbort: lambda m: 16 + len(m.reason),
    ECRegenRead: lambda m: (len(m.proj) + len(m.combine) + _blob(m.helpers)
                            + _blob(m.oids) + _blob(m.attrs)
                            + 8 * len(m.lengths) + 8 * len(m.versions)
                            + 16),
    ECRegenHelper: lambda m: _blob(m.streams) + 24,
    # the cluster-bus wrapper: header + the routed payload
    "PGEnvelope": lambda m: 16 + wire_accounting.wire_size(m.msg),
})


@dataclass
class _WireEnvelope:
    """A framed message in flight (wire-mode bus): real bytes between
    send and delivery.  from_shard survives outside the frame so the
    reorder scheduler keeps per-sender FIFO without parsing."""
    from_shard: int | None
    frame: bytes


@dataclass
class PGEnvelope:
    """Cluster-bus wrapper routing a PG-scoped message to the right PG on
    the destination OSD — the analog of the spg_t every reference OSD
    message carries for dispatch (src/osd/OSD.cc ms_fast_dispatch).
    ``from_shard`` mirrors the inner message's so reorder fault injection
    keeps per-sender FIFO semantics."""
    pgid: object
    msg: object
    from_shard: int | None = None
    # the sender's active TraceContext: the destination OSD activates it
    # around dispatch so its spans join the originating op's trace
    trace: object = None


class OSDEndpoint:
    """ONE bus registration per OSD: demuxes PGEnvelopes to the per-PG
    channels hosted on this OSD (the reference OSD's single messenger
    endpoint feeding many PGs)."""

    def __init__(self, osd: int):
        self.osd = osd
        self.pg_channels: dict = {}       # pgid -> PGChannel

    def handle_message(self, msg) -> None:
        if not isinstance(msg, PGEnvelope):
            raise TypeError(
                f"OSD endpoint {self.osd} got non-enveloped {type(msg)}")
        ch = self.pg_channels.get(msg.pgid)
        if ch is None:
            return           # PG deleted/moved: drop, like an unknown spg_t
        handler = ch.handlers.get(self.osd)
        if handler is None:
            return
        # the payload's own trace field (ECSubRead/ECSubWrite) wins: it
        # is stamped once and stays stable across reissues, while the
        # envelope's is whatever context the (re)sender held
        ctx = getattr(msg.msg, "trace", None) or msg.trace
        if ctx is None:
            handler.handle_message(msg.msg)
            return
        # a traced message: this OSD's dispatch becomes a child span on
        # its own track, so the stitched Chrome trace shows the sub-op
        # crossing the daemon boundary (client -> primary -> this shard)
        tr = default_tracer()
        with tr.activate(ctx, track=f"osd.{self.osd}"), \
                tr.span(f"osd.{type(msg.msg).__name__}", cat="rpc",
                        owner=ctx.op_class):
            handler.handle_message(msg.msg)


class PGChannel:
    """A PG's view of the shared cluster bus.

    Exposes the MessageBus surface the PG backends use (send/register/
    handlers/down/mark_*/deliver_*/listeners/fault injection) while the
    actual queues, down-set, and delivery loop live on ONE cluster-wide
    MessageBus with one OSDEndpoint per OSD — the reference's topology
    (one messenger per OSD, many PGs behind it).  Down/up are OSD-wide:
    killing an OSD affects every PG it serves, exactly like a real death.
    """

    def __init__(self, bus: MessageBus, pgid):
        self.bus = bus
        self.pgid = pgid
        self.handlers: dict[int, object] = {}   # this PG's shard handlers

    def register(self, shard: int, handler) -> None:
        self.handlers[shard] = handler
        ep = self.bus.handlers.get(shard)
        if not isinstance(ep, OSDEndpoint):
            ep = OSDEndpoint(shard)
            self.bus.register(shard, ep)
        ep.pg_channels[self.pgid] = self

    def unregister_all(self) -> None:
        """Drop this PG from every OSD endpoint (PG teardown)."""
        for ep in self.bus.handlers.values():
            if isinstance(ep, OSDEndpoint):
                ep.pg_channels.pop(self.pgid, None)

    def send(self, to_shard: int, msg) -> None:
        # trace propagation across the daemon boundary: stamp the
        # sender's active context onto the envelope AND onto payloads
        # that declare a trace field (ECSubRead/ECSubWrite — the wire
        # shape the reference's blkin hooks annotate)
        ctx = default_tracer().current_ctx()
        if ctx is not None and getattr(msg, "trace", True) is None:
            msg.trace = ctx
        self.bus.send(to_shard, PGEnvelope(
            self.pgid, msg, getattr(msg, "from_shard", None), trace=ctx))

    # -- delegation to the shared bus ---------------------------------------

    @property
    def down(self) -> set[int]:
        return self.bus.down

    def mark_down(self, shard: int) -> None:
        self.bus.mark_down(shard)

    def mark_up(self, shard: int) -> None:
        self.bus.mark_up(shard)

    def deliver_one(self, shard: int) -> bool:
        return self.bus.deliver_one(shard)

    def deliver_all(self, max_rounds: int = 10000) -> int:
        return self.bus.deliver_all(max_rounds)

    def inject_faults(self, cfg) -> None:
        self.bus.inject_faults(cfg)

    @property
    def down_listeners(self) -> list:
        return self.bus.down_listeners

    @property
    def up_listeners(self) -> list:
        return self.bus.up_listeners

    @property
    def queues(self):
        return self.bus.queues

    @property
    def wire(self) -> bool:
        return self.bus.wire

    @property
    def wire_secret(self):
        return self.bus.wire_secret

    @property
    def delivered(self) -> int:
        return self.bus.delivered

    @property
    def dropped(self) -> int:
        return self.bus.dropped

    @property
    def duplicated(self) -> int:
        return self.bus.duplicated


class MessageBus:
    """Per-shard FIFO queues; handlers registered per shard id.

    ``wire=True`` runs every message through the v2-style frame codec
    (backend/wire.py): send serializes to integrity-protected bytes,
    delivery parses them back — so codec/registration bugs and corrupted
    payloads surface as frame errors instead of silent shared-object
    aliasing.  ``wire_secret`` switches the frames from crc to secure
    (HMAC) mode, e.g. with a cephx session key."""

    def __init__(self, wire: bool = False, wire_secret: bytes | None = None):
        self.queues: dict[int, deque] = {}
        self.handlers: dict[int, object] = {}
        self.down: set[int] = set()
        self.wire = wire
        self.wire_secret = wire_secret
        self.delivered = 0
        self.dropped = 0
        self.duplicated = 0
        # optional WireAccounting (common/wire_accounting.py): when set,
        # every send charges byte/op counters per message type and per
        # owner op class — the in-process half of wire observability
        self.wire_stats = None
        # failure/revival notification fan-out: the reference's analog is the
        # osdmap epoch bump reaching each OSD after heartbeats report it
        self.down_listeners: list = []
        self.up_listeners: list = []
        # called at the top of deliver_all: the cluster hooks its daemon
        # op-queue drains here so "deliver everything" includes client
        # ops parked on live daemons (e.g. queued while their OSD was
        # down), matching the pre-shared-bus progress guarantees
        self.pre_deliver_hooks: list = []
        self._faults: FaultConfig | None = None
        self._fault_rng = None
        # optional event sink: fn(plane, kind, target=..., **detail) —
        # a FaultInjector.record, so bus drops/dups/reorders land in the
        # same seeded campaign log as every other plane's events
        self.fault_log = None

    def inject_faults(self, cfg) -> None:
        """Enable (or, with None, disable) fault injection.  Accepts the
        legacy bus :class:`FaultConfig` or a whole
        :class:`~ceph_tpu.failure.config.FaultPlan` (its bus plane, with
        the campaign seed, is what applies here)."""
        if cfg is not None and hasattr(cfg, "bus_config"):
            cfg = cfg.bus_config()
        self._faults = cfg
        if cfg is not None:
            import random
            self._fault_rng = random.Random(cfg.seed)

    def register(self, shard: int, handler) -> None:
        self.queues.setdefault(shard, deque())
        self.handlers[shard] = handler

    def mark_down(self, shard: int) -> None:
        """Drop the shard: pending + future messages to it vanish (a dead
        OSD's socket resets; the reference learns via heartbeats+osdmap).
        Edge-triggered: marking an already-down shard is a no-op, so the
        per-PG fan-out over a shared bus fires listeners exactly once."""
        if shard in self.down:
            return
        self.down.add(shard)
        if shard in self.queues:
            self.queues[shard].clear()
        for cb in self.down_listeners:
            cb(shard)

    def mark_up(self, shard: int) -> None:
        if shard not in self.down:
            return
        self.down.discard(shard)
        for cb in self.up_listeners:
            cb(shard)

    def send(self, to_shard: int, msg) -> None:
        if to_shard in self.down:
            return
        f = self._faults
        if f is not None and f.drop_prob and \
                self._fault_rng.random() < f.drop_prob:
            self.dropped += 1
            if self.fault_log is not None:
                self.fault_log("bus", "drop", target=to_shard)
            return
        acct = self.wire_stats
        # attribute to the PAYLOAD's type and trace — the envelope is
        # routing; the payload's own stamped ctx wins over the envelope's
        # (the precedence OSDEndpoint.handle_message applies) — but SIZE
        # the whole thing the wire carries, envelope included
        inner = msg.msg if isinstance(msg, PGEnvelope) else msg
        ctx = getattr(inner, "trace", None) or getattr(msg, "trace", None)
        # wire-mode buses charge the REAL frame length below: skip the
        # sizer walk entirely rather than estimate-then-discard
        nbytes = wire_accounting.wire_size(msg) \
            if acct is not None and not self.wire else None
        if self.wire:
            from .wire import message_encode
            sender = getattr(msg, "from_shard", None)
            frame = message_encode(msg, secret=self.wire_secret)
            if acct is not None:
                nbytes = len(frame)      # real framed bytes on this bus
            msg = _WireEnvelope(sender, frame)
        q = self.queues.setdefault(to_shard, deque())
        if acct is not None:
            acct.account_msg(inner, nbytes=nbytes, ctx=ctx)
            acct.note_queue_depth(len(q) + 1)
        q.append(msg)

    def _pick(self, q: deque):
        """Next message to deliver.  Under reorder injection: the earliest
        message of a uniformly random sender (per-sender FIFO preserved,
        cross-sender order randomized)."""
        f = self._faults
        if f is None or not f.reorder or len(q) < 2:
            return q.popleft()
        senders, seen = [], set()
        for m in q:
            s = getattr(m, "from_shard", None)
            if s not in seen:
                seen.add(s)
                senders.append(s)
        pick = self._fault_rng.choice(senders)
        for i, m in enumerate(q):
            if getattr(m, "from_shard", None) == pick:
                del q[i]
                return m
        return q.popleft()        # unreachable

    def deliver_one(self, shard: int) -> bool:
        q = self.queues.get(shard)
        if not q or shard in self.down:
            return False
        msg = self._pick(q)
        if isinstance(msg, _WireEnvelope):
            from .wire import FrameParser, message_decode
            parser = FrameParser(self.wire_secret)
            [(tag, segs)] = parser.feed(msg.frame)
            msg = message_decode(tag, segs)
        handler = self.handlers[shard]
        handler.handle_message(msg)
        self.delivered += 1
        f = self._faults
        if f is not None and f.dup_prob and \
                self._fault_rng.random() < f.dup_prob and \
                shard not in self.down:
            # immediate redelivery: the resend after a connection reset
            self.duplicated += 1
            if self.fault_log is not None:
                self.fault_log("bus", "dup", target=shard)
            handler.handle_message(msg)
        return True

    def deliver_all(self, max_rounds: int = 10000) -> int:
        """Drain every queue to quiescence; returns messages delivered."""
        n = 0
        for _ in range(max_rounds):
            for hook in self.pre_deliver_hooks:
                hook()
            progressed = False
            for shard in list(self.queues):
                while self.deliver_one(shard):
                    progressed = True
                    n += 1
            if not progressed:
                return n
        raise RuntimeError("message storm: bus did not quiesce")
