"""The erasure-coded backend: write pipeline, reconstructing reads, recovery.

Analog of the reference's ``ECBackend`` (reference: src/osd/ECBackend.{h,cc};
design note ECBackend.h:520-564) restructured TPU-first:

- Same three-stage ordered write pipeline — ``waiting_state ->
  waiting_reads -> waiting_commit`` — inherited from
  :class:`~ceph_tpu.backend.pg_backend.PGBackend` (the PGBackend.h:628
  abstraction shared with :class:`~ceph_tpu.backend.replicated.
  ReplicatedBackend`), with this class supplying the EC-specific hooks:
  RMW write planning, batched encode, reconstructing reads, and
  minimum_to_decode-driven recovery.
- Same sub-op fan-out over a messenger (the deterministic
  :class:`~ceph_tpu.backend.messages.MessageBus`), one shard-local
  transaction per acting shard (ECBackend.cc:2036-2070), self-delivery for
  the primary's own shard (:2059-2061).
- BUT encode/decode are **batched across all stripes of an op** into one
  device call via :mod:`ceph_tpu.backend.ecutil` instead of the reference's
  per-stripe loop — the restructuring SURVEY.md §2.2 calls the main TPU hook.

Shards are ``OSDShard`` objects (ObjectStore + handler).  Failure is modelled
by ``bus.mark_down``: a dead shard drops requests, the primary routes around
it using ``minimum_to_decode`` exactly like degraded reads do in the
reference (ECBackend.cc:1588-1625), and ``recover_object`` runs the
IDLE->READING->WRITING->COMPLETE machine (ECBackend.h:249-293).
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .bluestore import ChecksumError
from .ecutil import HINFO_KEY, HashInfo, StripeInfo, crc32c, decode_shards
from . import ecutil
from .extent import ExtentSet
from .extent_cache import ExtentCache
from .memstore import GObject, Transaction
from .messages import (ECPartialSumAbort, ECPartialSumApplied, ECSubRead,
                       ECSubReadReply, MessageBus, PushOp)
from .pg_backend import (Op, OSDShard, PG_META, PGBackend, RecoveryOp,
                         shard_store,
                         RecoveryState, RepairState, ShardRepairOp,
                         _slice_subchunks)
from .transaction import get_write_plan
from ..common.tracer import trace_span
from ..osd.pg_log import OP_DELETE, OP_MODIFY

__all__ = ["ECBackend", "OSDShard", "RecoveryState", "RecoveryOp",
           "RepairState", "ShardRepairOp", "Op", "ReadOp", "PG_META",
           "make_cluster"]


@dataclass
class _RecoveryWave:
    """One batch-fused recovery wave (the recovery scheduler's unit of
    work): many degraded objects read together — one ECSubRead per source
    shard carrying every oid — and reconstructed through ONE
    ``ecutil.decode_shards_many`` dispatch per survivor signature."""
    tid: int
    oids: dict[str, set[int]]            # oid -> missing chunks
    on_each: object                      # on_each(oid, ok)
    at_version: dict[str, int] = field(default_factory=dict)
    pending_sources: set[int] = field(default_factory=set)
    results: dict[str, dict[int, bytes]] = field(default_factory=dict)
    attrs: dict[str, dict[int, dict]] = field(default_factory=dict)
    # oids dropping to the battle-tested per-object path (read errors,
    # version bumps mid-read, too few survivors)
    fallback: set[str] = field(default_factory=set)
    pending_pushes: dict[str, set[int]] = field(default_factory=dict)
    failed: set[str] = field(default_factory=set)


@dataclass
class ReadOp:
    """In-flight client read (ECBackend::ReadOp, ECBackend.h:155-190)."""
    tid: int
    to_read: dict[str, list[tuple[int, int]]]     # oid -> [(logical off, len)]
    on_complete: object
    shard_extents: dict[str, tuple[int, int]] = field(default_factory=dict)  # oid -> (chunk off, len)
    want_shards: dict[str, set[int]] = field(default_factory=dict)
    # shard -> outstanding reply count (retries can address a shard twice)
    pending_shards: dict[int, int] = field(default_factory=dict)
    results: dict[str, dict[int, bytes]] = field(default_factory=dict)  # oid -> {shard: chunk bytes}
    errors: dict[str, set[int]] = field(default_factory=dict)
    tried_shards: dict[str, set[int]] = field(default_factory=dict)
    for_recovery: bool = False


class ECBackend(PGBackend):
    """Primary-side EC backend over a set of shard OSDs on a message bus."""

    def __init__(self, ec_impl, sinfo: StripeInfo, bus: MessageBus,
                 acting: list[int], whoami: int = 0, cct=None,
                 name: str = "", min_size: int = 0, store=None):
        n = ec_impl.get_chunk_count()
        assert len(acting) == n, f"acting set must have {n} shards"
        self.ec_impl = ec_impl
        self.sinfo = sinfo
        # regenerating MBR chunks expand on disk: let the plugin pin the
        # stored size so shard extents/hinfo stay in real on-disk units
        # (one hook covers every StripeInfo construction site)
        stored_hook = getattr(ec_impl, "get_stored_chunk_size", None)
        if stored_hook is not None:
            sinfo.stored_chunk_size = int(stored_hook(sinfo.chunk_size))
        # min_size floored at k: an ack on fewer than k shards would be
        # unreadable data, which is exactly the loss the gate prevents
        super().__init__(bus, acting, whoami=whoami, cct=cct, name=name,
                         min_size=min_size,
                         min_size_floor=ec_impl.get_data_chunk_count(),
                         store=store, perf_prefix="ec_backend")
        # RMW pipeline reads get a fresh tid per dispatch so replies from a
        # superseded dispatch (shard death re-issue, rollback re-queue)
        # find no mapping and drop instead of polluting the op's buffers
        self._rmw_read_tids: dict[int, Op] = {}
        self.extent_cache = ExtentCache()
        self.in_progress_reads: dict[int, ReadOp] = {}
        self.hinfo_cache: dict[str, HashInfo] = {}
        # batched recovery waves in their READ phase, keyed by read tid
        # (push-phase tracking lives in PGBackend._wave_pushes)
        self._recovery_waves: dict[int, _RecoveryWave] = {}
        # in-flight partial-sum chains (recovery/chain.py), keyed by tid
        self._recovery_chains: dict[int, object] = {}
        # optional serving engine (ceph_tpu/exec): when attached, encode/
        # decode dispatches route through its admission+coalescing queue
        # so CONCURRENT ops across PGs fuse into one device batch
        self.serving = None

    def attach_serving(self, engine) -> None:
        """Route this backend's codec dispatches through a
        :class:`~ceph_tpu.exec.ServingEngine` (throttled admission,
        deadline-driven cross-op coalescing, QoS-ordered batching)."""
        self.serving = engine

    def _serving_encode(self, logical) -> dict[int, np.ndarray]:
        if self.serving is not None:
            return self.serving.encode(logical, sinfo=self.sinfo,
                                       ec_impl=self.ec_impl)
        return ecutil.encode(self.sinfo, self.ec_impl, logical)

    def _serving_decode(self, by_chunk) -> bytes:
        if self.serving is not None:
            return self.serving.decode(by_chunk, sinfo=self.sinfo,
                                       ec_impl=self.ec_impl)
        return ecutil.decode(self.sinfo, self.ec_impl, by_chunk)

    # -- EC metadata ---------------------------------------------------------

    def _hinfo(self, oid: str) -> HashInfo:
        if oid not in self.hinfo_cache:
            self.hinfo_cache[oid] = self._read_hinfo(oid)
        return self.hinfo_cache[oid]

    def _read_hinfo(self, oid: str) -> HashInfo:
        """The authoritative stored hinfo, bypassing the cache.  Recovery
        sizes its reads with this: the CACHE may hold an in-flight
        write's projected state, and conversely evicting the cache to
        force a re-read would yank that projection out from under the
        write — it would then commit a STALE hinfo to every shard while
        the data/object-info move forward (observed as permanently short
        reads in the seed-244 soak)."""
        n = self.ec_impl.get_chunk_count()
        stored = None
        # hinfo replicates on every shard's copy: when the primary's
        # own copy is gone (bitrot/lost shard object), any CURRENT
        # peer's attr is the same authority — without this fallback a
        # missing primary copy poisons scrub/size for the whole
        # object (fresh version-0 hinfo marks every shard stale).
        # Stale revived shards are excluded: their hinfo may predate
        # writes they missed (current_shards() semantics).  That
        # applies to the PRIMARY'S OWN copy too — while it is stale
        # (repairing itself), current peers are the authority and the
        # local attr is consulted last.
        peers = [s for s in self.acting if s != self.whoami
                 and s in self.current_shards()]
        local_current = self.whoami in self.current_shards()
        order = ([self.whoami] + peers if local_current
                 else peers + [self.whoami])
        for shard in order:
            if shard not in self.bus.handlers:
                continue
            try:
                stored = shard_store(self.bus, shard).getattr(
                    GObject(oid, shard), HINFO_KEY)
                break
            except (FileNotFoundError, KeyError):
                continue
        h = HashInfo(n)
        if stored is not None:
            h.total_chunk_size = stored["total_chunk_size"]
            h.cumulative_shard_hashes = list(
                stored["cumulative_shard_hashes"])
            h.projected_total_chunk_size = h.total_chunk_size
            h.version = stored.get("version", 0)
        return h

    def object_size(self, oid: str) -> int:
        return self._hinfo(oid).get_total_logical_size(self.sinfo)

    def _on_local_rollback(self) -> None:
        # the authority-side hinfo cache reflects the rolled-back write and
        # must be re-read from the restored xattrs before ops re-plan
        self.hinfo_cache.clear()

    # -- write pipeline hooks ------------------------------------------------

    def _admit_op(self, op: Op) -> None:
        """Plan the RMW (ECBackend.cc:1830-1848) and satisfy reads from the
        extent cache where pinned; the remainder is read remotely when the
        op moves to waiting_reads."""
        if op.plan is None:
            op.plan = get_write_plan(
                self.sinfo, op.t, self._hinfo,
                sub_chunk_count=self.ec_impl.get_sub_chunk_count())

    def _op_blocked(self, op: Op) -> bool:
        """An RMW read overlapping an earlier in-flight write must wait until
        that write's bytes are pinned in the cache — the ordering invariant
        the reference's ExtentCache reservation enforces
        (doc/dev/osd_internals/erasure_coding/ecbackend.rst:190-206)."""
        for oid, to_read in op.plan.to_read.items():
            for off, length in to_read:
                # NB: a cache hit does NOT lift the block — cached bytes may
                # be an older op's; any not-yet-committed overlapping write
                # ahead of us must land in the cache first
                for other in self.waiting_reads:
                    ww = other.plan.will_write.get(oid)
                    if ww is not None and ww.intersects(off, length):
                        return True
        return False

    def _start_op_reads(self, op: Op) -> None:
        """(ECBackend.cc:1856-1928): cache-satisfied extents complete here;
        the rest go to the k data shards as chunk reads."""
        need_remote: dict[str, ExtentSet] = {}
        for oid, to_read in op.plan.to_read.items():
            for off, length in to_read:
                cached = self.extent_cache.read(oid, off, length)
                if cached is not None:
                    op.remote_reads.setdefault(oid, {})[off] = cached
                else:
                    need_remote.setdefault(oid, ExtentSet()).union_insert(
                        off, length)
        if need_remote:
            self._start_rmw_reads(op, need_remote)

    def _start_rmw_reads(self, op: Op, need: dict[str, ExtentSet]) -> None:
        """Read the full stripes from k data shards (reads are stripe-aligned
        whole stripes, so the k data chunks suffice when healthy; degraded
        objects fall back to the reconstructing read path)."""
        k = self.ec_impl.get_data_chunk_count()
        cur = self.current_shards()
        want = {self.ec_impl.chunk_index(i) for i in range(k)}
        avail = {i for i, s in enumerate(self.acting) if s in cur}
        avail -= getattr(op, "_rmw_failed", set())   # rotten sources
        minimum = self.ec_impl.minimum_to_decode(want, avail)
        # degraded RMW of a sub-chunked code (clay): the reconstruction
        # decode needs FULL chunks — a chunk slice is not a smaller
        # codeword when the sub-chunk interleave spans the whole height
        # (same rule as objects_read_and_reconstruct; the gap reads of
        # the planner's forced full-object rewrite hit this degraded)
        whole_chunks = ((self.ec_impl.get_sub_chunk_count() > 1
                         and set(minimum) != want)
                        or getattr(self.ec_impl, "requires_full_chunk_io",
                                   False))
        per_shard: dict[int, dict[str, list[tuple]]] = {}
        for oid, es in need.items():
            for off, length in es:
                c_off = self.sinfo.aligned_logical_offset_to_chunk_offset(off)
                c_len = self.sinfo.aligned_logical_offset_to_chunk_offset(length)
                if whole_chunks:
                    c_off, c_len = 0, None
                for chunk in minimum:
                    shard = self.acting[chunk]
                    entry = (c_off, c_len)
                    ext_list = per_shard.setdefault(shard, {}).setdefault(
                        oid, [])
                    if entry not in ext_list:
                        ext_list.append(entry)
        op._rmw_chunks = {c: self.acting[c] for c in minimum}
        op._rmw_need = need
        op._rmw_buf: dict[str, dict[int, dict[int, bytes]]] = {}
        # restarts (rotten-source retry, stall recovery) may carry stale
        # pending entries/sentinels: this dispatch defines the set
        op.pending_read_shards.clear()
        self._rmw_read_tids.pop(getattr(op, "_rmw_read_tid", None), None)
        self.next_tid += 1
        op._rmw_read_tid = self.next_tid
        self._rmw_read_tids[op._rmw_read_tid] = op
        for shard, to_read in per_shard.items():
            op.pending_read_shards.add(shard)
            self.bus.send(shard, ECSubRead(self.whoami, op._rmw_read_tid,
                                           to_read))

    def _apply_attr_updates(self, oid: str, objop, shard_txns) -> None:
        """Replicate the op's attr updates to every shard's transaction."""
        for shard in self.acting:
            obj = GObject(oid, shard)
            for name, value in objop.attr_updates.items():
                if value is None:
                    shard_txns[shard].rmattr(obj, name)
                else:
                    shard_txns[shard].setattr(obj, name, value)

    def _generate_transactions(self, op: Op):
        """(ECBackend.cc:1930-2087 / ECTransaction.cc generate_transactions):
        encode the will-write extents in one batched device call and
        scatter per-shard chunk writes."""
        n = self.ec_impl.get_chunk_count()
        shard_txns = {shard: Transaction() for shard in self.acting}
        log_entries = []
        for oid, will_write in op.plan.will_write.items():
            objop = op.plan.t.ops[oid]
            if objop.clone_to:
                # snapshot COW: clone the PRE-op shard chunks (+ attrs,
                # incl. hinfo — a chunk-wise clone is exact for EC).
                # Each clone gets its OWN log entry: a shard that missed
                # this transaction must replay the clone too, or log
                # repair would resurrect the head and silently drop the
                # snapshot state (observed: revived shards lost clones).
                for shard in self.acting:
                    src = GObject(oid, shard)
                    for clone_oid in objop.clone_to:
                        shard_txns[shard].clone(src, GObject(clone_oid,
                                                             shard))
                for clone_oid in objop.clone_to:
                    log_entries.append(self.pg_log.append(clone_oid,
                                                          OP_MODIFY))
                if oid in self.inconsistent_objects:
                    # COW copies the DAMAGED state under a new name: the
                    # clone inherits the flag, or the snapshot would
                    # serve laundered corruption while the head's
                    # wholesale-overwrite exoneration erases all trace
                    self.inconsistent_objects.update(objop.clone_to)
            if objop.rollback_from is not None:
                # replace head wholesale with the clone's shard state;
                # the cached head hinfo is now stale — the cloned attrs
                # carry the authoritative one.  attr updates staged by
                # the op engine (object_info/snapset) land ON TOP of the
                # cloned attrs, in the same atomic transaction.
                for shard in self.acting:
                    shard_txns[shard].clone(
                        GObject(objop.rollback_from, shard),
                        GObject(oid, shard))
                # rollback REPLACES the head with the source's state —
                # including its damage status: restoring from a damaged
                # clone flags the head (the COW-laundering fix's mirror
                # direction), restoring from a clean one exonerates it
                if objop.rollback_from in self.inconsistent_objects:
                    self.inconsistent_objects.add(oid)
                else:
                    self.inconsistent_objects.discard(oid)
                self._apply_attr_updates(oid, objop, shard_txns)
                log_entries.append(self.pg_log.append(oid, OP_MODIFY))
                self.hinfo_cache.pop(oid, None)
                op.plan.hash_infos.pop(oid, None)
                continue
            hinfo = op.plan.hash_infos[oid]
            hinfo.version += 1      # down shards miss this bump -> stale
            # one pg_log entry per touched object (pg_log_entry_t); a pure
            # delete logs DELETE, anything that leaves data logs MODIFY
            is_delete = (objop.delete_first and not objop.buffer_updates
                         and objop.truncate is None)
            log_entries.append(self.pg_log.append(
                oid, OP_DELETE if is_delete else OP_MODIFY))
            if objop.delete_first:
                for chunk, shard in enumerate(self.acting):
                    shard_txns[shard].remove(GObject(oid, shard))
                hinfo.clear()
            if objop.truncate is not None:
                # truncate-before-writes: shrink every shard to the chunk
                # offset of the next stripe boundary, then let the rewritten
                # partial stripe (planned by get_write_plan) land on top
                # (reference: ECTransaction.cc generate_transactions truncate
                # handling; ECTransaction.h:70-86)
                t_logical = self.sinfo.logical_to_next_stripe_offset(
                    objop.truncate[0])
                t_chunk = self.sinfo.chunk_to_stored(
                    self.sinfo.aligned_logical_offset_to_chunk_offset(
                        t_logical))
                if t_chunk < hinfo.total_chunk_size:
                    for chunk, shard in enumerate(self.acting):
                        shard_txns[shard].truncate(GObject(oid, shard), t_chunk)
                    hinfo.set_total_chunk_size_clear_hash(t_chunk)
            if objop.omap_ops:
                # EC pools do not support omap, exactly like the reference
                # (PrimaryLogPG rejects with -EOPNOTSUPP before it gets
                # here; this is the backend's own guard)
                raise ValueError("EC pools do not support omap operations")
            wholesale = objop.delete_first or (
                objop.truncate is not None and any(
                    off == 0 and len(d) >= objop.truncate[0]
                    for off, d in objop.buffer_updates))
            if wholesale:
                # WHOLESALE replacement re-derives every chunk from fresh
                # data: a damaged object is exonerated (operator restore).
                # A partial truncate+write is NOT enough — chunks below
                # the boundary could still hold laundered rot.
                self.inconsistent_objects.discard(oid)
            if objop.attr_updates and not is_delete:
                # object attrs replicate to every shard (the reference
                # stores xattrs on each shard's ghobject, PGTransaction.h).
                # A delete+recreate vector (delete_first AND new writes)
                # keeps its re-staged attrs: the remove is already queued
                # above, so these setattrs land on the fresh object.
                self._apply_attr_updates(oid, objop, shard_txns)
            if not will_write:
                if not objop.delete_first:
                    self._persist_hinfo(oid, hinfo, shard_txns)
                continue
            # assemble the logical bytes for every will_write extent
            pieces: list[tuple[int, bytes]] = []
            for off, length in will_write:
                pieces.append((off, self._assemble_extent(op, oid, objop, off, length)))
            # ONE batched encode over all extents' stripes — or adopt the
            # chunks a cross-op batch encoder (ecutil.encode_many via
            # put_many) precomputed, IF the plan really is the single
            # full-extent write they were computed for
            logical = np.concatenate(
                [np.frombuffer(b, dtype=np.uint8) for _, b in pieces])
            pre = objop.precomputed_chunks
            if (pre is not None and len(pieces) == 1 and
                    pieces[0][0] == 0 and
                    logical.tobytes() == getattr(objop, "precomputed_for",
                                                 None)):
                encoded = {c: np.asarray(pre[c], dtype=np.uint8)
                           for c in range(n)}
            else:
                with trace_span("ec.encode", oid=oid,
                                bytes=int(logical.nbytes),
                                backend=self.instance_name,
                                served=self.serving is not None), \
                        self.perf.time("encode_time"):
                    encoded = self._serving_encode(logical)
            self.perf.inc("stripe_bytes_encoded", int(logical.nbytes))
            if op.tracked:
                op.tracked.mark_event("encoded")
            # scatter per-extent chunk ranges into shard transactions
            c_cursor = 0
            old_size = hinfo.total_chunk_size
            append_chunks: dict[int, np.ndarray] = {}
            appended = 0
            pure_append = True
            for off, data in pieces:
                # shard extents live in STORED units: the encoded chunk
                # streams may be wider than the logical shares (MBR
                # expansion), so offsets/lengths convert before slicing
                c_off = self.sinfo.chunk_to_stored(
                    self.sinfo.aligned_logical_offset_to_chunk_offset(off))
                c_len = self.sinfo.chunk_to_stored(
                    self.sinfo.aligned_logical_offset_to_chunk_offset(
                        len(data)))
                for chunk in range(n):
                    shard = self.acting[chunk]
                    payload = encoded[chunk][c_cursor:c_cursor + c_len]
                    shard_txns[shard].write(
                        GObject(oid, shard), c_off, payload.tobytes())
                if pure_append and c_off == old_size + appended:
                    for chunk in range(n):
                        prev = append_chunks.get(chunk)
                        seg = encoded[chunk][c_cursor:c_cursor + c_len]
                        append_chunks[chunk] = seg if prev is None else \
                            np.concatenate([prev, seg])
                    appended += c_len
                else:
                    pure_append = False
                c_cursor += c_len
                self.extent_cache.claim(oid, op.tid, off, data)
                op.cache_claims.append((oid, op.tid))
            # hash maintenance: pure appends chain the crc (HashInfo::append,
            # ECUtil.cc:161-177); every OVERWRITE clears the hashes —
            # a mid-stream crc is unknowable, and re-deriving fresh
            # digests from the primary's own encode would certify bytes
            # nothing independent ever checked (scrub would then "locate"
            # rot against a self-issued receipt).  Hash-less objects are
            # covered honestly instead: deep scrub's parity-consistency
            # fallback detects rot (and locates it when m >= 2), and
            # verified recovery over inconsistent sources records
            # OBJECT_DAMAGED when one spare equation can detect but not
            # place the rot — rather than laundering it as repaired.
            total = hinfo.projected_total_chunk_size
            if pure_append and appended:
                # fused path: one device crc dispatch over the stacked
                # appended rows when the plugin has a device codec
                ecutil.hinfo_append(hinfo, old_size, append_chunks,
                                    ec_impl=self.ec_impl)
            elif not pure_append:
                hinfo.set_total_chunk_size_clear_hash(total)
            self._persist_hinfo(oid, hinfo, shard_txns)
        return shard_txns, log_entries

    def _assemble_extent(self, op: Op, oid: str, objop, off: int,
                         length: int) -> bytes:
        """Merge read-in stripes, cached stripes, and the op's new writes
        into the stripe-aligned extent [off, off+length)."""
        buf = bytearray(length)
        reads = op.remote_reads.get(oid, {})
        for r_off, data in reads.items():
            if r_off >= off + length or r_off + len(data) <= off:
                continue
            s = max(r_off, off)
            e = min(r_off + len(data), off + length)
            buf[s - off:e - off] = data[s - r_off:e - r_off]
        if objop.truncate is not None:
            t0 = objop.truncate[0]
            if off <= t0 < off + length:
                buf[t0 - off:] = b"\0" * (off + length - t0)
        for w_off, data in objop.buffer_updates:
            if w_off >= off + length or w_off + len(data) <= off:
                continue
            s = max(w_off, off)
            e = min(w_off + len(data), off + length)
            buf[s - off:e - off] = data[s - w_off:e - w_off]
        return bytes(buf)

    def _persist_hinfo(self, oid: str, hinfo: HashInfo, shard_txns) -> None:
        for shard in self.acting:
            shard_txns[shard].setattr(GObject(oid, shard), HINFO_KEY,
                                      hinfo.to_dict())

    def _op_reset_extra(self, op: Op) -> None:
        for oid, tid in op.cache_claims:
            self.extent_cache.release(oid, tid)
        op.cache_claims.clear()
        self._rmw_read_tids.pop(getattr(op, "_rmw_read_tid", None), None)
        op._rmw_buf = {}

    # -- failure-handling hooks ----------------------------------------------

    def _reissue_rmw(self, op: Op) -> None:
        """Re-issue an op's RMW reads from the current shard set; when too
        few shards remain the op parks (the PG is effectively down, like
        the reference's incomplete state) and is re-driven by on_shard_up.
        The -1 sentinel keeps try_reads_to_commit from running with
        missing data (no real reply ever clears it)."""
        op.pending_read_shards.clear()
        try:
            self._start_rmw_reads(op, op._rmw_need)
            op._rmw_stalled = False
        except IOError:
            op.pending_read_shards.add(-1)
            op._rmw_stalled = True

    def _on_shard_down_reads(self, shard: int, chunk: int) -> None:
        # batched recovery waves: a lost SOURCE aborts the wave's read
        # phase — every object re-drives through the per-object path
        # (which widens, parks, or fails with the usual semantics)
        for tid, wave in list(self._recovery_waves.items()):
            if shard in wave.pending_sources:
                del self._recovery_waves[tid]
                for oid in sorted(wave.oids):
                    self._wave_fallback_one(wave, oid)
        # a lost PUSH TARGET fails that object, the rest of the wave
        # proceeds (the _failed_push analog the per-object path applies)
        for oid, wave in list(self._wave_pushes.items()):
            pend = wave.pending_pushes.get(oid)
            if pend and shard in pend:
                pend.discard(shard)
                wave.failed.add(oid)
                if not pend:
                    self._finish_wave_oid(wave, oid)
        # chained streaming repair: a dead HOP strands the partial sum —
        # pop the chain record first (late acks/aborts become inert),
        # then re-drive its unfinished objects per-object; a dead TARGET
        # was already handled by the push loop above
        for tid, chain in list(self._recovery_chains.items()):
            if shard in getattr(chain, "hop_shards", ()):
                del self._recovery_chains[tid]
                self.perf.inc(f"{getattr(chain, 'kind', 'chain')}_fallbacks")
                for oid in sorted(chain.pending_pushes):
                    self._wave_pushes.pop(oid, None)
                    self._wave_fallback_one(chain, oid)
                chain.pending_pushes.clear()
        for tid, chain in list(self._recovery_chains.items()):
            if not chain.pending_pushes:
                del self._recovery_chains[tid]
        # RMW pipeline reads: re-issue from the remaining shards
        for op in list(self.waiting_reads):
            if shard in op.pending_read_shards:
                self._reissue_rmw(op)
        # client reads: treat like an error reply from that shard
        for rop in list(self.in_progress_reads.values()):
            if shard in rop.pending_shards:
                rop.pending_shards.pop(shard, None)
                for oid in rop.to_read:
                    # tried_shards holds every chunk actually requested
                    # (including retry-widened ones); want_shards is only
                    # the initial minimum set
                    if (chunk in rop.tried_shards.get(oid, ()) and
                            chunk not in rop.results.get(oid, {})):
                        rop.errors.setdefault(oid, set()).add(chunk)
                        self._retry_remaining_shards(rop, oid)
                if not rop.pending_shards:
                    self._complete_read_op(rop)

    def _redrive_reads(self) -> None:
        for op in list(self.waiting_reads):
            if getattr(op, "_rmw_stalled", False):
                self._reissue_rmw(op)

    # -- read path ---------------------------------------------------------

    def objects_read_and_reconstruct(self, reads: dict[str, list[tuple[int, int]]],
                                     on_complete, fast_read: bool = False) -> int:
        """(ECBackend.cc:2331-2385): choose min shards per object, read
        chunk extents, reconstruct if any data shard is unavailable."""
        self.next_tid += 1
        tid = self.next_tid
        rop = ReadOp(tid=tid, to_read=reads, on_complete=on_complete)
        k = self.ec_impl.get_data_chunk_count()
        cur = self.current_shards()
        avail = {i for i, s in enumerate(self.acting) if s in cur}
        want = {self.ec_impl.chunk_index(i) for i in range(k)}
        try:
            base_minimum = self.ec_impl.minimum_to_decode(want, avail)
        except IOError:
            # degraded below k current shards: the read cannot reconstruct
            # right now — EIO to the caller (mirrors the replicated
            # backend's no-current-source answer) rather than an exception
            # unwinding through the daemon's drain loop
            self.in_progress_reads.pop(tid, None)
            on_complete({}, {oid: -5 for oid in reads})
            return tid
        # reconstructing a sub-chunked code (clay): the decode's
        # interleave is a function of the WHOLE chunk height, so a
        # (c_off, c_len) chunk SLICE is not a smaller codeword the way it
        # is for per-byte-linear RS — decode full chunks and slice the
        # logical result instead (the write-planner's full-object-rewrite
        # rule, applied to the read side; found by the clay thrash soak)
        whole_chunks = ((self.ec_impl.get_sub_chunk_count() > 1
                         and set(base_minimum) != want)
                        or getattr(self.ec_impl, "requires_full_chunk_io",
                                   False))
        per_shard: dict[int, dict[str, list[tuple]]] = {}
        for oid, extents in reads.items():
            lo = min(off for off, _ in extents)
            hi = max(off + ln for off, ln in extents)
            start, length = self.sinfo.offset_len_to_stripe_bounds(lo, hi - lo)
            c_off = self.sinfo.aligned_logical_offset_to_chunk_offset(start)
            c_len = self.sinfo.aligned_logical_offset_to_chunk_offset(length)
            if whole_chunks:
                c_off, c_len = 0, None
            rop.shard_extents[oid] = (c_off, c_len)
            minimum = base_minimum
            if fast_read and len(avail) > len(minimum):
                # redundant reads: ask every available shard (ECBackend.cc:1609-1615)
                minimum = {c: [(0, self.ec_impl.get_sub_chunk_count())]
                           for c in avail}
            rop.want_shards[oid] = set(minimum)
            rop.tried_shards[oid] = set(minimum)
            for chunk, subchunks in minimum.items():
                shard = self.acting[chunk]
                runs = None if whole_chunks or subchunks == \
                    [(0, self.ec_impl.get_sub_chunk_count())] else subchunks
                per_shard.setdefault(shard, {}).setdefault(oid, []).append(
                    (c_off, c_len, runs))
        rop.pending_shards = {shard: 1 for shard in per_shard}
        self.in_progress_reads[tid] = rop
        for shard, to_read in per_shard.items():
            self.bus.send(shard, ECSubRead(
                self.whoami, tid, to_read,
                sub_chunk_count=self.ec_impl.get_sub_chunk_count()))
        return tid

    def _handle_other_read_reply(self, reply: ECSubReadReply) -> None:
        """(ECBackend.cc:1153-1320): collect; on error widen the shard set
        (send_all_remaining_reads :2386)."""
        # batched recovery wave reads
        wave = self._recovery_waves.get(reply.tid)
        if wave is not None:
            self._handle_wave_read_reply(wave, reply)
            return
        # RMW pipeline reads
        op = self._rmw_read_tids.get(reply.tid)
        if op is not None:
            self._handle_rmw_read_reply(op, reply)
            return
        rop = self.in_progress_reads.get(reply.tid)
        if rop is None:
            return
        left = rop.pending_shards.get(reply.from_shard, 0) - 1
        if left <= 0:
            rop.pending_shards.pop(reply.from_shard, None)
        else:
            rop.pending_shards[reply.from_shard] = left
        chunk_of_shard = {s: c for c, s in enumerate(self.acting)}
        chunk = chunk_of_shard[reply.from_shard]
        for oid, bufs in reply.buffers_read.items():
            data = b"".join(b for _, b in bufs)
            store = rop.results.setdefault(oid, {})
            # a whole-chunk upgrade (clay retry) re-reads chunks whose
            # sliced replies may still be in flight: under reordered or
            # duplicated delivery the short straggler can land AFTER the
            # full-height reply — the longer buffer always wins (equal
            # extents produce equal lengths, so this is inert otherwise)
            if len(data) >= len(store.get(chunk, b"")):
                store[chunk] = data
        for oid in reply.errors:
            rop.errors.setdefault(oid, set()).add(chunk)
            self._retry_remaining_shards(rop, oid)
        if not rop.pending_shards:
            self._complete_read_op(rop)

    def _retry_remaining_shards(self, rop: ReadOp, oid: str) -> None:
        """Incremental recovery from shard read errors (ECBackend.cc:1627-1671)."""
        k = self.ec_impl.get_data_chunk_count()
        up = self.current_shards()
        avail = {c for c, s in enumerate(self.acting)
                 if s in up and c not in rop.errors.get(oid, set())}
        untried = avail - rop.tried_shards[oid]
        # chunks already read + still outstanding on live shards + the new
        # candidates must reach k (ECBackend.cc:1627-1671 counts pending
        # shards as available too)
        pending = {c for c, s in enumerate(self.acting)
                   if s in rop.pending_shards and s in up and
                   c in rop.tried_shards[oid]}
        have_or_pending = (set(rop.results.get(oid, {})) | pending | untried) \
            - rop.errors.get(oid, set())
        if len(have_or_pending) < k:
            return  # complete_read_op will surface the failure
        c_off, c_len = rop.shard_extents[oid]
        resend = set(untried)
        if self.ec_impl.get_sub_chunk_count() > 1 and \
                not (c_off, c_len) == (0, None):
            # the widened read will DECODE (a failed source means
            # reconstruction), and a sub-chunked code cannot decode
            # chunk slices (see objects_read_and_reconstruct): upgrade
            # this object to whole-chunk reads, dropping the sliced
            # buffers already collected — every contributing chunk is
            # re-fetched at full height (FIFO delivery makes the full
            # reply land after any sliced one still in flight;
            # _complete_read_op drops short stragglers regardless)
            rop.shard_extents[oid] = (0, None)
            c_off, c_len = 0, None
            # ...including chunks whose SLICED replies already landed or
            # are still in flight: every contributor needs a full-height
            # re-read (the stragglers' short buffers are dropped at
            # completion either way)
            resend |= (set(rop.results.get(oid, {})) | pending) & avail
            rop.results.get(oid, {}).clear()
        for chunk in resend:
            shard = self.acting[chunk]
            rop.tried_shards[oid].add(chunk)
            rop.pending_shards[shard] = rop.pending_shards.get(shard, 0) + 1
            self.bus.send(shard, ECSubRead(
                self.whoami, rop.tid, {oid: [(c_off, c_len, None)]}))

    def _handle_rmw_read_reply(self, op: Op, reply: ECSubReadReply) -> None:
        if reply.errors:
            # a source failed (rotten at rest / vanished): restart the
            # WHOLE rmw read excluding that chunk — minimum_to_decode
            # picks a replacement; dropping the chunk silently would hand
            # the decode k-1 chunks (same widening client reads do via
            # _retry_remaining_shards)
            chunk = {s: c for c, s in
                     enumerate(self.acting)}[reply.from_shard]
            op._rmw_failed = getattr(op, "_rmw_failed", set()) | {chunk}
            try:
                self._start_rmw_reads(op, op._rmw_need)
                op._rmw_stalled = False
            except IOError:
                # not enough clean sources: stall like shard loss until
                # a repair/revival re-drives
                op.pending_read_shards.add(-1)
                op._rmw_stalled = True
            return
        op.pending_read_shards.discard(reply.from_shard)
        chunk_of_shard = {s: c for c, s in enumerate(self.acting)}
        chunk = chunk_of_shard[reply.from_shard]
        for oid, bufs in reply.buffers_read.items():
            store = op._rmw_buf.setdefault(oid, {})
            for c_off, data in bufs:
                store.setdefault(c_off, {})[chunk] = data
        if not op.pending_read_shards:
            self._rmw_read_tids.pop(getattr(op, "_rmw_read_tid", None), None)
            self._finish_rmw_reads(op)
            self.check_ops()

    def _finish_rmw_reads(self, op: Op) -> None:
        """Decode each read stripe-run back to logical bytes."""
        for oid, runs in op._rmw_buf.items():
            for c_off, by_chunk in runs.items():
                logical_off = self.sinfo.aligned_chunk_offset_to_logical_offset(c_off)
                with trace_span("ec.decode", oid=oid, kind="rmw_read",
                                backend=self.instance_name), \
                        self.perf.time("decode_time"):
                    data = self._serving_decode(by_chunk)
                op.remote_reads.setdefault(oid, {})[logical_off] = data

    def _complete_read_op(self, rop: ReadOp) -> None:
        """Reassemble/reconstruct and trim (ECBackend.cc:2273-2329)."""
        k = self.ec_impl.get_data_chunk_count()
        result: dict[str, list[tuple[int, int, bytes]]] = {}
        errors: dict[str, int] = {}
        for oid, extents in rop.to_read.items():
            by_chunk = rop.results.get(oid, {})
            by_chunk = {c: v for c, v in by_chunk.items()
                        if c not in rop.errors.get(oid, set())}
            if len(by_chunk) > 0 and \
                    self.ec_impl.get_sub_chunk_count() > 1:
                # a whole-chunk upgrade mid-read (clay retry) may leave
                # sliced stragglers alongside full chunks: only equal
                # full-height buffers may decode together — drop the
                # short ones (better a clean EIO below than garbage)
                full = max(len(v) for v in by_chunk.values())
                by_chunk = {c: v for c, v in by_chunk.items()
                            if len(v) == full}
            if len(by_chunk) < k:
                errors[oid] = -5  # EIO
                continue
            # keep exactly k shards for decode
            chosen = dict(sorted(by_chunk.items())[:k])
            with trace_span("ec.decode", oid=oid, kind="client_read",
                            backend=self.instance_name), \
                    self.perf.time("decode_time"):
                logical = self._serving_decode(chosen)
            c_off, _ = rop.shard_extents[oid]
            base = self.sinfo.aligned_chunk_offset_to_logical_offset(c_off)
            obj_size = self.object_size(oid)
            out = []
            for off, length in extents:
                end = min(off + length, obj_size)
                seg = logical[off - base:end - base] if end > off else b""
                out.append((off, length, seg))
            result[oid] = out
        del self.in_progress_reads[rop.tid]
        if result:
            self.perf.inc("reads")
        if errors:
            self.perf.inc("read_errors", len(errors))
        self.perf.inc("read_bytes", sum(
            len(seg) for segs in result.values() for _, _, seg in segs))
        rop.on_complete(result, errors)

    # -- recovery hooks ------------------------------------------------------

    def is_recoverable(self, oid: str, missing: set[int]) -> bool:
        """ECRecPred analog (ECBackend.h:581-607)."""
        avail = {c for c, s in enumerate(self.acting)
                 if s in self.current_shards() and c not in missing}
        try:
            self.ec_impl.minimum_to_decode(set(missing), avail)
            return True
        except IOError:
            return False

    def _recovery_issue_reads(self, rop: RecoveryOp) -> None:
        avail = {c for c, s in enumerate(self.acting)
                 if s in self.current_shards()
                 and c not in rop.missing_shards}
        minimum = self.ec_impl.minimum_to_decode(rop.missing_shards, avail)
        # recovery sizes its reads from the FRESHEST authoritative hinfo,
        # read PAST the cache: a cached entry may be an empty placeholder
        # from a moment when no source had applied the object yet
        # (reordered delivery) — and evicting the cache instead would
        # corrupt an in-flight write's projection (_read_hinfo docstring)
        hinfo = self._read_hinfo(rop.oid)
        c_len = hinfo.get_total_chunk_size()
        # VERIFIED recovery: when the hinfo hashes are gone (overwrites
        # clear them) the reconstruction sources cannot be crc-checked —
        # a silently rotten source would bake its rot into the rebuilt
        # chunk and the new parity would make the corruption
        # SELF-CONSISTENT (observed via the soak: repair of a revived
        # shard laundered bitrot past every later scrub).  Reading every
        # available full chunk restores the spare equations, and the
        # payload step cross-checks before pushing.
        # Reading all spares also serves the HASH-PRESENT path: a source
        # failing its crc check is dropped and rebuilt, which needs a
        # replacement source in hand.
        # pm_regen repairs whole stored chunks despite sub > 1, so its
        # sources can be crc-checked (and spares held) the same way
        verify = (len(avail) > len(minimum)
                  and (self.ec_impl.get_sub_chunk_count() == 1
                       or getattr(self.ec_impl,
                                  "supports_regenerating_repair",
                                  lambda: False)()))
        want = ({c: [(0, self.ec_impl.get_sub_chunk_count())]
                 for c in sorted(avail)} if verify else minimum)
        per_shard = {}
        for chunk, subchunks in want.items():
            shard = self.acting[chunk]
            runs = None if subchunks == [(0, self.ec_impl.get_sub_chunk_count())] \
                else subchunks
            # whole-chunk reads: a point-in-time LOCAL hinfo can lag a
            # just-generated write whose sub-ops are still queued (log
            # appends at generation, stores apply at delivery), and
            # sizing by it TRUNCATES the sources' newer chunks — the
            # seed-244 soak pushed 512 bytes of a 1024-byte chunk that
            # way.  Each source serves its own current full chunk; only
            # clay's fractional sub-chunk runs still need c_len.
            length = c_len if runs is not None else None
            per_shard.setdefault(shard, {})[rop.oid] = [(0, length, runs)]
        rop._pending = set(per_shard)
        # the replicated attr set (object_info, snapset, user xattrs —
        # identical on every shard) must come from a CURRENT source: the
        # local copy is the right fallback only while the primary itself
        # is current, and when repairing the primary's own stale shard it
        # is exactly the copy that missed the latest attrs
        for shard, to_read in per_shard.items():
            self.bus.send(shard, ECSubRead(
                self.whoami, rop.read_tid, to_read, attrs_to_read={"*"},
                sub_chunk_count=self.ec_impl.get_sub_chunk_count()))

    def _recovery_prepare_sources(self, oid: str,
                                  read_results: dict[int, object],
                                  read_attrs: dict[int, dict],
                                  missing: set[int],
                                  verify_parity: bool = True
                                  ) -> tuple[dict[int, np.ndarray],
                                             HashInfo, set[int], dict]:
        """Turn raw recovery-read replies into decode-ready inputs — ONE
        copy shared by the per-object payload builder and the batched
        wave: adopt a coherent hinfo, normalize source lengths, drop
        (and mark for rebuild) crc- or parity-rotten sources, and build
        the replicated attr set the pushes must carry.  Returns
        ``(available, hinfo, missing, attrs)`` with ``missing`` possibly
        EXTENDED by located rotten sources."""
        missing = set(missing)
        available = {c: (v if isinstance(v, np.ndarray)
                         else np.frombuffer(v, dtype=np.uint8))
                     for c, v in read_results.items()}
        # the hinfo must be COHERENT with the data the sources served:
        # each read reply carries data and attrs from one store state, so
        # a source's attr hinfo describes exactly the bytes it returned —
        # while the local attr can lag (or lead) the read by in-flight
        # sub-writes.  Prefer the newest source hinfo; fall back to the
        # local stored one, then to sizing from the bytes read.
        hinfo = self._read_hinfo(oid)         # uncached: see _read_hinfo
        peer_base = max(
            (a for _c, a in sorted(read_attrs.items())
             if a and HINFO_KEY in a),
            key=lambda a: a[HINFO_KEY].get("version", 0), default=None)
        if peer_base is not None and \
                peer_base[HINFO_KEY].get("version", 0) >= hinfo.version:
            d = peer_base[HINFO_KEY]
            nh = HashInfo(self.ec_impl.get_chunk_count())
            nh.total_chunk_size = d["total_chunk_size"]
            nh.cumulative_shard_hashes = list(
                d["cumulative_shard_hashes"])
            nh.projected_total_chunk_size = nh.total_chunk_size
            nh.version = d.get("version", 0)
            hinfo = nh
        if not hinfo.get_total_chunk_size():
            if available:
                # last resort: size from the bytes actually read
                nh = HashInfo(self.ec_impl.get_chunk_count())
                nh.total_chunk_size = max(len(v) for v in
                                          available.values())
                nh.projected_total_chunk_size = nh.total_chunk_size
                hinfo = nh
        # whole-chunk reads may catch sources mid-update at different
        # lengths: normalize to the adopted hinfo's size — a source whose
        # bytes are from another version then fails its crc (or the
        # parity-consistency check) and is dropped/rebuilt below.
        # Sub-chunk codes (clay) are exempt: their repair reads are
        # INTENTIONALLY shorter than the chunk (fractional sub-chunk
        # runs), and padding them to full length makes the plugin
        # mistake them for whole chunks and full-decode garbage — the
        # seed's wrong-bytes clay recovery (ROADMAP item 1).
        # pm_regen is sub-chunked too, but its recovery reads are always
        # WHOLE stored chunks (requires_full_chunk_io / the regen gate),
        # so length normalization and the crc check below stay valid
        whole_reads = (self.ec_impl.get_sub_chunk_count() == 1
                       or getattr(self.ec_impl,
                                  "supports_regenerating_repair",
                                  lambda: False)())
        total = hinfo.get_total_chunk_size()
        if total and whole_reads:
            available = {
                c: (v if len(v) == total else np.frombuffer(
                    v.tobytes()[:total].ljust(total, b"\0"),
                    dtype=np.uint8))
                for c, v in available.items()}
        k = self.ec_impl.get_data_chunk_count()
        if hinfo.has_chunk_hash() and whole_reads:
            # the reference CRC-verifies recovery reads against the
            # hinfo before reconstructing (ECBackend handle_recovery_
            # read_complete checks the cumulative hash): a source whose
            # crc mismatches is itself rotten — drop it and rebuild it
            # too rather than bake its rot into the new chunk
            rotten = [c for c, v in available.items()
                      if crc32c(0xFFFFFFFF, v) != hinfo.get_chunk_hash(c)]
            if rotten and len(available) - len(rotten) >= k:
                for c in rotten:
                    del available[c]
                missing |= set(rotten)
            elif rotten:
                # not enough clean sources to rebuild everything: the
                # reconstruction would embed rot — record damage
                self.inconsistent_objects.add(oid)
        if verify_parity and not hinfo.has_chunk_hash() \
                and len(available) > k \
                and self.ec_impl.get_sub_chunk_count() == 1:
            # verified recovery (see _recovery_issue_reads): cross-check
            # the sources with the spare equations and DROP a located
            # rotten source instead of baking it into the rebuilt chunk.
            # (The batched wave passes verify_parity=False and runs ONE
            # fused check per survivor signature instead.)
            available, missing = self._verify_parity_sources(
                oid, available, missing)
        # pushes REPLACE the target object, so the replicated attrs
        # (user xattrs, object_info, snapset — identical on every shard)
        # must travel too, from a CURRENT copy; without them, repairing a
        # located rotten source would WIPE the xattrs that shard held
        # correctly.  Prefer a recovery-read source's attrs (sources are
        # current by construction — the local copy is stale exactly when
        # the primary's own shard is the one being repaired); each
        # source's shard-specific hinfo is stripped.
        attrs = {HINFO_KEY: hinfo.to_dict()}
        base = next((a for _c, a in sorted(read_attrs.items())
                     if a), None)
        if base is None:
            try:
                base = self.local_shard.store.getattrs(
                    GObject(oid, self.whoami))
            except FileNotFoundError:
                base = {}
        attrs = {**{a: v for a, v in base.items() if a != HINFO_KEY},
                 **attrs}
        return available, hinfo, missing, attrs

    def _verify_parity_sources(self, oid: str,
                               available: dict[int, np.ndarray],
                               missing: set[int]
                               ) -> tuple[dict[int, np.ndarray], set[int]]:
        """Per-object spare-equation cross-check of hash-less recovery
        sources: a LOCATED rotten source is dropped and rebuilt; rot the
        spare equations can detect but not place marks OBJECT_DAMAGED
        (rebuilding would launder it, and erasing the trace is the seed
        regression this PR's satellite pins)."""
        k = self.ec_impl.get_data_chunk_count()
        out_map = {c: True for c in available}
        self._parity_consistency_scrub(
            oid, {c: v.tobytes() for c, v in available.items()}, out_map)
        bad = [c for c, ok in out_map.items() if not ok]
        if len(bad) == 1 and len(available) - 1 >= k:
            missing = missing | set(bad)
            available = {c: v for c, v in available.items() if c != bad[0]}
        elif bad:
            # inconsistent but unlocatable (one spare equation can
            # DETECT rot, never place it): the rebuild may launder
            # corruption — record the object as damaged
            self.inconsistent_objects.add(oid)
        return available, missing

    def _spare_equations_consistent(self,
                                    chunks: dict[int, np.ndarray]) -> bool:
        """ONE-decode detection over > k normalized chunk streams:
        reconstruct every spare chunk from a k-subset and compare against
        what the sources served.  For the MDS codes this path serves
        (jax_rs/isa/jerasure RS, xor) any single-chunk delta propagates
        into at least one reconstructed spare, so clean == consistent;
        plugins whose k-subsets are not all decodable (shec/lrc) raise
        and fall back to the thorough per-target scan.  This is the
        batched wave's fused verification: linear codes make the check
        distribute over concatenation, so one call covers every object
        sharing the survivor signature."""
        k = self.ec_impl.get_data_chunk_count()
        ids = sorted(chunks)
        spares = ids[k:]
        if not spares:
            return True                # no redundancy: vacuously consistent
        length = int(len(chunks[ids[0]]))
        try:
            rec = self.ec_impl.decode(
                set(spares), {i: chunks[i] for i in ids[:k]}, length)
        except Exception:              # non-MDS subset: thorough fallback
            out_map = {c: True for c in ids}
            self._parity_consistency_scrub(
                "", {c: v.tobytes() for c, v in chunks.items()}, out_map)
            return all(out_map.values())
        return all(np.array_equal(np.asarray(rec[s], dtype=np.uint8),
                                  chunks[s]) for s in spares)

    def _recovery_push_payloads(self, rop: RecoveryOp
                                ) -> dict[
            int, tuple[bytes, dict, dict | None, bytes]]:
        # reconstruct the missing chunks; chunk_size tells sub-chunk codes
        # (clay) the helpers are fractional
        available, hinfo, missing, attrs = self._recovery_prepare_sources(
            rop.oid, rop._read_results, rop._read_attrs,
            set(rop.missing_shards))
        rop.missing_shards = missing
        rec = decode_shards(self.sinfo, self.ec_impl, available,
                            rop.missing_shards,
                            chunk_size=hinfo.get_total_chunk_size())
        return {chunk: (bytes(rec[chunk]), dict(attrs), None, b"")
                for chunk in rop.missing_shards}

    # -- batch-fused recovery waves (the recovery scheduler's dispatch) ----

    def _recover_many(self, oids: dict[str, set[int]], on_each) -> None:
        """Recover a wave of degraded objects with ONE read per source
        shard and ONE ``decode_shards_many`` dispatch per survivor
        signature — instead of the per-object machine's N reads and N
        decodes.  Objects the batch cannot serve safely (sub-chunk codes,
        too few survivors, singletons with nothing to fuse) drop to the
        verified per-object path."""
        k = self.ec_impl.get_data_chunk_count()
        cur = self.current_shards()
        # regenerating codes (product-matrix MSR/MBR) take every
        # single-erasure object FIRST — d helper inner products move
        # fewer bytes than any decode-based path; leftovers (multi-loss,
        # too few helpers, plan gaps) fall through unchanged.  The probe
        # keeps non-regenerating codes entirely untouched.
        if oids and getattr(self.ec_impl, "supports_regenerating_repair",
                            lambda: False)():
            from ..recovery.regen import plan_regens
            oids = plan_regens(self, oids, on_each)
            if not oids:
                return
        if self.ec_impl.get_sub_chunk_count() != 1 or len(oids) < 2:
            # clay's fractional repair reads are not positionwise across
            # objects; a singleton has nothing to fuse — per-object keeps
            # the minimum-read plan
            super()._recover_many(oids, on_each)
            return
        singles: dict[str, set[int]] = {}
        batch: dict[str, set[int]] = {}
        for oid, missing in oids.items():
            avail = {c for c, s in enumerate(self.acting)
                     if s in cur and c not in missing}
            (batch if len(avail) >= k else singles)[oid] = set(missing)
        if singles:
            super()._recover_many(singles, on_each)
        if not batch:
            return
        # chained streaming repair takes every eligible object first
        # (linear whole-chunk codes, targets up, plan metadata present);
        # its leftovers fall through to the centralized wave below
        from ..recovery.chain import plan_chains
        batch = plan_chains(self, batch, on_each)
        if not batch:
            return
        if len(batch) == 1:
            super()._recover_many(batch, on_each)
            return
        self.next_tid += 1
        tid = self.next_tid
        wave = _RecoveryWave(tid=tid, oids=batch, on_each=on_each)
        per_shard: dict[int, dict[str, list[tuple]]] = {}
        for oid, missing in sorted(batch.items()):
            wave.at_version[oid] = self.pg_log.last_version_of(oid)
            for chunk in sorted({c for c, s in enumerate(self.acting)
                                 if s in cur and c not in missing}):
                # every available chunk, whole (the verified-recovery
                # read: spare equations cross-check the sources, and
                # each source serves its own current full chunk —
                # _recovery_issue_reads' sizing rationale)
                per_shard.setdefault(self.acting[chunk],
                                     {})[oid] = [(0, None, None)]
        wave.pending_sources = set(per_shard)
        self._recovery_waves[tid] = wave
        for shard, to_read in sorted(per_shard.items()):
            self.bus.send(shard, ECSubRead(self.whoami, tid, to_read,
                                           attrs_to_read={"*"}))

    def _handle_wave_read_reply(self, wave: _RecoveryWave,
                                reply: ECSubReadReply) -> None:
        chunk = {s: c for c, s in enumerate(self.acting)}[reply.from_shard]
        for oid in reply.errors:
            if oid in wave.oids:
                # ENOENT/EIO from one source: the per-object path knows
                # how to widen/park for this oid — don't fail the wave
                wave.fallback.add(oid)
        for oid, bufs in reply.buffers_read.items():
            if oid in wave.oids:
                wave.results.setdefault(oid, {})[chunk] = b"".join(
                    b for _, b in bufs)
        for oid, attrs in reply.attrs_read.items():
            if oid in wave.oids:
                wave.attrs.setdefault(oid, {})[chunk] = attrs
        wave.pending_sources.discard(reply.from_shard)
        if not wave.pending_sources:
            self._finish_wave_reads(wave)

    def _finish_wave_reads(self, wave: _RecoveryWave) -> None:
        """Every source replied: prepare each object's sources exactly
        like the per-object path (hinfo adoption, crc/parity verify),
        then reconstruct ALL of them through decode_shards_many and push."""
        self._recovery_waves.pop(wave.tid, None)
        k = self.ec_impl.get_data_chunk_count()
        ready: list[tuple[str, dict, set, dict]] = []
        # hash-less objects needing the spare-equation cross-check,
        # grouped by survivor signature for ONE fused check per group
        unverified: dict[frozenset, list[int]] = {}
        for oid in sorted(wave.oids):
            if oid in wave.fallback:
                continue
            if oid in self._wave_pushes:
                # ANOTHER wave (a sibling shard repair of the same batch
                # sharing this oid) registered its pushes first: the
                # push slot is per-oid, so this wave's copy re-drives
                # per-object — both pushes land, replies disambiguate by
                # from_shard (the targets are distinct shards)
                wave.fallback.add(oid)
                continue
            if self.pg_log.last_version_of(oid) != wave.at_version[oid]:
                # a write committed while the wave read was in flight:
                # the reconstructed bytes would be stale — re-drive
                wave.fallback.add(oid)
                continue
            available, hinfo, missing, attrs = \
                self._recovery_prepare_sources(
                    oid, wave.results.get(oid, {}),
                    wave.attrs.get(oid, {}), set(wave.oids[oid]),
                    verify_parity=False)
            if len(available) < k or not missing:
                wave.fallback.add(oid)
                continue
            if not hinfo.has_chunk_hash() and len(available) > k:
                unverified.setdefault(frozenset(available),
                                      []).append(len(ready))
            ready.append((oid, available, missing, attrs))
        # fused verified recovery: the code is linear, so a signature
        # group's CONCATENATED streams are spare-equation-consistent iff
        # every member object is — one decode verifies the whole group
        # (the per-object scan cost one decode per chunk per object,
        # which dwarfed the fused reconstruct the wave exists for).
        # Only an inconsistent group pays the per-object localization.
        for sig, idxs in sorted(unverified.items(),
                                key=lambda kv: kv[1][0]):
            concat = {c: np.concatenate([ready[i][1][c] for i in idxs])
                      for c in sorted(sig)}
            if self._spare_equations_consistent(concat):
                continue
            for i in idxs:
                oid, available, missing, attrs = ready[i]
                # _verify_parity_sources drops at most one source, and
                # only while >= k remain; missing only ever grows from a
                # non-empty entry — so the member stays decodable (and a
                # future violation surfaces via the decode's exception
                # fallback below)
                available, missing = self._verify_parity_sources(
                    oid, dict(available), set(missing))
                ready[i] = (oid, available, missing, attrs)
        ready = [r for r in ready if r[0] not in wave.fallback]
        rebuilt: list[dict] = []
        if ready:
            try:
                with trace_span("ec.decode_wave", objects=len(ready),
                                backend=self.instance_name), \
                        self.perf.time("decode_time"):
                    # scheduler-attached backends carry a shared device
                    # pipeline: signature groups dispatch async so group
                    # i+1's host pack overlaps group i's device decode
                    rebuilt = ecutil.decode_shards_many(
                        self.sinfo, self.ec_impl,
                        [(avail, missing)
                         for _o, avail, missing, _a in ready],
                        pipeline=getattr(self, "recovery_pipeline", None))
            except (IOError, ValueError, AssertionError):
                # a signature group failed to decode: every object drops
                # to the per-object path, which localizes the failure
                wave.fallback.update(oid for oid, *_ in ready)
                ready, rebuilt = [], []
        up = self.up_shards()
        for (oid, _avail, missing, attrs), rec in zip(ready, rebuilt):
            wave.pending_pushes[oid] = set()
            self._wave_pushes[oid] = wave
            for chunk in sorted(missing):
                shard = self.acting[chunk]
                if shard not in up:
                    # target died while the reads were in flight: the op
                    # fails for this object (_failed_push), the rest of
                    # the wave proceeds
                    wave.failed.add(oid)
                    continue
                data = bytes(rec[chunk])
                wave.pending_pushes[oid].add(shard)
                self.perf.inc("recovery_bytes", len(data))
                self.bus.send(shard, PushOp(self.whoami, oid, data,
                                            attrs=dict(attrs), omap=None,
                                            omap_header=b""))
            if not wave.pending_pushes[oid]:
                self._finish_wave_oid(wave, oid)
        for oid in sorted(wave.fallback):
            self._wave_fallback_one(wave, oid)

    def _wave_fallback_one(self, wave: _RecoveryWave, oid: str) -> None:
        def done(rec, _oid=oid, _wave=wave):
            _wave.on_each(_oid, rec.state == RecoveryState.COMPLETE)
        # a concurrent per-object recovery may have appeared (e.g. scrub):
        # the shared helper chains behind it per the one-op-per-object rule
        self._chain_or_recover(oid, set(wave.oids[oid]), done)

    def _wave_push_reply(self, wave: _RecoveryWave, reply) -> None:
        pend = wave.pending_pushes.get(reply.oid)
        if pend is None:
            return
        pend.discard(reply.from_shard)
        if not pend:
            self._finish_wave_oid(wave, reply.oid)

    def _finish_wave_oid(self, wave: _RecoveryWave, oid: str) -> None:
        self._wave_pushes.pop(oid, None)
        wave.pending_pushes.pop(oid, None)
        ok = oid not in wave.failed
        self.perf.inc("recoveries" if ok else "recovery_failures")
        wave.on_each(oid, ok)

    # -- chained streaming repair completion (recovery/chain.py) -----------

    def handle_message(self, msg) -> None:
        if isinstance(msg, ECPartialSumApplied):
            self._chain_applied(msg)
        elif isinstance(msg, ECPartialSumAbort):
            self._chain_abort(msg)
        else:
            super().handle_message(msg)

    def _chain_applied(self, msg: ECPartialSumApplied) -> None:
        chain = self._recovery_chains.get(msg.tid)
        if chain is None:
            return                        # late ack of an aborted chain
        pend = chain.pending_pushes.get(msg.oid)
        if pend is None or msg.from_shard not in pend:
            return                        # dup delivery
        pend.discard(msg.from_shard)
        # recovery_bytes counts chunk bytes LANDED on targets; the
        # centralized paths count at push-send — a chain's payloads
        # never transit the primary, so the ack is where the byte is
        # known delivered
        self.perf.inc("recovery_bytes", chain.lengths.get(msg.oid, 0))
        if pend:
            return
        if self.pg_log.last_version_of(msg.oid) != chain.at_version[msg.oid]:
            # a write raced the chain (the target-side stale gate already
            # refused genuinely older data): re-drive through the
            # verified per-object path rather than trust the mix
            self._wave_pushes.pop(msg.oid, None)
            chain.pending_pushes.pop(msg.oid, None)
            self._wave_fallback_one(chain, msg.oid)
        else:
            self.perf.inc(f"{getattr(chain, 'kind', 'chain')}_objects")
            self._finish_wave_oid(chain, msg.oid)
        if not chain.pending_pushes:
            self._recovery_chains.pop(msg.tid, None)
            self.perf.inc(f"{getattr(chain, 'kind', 'chain')}_repairs")

    def _chain_abort(self, msg: ECPartialSumAbort) -> None:
        """A hop refused its leg (missing/rotten/raced local chunk): the
        whole chain re-drives through the centralized verified path."""
        chain = self._recovery_chains.pop(msg.tid, None)
        if chain is None:
            return
        self.perf.inc(f"{getattr(chain, 'kind', 'chain')}_fallbacks")
        for oid in sorted(chain.pending_pushes):
            self._wave_pushes.pop(oid, None)
            self._wave_fallback_one(chain, oid)
        chain.pending_pushes.clear()

    # -- deep scrub (ECBackend.cc:2461-2546) -------------------------------

    def be_deep_scrub(self, oid: str) -> dict[int, bool]:
        """Recompute each up shard's cumulative crc vs its stored HashInfo;
        True = clean.  When overwrites have CLEARED the chunk hashes, fall
        back to parity-consistency checking: the code itself is the
        checksum (m redundant equations over the chunks), so silent bitrot
        is still detectable — and with a leave-one-out scan, locatable —
        without any stored digest."""
        out: dict[int, bool] = {}
        chunks_read: dict[int, bytes] = {}
        hash_cleared = False
        for chunk, shard in enumerate(self.acting):
            if shard in self.bus.down:
                continue
            store = shard_store(self.bus, shard)
            obj = GObject(oid, shard)
            try:
                data = store.read(obj)
                stored = store.getattr(obj, HINFO_KEY)
            except (FileNotFoundError, KeyError, ChecksumError):
                # ChecksumError: the store's at-rest crc located the rot
                out[chunk] = False
                continue
            # version check first: a shard that missed writes while down is
            # stale even when overwrites cleared the chunk hashes (the
            # PG-log-version role; see HashInfo.version)
            if stored.get("version", 0) != self._hinfo(oid).version:
                out[chunk] = False
                continue
            hashes = stored.get("cumulative_shard_hashes") or []
            if not hashes:
                hash_cleared = True
                chunks_read[chunk] = data
                out[chunk] = True          # provisional; parity check below
                continue
            out[chunk] = crc32c(0xFFFFFFFF, data) == hashes[chunk] and \
                len(data) == stored["total_chunk_size"]
        k = self.ec_impl.get_data_chunk_count()
        if hash_cleared and len(chunks_read) > k:
            # any spare equation suffices for DETECTION, even degraded
            self._parity_consistency_scrub(oid, chunks_read, out)
        return out

    def _parity_consistency_scrub(self, oid: str,
                                  chunks: dict[int, bytes],
                                  out: dict[int, bool]) -> None:
        """No stored digests (overwrites cleared them): the CODE is the
        checksum.  A chunk set with > k members is consistent iff every
        member is reproducible from k of the others; on inconsistency,
        leave-one-out localisation accepts a candidate only when it is
        UNIQUE (single rot with m >= 2).  Ambiguous rot — m=1, multi-chunk,
        or too-degraded-to-localise — flags every scanned chunk so the
        report surfaces it; repair skips such unrecoverable sets."""
        k = self.ec_impl.get_data_chunk_count()
        length = max(len(b) for b in chunks.values())
        stack = {c: np.frombuffer(b.ljust(length, b"\0"), dtype=np.uint8)
                 for c, b in chunks.items()}

        def consistent(ids) -> bool:
            ids = sorted(ids)
            if len(ids) <= k:
                return True          # no redundancy: vacuously consistent
            for target in ids:
                others = {i: stack[i] for i in ids if i != target}
                try:
                    rec = self.ec_impl.decode({target}, others, length)
                except Exception:
                    return False
                if not np.array_equal(
                        np.asarray(rec[target], dtype=np.uint8),
                        stack[target]):
                    return False
            return True

        present = sorted(stack)
        if consistent(present):
            return
        cands = [c for c in present
                 if consistent([i for i in present if i != c])]
        if len(cands) == 1:
            out[cands[0]] = False
        else:
            for c in present:        # detected but unlocatable
                out[c] = False


def make_cluster(ec_impl, chunk_size: int = 4096, cct=None):
    """Build a primary + shard OSDs wired on one bus; returns (backend, bus).

    Chunk i lives on shard id i (identity crush mapping) with the primary
    colocated on shard 0, the common layout in the standalone EC tests
    (reference: qa/standalone/erasure-code/test-erasure-code.sh:21-66).
    """
    n = ec_impl.get_chunk_count()
    k = ec_impl.get_data_chunk_count()
    bus = MessageBus()
    backend = ECBackend(ec_impl, StripeInfo(k, chunk_size), bus,
                        acting=list(range(n)), whoami=0, cct=cct)
    for shard in range(1, n):
        OSDShard(shard, bus)
    return backend, bus
