"""The erasure-coded backend: write pipeline, reconstructing reads, recovery.

Analog of the reference's ``ECBackend`` (reference: src/osd/ECBackend.{h,cc};
design note ECBackend.h:520-564) restructured TPU-first:

- Same three-stage ordered write pipeline — ``waiting_state ->
  waiting_reads -> waiting_commit`` driven by ``try_state_to_reads /
  try_reads_to_commit / try_finish_rmw`` from ``check_ops``
  (ECBackend.cc:1856,1930,2089,2137).
- Same sub-op fan-out over a messenger (here the deterministic
  :class:`~ceph_tpu.backend.messages.MessageBus`), one shard-local
  transaction per acting shard (ECBackend.cc:2036-2070), self-delivery for
  the primary's own shard (:2059-2061).
- BUT encode/decode are **batched across all stripes of an op** into one
  device call via :mod:`ceph_tpu.backend.ecutil` instead of the reference's
  per-stripe loop — the restructuring SURVEY.md §2.2 calls the main TPU hook.

Shards are ``OSDShard`` objects (MemStore + handler).  Failure is modelled by
``bus.mark_down``: a dead shard drops requests, the primary routes around it
using ``minimum_to_decode`` exactly like degraded reads do in the reference
(ECBackend.cc:1588-1625), and ``recover_object`` runs the
IDLE->READING->WRITING->COMPLETE machine (ECBackend.h:249-293).
"""
from __future__ import annotations

import pickle
from collections import deque
from dataclasses import dataclass, field
from enum import Enum

import numpy as np

from .ecutil import HINFO_KEY, HashInfo, StripeInfo, crc32c, decode_shards
from . import ecutil
from .extent import ExtentSet
from .extent_cache import ExtentCache
from .memstore import GObject, MemStore, Transaction
from .messages import (ECSubRead, ECSubReadReply, ECSubWrite, ECSubWriteReply,
                       MessageBus, PGLogInfo, PGLogQuery, PGLogUpdate,
                       PGScan, PGScanReply, PushOp, PushReply,
                       RollForward, Rollback)
from .transaction import PGTransaction, WritePlan, get_write_plan
from ..osd.pg_log import OP_DELETE, OP_MODIFY, PGLog, dedup_latest


PG_META = "_pgmeta_"          # the reference's pgmeta object: PG log +
                              # rollback info live in its omap so they
                              # commit atomically with the data they cover


def _log_key(version: int) -> str:
    return f"log.{version:016d}"


def _rb_key(version: int) -> str:
    return f"rb.{version:016d}"


class OSDShard:
    """One shard OSD: an ObjectStore plus the server side of the EC sub-ops
    (handle_sub_write ECBackend.cc:910-983, handle_sub_read :985-1031,
    recovery push :511-563) and a per-shard PG log that advances with
    every applied sub-write (the reference logs entries in
    handle_sub_write before queueing the transaction, ECBackend.cc:956).

    The PG log, its (head, tail) and per-write rollback info persist in
    the ``_pgmeta_`` object's omap INSIDE the same transaction as the data
    they describe — the reference stores the PG log in the pgmeta omap the
    same way — so a durable store (FileStore) survives restart with log
    and rollback state intact and boots via ``_load_pg_state``."""

    def __init__(self, shard: int, bus: MessageBus, store=None):
        self.shard = shard
        self.store = store if store is not None else MemStore()
        self.bus = bus
        self.pg_log = PGLog()
        # at_version -> inverse transaction restoring the pre-write state:
        # the rollback info the reference's log entries carry until the
        # write is rolled forward (ecbackend.rst:149-174)
        self.pending_rollbacks: dict[int, Transaction] = {}
        self._load_pg_state()
        bus.register(shard, self)

    def _meta(self) -> GObject:
        return GObject(PG_META, self.shard)

    def _load_pg_state(self) -> None:
        """Boot: rebuild the in-RAM log + rollback map from the pgmeta
        omap (the OSD::init superblock/PG-load path, OSD.cc:2719)."""
        if not self.store.exists(self._meta()):
            return
        omap = self.store.get_omap(self._meta())
        head, tail = pickle.loads(omap["vi"]) if "vi" in omap else (0, 0)
        self.pg_log.tail = tail
        self.pg_log.head = tail
        for key in sorted(k for k in omap if k.startswith("log.")):
            e = pickle.loads(omap[key])
            if e.version > self.pg_log.head:
                self.pg_log.record(e)
        self.pg_log.head = max(self.pg_log.head, head)
        for key in (k for k in omap if k.startswith("rb.")):
            inv = Transaction()
            inv.ops = pickle.loads(omap[key])
            self.pending_rollbacks[int(key[3:])] = inv

    def _persist_vi(self, t: Transaction) -> None:
        t.omap_setkeys(self._meta(), {"vi": pickle.dumps(
            (self.pg_log.head, self.pg_log.tail))})

    def _capture_rollback(self, t: Transaction) -> Transaction:
        """Inverse transaction: snapshot every touched object's prior state
        (chunk-sized objects make whole-object capture cheap).  The pgmeta
        object is never captured — its log/rb keys are unwound explicitly
        by _rollback, and snapshotting it would embed every prior rb blob
        in each new one."""
        touched = {op[1] for op in t.ops}
        touched |= {op[2] for op in t.ops if op[0] == "clone"}
        touched = {obj for obj in touched if obj.oid != PG_META}
        inv = Transaction()
        for obj in sorted(touched, key=lambda g: (g.oid, g.shard)):
            o = self.store.objects.get(obj)
            inv.remove(obj)
            if o is not None:
                inv.write(obj, 0, bytes(o.data))
                for name, value in o.xattrs.items():
                    inv.setattr(obj, name, value)
                if o.omap:
                    inv.omap_setkeys(obj, dict(o.omap))
        return inv

    def _roll_forward(self, to: int, txn: Transaction | None = None) -> None:
        """Drop rollback data for entries <= ``to``; the key removals ride
        ``txn`` when given (piggybacked roll-forward) or commit on their
        own (the standalone kick)."""
        dropped = [v for v in self.pending_rollbacks if v <= to]
        if not dropped:
            return
        for v in dropped:
            del self.pending_rollbacks[v]
        t = txn if txn is not None else Transaction()
        t.omap_rmkeys(self._meta(), [_rb_key(v) for v in dropped])
        if txn is None:
            self.store.queue_transaction(t)

    def _rollback(self, to: int) -> None:
        """Undo logged-but-not-rolled-forward entries past ``to``, newest
        first, and rewind the log — one atomic transaction."""
        t = Transaction()
        rb = sorted((v for v in self.pending_rollbacks if v > to),
                    reverse=True)
        for v in rb:
            t.append(self.pending_rollbacks.pop(v))
        dropped = self.pg_log.rewind(to)
        if not rb and not dropped:
            return
        t.omap_rmkeys(self._meta(),
                      [_rb_key(v) for v in rb] +
                      [_log_key(e.version) for e in dropped])
        self._persist_vi(t)
        self.store.queue_transaction(t)

    def handle_message(self, msg) -> None:
        if isinstance(msg, ECSubWrite):
            if msg.log_entries and msg.at_version <= self.pg_log.head:
                # duplicate delivery of an already-applied write: re-ack
                # without re-applying (reqid dedup in the reference)
                self.bus.send(msg.from_shard,
                              ECSubWriteReply(self.shard, msg.tid,
                                              gen=msg.gen))
                return
            t = msg.t
            if msg.log_entries:
                # capture rollback info FIRST — before roll-forward/meta
                # ops are appended to t — so the inverse covers only the
                # data objects; log keys are unwound explicitly by
                # _rollback
                inv = self._capture_rollback(t)
                self.pending_rollbacks[msg.at_version] = inv
                kvs = {_rb_key(msg.at_version):
                       pickle.dumps(inv.ops,
                                    protocol=pickle.HIGHEST_PROTOCOL)}
                for e in msg.log_entries:
                    if e.version > self.pg_log.head:
                        self.pg_log.record(e)
                    kvs[_log_key(e.version)] = pickle.dumps(
                        e, protocol=pickle.HIGHEST_PROTOCOL)
                t.omap_setkeys(self._meta(), kvs)
            if msg.roll_forward_to:
                self._roll_forward(msg.roll_forward_to, txn=t)
            if msg.trim_to:
                old_tail = self.pg_log.tail
                if self.pg_log.trim(msg.trim_to):
                    t.omap_rmkeys(self._meta(), [
                        _log_key(v)
                        for v in range(old_tail + 1, msg.trim_to + 1)])
                self._roll_forward(msg.trim_to, txn=t)
            if msg.log_entries or msg.trim_to:
                self._persist_vi(t)
            self.store.queue_transaction(t)
            self.bus.send(msg.from_shard,
                          ECSubWriteReply(self.shard, msg.tid, gen=msg.gen))
        elif isinstance(msg, RollForward):
            self._roll_forward(msg.to)
        elif isinstance(msg, Rollback):
            self._rollback(msg.to)
        elif isinstance(msg, PGLogQuery):
            self.bus.send(msg.from_shard, PGLogInfo(
                self.shard, self.pg_log.head, self.pg_log.tail,
                entries=self.pg_log.entries_after(msg.since) or []))
        elif isinstance(msg, PGScan):
            self.bus.send(msg.from_shard, PGScanReply(
                self.shard, oids=sorted({g.oid for g in self.store.objects
                                         if g.shard == self.shard
                                         and g.oid != PG_META})))
        elif isinstance(msg, PGLogUpdate):
            # divergent entries past the rewind point were superseded by the
            # repair's pushes: drop their rollback data without applying it
            dropped_rb = [v for v in self.pending_rollbacks
                          if v > msg.rewind_to]
            for v in dropped_rb:
                del self.pending_rollbacks[v]
            pre = {_log_key(e.version) for e in self.pg_log.entries}
            self.pg_log.merge_authoritative(
                msg.entries, msg.last_update, msg.rewind_to, msg.trim_to)
            post = {e.version: e for e in self.pg_log.entries}
            t = Transaction()
            gone = sorted(pre - {_log_key(v) for v in post}) + \
                [_rb_key(v) for v in dropped_rb]
            if gone:
                t.omap_rmkeys(self._meta(), gone)
            # only the shipped segment can contain new/changed entries;
            # surviving pre-merge keys are already on disk
            new_kvs = {_log_key(e.version): pickle.dumps(
                           e, protocol=pickle.HIGHEST_PROTOCOL)
                       for e in msg.entries if post.get(e.version) == e}
            if new_kvs:
                t.omap_setkeys(self._meta(), new_kvs)
            self._persist_vi(t)
            self.store.queue_transaction(t)
        elif isinstance(msg, ECSubRead):
            reply = ECSubReadReply(self.shard, msg.tid)
            for oid, extents in msg.to_read.items():
                obj = GObject(oid, self.shard)
                try:
                    bufs = []
                    for ext in extents:
                        off, length = ext[0], ext[1]
                        subchunks = ext[2] if len(ext) > 2 else None
                        data = self.store.read(obj, off, length)
                        if len(data) < length:
                            data = data + b"\0" * (length - len(data))
                        if subchunks is not None:
                            data = _slice_subchunks(data, subchunks,
                                                    msg.sub_chunk_count)
                        bufs.append((off, data))
                    reply.buffers_read[oid] = bufs
                    if msg.attrs_to_read:
                        reply.attrs_read[oid] = {
                            a: self.store.getattr(obj, a)
                            for a in msg.attrs_to_read
                            if a in self.store.objects[obj].xattrs}
                except FileNotFoundError:
                    reply.errors[oid] = -2  # ENOENT
            self.bus.send(msg.from_shard, reply)
        elif isinstance(msg, PushOp):
            t = Transaction()
            obj = GObject(msg.oid, self.shard)
            t.remove(obj).write(obj, 0, msg.data)
            for name, value in msg.attrs.items():
                t.setattr(obj, name, value)
            self.store.queue_transaction(t)
            self.bus.send(msg.from_shard, PushReply(self.shard, msg.oid))
        else:
            raise TypeError(f"shard {self.shard}: unexpected {msg!r}")


def _slice_subchunks(data: bytes, runs: list[tuple[int, int]],
                     sub_chunk_count: int) -> bytes:
    """Extract (offset, count) sub-chunk runs out of ``sub_chunk_count``
    equal sub-chunks (clay fractional reads, ECBackend.cc:1002-1024)."""
    sub_size = len(data) // max(sub_chunk_count, 1)
    return b"".join(data[off * sub_size:(off + c) * sub_size]
                    for off, c in runs)


class RecoveryState(Enum):
    IDLE = "IDLE"
    READING = "READING"
    WRITING = "WRITING"
    COMPLETE = "COMPLETE"
    # a push target died before acking: the object is still degraded there
    # (the reference's _failed_push path, ECBackend.cc:211-248)
    FAILED = "FAILED"


@dataclass
class RecoveryOp:
    """ECBackend::RecoveryOp (ECBackend.h:249-293)."""
    oid: str
    missing_shards: set[int]
    state: RecoveryState = RecoveryState.IDLE
    read_tid: int | None = None
    # pg_log version of the object when the recovery read was issued; a
    # bump while the read was in flight means a write landed and the
    # reconstructed bytes are stale — re-read instead of pushing them
    # (the reference serializes this with per-object recovery locks)
    at_version: int = 0
    pending_pushes: set[int] = field(default_factory=set)
    # sticky: a push target died before acking; even if the remaining
    # pushes ack, the op must finish FAILED (reference _failed_push fails
    # the whole op for any dead push target)
    failed: bool = False
    on_complete: object = None


class RepairState(Enum):
    QUERY = "QUERY"               # waiting for the shard's PGLogInfo
    SCAN = "SCAN"                 # backfill: waiting for the object list
    RECOVERING = "RECOVERING"     # pushes/deletes in flight
    COMPLETE = "COMPLETE"
    FAILED = "FAILED"


@dataclass
class ShardRepairOp:
    """Catch one stale/revived shard up, cheapest plan first: log equality
    (free) -> log replay (O(missed writes), PGLog.cc semantics) -> full
    backfill (O(objects), only past the log horizon)."""
    shard: int
    chunk: int
    state: RepairState = RepairState.QUERY
    plan: str = ""                # "clean" | "log" | "backfill"
    rewind_to: int = 0
    # authority log head when the repair's todo set was computed; writes
    # committing past it mid-repair skipped the stale target and must be
    # caught up before the shard is declared current
    caught_up_to: int = 0
    pending: set = field(default_factory=set)   # ("recover"|"delete", oid)
    objects_repaired: int = 0
    failed: bool = False
    on_complete: object = None


@dataclass
class Op:
    """In-flight client write (ECBackend::Op, ECBackend.h:390-440)."""
    tid: int
    t: PGTransaction
    on_commit: object
    # computed at pipeline admission (try_state_to_reads) so a rolled-back
    # op re-plans against the restored object state when re-admitted
    plan: WritePlan | None = None
    pending_read_shards: set[int] = field(default_factory=set)
    remote_reads: dict[str, dict[int, bytes]] = field(default_factory=dict)  # oid -> {logical off: stripe data}
    pending_commit_shards: set[int] = field(default_factory=set)
    acked_shards: set[int] = field(default_factory=set)
    cache_claims: list[tuple[str, int]] = field(default_factory=list)
    # version span (first_version, at_version] of this op's log entries,
    # recorded at fan-out; rollback rewinds to first_version - 1
    first_version: int = 0
    at_version: int = 0
    # dispatch generation: bumped each fan-out so stale acks from a
    # rolled-back dispatch are ignored
    gen: int = 0
    # reads unrecoverable with current up set; re-driven by on_shard_up
    _rmw_stalled: bool = False
    tracked: object = None      # OpTracker request (mark_event timeline)


@dataclass
class ReadOp:
    """In-flight client read (ECBackend::ReadOp, ECBackend.h:155-190)."""
    tid: int
    to_read: dict[str, list[tuple[int, int]]]     # oid -> [(logical off, len)]
    on_complete: object
    shard_extents: dict[str, tuple[int, int]] = field(default_factory=dict)  # oid -> (chunk off, len)
    want_shards: dict[str, set[int]] = field(default_factory=dict)
    # shard -> outstanding reply count (retries can address a shard twice)
    pending_shards: dict[int, int] = field(default_factory=dict)
    results: dict[str, dict[int, bytes]] = field(default_factory=dict)  # oid -> {shard: chunk bytes}
    errors: dict[str, set[int]] = field(default_factory=dict)
    tried_shards: dict[str, set[int]] = field(default_factory=dict)
    for_recovery: bool = False


class ECBackend:
    """Primary-side EC backend over a set of shard OSDs on a message bus."""

    def __init__(self, ec_impl, sinfo: StripeInfo, bus: MessageBus,
                 acting: list[int], whoami: int = 0, cct=None,
                 name: str = "", min_size: int = 0, store=None):
        # `name` disambiguates observability registrations when several
        # backends (e.g. one per PG) share a Context and a primary OSD id
        n = ec_impl.get_chunk_count()
        assert len(acting) == n, f"acting set must have {n} shards"
        self.ec_impl = ec_impl
        self.sinfo = sinfo
        self.bus = bus
        self.acting = list(acting)
        self.whoami = whoami
        # write availability floor: a write is never acked with fewer than
        # min_size current shards holding it (the pool min_size the
        # reference's PeeringState enforces by going inactive; VERDICT r3
        # item 1).  Floored at k: an ack on fewer than k shards would be
        # unreadable data, which is exactly the loss the gate prevents.
        self.min_size = max(min_size or 0, ec_impl.get_data_chunk_count())
        self.local_shard = OSDShard(whoami, bus, store=store)
        bus.handlers[whoami] = self  # primary intercepts its own queue
        self.next_tid = 0
        # write pipeline (ECBackend.h:562-564)
        self.waiting_state: deque[Op] = deque()
        self.waiting_reads: deque[Op] = deque()
        self.waiting_commit: deque[Op] = deque()
        self.tid_to_op: dict[int, Op] = {}
        # RMW pipeline reads get a fresh tid per dispatch so replies from a
        # superseded dispatch (shard death re-issue, rollback re-queue)
        # find no mapping and drop instead of polluting the op's buffers
        self._rmw_read_tids: dict[int, Op] = {}
        self.extent_cache = ExtentCache()
        # read path
        self.in_progress_reads: dict[int, ReadOp] = {}
        # recovery
        self.recovery_ops: dict[str, RecoveryOp] = {}
        self._recovery_read_tids: dict[int, RecoveryOp] = {}
        self.hinfo_cache: dict[str, HashInfo] = {}
        self._stalled_recoveries: list[RecoveryOp] = []
        # The authority log advances at fan-out; the local shard's own log
        # advances only when its self-delivered sub-write APPLIES.  Keeping
        # them separate is what lets a revived primary detect its own
        # staleness (writes committed by the other shards while it was
        # down) and repair itself through the same query/replay machinery.
        # On boot from a durable store, the local shard's persisted log IS
        # the authority (the reference elects the authoritative log during
        # peering; the primary's own is the single-primary analog) — half-
        # applied writes it logged roll FORWARD by repairing the peers.
        self.pg_log = PGLog()
        self.pg_log.tail = self.local_shard.pg_log.tail
        self.pg_log.head = self.local_shard.pg_log.tail
        for e in self.local_shard.pg_log.entries:
            self.pg_log.record(e)
        self.pg_log.head = max(self.pg_log.head,
                               self.local_shard.pg_log.head)
        # two-phase commit bookkeeping: committed_to = newest version acked
        # by >= min_size shards (the roll-forward point); _rolled_forward_to
        # = the point already announced to the shards
        self.committed_to = self.pg_log.head
        self._rolled_forward_to = self.pg_log.head
        self._rollback_pending = 0
        # shards that revived but have not been repaired yet: excluded from
        # reads AND from write fan-out until a shard repair completes (the
        # reference keeps stale shards out of the acting set until
        # recovery/backfill, PeeringState.cc)
        self.stale: set[int] = set()
        # boot peering (crash recovery): shard -> PGLogInfo while collecting
        self._boot_peering: dict[int, PGLogInfo] | None = None
        self._boot_peering_expect: set[int] = set()
        self.shard_repairs: dict[int, "ShardRepairOp"] = {}
        self._repair_write_tids: dict[int, tuple["ShardRepairOp", str]] = {}
        self._scan_waiters: dict[int, "ShardRepairOp"] = {}
        bus.down_listeners.append(self.on_shard_down)
        bus.up_listeners.append(self.on_shard_up)
        # observability (SURVEY.md §5): counters + op tracking + admin cmds
        from ..common import OpTracker, PerfCountersBuilder, default_context
        self.cct = cct if cct is not None else default_context()
        self.instance_name = name or str(whoami)
        self.perf = (
            PerfCountersBuilder(f"ec_backend.{self.instance_name}")
            .add_u64_counter("writes", "client writes committed")
            .add_u64_counter("write_rollbacks",
                             "in-flight writes rolled back (min_size)")
            .add_u64_counter("reads", "client reads completed")
            .add_u64_counter("read_errors", "per-object read failures (EIO)")
            .add_u64_counter("write_bytes", "client bytes written")
            .add_u64_counter("stripe_bytes_encoded",
                             "stripe-aligned bytes through encode (>= "
                             "write_bytes: RMW pads to whole stripes)")
            .add_u64_counter("read_bytes", "logical bytes returned")
            .add_u64_counter("recoveries", "recovery ops completed")
            .add_u64_counter("recovery_failures", "recovery ops failed")
            .add_u64_counter("log_repairs_clean",
                             "shard repairs satisfied by log equality alone")
            .add_u64_counter("log_repairs", "log-based shard catch-ups")
            .add_u64_counter("log_repair_objects",
                             "objects replayed by log catch-up")
            .add_u64_counter("shard_backfills",
                             "repairs past the log horizon (full backfill)")
            .add_u64_counter("backfill_objects",
                             "objects moved by shard backfill")
            .add_time_avg("encode_time", "batched encode wall time")
            .add_time_avg("decode_time", "batched decode wall time")
            .add_u64("pipeline_depth", "ops across the three wait lists")
            .create_perf_counters())
        self.cct.perf.add(self.perf)
        self.op_tracker = OpTracker()
        for cmd, fn in ((f"dump_ops_in_flight.{self.instance_name}",
                         lambda **kw: self.op_tracker.dump_ops_in_flight()),
                        (f"dump_historic_ops.{self.instance_name}",
                         lambda **kw: self.op_tracker.dump_historic_ops())):
            # a re-created backend with the same name takes over the hook
            # (leaving the old registration would serve — and pin — the
            # dead backend's tracker)
            self.cct.admin_socket.unregister(cmd)
            self.cct.admin_socket.register(cmd, fn)

    # -- helpers -----------------------------------------------------------

    def up_shards(self) -> set[int]:
        return {s for s in self.acting if s not in self.bus.down}

    def current_shards(self) -> set[int]:
        """Up AND repaired: the shards that may serve reads and receive
        write fan-out (the reference's acting set after peering; stale
        revived shards rejoin once their shard repair completes)."""
        return {s for s in self.acting
                if s not in self.bus.down and s not in self.stale}

    def is_active(self) -> bool:
        """Writes proceed only while >= min_size current shards exist (the
        PG-active gate of PeeringState; below it client writes park in
        waiting_state until shards return — never acked, never lost)."""
        return len(self.current_shards()) >= self.min_size

    def _hinfo(self, oid: str) -> HashInfo:
        if oid not in self.hinfo_cache:
            n = self.ec_impl.get_chunk_count()
            try:
                stored = self.local_shard.store.getattr(
                    GObject(oid, self.whoami), HINFO_KEY)
                h = HashInfo(n)
                h.total_chunk_size = stored["total_chunk_size"]
                h.cumulative_shard_hashes = list(stored["cumulative_shard_hashes"])
                h.projected_total_chunk_size = h.total_chunk_size
                h.version = stored.get("version", 0)
            except (FileNotFoundError, KeyError):
                h = HashInfo(n)
            self.hinfo_cache[oid] = h
        return self.hinfo_cache[oid]

    def object_size(self, oid: str) -> int:
        return self._hinfo(oid).get_total_logical_size(self.sinfo)

    # -- message dispatch --------------------------------------------------

    def handle_message(self, msg) -> None:
        if isinstance(msg, ECSubWriteReply):
            self.handle_sub_write_reply(msg)
        elif isinstance(msg, ECSubReadReply):
            self.handle_sub_read_reply(msg)
        elif isinstance(msg, PushReply):
            self.handle_push_reply(msg)
        elif isinstance(msg, PGLogInfo):
            self.handle_pg_log_info(msg)
        elif isinstance(msg, PGScanReply):
            self.handle_pg_scan_reply(msg)
        elif isinstance(msg, Rollback):
            # primary's own shard rolls back; the authority-side hinfo cache
            # reflects the rolled-back write and must be re-read from the
            # restored xattrs before re-queued ops re-plan
            self.local_shard.handle_message(msg)
            self.hinfo_cache.clear()
            self._rollback_pending = max(0, self._rollback_pending - 1)
            self.check_ops()
        else:
            self.local_shard.handle_message(msg)

    def shutdown(self, checkpoint_store: bool = True) -> None:
        """Unhook from the shared Context and bus so a discarded backend is
        collectable (registration without teardown pins the backend — and
        its trackers/stores — for the context's lifetime)."""
        self.cct.perf.remove(self.perf.name)
        self.cct.admin_socket.unregister(
            f"dump_ops_in_flight.{self.instance_name}")
        self.cct.admin_socket.unregister(
            f"dump_historic_ops.{self.instance_name}")
        for lst in (self.bus.down_listeners, self.bus.up_listeners):
            for cb in list(lst):
                if getattr(cb, "__self__", None) is self:
                    lst.remove(cb)
        # hand the shard queue back to the plain shard handler so the bus
        # no longer references this backend
        if self.bus.handlers.get(self.whoami) is self:
            self.bus.handlers[self.whoami] = self.local_shard
        if hasattr(self.local_shard.store, "close"):
            self.local_shard.store.close(checkpoint=checkpoint_store)

    # -- failure handling --------------------------------------------------

    def on_shard_down(self, shard: int) -> None:
        """Route around a shard that died with requests outstanding — the
        analog of the reference's on_change/check_recovery_sources paths
        re-driving in-flight ops when the acting set changes
        (ECBackend.cc check_recovery_sources, _failed_push).  The commit
        stage already prunes in try_finish_rmw; this covers the read
        stages."""
        if shard not in set(self.acting):
            return
        chunk = self.acting.index(shard)
        # RMW pipeline reads: re-issue from the remaining shards
        for op in list(self.waiting_reads):
            if shard in op.pending_read_shards:
                op.pending_read_shards.clear()
                try:
                    self._start_rmw_reads(op, op._rmw_need)
                    op._rmw_stalled = False
                except IOError:
                    # unrecoverable: too few shards — the op stays queued
                    # (the PG is effectively down, like the reference's
                    # incomplete state) and is re-driven by on_shard_up;
                    # the -1 sentinel keeps try_reads_to_commit from running
                    # with missing data (no real reply ever clears it)
                    op.pending_read_shards.add(-1)
                    op._rmw_stalled = True
        # client reads: treat like an error reply from that shard
        for rop in list(self.in_progress_reads.values()):
            if shard in rop.pending_shards:
                rop.pending_shards.pop(shard, None)
                for oid in rop.to_read:
                    # tried_shards holds every chunk actually requested
                    # (including retry-widened ones); want_shards is only
                    # the initial minimum set
                    if (chunk in rop.tried_shards.get(oid, ()) and
                            chunk not in rop.results.get(oid, {})):
                        rop.errors.setdefault(oid, set()).add(chunk)
                        self._retry_remaining_shards(rop, oid)
                if not rop.pending_shards:
                    self._complete_read_op(rop)
        # recovery reads: restart the op's READING phase from live shards
        for tid, rop in list(self._recovery_read_tids.items()):
            if shard in rop._pending:
                del self._recovery_read_tids[tid]
                rop.state = RecoveryState.IDLE
                try:
                    self.continue_recovery_op(rop)
                except IOError:
                    # too few survivors: park; re-driven by on_shard_up
                    self._stalled_recoveries.append(rop)
        # recovery pushes: a dead target never acks and is still degraded —
        # the op FAILS (the reference's _failed_push), it is not COMPLETE
        for oid, rop in list(self.recovery_ops.items()):
            if shard in rop.pending_pushes:
                rop.pending_pushes.discard(shard)
                rop.failed = True
                if not rop.pending_pushes and rop.state == RecoveryState.WRITING:
                    self._finish_recovery_op(rop, failed=True)
        # a shard under repair that dies again: the repair fails (its
        # revival restarts it via the boot path)
        srop = self.shard_repairs.get(shard)
        if srop is not None:
            srop.failed = True
            self._repair_write_tids = {
                tid: v for tid, v in self._repair_write_tids.items()
                if v[0] is not srop}
            srop.pending.clear()
            self._finish_shard_repair(srop)
        self.try_finish_rmw()
        self.check_ops()

    def on_shard_up(self, shard: int) -> None:
        """A revived shard is stale — it missed every write since it died —
        so it is kept out of reads and write fan-out and a shard repair
        starts automatically (the reference re-peers on the osdmap epoch
        bump, which drives log-based recovery the same way).  Parked work
        re-drives now and again when the repair completes."""
        if shard in self.acting:
            # stale until repair completes: serving reads could return old
            # bytes; receiving new writes would make its log head current
            # while mid-history entries are missing, defeating log catch-up
            self.stale.add(shard)
            if shard not in self.shard_repairs:
                self.start_shard_repair(shard)
        self._redrive_parked()

    def _redrive_parked(self) -> None:
        """Re-drive ops parked by unrecoverable shard loss (called on shard
        revival and on repair completion, when current_shards() grows)."""
        for op in list(self.waiting_reads):
            if getattr(op, "_rmw_stalled", False):
                op.pending_read_shards.clear()
                try:
                    self._start_rmw_reads(op, op._rmw_need)
                    op._rmw_stalled = False
                except IOError:
                    op.pending_read_shards.add(-1)
                    op._rmw_stalled = True
        stalled, self._stalled_recoveries = self._stalled_recoveries, []
        for rop in stalled:
            try:
                self.continue_recovery_op(rop)
            except IOError:
                self._stalled_recoveries.append(rop)
        # a stale shard whose repair FAILED (a peer died mid-repair) gets a
        # fresh repair on the next cluster event — the role re-peering on
        # a map change plays in the reference
        for shard in sorted(self.stale & self.up_shards()):
            if shard not in self.shard_repairs:
                self.start_shard_repair(shard)
        self.check_ops()

    # -- write pipeline ----------------------------------------------------

    def submit_transaction(self, t: PGTransaction, on_commit=None) -> int:
        """Client entry point (ECBackend.cc:1477 -> start_rmw :1830).

        While the PG is inactive (< min_size current shards) the op parks
        in waiting_state — queued, unacked, unapplied — and is re-driven
        when shards return (the reference blocks I/O on an inactive PG)."""
        self.next_tid += 1
        tid = self.next_tid
        op = Op(tid=tid, t=t, on_commit=on_commit)
        op.tracked = self.op_tracker.create_request(
            f"osd_op(write tid={tid} objects={sorted(t.ops)})")
        op.tracked.mark_event("queued_for_pg")
        self.tid_to_op[tid] = op
        self.waiting_state.append(op)
        self._update_pipeline_depth()
        self.check_ops()
        return tid

    def _update_pipeline_depth(self) -> None:
        self.perf.set("pipeline_depth",
                      len(self.waiting_state) + len(self.waiting_reads) +
                      len(self.waiting_commit))

    def check_ops(self) -> None:
        """Advance each pipeline stage's head as far as possible
        (ECBackend.cc:2137-2145).  Re-loops because an op reaching the
        commit stage pins its result in the extent cache, which can unblock
        a stalled overlapping op behind it.  Gated on the PG being active
        (min_size current shards) and on no rollback being mid-flight (a
        re-queued op must re-plan against the restored state)."""
        if not self.is_active() or self._rollback_pending:
            return
        progress = True
        while progress:
            progress = False
            if self.waiting_state and self.try_state_to_reads():
                progress = True
            if self.waiting_reads and self.try_reads_to_commit():
                progress = True

    def _blocked_on_inflight_write(self, op: Op) -> bool:
        """An RMW read overlapping an earlier in-flight write must wait until
        that write's bytes are pinned in the cache — the ordering invariant
        the reference's ExtentCache reservation enforces
        (doc/dev/osd_internals/erasure_coding/ecbackend.rst:190-206)."""
        for oid, to_read in op.plan.to_read.items():
            for off, length in to_read:
                # NB: a cache hit does NOT lift the block — cached bytes may
                # be an older op's; any not-yet-committed overlapping write
                # ahead of us must land in the cache first
                for other in self.waiting_reads:
                    ww = other.plan.will_write.get(oid)
                    if ww is not None and ww.intersects(off, length):
                        return True
        return False

    def try_state_to_reads(self) -> bool:
        """(ECBackend.cc:1856-1928): satisfy RMW reads from the extent cache
        where pinned; issue remote shard reads for the rest."""
        op = self.waiting_state[0]
        if op.plan is None:
            op.plan = get_write_plan(self.sinfo, op.t, self._hinfo)
        if self._blocked_on_inflight_write(op):
            return False
        need_remote: dict[str, ExtentSet] = {}
        for oid, to_read in op.plan.to_read.items():
            for off, length in to_read:
                cached = self.extent_cache.read(oid, off, length)
                if cached is not None:
                    op.remote_reads.setdefault(oid, {})[off] = cached
                else:
                    need_remote.setdefault(oid, ExtentSet()).union_insert(off, length)
        self.waiting_state.popleft()
        self.waiting_reads.append(op)
        if need_remote:
            self._start_rmw_reads(op, need_remote)
        return True

    def _start_rmw_reads(self, op: Op, need: dict[str, ExtentSet]) -> None:
        """Read the full stripes from k data shards (reads are stripe-aligned
        whole stripes, so the k data chunks suffice when healthy; degraded
        objects fall back to the reconstructing read path)."""
        k = self.ec_impl.get_data_chunk_count()
        cur = self.current_shards()
        want = {self.ec_impl.chunk_index(i) for i in range(k)}
        avail = {i for i, s in enumerate(self.acting) if s in cur}
        minimum = self.ec_impl.minimum_to_decode(want, avail)
        per_shard: dict[int, dict[str, list[tuple]]] = {}
        for oid, es in need.items():
            for off, length in es:
                c_off = self.sinfo.aligned_logical_offset_to_chunk_offset(off)
                c_len = self.sinfo.aligned_logical_offset_to_chunk_offset(length)
                for chunk in minimum:
                    shard = self.acting[chunk]
                    per_shard.setdefault(shard, {}).setdefault(oid, []).append(
                        (c_off, c_len))
        op._rmw_chunks = {c: self.acting[c] for c in minimum}
        op._rmw_need = need
        op._rmw_buf: dict[str, dict[int, dict[int, bytes]]] = {}
        self._rmw_read_tids.pop(getattr(op, "_rmw_read_tid", None), None)
        self.next_tid += 1
        op._rmw_read_tid = self.next_tid
        self._rmw_read_tids[op._rmw_read_tid] = op
        for shard, to_read in per_shard.items():
            op.pending_read_shards.add(shard)
            self.bus.send(shard, ECSubRead(self.whoami, op._rmw_read_tid,
                                           to_read))

    def try_reads_to_commit(self) -> bool:
        """(ECBackend.cc:1930-2087): encode the will-write extents in one
        batched device call and fan out per-shard transactions."""
        op = self.waiting_reads[0]
        if op.pending_read_shards:
            return False
        self.waiting_reads.popleft()
        self.waiting_commit.append(op)

        n = self.ec_impl.get_chunk_count()
        shard_txns = {shard: Transaction() for shard in self.acting}
        log_entries = []
        op.first_version = self.pg_log.head + 1
        for oid, will_write in op.plan.will_write.items():
            objop = op.plan.t.ops[oid]
            hinfo = op.plan.hash_infos[oid]
            hinfo.version += 1      # down shards miss this bump -> stale
            # one pg_log entry per touched object (pg_log_entry_t); a pure
            # delete logs DELETE, anything that leaves data logs MODIFY
            is_delete = (objop.delete_first and not objop.buffer_updates
                         and objop.truncate is None)
            log_entries.append(self.pg_log.append(
                oid, OP_DELETE if is_delete else OP_MODIFY))
            if objop.delete_first:
                for chunk, shard in enumerate(self.acting):
                    shard_txns[shard].remove(GObject(oid, shard))
                hinfo.clear()
            if objop.truncate is not None:
                # truncate-before-writes: shrink every shard to the chunk
                # offset of the next stripe boundary, then let the rewritten
                # partial stripe (planned by get_write_plan) land on top
                # (reference: ECTransaction.cc generate_transactions truncate
                # handling; ECTransaction.h:70-86)
                t_logical = self.sinfo.logical_to_next_stripe_offset(
                    objop.truncate[0])
                t_chunk = self.sinfo.aligned_logical_offset_to_chunk_offset(
                    t_logical)
                if t_chunk < hinfo.total_chunk_size:
                    for chunk, shard in enumerate(self.acting):
                        shard_txns[shard].truncate(GObject(oid, shard), t_chunk)
                    hinfo.set_total_chunk_size_clear_hash(t_chunk)
            if not will_write:
                if not objop.delete_first:
                    self._persist_hinfo(oid, hinfo, shard_txns)
                continue
            # assemble the logical bytes for every will_write extent
            pieces: list[tuple[int, bytes]] = []
            for off, length in will_write:
                pieces.append((off, self._assemble_extent(op, oid, objop, off, length)))
            # ONE batched encode over all extents' stripes
            logical = np.concatenate(
                [np.frombuffer(b, dtype=np.uint8) for _, b in pieces])
            with self.perf.time("encode_time"):
                encoded = ecutil.encode(self.sinfo, self.ec_impl, logical)
            self.perf.inc("stripe_bytes_encoded", int(logical.nbytes))
            if op.tracked:
                op.tracked.mark_event("encoded")
            # scatter per-extent chunk ranges into shard transactions
            c_cursor = 0
            old_size = hinfo.total_chunk_size
            append_chunks: dict[int, np.ndarray] = {}
            appended = 0
            pure_append = True
            for off, data in pieces:
                c_off = self.sinfo.aligned_logical_offset_to_chunk_offset(off)
                c_len = self.sinfo.aligned_logical_offset_to_chunk_offset(len(data))
                for chunk in range(n):
                    shard = self.acting[chunk]
                    payload = encoded[chunk][c_cursor:c_cursor + c_len]
                    shard_txns[shard].write(
                        GObject(oid, shard), c_off, payload.tobytes())
                if pure_append and c_off == old_size + appended:
                    for chunk in range(n):
                        prev = append_chunks.get(chunk)
                        seg = encoded[chunk][c_cursor:c_cursor + c_len]
                        append_chunks[chunk] = seg if prev is None else \
                            np.concatenate([prev, seg])
                    appended += c_len
                else:
                    pure_append = False
                c_cursor += c_len
                self.extent_cache.claim(oid, op.tid, off, data)
                op.cache_claims.append((oid, op.tid))
            # hash maintenance: pure appends chain the crc (HashInfo::append,
            # ECUtil.cc:161-177); overwrites invalidate it and deep scrub
            # recomputes from data
            if pure_append and appended:
                hinfo.append(old_size, append_chunks)
            elif not pure_append:
                hinfo.set_total_chunk_size_clear_hash(
                    hinfo.projected_total_chunk_size)
            self._persist_hinfo(oid, hinfo, shard_txns)

        # fan out ECSubWrite to every current shard (down/stale shards miss
        # the write and are repaired later by the log — the reference's
        # peering likewise keeps them out of the acting set)
        cur = self.current_shards()
        op.at_version = self.pg_log.head
        op.gen += 1
        op.acked_shards = set()
        op.pending_commit_shards = set(cur)
        trim_to = self.pg_log.trim_target()
        for shard in self.acting:
            if shard in cur:
                self.bus.send(shard, ECSubWrite(
                    self.whoami, op.tid, shard_txns[shard],
                    at_version=op.at_version, trim_to=trim_to,
                    log_entries=list(log_entries),
                    roll_forward_to=self.committed_to, gen=op.gen))
        self._rolled_forward_to = max(self._rolled_forward_to,
                                      self.committed_to)
        self.pg_log.maybe_trim()
        return True

    def _assemble_extent(self, op: Op, oid: str, objop, off: int,
                         length: int) -> bytes:
        """Merge read-in stripes, cached stripes, and the op's new writes
        into the stripe-aligned extent [off, off+length)."""
        buf = bytearray(length)
        reads = op.remote_reads.get(oid, {})
        for r_off, data in reads.items():
            if r_off >= off + length or r_off + len(data) <= off:
                continue
            s = max(r_off, off)
            e = min(r_off + len(data), off + length)
            buf[s - off:e - off] = data[s - r_off:e - r_off]
        if objop.truncate is not None:
            t0 = objop.truncate[0]
            if off <= t0 < off + length:
                buf[t0 - off:] = b"\0" * (off + length - t0)
        for w_off, data in objop.buffer_updates:
            if w_off >= off + length or w_off + len(data) <= off:
                continue
            s = max(w_off, off)
            e = min(w_off + len(data), off + length)
            buf[s - off:e - off] = data[s - w_off:e - w_off]
        return bytes(buf)

    def _persist_hinfo(self, oid: str, hinfo: HashInfo, shard_txns) -> None:
        for shard in self.acting:
            shard_txns[shard].setattr(GObject(oid, shard), HINFO_KEY,
                                      hinfo.to_dict())

    def handle_sub_write_reply(self, reply: ECSubWriteReply) -> None:
        """(ECBackend.cc:1120-1152) -> try_finish_rmw (:2089)."""
        rep = self._repair_write_tids.pop(reply.tid, None)
        if rep is not None:                 # a shard-repair delete acked
            rop, oid = rep
            rop.pending.discard(("delete", oid))
            self._maybe_finish_shard_repair(rop)
            return
        op = self.tid_to_op.get(reply.tid)
        if op is None or reply.gen != op.gen:
            return                      # stale ack from a rolled-back dispatch
        op.acked_shards.add(reply.from_shard)
        op.pending_commit_shards.discard(reply.from_shard)
        self.try_finish_rmw()

    def try_finish_rmw(self) -> None:
        while self.waiting_commit:
            op = self.waiting_commit[0]
            # shards that died after dispatch can never ack
            op.pending_commit_shards &= self.up_shards()
            if op.pending_commit_shards:
                return
            # write-availability gate (ecbackend.rst:149-174): the write is
            # durable only if >= min_size shards hold it.  Shards that died
            # after acking still hold it on disk but can't serve; count
            # only live acks.  Below the floor the write — and every later
            # in-flight write — rolls back; nothing was ever acked to the
            # client, so nothing is lost.
            live_acked = op.acked_shards & self.up_shards()
            if len(live_acked) < self.min_size:
                self._rollback_incomplete()
                return
            self.waiting_commit.popleft()
            self.committed_to = max(self.committed_to, op.at_version)
            for oid, tid in op.cache_claims:
                self.extent_cache.release(oid, tid)
            del self.tid_to_op[op.tid]
            self.perf.inc("writes")
            self.perf.inc("write_bytes", sum(
                len(d) for objop in op.t.ops.values()
                for _, d in objop.buffer_updates))
            self._update_pipeline_depth()
            if op.tracked:
                op.tracked.mark_event("commit_sent")
                op.tracked.finish()
            if op.on_commit:
                op.on_commit(op.tid)
        # pipeline drained with an unannounced roll-forward point: kick it
        # to the shards so they drop rollback data (the reference's dummy
        # transaction, ECBackend.cc:2106-2120)
        if self.committed_to > self._rolled_forward_to:
            self._rolled_forward_to = self.committed_to
            for shard in sorted(self.current_shards()):
                self.bus.send(shard, RollForward(self.whoami,
                                                 self.committed_to))

    def _rollback_incomplete(self) -> None:
        """Undo every in-flight commit-stage write (head first failed; all
        later ones have higher versions and must unwind with it), rewind
        the authority log, and re-queue the ops at the pipeline head to
        re-plan and re-execute once the PG is active again.

        Ops still in waiting_reads / waiting_state are reset too: their
        plans and RMW reads were computed against HashInfo state and
        extent-cache bytes of the writes being rolled back."""
        ops = list(self.waiting_commit)
        self.waiting_commit.clear()
        to = ops[0].first_version - 1
        self.perf.inc("write_rollbacks", len(ops))
        read_ops = list(self.waiting_reads)
        self.waiting_reads.clear()
        state_ops = list(self.waiting_state)
        self.waiting_state.clear()
        ops = ops + read_ops + state_ops    # original pipeline order
        for shard in sorted(self.up_shards()):
            # FIFO per-shard queues order the Rollback after any still-
            # undelivered sub-writes of these ops, so every shard unwinds
            # exactly what it applied
            if shard == self.whoami:
                self._rollback_pending += 1
            self.bus.send(shard, Rollback(self.whoami, to))
        if self.whoami not in self.up_shards():
            # local shard marked down: its queue was cleared, so no sub-
            # write can race a synchronous local unwind
            self.local_shard._rollback(to)
            self.hinfo_cache.clear()
        self.pg_log.rewind(to)
        self.committed_to = min(self.committed_to, to)
        for op in ops:
            for oid, tid in op.cache_claims:
                self.extent_cache.release(oid, tid)
            op.cache_claims.clear()
            op.plan = None
            op.pending_read_shards.clear()
            op.remote_reads.clear()
            op.pending_commit_shards.clear()
            op.acked_shards.clear()
            self._rmw_read_tids.pop(getattr(op, "_rmw_read_tid", None), None)
            op._rmw_buf = {}
            op._rmw_stalled = False
            if op.tracked:
                op.tracked.mark_event("rolled_back")
        self.waiting_state.extend(ops)
        self._update_pipeline_depth()

    # -- read path ---------------------------------------------------------

    def objects_read_and_reconstruct(self, reads: dict[str, list[tuple[int, int]]],
                                     on_complete, fast_read: bool = False) -> int:
        """(ECBackend.cc:2331-2385): choose min shards per object, read
        chunk extents, reconstruct if any data shard is unavailable."""
        self.next_tid += 1
        tid = self.next_tid
        rop = ReadOp(tid=tid, to_read=reads, on_complete=on_complete)
        k = self.ec_impl.get_data_chunk_count()
        cur = self.current_shards()
        avail = {i for i, s in enumerate(self.acting) if s in cur}
        want = {self.ec_impl.chunk_index(i) for i in range(k)}
        per_shard: dict[int, dict[str, list[tuple]]] = {}
        for oid, extents in reads.items():
            lo = min(off for off, _ in extents)
            hi = max(off + ln for off, ln in extents)
            start, length = self.sinfo.offset_len_to_stripe_bounds(lo, hi - lo)
            c_off = self.sinfo.aligned_logical_offset_to_chunk_offset(start)
            c_len = self.sinfo.aligned_logical_offset_to_chunk_offset(length)
            rop.shard_extents[oid] = (c_off, c_len)
            minimum = self.ec_impl.minimum_to_decode(want, avail)
            if fast_read and len(avail) > len(minimum):
                # redundant reads: ask every available shard (ECBackend.cc:1609-1615)
                minimum = {c: [(0, self.ec_impl.get_sub_chunk_count())]
                           for c in avail}
            rop.want_shards[oid] = set(minimum)
            rop.tried_shards[oid] = set(minimum)
            for chunk, subchunks in minimum.items():
                shard = self.acting[chunk]
                runs = None if subchunks == [(0, self.ec_impl.get_sub_chunk_count())] \
                    else subchunks
                per_shard.setdefault(shard, {}).setdefault(oid, []).append(
                    (c_off, c_len, runs))
        rop.pending_shards = {shard: 1 for shard in per_shard}
        self.in_progress_reads[tid] = rop
        for shard, to_read in per_shard.items():
            self.bus.send(shard, ECSubRead(
                self.whoami, tid, to_read,
                sub_chunk_count=self.ec_impl.get_sub_chunk_count()))
        return tid

    def handle_sub_read_reply(self, reply: ECSubReadReply) -> None:
        """(ECBackend.cc:1153-1320): collect; on error widen the shard set
        (send_all_remaining_reads :2386)."""
        rop_rec = self._recovery_read_tids.get(reply.tid)
        if rop_rec is not None:
            self.handle_recovery_read_reply(rop_rec, reply)
            return
        # RMW pipeline reads
        op = self._rmw_read_tids.get(reply.tid)
        if op is not None:
            self._handle_rmw_read_reply(op, reply)
            return
        rop = self.in_progress_reads.get(reply.tid)
        if rop is None:
            return
        left = rop.pending_shards.get(reply.from_shard, 0) - 1
        if left <= 0:
            rop.pending_shards.pop(reply.from_shard, None)
        else:
            rop.pending_shards[reply.from_shard] = left
        chunk_of_shard = {s: c for c, s in enumerate(self.acting)}
        chunk = chunk_of_shard[reply.from_shard]
        for oid, bufs in reply.buffers_read.items():
            data = b"".join(b for _, b in bufs)
            rop.results.setdefault(oid, {})[chunk] = data
        for oid in reply.errors:
            rop.errors.setdefault(oid, set()).add(chunk)
            self._retry_remaining_shards(rop, oid)
        if not rop.pending_shards:
            self._complete_read_op(rop)

    def _retry_remaining_shards(self, rop: ReadOp, oid: str) -> None:
        """Incremental recovery from shard read errors (ECBackend.cc:1627-1671)."""
        k = self.ec_impl.get_data_chunk_count()
        up = self.current_shards()
        avail = {c for c, s in enumerate(self.acting)
                 if s in up and c not in rop.errors.get(oid, set())}
        untried = avail - rop.tried_shards[oid]
        # chunks already read + still outstanding on live shards + the new
        # candidates must reach k (ECBackend.cc:1627-1671 counts pending
        # shards as available too)
        pending = {c for c, s in enumerate(self.acting)
                   if s in rop.pending_shards and s in up and
                   c in rop.tried_shards[oid]}
        have_or_pending = (set(rop.results.get(oid, {})) | pending | untried) \
            - rop.errors.get(oid, set())
        if len(have_or_pending) < k:
            return  # complete_read_op will surface the failure
        c_off, c_len = rop.shard_extents[oid]
        for chunk in untried:
            shard = self.acting[chunk]
            rop.tried_shards[oid].add(chunk)
            rop.pending_shards[shard] = rop.pending_shards.get(shard, 0) + 1
            self.bus.send(shard, ECSubRead(
                self.whoami, rop.tid, {oid: [(c_off, c_len, None)]}))

    def _handle_rmw_read_reply(self, op: Op, reply: ECSubReadReply) -> None:
        op.pending_read_shards.discard(reply.from_shard)
        chunk_of_shard = {s: c for c, s in enumerate(self.acting)}
        chunk = chunk_of_shard[reply.from_shard]
        for oid, bufs in reply.buffers_read.items():
            store = op._rmw_buf.setdefault(oid, {})
            for c_off, data in bufs:
                store.setdefault(c_off, {})[chunk] = data
        if not op.pending_read_shards:
            self._rmw_read_tids.pop(getattr(op, "_rmw_read_tid", None), None)
            self._finish_rmw_reads(op)
            self.check_ops()

    def _finish_rmw_reads(self, op: Op) -> None:
        """Decode each read stripe-run back to logical bytes."""
        for oid, runs in op._rmw_buf.items():
            for c_off, by_chunk in runs.items():
                logical_off = self.sinfo.aligned_chunk_offset_to_logical_offset(c_off)
                with self.perf.time("decode_time"):
                    data = ecutil.decode(self.sinfo, self.ec_impl, by_chunk)
                op.remote_reads.setdefault(oid, {})[logical_off] = data

    def _complete_read_op(self, rop: ReadOp) -> None:
        """Reassemble/reconstruct and trim (ECBackend.cc:2273-2329)."""
        k = self.ec_impl.get_data_chunk_count()
        result: dict[str, list[tuple[int, int, bytes]]] = {}
        errors: dict[str, int] = {}
        for oid, extents in rop.to_read.items():
            by_chunk = rop.results.get(oid, {})
            by_chunk = {c: v for c, v in by_chunk.items()
                        if c not in rop.errors.get(oid, set())}
            if len(by_chunk) < k:
                errors[oid] = -5  # EIO
                continue
            # keep exactly k shards for decode
            chosen = dict(sorted(by_chunk.items())[:k])
            with self.perf.time("decode_time"):
                logical = ecutil.decode(self.sinfo, self.ec_impl, chosen)
            c_off, _ = rop.shard_extents[oid]
            base = self.sinfo.aligned_chunk_offset_to_logical_offset(c_off)
            obj_size = self.object_size(oid)
            out = []
            for off, length in extents:
                end = min(off + length, obj_size)
                seg = logical[off - base:end - base] if end > off else b""
                out.append((off, length, seg))
            result[oid] = out
        del self.in_progress_reads[rop.tid]
        if result:
            self.perf.inc("reads")
        if errors:
            self.perf.inc("read_errors", len(errors))
        self.perf.inc("read_bytes", sum(
            len(seg) for segs in result.values() for _, _, seg in segs))
        rop.on_complete(result, errors)

    # -- recovery (ECBackend.cc:565-732; state ECBackend.h:249-293) --------

    def is_recoverable(self, oid: str, missing: set[int]) -> bool:
        """ECRecPred analog (ECBackend.h:581-607)."""
        avail = {c for c, s in enumerate(self.acting)
                 if s in self.current_shards() and c not in missing}
        try:
            self.ec_impl.minimum_to_decode(set(missing), avail)
            return True
        except IOError:
            return False

    def recover_object(self, oid: str, missing_chunks: set[int],
                       on_complete=None) -> RecoveryOp:
        rop = RecoveryOp(oid=oid, missing_shards=set(missing_chunks),
                         on_complete=on_complete)
        self.recovery_ops[oid] = rop
        try:
            self.continue_recovery_op(rop)
        except IOError:
            # too few current shards right now: park; re-driven when a
            # shard returns (the reference defers recovery the same way
            # when sources are missing)
            self._stalled_recoveries.append(rop)
        return rop

    def continue_recovery_op(self, rop: RecoveryOp) -> None:
        if rop.state == RecoveryState.IDLE:
            avail = {c for c, s in enumerate(self.acting)
                     if s in self.current_shards()
                     and c not in rop.missing_shards}
            minimum = self.ec_impl.minimum_to_decode(rop.missing_shards, avail)
            self.next_tid += 1
            rop.read_tid = self.next_tid
            rop.at_version = self.pg_log.last_version_of(rop.oid)
            hinfo = self._hinfo(rop.oid)
            c_len = hinfo.get_total_chunk_size()
            per_shard = {}
            for chunk, subchunks in minimum.items():
                shard = self.acting[chunk]
                runs = None if subchunks == [(0, self.ec_impl.get_sub_chunk_count())] \
                    else subchunks
                per_shard.setdefault(shard, {})[rop.oid] = [(0, c_len, runs)]
            rop._read_results = {}
            rop._pending = set(per_shard)
            rop.state = RecoveryState.READING
            self._recovery_read_tids[rop.read_tid] = rop
            for shard, to_read in per_shard.items():
                self.bus.send(shard, ECSubRead(
                    self.whoami, rop.read_tid, to_read,
                    sub_chunk_count=self.ec_impl.get_sub_chunk_count()))

    def handle_recovery_read_reply(self, rop: RecoveryOp,
                                   reply: ECSubReadReply) -> None:
        if rop.state != RecoveryState.READING:
            return                      # stale/duplicate reply
        chunk_of_shard = {s: c for c, s in enumerate(self.acting)}
        chunk = chunk_of_shard[reply.from_shard]
        for oid, bufs in reply.buffers_read.items():
            rop._read_results[chunk] = b"".join(b for _, b in bufs)
        rop._pending.discard(reply.from_shard)
        if rop._pending:
            return
        self._recovery_read_tids.pop(rop.read_tid, None)
        if self.pg_log.last_version_of(rop.oid) != rop.at_version:
            # a write to this oid committed between the recovery read and
            # now: the reconstructed bytes predate it.  Re-read (the new
            # chunks are on the survivors) instead of pushing stale data.
            rop.state = RecoveryState.IDLE
            self.continue_recovery_op(rop)
            return
        # READING -> WRITING: reconstruct the missing chunks, push them.
        # chunk_size tells sub-chunk codes (clay) the helpers are fractional
        available = {c: np.frombuffer(v, dtype=np.uint8)
                     for c, v in rop._read_results.items()}
        hinfo = self._hinfo(rop.oid)
        rec = decode_shards(self.sinfo, self.ec_impl, available,
                            rop.missing_shards,
                            chunk_size=hinfo.get_total_chunk_size())
        rop.state = RecoveryState.WRITING
        up = self.up_shards()
        for chunk in rop.missing_shards:
            shard = self.acting[chunk]
            if shard not in up:
                # target died while the reads were in flight: a push would
                # drop silently and never ack — fail now exactly as
                # on_shard_down fails an already-sent push (_failed_push)
                rop.failed = True
                continue
            rop.pending_pushes.add(shard)
            self.bus.send(shard, PushOp(
                self.whoami, rop.oid, bytes(rec[chunk]),
                attrs={HINFO_KEY: hinfo.to_dict()}))
        if not rop.pending_pushes:
            self._finish_recovery_op(rop, failed=rop.failed)

    def handle_push_reply(self, reply: PushReply) -> None:
        rop = self.recovery_ops.get(reply.oid)
        if rop is None:
            return
        rop.pending_pushes.discard(reply.from_shard)
        if not rop.pending_pushes and rop.state == RecoveryState.WRITING:
            self._finish_recovery_op(rop, failed=rop.failed)

    def _finish_recovery_op(self, rop: RecoveryOp, failed: bool = False) -> None:
        """COMPLETE (or FAILED) + drop tracking state so late replies are
        inert (the reference erases the RecoveryOp from recovery_ops on
        on_global_recover; failures go through _failed_push)."""
        rop.state = RecoveryState.FAILED if failed else RecoveryState.COMPLETE
        self.recovery_ops.pop(rop.oid, None)
        self._recovery_read_tids.pop(rop.read_tid, None)
        self.perf.inc("recovery_failures" if failed else "recoveries")
        if rop.on_complete:
            rop.on_complete(rop)

    # -- shard repair: log catch-up or backfill ----------------------------
    # (the role PGLog::merge_log + log-based recovery + backfill play in the
    # reference, src/osd/PGLog.cc; replaces the old O(all objects) deep
    # scrub on every boot)

    def start_shard_repair(self, shard: int, on_complete=None
                           ) -> ShardRepairOp:
        """Bring a revived/stale shard current.  Queries its log; replays
        exactly the missed entries when they are within the horizon, falls
        back to a scan+push backfill when not.  COMPLETE means the shard's
        data AND log match the authority's.  Works for the primary's own
        shard too: its local log lags the authority log by exactly the
        writes that committed while it was down, and the recovery pushes
        self-deliver over the bus."""
        existing = self.shard_repairs.get(shard)
        if existing is not None:
            # one repair per shard at a time: revival auto-starts one, an
            # explicit caller joins it
            if on_complete is not None:
                prev = existing.on_complete

                def chained(r, _prev=prev, _cb=on_complete):
                    if _prev:
                        _prev(r)
                    _cb(r)
                existing.on_complete = chained
            return existing
        chunk = self.acting.index(shard)
        rop = ShardRepairOp(shard=shard, chunk=chunk,
                            on_complete=on_complete)
        self.shard_repairs[shard] = rop
        self.bus.send(shard, PGLogQuery(self.whoami,
                                        since=self.pg_log.tail))
        return rop

    # -- boot peering (crash recovery) -------------------------------------

    def start_boot_peering(self) -> None:
        """After a restart from durable stores, decide what survived BEFORE
        serving: query every up peer's persisted log, adopt the best
        (furthest-ahead witnessed) log as the authority, and roll back any
        entry persisted on fewer than min_size shards — such a write was
        never acked, and repairing peers toward it would mix chunk
        versions into garbage.  This is the single-primary analog of the
        reference's peering (PeeringState GetInfo/GetLog; authoritative-
        log election + divergent-entry rollback)."""
        peers = {s for s in self.acting
                 if s != self.whoami and s not in self.bus.down}
        if not peers:
            return
        self._boot_peering = {}
        self._boot_peering_expect = peers
        for shard in sorted(peers):
            self.bus.send(shard, PGLogQuery(self.whoami, since=0))

    def _finish_boot_peering(self) -> None:
        infos = self._boot_peering
        self._boot_peering = None
        self._boot_peering_expect = set()
        # adopt the furthest-ahead log: the primary may itself have been
        # down while peers committed (its RAM authority died with it)
        local = self.local_shard.pg_log
        best_shard, best_head = self.whoami, self.pg_log.head
        for shard, info in infos.items():
            if info.last_update > best_head:
                best_shard, best_head = shard, info.last_update
        if best_shard != self.whoami:
            binfo = infos[best_shard]
            if binfo.tail > self.pg_log.head:
                # our persisted log is beyond the best peer's horizon:
                # adopt its log wholesale (the data repairs via backfill)
                self.pg_log = PGLog()
                self.pg_log.tail = self.pg_log.head = binfo.tail
            for e in sorted(binfo.entries, key=lambda e: e.version):
                if e.version > self.pg_log.head:
                    self.pg_log.record(e)
            self.pg_log.head = max(self.pg_log.head, binfo.last_update)
        # witness count per version: a shard witnesses v if its log
        # provably contains the authority's entry at v
        auth = {e.version: e for e in self.pg_log.entries}
        shard_logs = {self.whoami: (local.head, local.tail,
                                    {e.version: e for e in local.entries})}
        for shard, info in infos.items():
            shard_logs[shard] = (info.last_update, info.tail,
                                 {e.version: e for e in info.entries})

        def witnesses(v: int) -> int:
            n = 0
            for head, tail, by_v in shard_logs.values():
                if head < v:
                    continue
                if v > tail and by_v.get(v) != auth.get(v):
                    continue
                n += 1
            return n

        boundary = self.pg_log.head
        if len(shard_logs) >= self.min_size:
            while boundary > self.pg_log.tail and \
                    witnesses(boundary) < self.min_size:
                boundary -= 1
        # roll back everything past the boundary, everywhere (FIFO-safe:
        # nothing else is in flight during boot), then roll the kept
        # prefix forward so stale rollback data drops
        if boundary < self.pg_log.head:
            for shard in sorted(self.up_shards()):
                if shard == self.whoami:
                    self._rollback_pending += 1
                self.bus.send(shard, Rollback(self.whoami, boundary))
            if self.whoami not in self.up_shards():
                self.local_shard._rollback(boundary)
            self.pg_log.rewind(boundary)
            self.hinfo_cache.clear()
        self.committed_to = boundary
        self._rolled_forward_to = boundary
        for shard in sorted(self.up_shards()):
            self.bus.send(shard, RollForward(self.whoami, boundary))

    def handle_pg_log_info(self, info: PGLogInfo) -> None:
        if self._boot_peering is not None and \
                info.from_shard in self._boot_peering_expect:
            self._boot_peering[info.from_shard] = info
            if set(self._boot_peering) == self._boot_peering_expect:
                self._finish_boot_peering()
            return
        rop = self.shard_repairs.get(info.from_shard)
        if rop is None or rop.state != RepairState.QUERY:
            return
        divergent, div_rewind = self.pg_log.divergent_oids(info.entries)
        plan, entries = self.pg_log.catch_up_plan(info.last_update)
        # the rewind point: last shard version consistent with our log
        rop.rewind_to = min(info.last_update, self.pg_log.head, div_rewind)
        rop.caught_up_to = self.pg_log.head
        if plan == "backfill":
            rop.plan = "backfill"
            rop.state = RepairState.SCAN
            self.perf.inc("shard_backfills")
            self._start_scan(rop)
            return
        rop.plan = plan
        todo: dict[str, str] = {}          # oid -> op
        for e in entries:
            todo[e.oid] = e.op
        for oid in divergent:
            # authority wins: re-push our state, or delete what we lack
            todo[oid] = OP_MODIFY if self._object_exists(oid) else OP_DELETE
        if not todo:
            self.perf.inc("log_repairs_clean")
            self._finish_shard_repair(rop)
            return
        self.perf.inc("log_repairs")
        rop.state = RepairState.RECOVERING
        for oid, op in sorted(todo.items()):
            self._repair_one(rop, oid, op)
        self._maybe_finish_shard_repair(rop)

    def _start_scan(self, rop: ShardRepairOp) -> None:
        """Backfill needs the authoritative object list.  Repairing a
        replica: the primary's own store is the authority, scan the stale
        target for extras.  Repairing the primary itself: any other up
        (hence current) shard supplies the authority list, and the stale
        local store supplies the extras."""
        target = rop.shard
        if rop.shard == self.whoami:
            others = [s for s in self.acting
                      if s != self.whoami and s in self.current_shards()]
            if not others:
                rop.failed = True
                self._finish_shard_repair(rop)
                return
            target = others[0]
        self._scan_waiters[target] = rop
        self.bus.send(target, PGScan(self.whoami))

    def handle_pg_scan_reply(self, reply: PGScanReply) -> None:
        rop = self._scan_waiters.pop(reply.from_shard, None)
        if rop is None or rop.state != RepairState.SCAN:
            return
        if rop.shard == self.whoami:
            authority = set(reply.oids)        # a current replica's list
            target_list = self._local_oids()   # the stale local store
        else:
            authority = self._local_oids()
            target_list = set(reply.oids)
        # the object lists reflect this moment: writes after it are the
        # delta _maybe_finish_shard_repair catches up
        rop.caught_up_to = self.pg_log.head
        rop.state = RepairState.RECOVERING
        for oid in sorted(authority):
            self._repair_one(rop, oid, OP_MODIFY)
        for oid in sorted(target_list - authority):
            self._repair_one(rop, oid, OP_DELETE)
        self._maybe_finish_shard_repair(rop)

    def _local_oids(self) -> set[str]:
        return {g.oid for g in self.local_shard.store.objects
                if g.shard == self.whoami and g.oid != PG_META}

    def _object_exists(self, oid: str) -> bool:
        return GObject(oid, self.whoami) in self.local_shard.store.objects

    def _repair_one(self, rop: ShardRepairOp, oid: str, op: str) -> None:
        rop.objects_repaired += 1
        if op == OP_DELETE:
            self.next_tid += 1
            tid = self.next_tid
            rop.pending.add(("delete", oid))
            self._repair_write_tids[tid] = (rop, oid)
            t = Transaction().remove(GObject(oid, rop.shard))
            self.bus.send(rop.shard, ECSubWrite(self.whoami, tid, t))
        else:
            rop.pending.add(("recover", oid))

            def done(rec, _rop=rop, _oid=oid):
                _rop.pending.discard(("recover", _oid))
                if rec.state != RecoveryState.COMPLETE:
                    _rop.failed = True
                self._maybe_finish_shard_repair(_rop)

            existing = self.recovery_ops.get(oid)
            if existing is not None:
                # one RecoveryOp per object at a time: chain behind it
                prev = existing.on_complete

                def chained(rec, _prev=prev, _oid=oid, _rop=rop,
                            _done=done):
                    if _prev:
                        _prev(rec)
                    self.recover_object(_oid, {_rop.chunk},
                                        on_complete=_done)
                existing.on_complete = chained
            else:
                self.recover_object(oid, {rop.chunk}, on_complete=done)

    def _maybe_finish_shard_repair(self, rop: ShardRepairOp) -> None:
        if rop.state != RepairState.RECOVERING or rop.pending:
            return
        # writes that committed while the repair was in flight skipped the
        # stale target (it is out of the fan-out): repair the delta before
        # declaring it current, else its log would claim writes whose data
        # it never received
        if not rop.failed and self.pg_log.head > rop.caught_up_to:
            delta = dedup_latest([e for e in self.pg_log.entries
                                  if e.version > rop.caught_up_to])
            rop.caught_up_to = self.pg_log.head
            for e in delta:
                self._repair_one(rop, e.oid, e.op)
            if rop.pending:
                return
        self._finish_shard_repair(rop)

    def _finish_shard_repair(self, rop: ShardRepairOp) -> None:
        self.shard_repairs.pop(rop.shard, None)
        if rop.failed:
            rop.state = RepairState.FAILED
        else:
            # repaired: the shard is current again — it rejoins reads and
            # write fan-out, and its return may reactivate a parked PG
            self.stale.discard(rop.shard)
            # data is current: ship the authoritative log segment so the
            # shard's next repair takes the clean fast path
            self.bus.send(rop.shard, PGLogUpdate(
                self.whoami,
                entries=self.pg_log.entries_after(rop.rewind_to) or [],
                last_update=self.pg_log.head,
                rewind_to=rop.rewind_to,
                trim_to=self.pg_log.tail))
            rop.state = RepairState.COMPLETE
            self.perf.inc("log_repair_objects" if rop.plan != "backfill"
                          else "backfill_objects", rop.objects_repaired)
        if rop.on_complete:
            rop.on_complete(rop)
        if not rop.failed:
            self._redrive_parked()

    # -- deep scrub (ECBackend.cc:2461-2546) -------------------------------

    def be_deep_scrub(self, oid: str) -> dict[int, bool]:
        """Recompute each up shard's cumulative crc vs its stored HashInfo;
        True = clean."""
        out: dict[int, bool] = {}
        for chunk, shard in enumerate(self.acting):
            if shard in self.bus.down:
                continue
            handler = self.bus.handlers[shard]
            store = handler.store if isinstance(handler, OSDShard) else \
                handler.local_shard.store
            obj = GObject(oid, shard)
            try:
                data = store.read(obj)
                stored = store.getattr(obj, HINFO_KEY)
            except (FileNotFoundError, KeyError):
                out[chunk] = False
                continue
            # version check first: a shard that missed writes while down is
            # stale even when overwrites cleared the chunk hashes (the
            # PG-log-version role; see HashInfo.version)
            if stored.get("version", 0) != self._hinfo(oid).version:
                out[chunk] = False
                continue
            hashes = stored.get("cumulative_shard_hashes") or []
            if not hashes:
                out[chunk] = True  # hash cleared by overwrite; version matched
                continue
            out[chunk] = crc32c(0xFFFFFFFF, data) == hashes[chunk] and \
                len(data) == stored["total_chunk_size"]
        return out


def make_cluster(ec_impl, chunk_size: int = 4096, cct=None):
    """Build a primary + shard OSDs wired on one bus; returns (backend, bus).

    Chunk i lives on shard id i (identity crush mapping) with the primary
    colocated on shard 0, the common layout in the standalone EC tests
    (reference: qa/standalone/erasure-code/test-erasure-code.sh:21-66).
    """
    n = ec_impl.get_chunk_count()
    k = ec_impl.get_data_chunk_count()
    bus = MessageBus()
    backend = ECBackend(ec_impl, StripeInfo(k, chunk_size), bus,
                        acting=list(range(n)), whoami=0, cct=cct)
    for shard in range(1, n):
        OSDShard(shard, bus)
    return backend, bus
