"""Stripe offset algebra, per-shard hashes, and batched stripe codecs.

Analog of the reference's ``ECUtil`` (reference: src/osd/ECUtil.{h,cc}) with
the one deliberate TPU-first restructuring called out in SURVEY.md §2.2: the
reference encodes **per stripe** (one plugin call per stripe_width bytes,
ECUtil.cc:136-148); here :func:`encode`/:func:`decode` make ONE plugin call
for the whole multi-stripe buffer by laying stripes out as contiguous
per-shard chunk streams.  RS parity is positionwise, so batching across
stripes is a pure relayout — bit-identical output, MXU-sized launches.
"""
from __future__ import annotations

import functools

import numpy as np

from ..common import copy_ledger

# -- crc32c (Castagnoli), seed-chained like ceph_crc32c ----------------------
# HashInfo chains bufferlist::crc32c(seed) per shard with initial seed -1
# (reference: src/osd/ECUtil.h:110-112, ECUtil.cc:161-177).

_CRC32C_POLY = 0x82F63B78


def _make_crc_tables(n_tables: int = 16) -> list[list[int]]:
    """Slice-by-N tables: T[j][b] advances byte b through j+1 zero bytes."""
    t0 = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ _CRC32C_POLY if c & 1 else c >> 1
        t0.append(c)
    tables = [t0]
    for _ in range(n_tables - 1):
        prev = tables[-1]
        tables.append([(prev[b] >> 8) ^ t0[prev[b] & 0xFF] for b in range(256)])
    return tables


_CRC_TABLES = _make_crc_tables()

_native_crc = None


def _load_native_crc():
    """SSE4.2 CRC32C from the native lib (gf8_simd.cc ec_crc32c); the pure
    Python path below stays as the oracle and no-toolchain fallback."""
    global _native_crc
    if _native_crc is not None:
        return _native_crc or None
    try:
        from ..native import registry_lib
        _native_crc = registry_lib().ec_crc32c
    except Exception:
        _native_crc = False
    return _native_crc or None


def crc32c(seed: int, data: bytes | np.ndarray) -> int:
    """ceph_crc32c semantics: raw reflected CRC-32C update, no final xor —
    the caller chains seeds (standard crc32c(x) = crc32c(0xffffffff, x) ^ 0xffffffff).

    Dispatches to the native SSE4.2/table kernel when built; pure-Python
    slice-by-16 otherwise (one iteration consumes 16 bytes).
    """
    fn = _load_native_crc()
    if fn is not None and isinstance(data, np.ndarray):
        # zero-copy for contiguous arrays: the kernel needs pointer+length
        arr = np.ascontiguousarray(data).reshape(-1)
        return fn(seed & 0xFFFFFFFF, arr.ctypes.data, arr.nbytes)
    if isinstance(data, np.ndarray):
        buf = np.ascontiguousarray(data.ravel()).tobytes()
    else:
        buf = bytes(data)
    if fn is not None:
        return fn(seed & 0xFFFFFFFF, buf, len(buf))
    crc = seed & 0xFFFFFFFF
    t = _CRC_TABLES
    (t15, t14, t13, t12, t11, t10, t9, t8,
     t7, t6, t5, t4, t3, t2, t1, t0) = t[15], t[14], t[13], t[12], t[11], \
        t[10], t[9], t[8], t[7], t[6], t[5], t[4], t[3], t[2], t[1], t[0]
    n16 = len(buf) & ~15
    for i in range(0, n16, 16):
        b = buf[i:i + 16]
        crc ^= b[0] | (b[1] << 8) | (b[2] << 16) | (b[3] << 24)
        crc = (t15[crc & 0xFF] ^ t14[(crc >> 8) & 0xFF] ^
               t13[(crc >> 16) & 0xFF] ^ t12[crc >> 24] ^
               t11[b[4]] ^ t10[b[5]] ^ t9[b[6]] ^ t8[b[7]] ^
               t7[b[8]] ^ t6[b[9]] ^ t5[b[10]] ^ t4[b[11]] ^
               t3[b[12]] ^ t2[b[13]] ^ t1[b[14]] ^ t0[b[15]])
    for i in range(n16, len(buf)):
        crc = t0[(crc ^ buf[i]) & 0xFF] ^ (crc >> 8)
    return crc


# -- crc32c combine algebra (the fused-checksum kernel's host half) ---------
#
# The crc32c register update is GF(2)-linear in (seed, data bits), so
#     crc32c(seed, D) == crc32c(seed, zeros(len(D))) ^ crc32c(0, D)
# (zlib's crc32_combine identity).  That factorization is what lets the
# device compute seed-FREE per-row crcs inside the encode dispatch
# (ops/rs_kernels.crc32c_rows) while HashInfo's seed-chained ceph
# semantics are restored exactly on the host with one 32x32 GF(2)
# matrix application per append: advance the previous cumulative crc
# through n zero bytes, then xor the device's crc32c(0, chunk).

def _gf2_times(op: list[int], vec: int) -> int:
    out = 0
    i = 0
    while vec:
        if vec & 1:
            out ^= op[i]
        vec >>= 1
        i += 1
    return out


def _gf2_square(op: list[int]) -> list[int]:
    return [_gf2_times(op, op[i]) for i in range(32)]


@functools.lru_cache(maxsize=None)
def crc32c_zeros_op(nbytes: int) -> tuple:
    """The 32x32 GF(2) operator advancing a crc32c register through
    ``nbytes`` zero bytes, as bit-image columns (entry i = image of
    register bit i).  Square-and-multiply over the one-zero-byte
    operator: O(log n) squarings, lru-cached per length."""
    assert nbytes >= 0
    t0 = _CRC_TABLES[0]
    # one zero byte: crc' = (crc >> 8) ^ T0[crc & 0xFF]
    byte_op = [t0[1 << i] if i < 8 else (1 << (i - 8)) for i in range(32)]
    result = [1 << i for i in range(32)]          # identity
    while nbytes:
        if nbytes & 1:
            result = [_gf2_times(byte_op, result[i]) for i in range(32)]
        byte_op = _gf2_square(byte_op)
        nbytes >>= 1
    return tuple(result)


def crc32c_zeros(crc: int, nbytes: int) -> int:
    """``crc32c(crc, b"\\x00" * nbytes)`` in O(log n) (no zero buffer)."""
    return _gf2_times(list(crc32c_zeros_op(nbytes)), crc & 0xFFFFFFFF)


class StripeInfo:
    """stripe_info_t: logical<->chunk offset algebra (ECUtil.h:27-80).

    ``stripe_width = k * chunk_size``; logical offsets live in object space,
    chunk offsets in per-shard space.
    """

    def __init__(self, k: int, chunk_size: int,
                 stored_chunk_size: int | None = None):
        self.k = k
        self.chunk_size = chunk_size
        self.stripe_width = k * chunk_size
        # On-disk bytes per chunk_size logical share bytes.  Equal for
        # every classic code; regenerating MBR chunks expand (plugin
        # get_stored_chunk_size), so shard extents, hinfo sizes and
        # transaction offsets all live in STORED units while logical
        # offset algebra stays in share units.
        self.stored_chunk_size = (chunk_size if stored_chunk_size is None
                                  else stored_chunk_size)

    def chunk_to_stored(self, chunk_off: int) -> int:
        """Share-space chunk offset/length -> stored (on-disk) units."""
        if self.stored_chunk_size == self.chunk_size:
            return chunk_off
        scaled = chunk_off * self.stored_chunk_size
        assert scaled % self.chunk_size == 0, \
            f"chunk offset {chunk_off} not stored-convertible"
        return scaled // self.chunk_size

    def stored_to_chunk(self, stored_off: int) -> int:
        """Stored (on-disk) offset/length -> share-space chunk units."""
        if self.stored_chunk_size == self.chunk_size:
            return stored_off
        scaled = stored_off * self.chunk_size
        assert scaled % self.stored_chunk_size == 0, \
            f"stored offset {stored_off} not share-convertible"
        return scaled // self.stored_chunk_size

    def logical_offset_is_stripe_aligned(self, logical: int) -> bool:
        return logical % self.stripe_width == 0

    def logical_to_prev_chunk_offset(self, offset: int) -> int:
        return (offset // self.stripe_width) * self.chunk_size

    def logical_to_next_chunk_offset(self, offset: int) -> int:
        return ((offset + self.stripe_width - 1) // self.stripe_width) * self.chunk_size

    def logical_to_prev_stripe_offset(self, offset: int) -> int:
        return offset - (offset % self.stripe_width)

    def logical_to_next_stripe_offset(self, offset: int) -> int:
        rem = offset % self.stripe_width
        return offset + (self.stripe_width - rem) if rem else offset

    def aligned_logical_offset_to_chunk_offset(self, offset: int) -> int:
        assert offset % self.stripe_width == 0
        return (offset // self.stripe_width) * self.chunk_size

    def aligned_chunk_offset_to_logical_offset(self, offset: int) -> int:
        assert offset % self.chunk_size == 0
        return (offset // self.chunk_size) * self.stripe_width

    def aligned_offset_len_to_chunk(self, off: int, length: int) -> tuple[int, int]:
        return (self.aligned_logical_offset_to_chunk_offset(off),
                self.aligned_logical_offset_to_chunk_offset(length))

    def offset_len_to_stripe_bounds(self, off: int, length: int) -> tuple[int, int]:
        start = self.logical_to_prev_stripe_offset(off)
        end_len = self.logical_to_next_stripe_offset((off - start) + length)
        return start, end_len


class HashInfo:
    """Per-shard cumulative crc32c of appended chunk bytes (ECUtil.h:101-168).

    Appends must be contiguous with the current size; out-of-order appends
    clear the hashes the way the reference asserts them away.
    """

    def __init__(self, num_chunks: int):
        self.total_chunk_size = 0
        self.cumulative_shard_hashes = [0xFFFFFFFF] * num_chunks
        self.projected_total_chunk_size = 0
        # per-object write version, bumped on every committed transaction
        # and persisted with each shard: a shard that missed writes while
        # down is detectably stale even after overwrites cleared the chunk
        # hashes (the role the reference's PG log versions play,
        # src/osd/PGLog.cc divergence detection)
        self.version = 0

    def append(self, old_size: int, to_append: dict[int, np.ndarray]) -> None:
        assert old_size == self.total_chunk_size
        if not to_append:
            return
        sizes = {len(v) for v in to_append.values()}
        assert len(sizes) == 1, "uneven shard appends"
        if self.has_chunk_hash():
            for shard, buf in to_append.items():
                self.cumulative_shard_hashes[shard] = crc32c(
                    self.cumulative_shard_hashes[shard], buf)
        self.total_chunk_size += sizes.pop()

    def append_crcs(self, old_size: int, crc0s: dict[int, int],
                    nbytes: int) -> None:
        """Append with PRE-computed seed-free crcs — the fused device
        checksum path.  ``crc0s[shard] = crc32c(0, chunk_bytes)`` (what
        ``ops.rs_kernels.crc32c_rows`` returns); each running hash
        advances by the crc32_combine identity

            crc32c(seed, D) == crc32c_zeros(seed, len(D)) ^ crc32c(0, D)

        so the device never needs the host's running seed.  Bitwise
        identical to :meth:`append` on the same bytes."""
        assert old_size == self.total_chunk_size
        if not crc0s:
            return
        if self.has_chunk_hash():
            for shard, c0 in crc0s.items():
                self.cumulative_shard_hashes[shard] = crc32c_zeros(
                    self.cumulative_shard_hashes[shard], nbytes) ^ c0
        self.total_chunk_size += nbytes

    def clear(self) -> None:
        self.total_chunk_size = 0
        self.cumulative_shard_hashes = [0xFFFFFFFF] * len(self.cumulative_shard_hashes)

    def get_chunk_hash(self, shard: int) -> int:
        return self.cumulative_shard_hashes[shard]

    def get_total_chunk_size(self) -> int:
        return self.total_chunk_size

    def get_projected_total_chunk_size(self) -> int:
        return self.projected_total_chunk_size

    def get_total_logical_size(self, sinfo: StripeInfo) -> int:
        # chunk sizes are STORED units; convert back to share space
        # before multiplying out to logical bytes
        return sinfo.stored_to_chunk(self.total_chunk_size) * \
            (sinfo.stripe_width // sinfo.chunk_size)

    def get_projected_total_logical_size(self, sinfo: StripeInfo) -> int:
        return sinfo.stored_to_chunk(self.projected_total_chunk_size) * \
            (sinfo.stripe_width // sinfo.chunk_size)

    def set_projected_total_logical_size(self, sinfo: StripeInfo, logical: int) -> None:
        assert sinfo.logical_offset_is_stripe_aligned(logical)
        self.projected_total_chunk_size = sinfo.chunk_to_stored(
            sinfo.aligned_logical_offset_to_chunk_offset(logical))

    def set_total_chunk_size_clear_hash(self, new_chunk_size: int) -> None:
        self.cumulative_shard_hashes = []
        self.total_chunk_size = new_chunk_size

    def has_chunk_hash(self) -> bool:
        return bool(self.cumulative_shard_hashes)

    def to_dict(self) -> dict:
        return {"total_chunk_size": self.total_chunk_size,
                "cumulative_shard_hashes": list(self.cumulative_shard_hashes),
                "version": self.version}


# -- batched stripe codec ----------------------------------------------------

def _to_shard_major(buf: np.ndarray, k: int, chunk_size: int) -> np.ndarray:
    """[S * stripe_width] logical bytes -> [k, S * chunk_size] shard streams.

    Stripe s contributes bytes [s*W + i*c, s*W + (i+1)*c) to shard i at chunk
    offset s*c (doc/dev/osd_internals/erasure_coding.rst:55-75 layout).
    """
    stripes = buf.reshape(-1, k, chunk_size)          # [S, k, c]
    return np.ascontiguousarray(stripes.transpose(1, 0, 2)).reshape(k, -1)


def _from_shard_major(shards: np.ndarray, chunk_size: int) -> np.ndarray:
    """[k, S * chunk_size] shard streams -> [S * stripe_width] logical bytes."""
    k = shards.shape[0]
    stripes = shards.reshape(k, -1, chunk_size).transpose(1, 0, 2)  # [S, k, c]
    return np.ascontiguousarray(stripes).reshape(-1)


def _pack_shard_major(arrs: list[np.ndarray], k: int,
                      chunk_size: int) -> np.ndarray:
    """Single-copy shard-major pack of MANY logical buffers: each
    buffer's [S, k, c] stripe view lands transposed DIRECTLY into one
    contiguous [k, total] output — one strided ``copyto`` per buffer —
    replacing the two-copy ``_to_shard_major``-then-``concatenate``
    relayout.  The surviving copy is the data path's host relayout
    floor (until staging buffers land shard-major), reported to the
    copy ledger as ``relayout``."""
    total = sum(len(b) for b in arrs) // k
    out = np.empty((k, total), dtype=np.uint8)
    off = 0
    for b in arrs:
        ln = len(b) // k
        s = ln // chunk_size
        # out[:, off:off+ln].reshape splits the row extent into chunk
        # cells without copying (strides stay expressible), so copyto
        # streams straight from the stripe view into the packed output
        np.copyto(out[:, off:off + ln].reshape(k, s, chunk_size),
                  b.reshape(s, k, chunk_size).swapaxes(0, 1))
        off += ln
    copy_ledger.count_copy("relayout", out.nbytes)
    return out


def encode(sinfo: StripeInfo, ec_impl, data: bytes | np.ndarray,
           want: set | None = None) -> dict[int, np.ndarray]:
    """Encode a stripe-aligned logical buffer into per-shard chunk buffers.

    One ``encode_chunks`` call for ALL stripes (vs the reference's per-stripe
    loop at ECUtil.cc:136-148); returns {shard: concatenated chunk bytes}.
    """
    buf = np.frombuffer(data, dtype=np.uint8) if isinstance(data, (bytes, bytearray)) \
        else np.asarray(data, dtype=np.uint8)
    assert len(buf) % sinfo.stripe_width == 0, \
        f"len {len(buf)} not stripe aligned ({sinfo.stripe_width})"
    k = ec_impl.get_data_chunk_count()
    n = ec_impl.get_chunk_count()
    assert k == sinfo.k
    if want is None:
        want = set(range(n))
    shard_len = (len(buf) // sinfo.stripe_width) * sinfo.chunk_size
    data_shards = _to_shard_major(buf, k, sinfo.chunk_size)
    encoded = {ec_impl.chunk_index(i): data_shards[i].copy() for i in range(k)}
    for i in range(k, n):
        encoded[ec_impl.chunk_index(i)] = np.zeros(shard_len, dtype=np.uint8)
    ec_impl.encode_chunks(set(range(n)), encoded)
    return {i: encoded[i] for i in want}


def encode_many(sinfo: StripeInfo, ec_impl,
                bufs: list[bytes | np.ndarray]) -> list[dict[int, np.ndarray]]:
    """Encode MANY stripe-aligned buffers (different objects, different
    PGs) in ONE ``encode_chunks`` call — the cross-op/cross-PG coalescing
    the per-op :func:`encode` cannot do.  All buffers share the codec, so
    their shard streams concatenate along the byte axis and one device
    dispatch covers the lot; results split back per buffer.

    Returns one ``{chunk: bytes}`` dict per input buffer, identical to
    calling :func:`encode` per buffer.  An empty batch is a no-op (the
    coalescer's drain can race a flush to zero ops)."""
    if not bufs:
        return []
    k = ec_impl.get_data_chunk_count()
    n = ec_impl.get_chunk_count()
    arrs = []
    for data in bufs:
        buf = np.frombuffer(data, dtype=np.uint8) \
            if isinstance(data, (bytes, bytearray)) \
            else np.asarray(data, dtype=np.uint8)
        assert len(buf) % sinfo.stripe_width == 0, \
            f"len {len(buf)} not stripe aligned"
        arrs.append(buf)
    shard_lens = [(len(b) // sinfo.stripe_width) * sinfo.chunk_size
                  for b in arrs]
    data_shards = _pack_shard_major(arrs, k, sinfo.chunk_size)
    total = data_shards.shape[1]
    encoded = {ec_impl.chunk_index(i): data_shards[i].copy()
               for i in range(k)}
    for i in range(k, n):
        encoded[ec_impl.chunk_index(i)] = np.zeros(total, dtype=np.uint8)
    ec_impl.encode_chunks(set(range(n)), encoded)
    out: list[dict[int, np.ndarray]] = []
    off = 0
    for ln in shard_lens:
        out.append({c: encoded[c][off:off + ln] for c in range(n)})
        off += ln
    return out


def _as_u8(v) -> np.ndarray:
    if isinstance(v, (bytes, bytearray, memoryview)):
        return np.frombuffer(v, dtype=np.uint8)
    return np.asarray(v, dtype=np.uint8)


# -- device-resident pipelined variants ---------------------------------------
# These route the SAME batched relayouts through ops.pipeline.CodecPipeline:
# the host pack (the `_to_shard_major` transposes and concatenations below)
# runs while earlier batches' device kernels are still in flight, and the
# `device_get` happens only at the pipeline's completion boundary.  They
# engage only when the plugin exposes a device codec (`device_codec`, the
# jax_rs capability hook) for a call of this size — everything else (numpy
# routing, sub-chunk codes, non-RS plugins) returns None and the caller
# keeps the verified synchronous path.

def _device_codec(ec_impl, nbytes: int):
    probe = getattr(ec_impl, "device_codec", None)
    if probe is None or ec_impl.get_sub_chunk_count() != 1:
        return None
    return probe(int(nbytes))


def hinfo_append(hinfo: HashInfo, old_size: int,
                 chunks: dict[int, np.ndarray], ec_impl=None) -> None:
    """HashInfo maintenance with the checksum fused into a device
    dispatch: when the plugin has a device codec and the hashes are
    live, the appended chunk rows stack into ONE ``crc32c_rows`` call
    and the seed-free results chain through
    :meth:`HashInfo.append_crcs` — no host crc loop over the shards.
    Everything else (numpy routing, hash-less objects, uneven appends)
    falls through to the bitwise-identical :meth:`HashInfo.append`."""
    if not chunks:
        return
    if hinfo.has_chunk_hash() and ec_impl is not None:
        lens = {len(v) for v in chunks.values()}
        if len(lens) == 1:
            nbytes = lens.pop()
            codec = _device_codec(ec_impl, nbytes * len(chunks)) \
                if nbytes else None
            if codec is not None:
                shards = sorted(chunks)
                rows = np.stack([_as_u8(chunks[s]) for s in shards])
                from ..ops import rs_kernels
                crc0 = np.asarray(rs_kernels.crc32c_rows(rows))
                hinfo.append_crcs(old_size,
                                  {s: int(c)
                                   for s, c in zip(shards, crc0)}, nbytes)
                return
    hinfo.append(old_size, chunks)


def encode_many_pipelined(sinfo: StripeInfo, ec_impl,
                          bufs: list[bytes | np.ndarray], pipeline,
                          owner: str | None = None):
    """Async :func:`encode_many`: returns a ``PipelineFuture`` resolving
    to the identical per-buffer ``{chunk: bytes}`` list, or None when the
    codec has no device path.  Pack (shard-major relayout) runs now and
    overlaps in-flight device work; parity lands at the completion
    boundary."""
    if not bufs:
        return None
    k = ec_impl.get_data_chunk_count()
    n = ec_impl.get_chunk_count()
    arrs = []
    for data in bufs:
        buf = np.frombuffer(data, dtype=np.uint8) \
            if isinstance(data, (bytes, bytearray)) \
            else np.asarray(data, dtype=np.uint8)
        assert len(buf) % sinfo.stripe_width == 0, \
            f"len {len(buf)} not stripe aligned"
        arrs.append(buf)
    codec = _device_codec(ec_impl, sum(len(b) for b in arrs))
    if codec is None:
        return None
    shard_lens = [(len(b) // sinfo.stripe_width) * sinfo.chunk_size
                  for b in arrs]

    def pack():
        return _pack_shard_major(arrs, k, sinfo.chunk_size)

    def dispatch(data_shards):
        return pipeline.dispatch_encode(codec, data_shards,
                                        sinfo.chunk_size)

    def unpack(data_shards, parity):
        out: list[dict[int, np.ndarray]] = []
        off = 0
        for ln in shard_lens:
            chunks = {ec_impl.chunk_index(i): data_shards[i, off:off + ln]
                      for i in range(k)}
            for j in range(n - k):
                chunks[ec_impl.chunk_index(k + j)] = parity[j, off:off + ln]
            out.append(chunks)
            off += ln
        return out

    def host_fallback(data_shards):
        # breaker-open / device-failure path: same parity, host codec
        return pipeline.host_encode(codec, data_shards, sinfo.chunk_size)

    return pipeline.submit(pack, dispatch, unpack, kind="encode",
                           owner=owner, host_fallback=host_fallback,
                           ops=len(bufs))


def decode_many_pipelined(sinfo: StripeInfo, ec_impl,
                          batches: list[dict[int, np.ndarray]],
                          pipeline, pad_chunks=None,
                          chunk_size: int | None = None,
                          owner: str | None = None):
    """Async :func:`decode_many`: one pipeline item per distinct
    available-chunk signature.  Returns ``[(idxs, future), ...]`` where
    each future resolves to the logical bytes for those batch indices, or
    None when the codec has no device path."""
    if not batches:
        return None
    total_bytes = sum(sum(_as_u8(v).nbytes for v in chunks.values())
                      for chunks in batches)
    codec = _device_codec(ec_impl, total_bytes)
    if codec is None:
        return None
    by_sig: dict[frozenset, list[int]] = {}
    for i, chunks in enumerate(batches):
        by_sig.setdefault(frozenset(chunks), []).append(i)
    pending = []
    for sig, idxs in sorted(by_sig.items(), key=lambda kv: kv[1][0]):
        pending.append((list(idxs),
                        _submit_decode_group(sinfo, ec_impl, codec, batches,
                                             sig, idxs, pipeline, pad_chunks,
                                             chunk_size, owner)))
    return pending


def _submit_decode_group(sinfo, ec_impl, codec, batches, sig, idxs,
                         pipeline, pad_chunks, chunk_size,
                         owner: str | None = None):
    """One signature group's pack/dispatch/unpack trio, submitted."""
    k = ec_impl.get_data_chunk_count()

    def pack():
        concat, lens = _group_streams(
            [batches[i] for i in idxs], sig, pad_chunks=pad_chunks,
            quantum=chunk_size if chunk_size else sinfo.chunk_size)
        # wire ids are PHYSICAL; the codec's rows are LOGICAL
        avail_l, _ = ec_impl.remap_for_decode(concat, [])
        erasures_l = [i for i in range(k) if i not in avail_l]
        stack = None
        if erasures_l:
            _D, src = codec.decode_matrix(erasures_l,
                                          available=list(avail_l))
            stack = np.stack([avail_l[s] for s in src])
        return avail_l, erasures_l, stack, lens

    def dispatch(packed):
        avail_l, erasures_l, stack, _lens = packed
        if not erasures_l:
            return None                  # all data rows survived: host-only
        return pipeline.dispatch_decode(codec, stack, erasures_l,
                                        list(avail_l))

    def unpack(packed, rec):
        avail_l, erasures_l, _stack, lens = packed
        rows = {e: rec[i] for i, e in enumerate(erasures_l)} \
            if erasures_l else {}
        data = np.stack([avail_l[i] if i in avail_l else rows[i]
                         for i in range(k)])
        out: list[bytes] = []
        off = 0
        for ln in lens:
            out.append(_from_shard_major(
                np.ascontiguousarray(data[:, off:off + ln]),
                sinfo.chunk_size).tobytes())
            off += ln
        return out

    def host_fallback(packed):
        avail_l, erasures_l, stack, _lens = packed
        if not erasures_l:
            return None                  # host-only group either way
        return pipeline.host_decode(codec, stack, erasures_l,
                                    list(avail_l))

    return pipeline.submit(pack, dispatch, unpack, kind="decode",
                           owner=owner, host_fallback=host_fallback,
                           ops=len(idxs))


def decode(sinfo: StripeInfo, ec_impl,
           to_decode: dict[int, np.ndarray]) -> bytes:
    """Reconstruct the logical buffer from >=k shard chunk streams
    (ECUtil.cc:9-45), batched across all stripes in one decode call."""
    chunks = {i: _as_u8(v) for i, v in to_decode.items()}
    total = {len(v) for v in chunks.values()}
    assert len(total) == 1, "uneven shard buffers"
    decoded = ec_impl.decode_concat(chunks)
    k = ec_impl.get_data_chunk_count()
    total.pop()
    # reshape by row count, not input length: expanded (MBR) stored
    # chunks decode to SHORTER share streams than the stored input
    logical = _from_shard_major(
        np.frombuffer(decoded, dtype=np.uint8).reshape(k, -1),
        sinfo.chunk_size)
    return logical.tobytes()


def _group_streams(chunk_dicts: list[dict], sig,
                   pad_chunks=None, quantum: int | None = None
                   ) -> tuple[dict[int, np.ndarray], list[int]]:
    """Assemble one signature group's per-op shard streams into
    ``({chunk: concatenated [total] bytes}, per-op lens)`` — the ONE copy
    of the stacking/validation/size-bucket-padding logic shared by the
    sync and pipelined decode paths (they are asserted bitwise-identical,
    so they must assemble identically by construction).  ``pad_chunks``
    optionally rounds the group's total chunk count up (zero chunks
    decode to zero bytes — linear code — and the pad slices off)."""
    streams: dict[int, list[np.ndarray]] = {c: [] for c in sig}
    lens: list[int] = []
    for chunks in chunk_dicts:
        chunks = {c: _as_u8(v) for c, v in chunks.items()}
        sizes = {len(v) for v in chunks.values()}
        assert len(sizes) == 1, "uneven shard buffers"
        lens.append(sizes.pop())
        for c in sig:
            streams[c].append(chunks[c])
    total = sum(lens)
    if pad_chunks is not None and quantum and total % quantum == 0:
        padded = pad_chunks(total // quantum) * quantum
        if padded > total:
            pad = np.zeros(padded - total, dtype=np.uint8)
            for c in sig:
                streams[c].append(pad)
    return ({c: (np.concatenate(v) if len(v) > 1 else v[0])
             for c, v in streams.items()}, lens)


def decode_many(sinfo: StripeInfo, ec_impl,
                batches: list[dict[int, np.ndarray]],
                pad_chunks=None, chunk_size: int | None = None
                ) -> list[bytes]:
    """Decode MANY ops' shard chunk-dicts with ONE ``decode_concat`` per
    distinct available-chunk signature — the decode-side sibling of
    :func:`encode_many`.  Ops sharing a survivor set share a decode
    matrix, so their shard streams concatenate along the byte axis into
    one device dispatch; results split back per op, bit-identical to
    calling :func:`decode` per dict.

    ``pad_chunks(stripes) -> padded_stripes`` optionally rounds each
    group's total stripe count up (size bucketing: zero chunks decode to
    zero bytes — linear code — and the pad slices off exactly), keeping
    the jitted device path's shape set bounded."""
    if not batches:
        return []
    results: list[bytes | None] = [None] * len(batches)
    by_sig: dict[frozenset, list[int]] = {}
    for i, chunks in enumerate(batches):
        by_sig.setdefault(frozenset(chunks), []).append(i)
    k = ec_impl.get_data_chunk_count()
    for sig, idxs in by_sig.items():
        concat, lens = _group_streams(
            [batches[i] for i in idxs], sig, pad_chunks=pad_chunks,
            quantum=chunk_size if chunk_size else sinfo.chunk_size)
        decoded = np.frombuffer(
            ec_impl.decode_concat(concat), dtype=np.uint8).reshape(k, -1)
        off = 0
        for i, ln in zip(idxs, lens):
            logical = _from_shard_major(
                np.ascontiguousarray(decoded[:, off:off + ln]),
                sinfo.chunk_size)
            results[i] = logical.tobytes()
            off += ln
    return results


def decode_shards_many(sinfo: StripeInfo, ec_impl,
                       batches: list[tuple[dict[int, np.ndarray], set]],
                       pipeline=None, owner: str | None = "recovery"
                       ) -> list[dict[int, np.ndarray]]:
    """Reconstruct specific shards for MANY objects with ONE
    ``ec_impl.decode`` per distinct (survivor signature, want set) — the
    recovery-side sibling of :func:`decode_many`.  Parity is positionwise,
    so objects sharing both signatures share a decode matrix and their
    chunk streams concatenate along the byte axis into one device
    dispatch; results split back per object, bit-identical to calling
    :func:`decode_shards` per object.

    ``batches`` is ``[(available {chunk: bytes}, want set), ...]``.  Only
    valid for whole-chunk codes (``get_sub_chunk_count() == 1``) — clay's
    fractional repair reads are not positionwise across objects; callers
    gate on that and fall back to per-object :func:`decode_shards`.

    With a ``pipeline``, each (signature, want) group dispatches async
    through the device pipeline: group i+1's host pack overlaps group i's
    in-flight device reconstruct, and results fetch at the end — the
    repair-wave overlap the recovery scheduler rides."""
    if not batches:
        return []
    results: list[dict[int, np.ndarray] | None] = [None] * len(batches)
    by_sig: dict[tuple[frozenset, frozenset], list[int]] = {}
    for i, (available, want) in enumerate(batches):
        by_sig.setdefault((frozenset(available), frozenset(want)),
                          []).append(i)
    if pipeline is not None:
        pending = _decode_shards_groups_pipelined(sinfo, ec_impl, batches,
                                                  by_sig, pipeline, owner)
        if pending is not None:
            # every group is dispatched before the first fetch: the host
            # pack of later groups overlapped earlier device compute
            for idxs, fut in pending:
                for i, rec in zip(idxs, fut.result()):
                    results[i] = rec
            return results
    for (sig, want_sig), idxs in by_sig.items():
        want = set(want_sig)
        concat, lens = _group_streams([batches[i][0] for i in idxs], sig)
        decoded = ec_impl.decode(want, concat, 0)
        off = 0
        for i, ln in zip(idxs, lens):
            results[i] = {c: np.asarray(decoded[c], dtype=np.uint8)
                          [off:off + ln] for c in want}
            off += ln
    return results


def _decode_shards_groups_pipelined(sinfo, ec_impl, batches, by_sig,
                                    pipeline, owner: str | None = "recovery"):
    """Submit every (signature, want) recovery group through the device
    pipeline; ``[(idxs, future), ...]`` or None when no device path."""
    total_bytes = sum(sum(_as_u8(v).nbytes for v in avail.values())
                      for avail, _want in batches)
    codec = _device_codec(ec_impl, total_bytes)
    if codec is None:
        return None
    n = ec_impl.get_chunk_count()
    pending = []
    for (sig, want_sig), idxs in sorted(by_sig.items(),
                                        key=lambda kv: kv[1][0]):
        want = sorted(want_sig)

        def pack(sig=sig, want=want, idxs=idxs):
            concat, lens = _group_streams([batches[i][0] for i in idxs],
                                          sig)
            avail_l, want_l = ec_impl.remap_for_decode(concat, want)
            erasures_l = [i for i in range(n) if i not in avail_l]
            _D, src = codec.decode_matrix(erasures_l,
                                          available=list(avail_l))
            stack = np.stack([avail_l[s] for s in src])
            return erasures_l, want_l, list(avail_l), stack, lens

        def dispatch(packed):
            erasures_l, _want_l, avail_ids, stack, _lens = packed
            return pipeline.dispatch_decode(codec, stack, erasures_l,
                                            avail_ids)

        def unpack(packed, rec):
            erasures_l, want_l, _avail_ids, _stack, lens = packed
            rows = {e: rec[i] for i, e in enumerate(erasures_l)}
            out: list[dict[int, np.ndarray]] = []
            off = 0
            for ln in lens:
                out.append({ec_impl.chunk_index(w): rows[w][off:off + ln]
                            for w in want_l})
                off += ln
            return out

        def host_fallback(packed):
            erasures_l, _want_l, avail_ids, stack, _lens = packed
            return pipeline.host_decode(codec, stack, erasures_l,
                                        avail_ids)

        pending.append((list(idxs),
                        pipeline.submit(pack, dispatch, unpack,
                                        kind="recover", owner=owner,
                                        host_fallback=host_fallback,
                                        ops=len(idxs))))
    return pending


def decode_shards(sinfo: StripeInfo, ec_impl, available: dict[int, np.ndarray],
                  want: set, chunk_size: int = 0) -> dict[int, np.ndarray]:
    """Reconstruct specific shards (recovery path, ECUtil.cc:47-118 shape).

    ``chunk_size`` is the full per-shard size; when the available buffers are
    smaller, sub-chunk-aware codes (clay) route through their fractional
    repair path (ErasureCodeClay.cc:107-122)."""
    chunks = {i: _as_u8(v) for i, v in available.items()}
    return ec_impl.decode(set(want), chunks, chunk_size)


def partial_sum_accumulate(coeffs, stream, acc, pipeline=None,
                           owner: str | None = "recovery",
                           use_device: bool = False) -> list[bytes]:
    """One streaming-repair hop's partial-sum update: scale the hop's
    local chunk ``stream`` (every plan object concatenated) by its
    per-erased-row decode ``coeffs`` and XOR into ``acc``.

    ``acc`` is ``None`` on the first hop, else one running buffer per
    erased row.  Returns one bytes buffer per row.  With a ``pipeline``
    and ``use_device`` the single fused scale-accumulate dispatch rides
    the shared CodecPipeline — breaker, host fallback, and device-time
    attribution for free; otherwise (or when the breaker trips) the
    exact host GF math runs."""
    from ..gf import ref as gfref                       # noqa: F401 (host path)
    from ..ops import codec as _codec
    data = _as_u8(stream).reshape(1, -1)
    mat = np.asarray([[int(c) & 0xFF] for c in coeffs], dtype=np.uint8)
    acc_stack = None if acc is None \
        else np.stack([_as_u8(a) for a in acc])

    def _rows(out) -> list[bytes]:
        out = np.asarray(out, dtype=np.uint8)
        return [out[i].tobytes() for i in range(out.shape[0])]

    if pipeline is None or not use_device:
        return _rows(_codec.scale_accumulate_host(mat, data, acc_stack))

    def pack():
        return mat, data, acc_stack

    def dispatch(packed):
        m, d, a = packed
        return _codec.scale_accumulate_device(m, d, a)

    def unpack(packed, host):
        return _rows(host)

    def host_fallback(packed):
        m, d, a = packed
        return _codec.scale_accumulate_host(m, d, a)

    fut = pipeline.submit(pack, dispatch, unpack, kind="partial_sum",
                          owner=owner, host_fallback=host_fallback, ops=1)
    return fut.result()


def _gf_matmul_routed(mat: np.ndarray, data: np.ndarray, pipeline=None,
                      owner: str | None = "recovery",
                      use_device: bool = False) -> np.ndarray:
    """One GF(2^8) matrix product routed through the recovery
    CodecPipeline (breaker / host-fallback / attribution) when present,
    host otherwise — the shared engine under the regenerating-repair
    legs."""
    from ..ops import codec as _codec
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    if pipeline is None or not use_device:
        return _codec.gf_inner_product_host(mat, data)

    def pack():
        return mat, data

    def dispatch(packed):
        m, d = packed
        return _codec.gf_inner_product_device(m, d)

    def unpack(packed, host):
        return np.asarray(host, dtype=np.uint8)

    def host_fallback(packed):
        m, d = packed
        return _codec.gf_inner_product_host(m, d)

    fut = pipeline.submit(pack, dispatch, unpack, kind="regen",
                          owner=owner, host_fallback=host_fallback, ops=1)
    return fut.result()


def regen_project(coeffs: bytes | np.ndarray, stream, sub_count: int,
                  pipeline=None, owner: str | None = "recovery",
                  use_device: bool = False) -> bytes:
    """One helper's regenerating-repair leg: project the stored chunk's
    ``sub_count`` symbol rows down to the single beta-stream
    ``psi_f . chunk`` it ships to the newcomer (len(stream)/sub_count
    bytes — the d-fold wire saving the product-matrix code exists
    for)."""
    data = _as_u8(stream)
    assert data.size % sub_count == 0, "chunk not sub-chunk aligned"
    mat = np.frombuffer(bytes(coeffs), dtype=np.uint8).reshape(1, sub_count)
    out = _gf_matmul_routed(mat, data.reshape(sub_count, -1),
                            pipeline=pipeline, owner=owner,
                            use_device=use_device)
    return out.reshape(-1).tobytes()


def regen_combine(mat: bytes | np.ndarray, streams: list, sub_count: int,
                  pipeline=None, owner: str | None = "recovery",
                  use_device: bool = False) -> bytes:
    """The newcomer's regenerating-repair leg: combine the d stacked
    helper beta-streams into the lost chunk's ``sub_count`` symbol rows
    (bitwise-exact repair)."""
    stack = np.stack([_as_u8(s) for s in streams])
    m = np.frombuffer(bytes(mat), dtype=np.uint8).reshape(sub_count,
                                                          len(streams))
    out = _gf_matmul_routed(m, stack, pipeline=pipeline, owner=owner,
                            use_device=use_device)
    return out.reshape(-1).tobytes()


HINFO_KEY = "hinfo_key"  # xattr name (ECUtil.cc:235, get_hinfo_key)
