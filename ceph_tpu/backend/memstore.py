"""In-memory object store with atomic transactions.

Analog of the reference's MemStore (reference: src/os/memstore/MemStore.cc —
the in-RAM ObjectStore used by fast OSD-level tests) exposing the
``ObjectStore::Transaction`` surface the EC path needs (reference:
src/os/ObjectStore.h, src/os/Transaction.h): write/zero/truncate/remove plus
object xattrs.  Object names carry a shard id the way ``ghobject_t`` does
(oid, NO_GEN, shard) — reference: src/osd/ECTransaction.cc:62-81.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

NO_SHARD = -1


@dataclass(frozen=True)
class GObject:
    """ghobject_t: an object name + shard id."""
    oid: str
    shard: int = NO_SHARD


@dataclass
class _Object:
    data: bytearray = field(default_factory=bytearray)
    xattrs: dict[str, Any] = field(default_factory=dict)
    omap: dict[str, bytes] = field(default_factory=dict)
    omap_header: bytes = b""

    def copy(self) -> "_Object":
        return _Object(bytearray(self.data), dict(self.xattrs),
                       dict(self.omap), self.omap_header)


class Transaction:
    """Ordered op list applied atomically (ObjectStore::Transaction shape)."""

    def __init__(self):
        self.ops: list[tuple] = []

    def write(self, obj: GObject, offset: int, data: bytes) -> "Transaction":
        self.ops.append(("write", obj, offset, bytes(data)))
        return self

    def zero(self, obj: GObject, offset: int, length: int) -> "Transaction":
        self.ops.append(("zero", obj, offset, length))
        return self

    def truncate(self, obj: GObject, size: int) -> "Transaction":
        self.ops.append(("truncate", obj, size))
        return self

    def remove(self, obj: GObject) -> "Transaction":
        self.ops.append(("remove", obj))
        return self

    def touch(self, obj: GObject) -> "Transaction":
        self.ops.append(("touch", obj))
        return self

    def clone(self, src: GObject, dst: GObject) -> "Transaction":
        self.ops.append(("clone", src, dst))
        return self

    def setattr(self, obj: GObject, name: str, value) -> "Transaction":
        self.ops.append(("setattr", obj, name, value))
        return self

    def rmattr(self, obj: GObject, name: str) -> "Transaction":
        self.ops.append(("rmattr", obj, name))
        return self

    def omap_setkeys(self, obj: GObject, kvs: dict[str, bytes]) -> "Transaction":
        self.ops.append(("omap_setkeys", obj, dict(kvs)))
        return self

    def omap_rmkeys(self, obj: GObject, keys) -> "Transaction":
        self.ops.append(("omap_rmkeys", obj, list(keys)))
        return self

    def omap_clear(self, obj: GObject) -> "Transaction":
        self.ops.append(("omap_clear", obj))
        return self

    def omap_setheader(self, obj: GObject, header: bytes) -> "Transaction":
        self.ops.append(("omap_setheader", obj, bytes(header)))
        return self

    def append(self, other: "Transaction") -> "Transaction":
        self.ops.extend(other.ops)
        return self

    def empty(self) -> bool:
        return not self.ops


class MemStore:
    """Flat in-RAM store; transactions apply all-or-nothing on op error."""

    def __init__(self):
        self.objects: dict[GObject, _Object] = {}
        self.committed_seq = 0

    # -- transactions ------------------------------------------------------

    def queue_transaction(self, t: Transaction) -> int:
        """Apply atomically; returns the commit sequence number.

        Atomicity by staging copies of only the objects the transaction
        names (not the whole store): on any op error nothing merges back."""
        touched: set[GObject] = set()
        for op in t.ops:
            touched.add(op[1])
            if op[0] == "clone":
                touched.add(op[2])
        staged: dict[GObject, _Object] = {}
        for obj in touched:
            o = self.objects.get(obj)
            if o is not None:
                staged[obj] = o.copy()
        for op in t.ops:
            self._apply(staged, op)
        for obj in touched:
            if obj in staged:
                self.objects[obj] = staged[obj]
            else:
                self.objects.pop(obj, None)
        self.committed_seq += 1
        return self.committed_seq

    def _apply(self, objs: dict[GObject, _Object], op: tuple) -> None:
        kind = op[0]
        if kind == "write":
            _, obj, offset, data = op
            o = objs.setdefault(obj, _Object())
            end = offset + len(data)
            if len(o.data) < end:
                o.data.extend(b"\0" * (end - len(o.data)))
            o.data[offset:end] = data
        elif kind == "zero":
            _, obj, offset, length = op
            o = objs.setdefault(obj, _Object())
            end = offset + length
            if len(o.data) < end:
                o.data.extend(b"\0" * (end - len(o.data)))
            o.data[offset:end] = b"\0" * length
        elif kind == "truncate":
            _, obj, size = op
            o = objs.setdefault(obj, _Object())
            if len(o.data) > size:
                del o.data[size:]
            else:
                o.data.extend(b"\0" * (size - len(o.data)))
        elif kind == "remove":
            objs.pop(op[1], None)
        elif kind == "touch":
            objs.setdefault(op[1], _Object())
        elif kind == "clone":
            _, src, dst = op
            objs[dst] = objs.get(src, _Object()).copy()
        elif kind == "setattr":
            _, obj, name, value = op
            objs.setdefault(obj, _Object()).xattrs[name] = value
        elif kind == "rmattr":
            _, obj, name = op
            objs.setdefault(obj, _Object()).xattrs.pop(name, None)
        elif kind == "omap_setkeys":
            _, obj, kvs = op
            objs.setdefault(obj, _Object()).omap.update(kvs)
        elif kind == "omap_rmkeys":
            _, obj, keys = op
            o = objs.setdefault(obj, _Object())
            for key in keys:
                o.omap.pop(key, None)
        elif kind == "omap_clear":
            o = objs.setdefault(op[1], _Object())
            o.omap.clear()
            o.omap_header = b""
        elif kind == "omap_setheader":
            objs.setdefault(op[1], _Object()).omap_header = op[2]
        else:
            raise ValueError(f"unknown op {kind}")

    # -- reads -------------------------------------------------------------

    def read(self, obj: GObject, offset: int = 0, length: int | None = None) -> bytes:
        o = self.objects.get(obj)
        if o is None:
            raise FileNotFoundError(obj)
        if length is None:
            return bytes(o.data[offset:])
        return bytes(o.data[offset:offset + length])

    def stat(self, obj: GObject) -> int:
        o = self.objects.get(obj)
        if o is None:
            raise FileNotFoundError(obj)
        return len(o.data)

    def exists(self, obj: GObject) -> bool:
        return obj in self.objects

    def getattr(self, obj: GObject, name: str):
        o = self.objects.get(obj)
        if o is None:
            raise FileNotFoundError(obj)
        return o.xattrs[name]

    def get_omap(self, obj: GObject) -> dict[str, bytes]:
        o = self.objects.get(obj)
        if o is None:
            raise FileNotFoundError(obj)
        return dict(o.omap)

    def get_omap_header(self, obj: GObject) -> bytes:
        o = self.objects.get(obj)
        if o is None:
            raise FileNotFoundError(obj)
        return o.omap_header

    def getattrs(self, obj: GObject) -> dict[str, Any]:
        o = self.objects.get(obj)
        if o is None:
            raise FileNotFoundError(obj)
        return dict(o.xattrs)

    def list_objects(self) -> list[GObject]:
        return sorted(self.objects, key=lambda g: (g.oid, g.shard))
