"""Interval (extent) set arithmetic.

Analog of the reference's ``extent_set``/``interval_set`` used throughout the
EC write-planning and cache code (reference: src/include/interval_set.h,
src/osd/ECTransaction.h:29-31).  Extents are half-open byte ranges
``[start, end)`` kept sorted and coalesced.
"""
from __future__ import annotations

from bisect import bisect_right
from collections.abc import Iterator


class ExtentSet:
    """Sorted, coalesced set of half-open intervals."""

    def __init__(self, extents=()):  # iterable of (start, len)
        self._spans: list[tuple[int, int]] = []  # (start, end)
        for off, length in extents:
            self.union_insert(off, length)

    # -- mutation ----------------------------------------------------------

    def union_insert(self, off: int, length: int) -> None:
        """Insert [off, off+length), merging overlaps (interval_set::union_insert)."""
        if length <= 0:
            return
        start, end = off, off + length
        spans = self._spans
        i = bisect_right(spans, (start,)) - 1
        if i >= 0 and spans[i][1] >= start:
            start = min(start, spans[i][0])
        else:
            i += 1
        j = i
        while j < len(spans) and spans[j][0] <= end:
            end = max(end, spans[j][1])
            j += 1
        spans[i:j] = [(start, end)]

    def subtract(self, other: "ExtentSet") -> None:
        for off, end in other._spans:
            self.erase(off, end - off)

    def erase(self, off: int, length: int) -> None:
        if length <= 0:
            return
        start, end = off, off + length
        out = []
        for s, e in self._spans:
            if e <= start or s >= end:
                out.append((s, e))
                continue
            if s < start:
                out.append((s, start))
            if e > end:
                out.append((end, e))
        self._spans = out

    # -- queries -----------------------------------------------------------

    def contains(self, off: int, length: int = 1) -> bool:
        i = bisect_right(self._spans, (off,))
        if i and self._spans[i - 1][0] <= off and off + length <= self._spans[i - 1][1]:
            return True
        # exact-start span
        if i < len(self._spans) and self._spans[i][0] == off:
            return off + length <= self._spans[i][1]
        return False

    def intersects(self, off: int, length: int) -> bool:
        end = off + length
        for s, e in self._spans:
            if s < end and off < e:
                return True
        return False

    def intersection(self, other: "ExtentSet") -> "ExtentSet":
        out = ExtentSet()
        a, b = self._spans, other._spans
        i = j = 0
        while i < len(a) and j < len(b):
            s = max(a[i][0], b[j][0])
            e = min(a[i][1], b[j][1])
            if s < e:
                out._spans.append((s, e))
            if a[i][1] < b[j][1]:
                i += 1
            else:
                j += 1
        return out

    def union(self, other: "ExtentSet") -> "ExtentSet":
        out = ExtentSet()
        for s, e in self._spans:
            out.union_insert(s, e - s)
        for s, e in other._spans:
            out.union_insert(s, e - s)
        return out

    def size(self) -> int:
        """Total bytes covered."""
        return sum(e - s for s, e in self._spans)

    def range_start(self) -> int:
        return self._spans[0][0]

    def range_end(self) -> int:
        return self._spans[-1][1]

    def __bool__(self) -> bool:
        return bool(self._spans)

    def __len__(self) -> int:
        return len(self._spans)

    def __iter__(self) -> Iterator[tuple[int, int]]:
        """Yield (start, length) pairs."""
        return ((s, e - s) for s, e in self._spans)

    def __eq__(self, other) -> bool:
        return isinstance(other, ExtentSet) and self._spans == other._spans

    def __repr__(self) -> str:
        return "ExtentSet([%s])" % ", ".join(
            f"{s}~{e - s}" for s, e in self._spans)
