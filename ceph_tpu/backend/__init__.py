"""EC backend: stripe algebra, object store, write pipeline, recovery.

The TPU-native analog of the reference's OSD erasure-coded I/O path
(reference: src/osd/ECUtil.*, ECTransaction.*, ECBackend.*, ECMsgTypes.*,
ExtentCache.*, src/os/memstore/ — SURVEY.md §2.2), restructured so every
encode/decode is one batched device call across all stripes of an op.
"""
from .ecutil import HINFO_KEY, HashInfo, StripeInfo, crc32c, decode, decode_shards, encode
from .extent import ExtentSet
from .extent_cache import ExtentCache
from .ec_backend import ECBackend, make_cluster
from .pg_backend import OSDShard, PGBackend, RecoveryState
from .replicated import ReplicatedBackend, make_replicated_cluster
from .filestore import FileStore
from .memstore import GObject, MemStore, Transaction
from .messages import (ECSubRead, ECSubReadReply, ECSubWrite, ECSubWriteReply,
                       MessageBus, PushOp, PushReply)
from .transaction import ObjectOperation, PGTransaction, WritePlan, get_write_plan

__all__ = [
    "HINFO_KEY", "HashInfo", "StripeInfo", "crc32c", "decode", "decode_shards",
    "encode", "ExtentSet", "ExtentCache", "ECBackend", "PGBackend",
    "ReplicatedBackend", "make_replicated_cluster", "FileStore", "OSDShard",
    "RecoveryState", "make_cluster", "GObject", "MemStore", "Transaction",
    "ECSubRead", "ECSubReadReply", "ECSubWrite", "ECSubWriteReply",
    "MessageBus", "PushOp", "PushReply", "ObjectOperation", "PGTransaction",
    "WritePlan", "get_write_plan",
]
