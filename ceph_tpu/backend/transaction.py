"""Client-level object transactions and EC write planning.

Analog of the reference's ``PGTransaction`` (reference:
src/osd/PGTransaction.h) and ``ECTransaction::get_write_plan`` (reference:
src/osd/ECTransaction.h:40-183): computes which whole stripes must be read
(RMW head/tail partials) and which stripe-aligned extents will be written.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from .ecutil import HashInfo, StripeInfo
from .extent import ExtentSet


@dataclass
class ObjectOperation:
    """One object's mutation set (PGTransaction::ObjectOperation shape)."""
    delete_first: bool = False
    # buffer updates: (logical offset, payload bytes)
    buffer_updates: list[tuple[int, bytes]] = field(default_factory=list)
    # (truncate_before_writes, truncate_after_writes) — ECTransaction.h:71,154
    truncate: tuple[int, int] | None = None
    source: str | None = None  # rename/clone source oid
    # pre-encoded chunk streams ({chunk index: bytes-like}) supplied by a
    # cross-op batch encoder (ecutil.encode_many): the backend uses them
    # instead of encoding, IF the assembled write bytes equal
    # ``precomputed_for`` exactly (a plan that turned into an RMW falls
    # back to a live encode) — the cross-PG coalescing hook SURVEY §3.2
    # marks as the main TPU restructuring
    precomputed_chunks: dict | None = None
    precomputed_for: bytes | None = None
    # object attribute updates (name -> value, None = remove), applied to
    # every shard like the reference's per-shard xattr replication
    # (PGTransaction::ObjectOperation::attr_updates, src/osd/PGTransaction.h)
    attr_updates: dict[str, object] = field(default_factory=dict)
    # omap mutations in order: ("set", {k: v}) | ("rm", [k]) | ("clear",)
    # — replicated pools only; EC pools reject omap like the reference
    omap_ops: list[tuple] = field(default_factory=list)
    # snapshot copy-on-write: clone this object's PRE-op state to each
    # listed oid before mutations apply (PGTransaction's clone op; the
    # make_writable COW, src/osd/PrimaryLogPG.cc).  Shard-local clones
    # are exact for both pool types (chunks clone chunk-wise).
    clone_to: list[str] = field(default_factory=list)
    # snapshot rollback: replace this object wholesale with the named
    # source object's state (CEPH_OSD_OP_ROLLBACK -> _rollback_to)
    rollback_from: str | None = None

    def write(self, offset: int, data: bytes) -> "ObjectOperation":
        self.buffer_updates.append((offset, bytes(data)))
        return self

    def setattr(self, name: str, value) -> "ObjectOperation":
        self.attr_updates[name] = value
        return self

    def rmattr(self, name: str) -> "ObjectOperation":
        self.attr_updates[name] = None
        return self


class PGTransaction:
    """oid -> ObjectOperation, applied in insertion order."""

    def __init__(self):
        self.ops: dict[str, ObjectOperation] = {}

    def touch(self, oid: str) -> ObjectOperation:
        return self.ops.setdefault(oid, ObjectOperation())

    def write(self, oid: str, offset: int, data: bytes) -> "PGTransaction":
        self.touch(oid).write(offset, data)
        return self

    def delete(self, oid: str) -> "PGTransaction":
        self.touch(oid).delete_first = True
        return self

    def truncate_to(self, oid: str, size: int) -> "PGTransaction":
        self.touch(oid).truncate = (size, size)
        return self


@dataclass
class WritePlan:
    """ECTransaction::WritePlan (ECTransaction.h:26-33)."""
    t: PGTransaction
    to_read: dict[str, ExtentSet] = field(default_factory=dict)
    will_write: dict[str, ExtentSet] = field(default_factory=dict)
    hash_infos: dict[str, HashInfo] = field(default_factory=dict)
    invalidates_cache: bool = False


def get_write_plan(sinfo: StripeInfo, t: PGTransaction, get_hinfo,
                   sub_chunk_count: int = 1) -> WritePlan:
    """Mirror of the reference planner (ECTransaction.h:40-183).

    ``get_hinfo(oid) -> HashInfo`` supplies the projected-size oracle.  For
    each object: unaligned truncates force a read+rewrite of their last
    stripe; every write extent reads its partial head/tail stripes when they
    overlap existing data; ``will_write`` is the stripe-aligned hull of the
    writes (a superset of ``to_read``).

    ``sub_chunk_count > 1`` (clay) additionally forces any PARTIAL write
    to a full-object read+rewrite: the sub-chunk interleave is a function
    of the WHOLE chunk height, so a write that left old bytes in place
    would stitch codewords of different geometries into one stored chunk
    and every later decode — degraded read, fractional repair — would
    reconstruct garbage (found by the clay thrash soak).  The reference
    never hits this because it encodes strictly per stripe; this
    codebase's whole-extent batched encode is bit-identical only for
    per-byte-linear codes, so sub-chunked codes pay the rewrite instead.
    """
    plan = WritePlan(t=t)
    for oid, op in t.ops.items():
        hinfo = get_hinfo(oid)
        plan.hash_infos[oid] = hinfo
        projected_size = hinfo.get_projected_total_logical_size(sinfo)

        if op.delete_first:
            projected_size = 0
        if op.source is not None:
            plan.invalidates_cache = True
            shinfo = get_hinfo(op.source)
            projected_size = shinfo.get_projected_total_logical_size(sinfo)
            plan.hash_infos[op.source] = shinfo

        will_write = plan.will_write.setdefault(oid, ExtentSet())

        if op.truncate is not None and op.truncate[0] < projected_size:
            if not sinfo.logical_offset_is_stripe_aligned(op.truncate[0]):
                prev = sinfo.logical_to_prev_stripe_offset(op.truncate[0])
                plan.to_read.setdefault(oid, ExtentSet()).union_insert(
                    prev, sinfo.stripe_width)
                will_write.union_insert(prev, sinfo.stripe_width)
            projected_size = sinfo.logical_to_next_stripe_offset(op.truncate[0])

        raw_write_set = ExtentSet()
        for off, data in op.buffer_updates:
            raw_write_set.union_insert(off, len(data))

        orig_size = projected_size
        for off, length in raw_write_set:
            head_start = sinfo.logical_to_prev_stripe_offset(off)
            head_finish = sinfo.logical_to_next_stripe_offset(off)
            if head_start > projected_size:
                head_start = projected_size
            if head_start != head_finish and head_start < orig_size:
                plan.to_read.setdefault(oid, ExtentSet()).union_insert(
                    head_start, sinfo.stripe_width)

            tail_start = sinfo.logical_to_prev_stripe_offset(off + length)
            tail_finish = sinfo.logical_to_next_stripe_offset(off + length)
            if (tail_start != tail_finish and
                    (head_start == head_finish or tail_start != head_start) and
                    tail_start < orig_size):
                plan.to_read.setdefault(oid, ExtentSet()).union_insert(
                    tail_start, sinfo.stripe_width)

            if head_start != tail_finish:
                will_write.union_insert(head_start, tail_finish - head_start)
                if tail_finish > projected_size:
                    projected_size = tail_finish

        if op.truncate is not None and op.truncate[1] > projected_size:
            truncating_to = sinfo.logical_to_next_stripe_offset(op.truncate[1])
            will_write.union_insert(projected_size,
                                    truncating_to - projected_size)
            projected_size = truncating_to

        if sub_chunk_count > 1 and len(list(will_write)):
            # one object = ONE codeword: extend a partial write to cover
            # the whole object, reading back every stripe the op's own
            # writes don't supply (the RMW machinery overlays reads and
            # writes before the single full-height encode)
            end = sinfo.logical_to_next_stripe_offset(projected_size)
            spans = list(will_write)
            if not (len(spans) == 1 and spans[0][0] == 0
                    and spans[0][1] >= end):
                old_end = min(sinfo.logical_to_next_stripe_offset(
                    orig_size), end)
                gaps = ExtentSet([(0, old_end)])
                gaps.subtract(will_write)
                to_read = plan.to_read.setdefault(oid, ExtentSet())
                for g_off, g_len in gaps:
                    to_read.union_insert(g_off, g_len)
                will_write.union_insert(0, end)

        hinfo.set_projected_total_logical_size(sinfo, projected_size)
    return plan
