"""BlueStore-lite: an extent-allocator object store over one flat file.

The durable store modeled on the reference's flagship ObjectStore
(reference: src/os/bluestore/BlueStore.cc — structure, not scale):

- **data** lives in ONE flat block file, allocated in ``min_alloc``-sized
  units by a run-list allocator with bitmap semantics (the reference's
  BitmapAllocator, src/os/bluestore/BitmapAllocator.h);
- **blobs** are immutable physical regions: every write allocates a fresh
  blob and remaps logical extents onto it (the reference's copy-on-write
  blob model), so a crash mid-write leaves old metadata pointing at old
  bytes — never torn data;
- **checksums at rest**: each blob stores the crc32c of its physical
  bytes, verified on EVERY read (``bluestore_csum_type=crc32c``); a
  mismatch raises :class:`ChecksumError` (EIO), which deep scrub surfaces
  without any majority vote;
- **inline compression** via the CompressorRegistry: blobs compress when
  the configured compressor saves at least one allocation unit, storing
  ``raw_len`` for exact reconstruction (``bluestore_compression_mode``);
- **clones share blobs** by refcount — O(extent-map) clone, no data copy
  (the snapshot COW path rides this);
- **metadata** (onodes: size + extent maps + xattrs + omap; the blob
  table) journals through a WAL and periodic checkpoints, exactly like
  :class:`~ceph_tpu.backend.filestore.FileStore` — but checkpoints carry
  ONLY metadata, so their cost scales with object count, not data volume
  (the r4 whole-store-pickle weakness).  The allocator's free list is
  REBUILT from the blob table on open (self-healing, like the
  reference's freelist-from-RocksDB startup).

Implements the full MemStore/FileStore ObjectStore surface, so it can
back OSD daemons via collections unchanged.
"""
from __future__ import annotations

import os
import pickle
import struct
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

from .ecutil import crc32c
from .memstore import GObject, Transaction, _Object

_FRAME = struct.Struct("<II")        # payload length, crc32c(payload)
_SNAP = "kv.snap"
_WAL = "kv.log"
_BLOCK = "block"


class ChecksumError(IOError):
    """A blob's bytes at rest no longer match their stored crc32c (the
    reference returns -EIO from _verify_csum)."""


@dataclass
class Blob:
    """An immutable physical region of the block file."""
    poff: int            # byte offset in the block file
    plen: int            # stored (possibly compressed) byte length
    alloc: int           # allocated bytes (plen rounded up to units)
    raw_len: int         # decompressed length
    csum: int            # crc32c of the STORED bytes
    comp: str | None     # compressor name, None = raw
    refs: int = 1        # extents (across all onodes) mapping this blob


@dataclass
class Extent:
    """A logical range of an object mapped onto part of a blob."""
    loff: int            # logical offset in the object
    length: int
    blob: int            # blob id
    boff: int            # offset into the blob's RAW content


@dataclass
class Onode:
    size: int = 0
    extents: list[Extent] = field(default_factory=list)   # sorted by loff
    xattrs: dict[str, Any] = field(default_factory=dict)
    omap: dict[str, bytes] = field(default_factory=dict)
    omap_header: bytes = b""

    def copy(self) -> "Onode":
        return Onode(self.size,
                     [Extent(e.loff, e.length, e.blob, e.boff)
                      for e in self.extents],
                     dict(self.xattrs), dict(self.omap), self.omap_header)


class RunListAllocator:
    """Free-space tracking with bitmap semantics over allocation units:
    sorted, coalesced (start, length) free runs below a growth watermark
    (BitmapAllocator.h behavior at run-list cost)."""

    def __init__(self, unit: int):
        self.unit = unit
        self.runs: list[list[int]] = []     # sorted [start_unit, n_units]
        self.watermark = 0                  # units ever claimed

    def alloc(self, nbytes: int) -> tuple[int, int]:
        """(byte offset, allocated bytes) — first-fit over the free runs,
        else grow the watermark."""
        units = max(1, -(-nbytes // self.unit))
        for i, (start, n) in enumerate(self.runs):
            if n >= units:
                self.runs[i][0] += units
                self.runs[i][1] -= units
                if self.runs[i][1] == 0:
                    del self.runs[i]
                return start * self.unit, units * self.unit
        start = self.watermark
        self.watermark += units
        return start * self.unit, units * self.unit

    def free(self, poff: int, nbytes: int) -> None:
        start, units = poff // self.unit, max(1, -(-nbytes // self.unit))
        import bisect
        i = bisect.bisect_left(self.runs, [start, 0])
        self.runs.insert(i, [start, units])
        # coalesce with neighbours
        if i + 1 < len(self.runs) and \
                self.runs[i][0] + self.runs[i][1] == self.runs[i + 1][0]:
            self.runs[i][1] += self.runs[i + 1][1]
            del self.runs[i + 1]
        if i > 0 and self.runs[i - 1][0] + self.runs[i - 1][1] == \
                self.runs[i][0]:
            self.runs[i - 1][1] += self.runs[i][1]
            del self.runs[i]

    def free_bytes(self) -> int:
        return sum(n for _s, n in self.runs) * self.unit

    def rebuild(self, blobs: dict[int, Blob]) -> None:
        """Free list = everything under the watermark not covered by a
        live blob (freelist-from-metadata startup)."""
        self.runs = []
        covered = sorted((b.poff // self.unit, b.alloc // self.unit)
                        for b in blobs.values())
        self.watermark = 0
        pos = 0
        for start, units in covered:
            if start > pos:
                self.runs.append([pos, start - pos])
            pos = max(pos, start + units)
        self.watermark = pos


class BlueStoreLite:
    """Durable ObjectStore over ONE block file + metadata WAL/checkpoint;
    same surface as MemStore/FileStore."""

    def __init__(self, path: str | os.PathLike, min_alloc: int = 4096,
                 compression: str | None = None, sync: bool = False,
                 checkpoint_every: int = 512):
        self.path = Path(path)
        self.path.mkdir(parents=True, exist_ok=True)
        self.min_alloc = min_alloc
        self.sync = sync
        self.checkpoint_every = checkpoint_every
        self.onodes: dict[GObject, Onode] = {}
        self.blobs: dict[int, Blob] = {}
        self.next_blob = 1
        self.committed_seq = 0
        self.alloc = RunListAllocator(min_alloc)
        self._compressor = None
        self.compression = compression
        if compression:
            from ..compressor import CompressorRegistry
            self._compressor = CompressorRegistry.instance().create(
                compression)
        self._wal_records = 0
        self._load()
        self._block = open(self.path / _BLOCK, "r+b")
        self._wal = open(self.path / _WAL, "ab")

    # -- persistence --------------------------------------------------------

    def _load(self) -> None:
        (self.path / _BLOCK).touch()
        snap = self.path / _SNAP
        if snap.exists():
            with open(snap, "rb") as f:
                (self.committed_seq, self.onodes, self.blobs,
                 self.next_blob) = pickle.load(f)
        wal = self.path / _WAL
        if wal.exists():
            with open(wal, "rb") as f:
                buf = f.read()
            off = 0
            snap_seq = self.committed_seq
            while off + _FRAME.size <= len(buf):
                length, crc = _FRAME.unpack_from(buf, off)
                payload = buf[off + _FRAME.size:off + _FRAME.size + length]
                if len(payload) < length or \
                        crc32c(0xFFFFFFFF, payload) != crc:
                    break             # torn tail: never committed
                off += _FRAME.size + length
                seq, onode_delta, blob_delta, freed, nb = \
                    pickle.loads(payload)
                if seq <= snap_seq:
                    continue          # predates the checkpoint
                self._apply_meta(onode_delta, blob_delta, freed)
                self.next_blob = max(self.next_blob, nb)
                self.committed_seq = seq
                self._wal_records += 1
            if off < len(buf):
                os.truncate(wal, off)
        # the free list is DERIVED state: rebuild from live blobs
        self.alloc.rebuild(self.blobs)

    def _apply_meta(self, onode_delta, blob_delta, freed) -> None:
        for bid in freed:
            self.blobs.pop(bid, None)
        self.blobs.update(blob_delta)
        for obj, onode in onode_delta.items():
            if onode is None:
                self.onodes.pop(obj, None)
            else:
                self.onodes[obj] = onode

    def checkpoint(self) -> None:
        """Metadata-only snapshot (onodes + blob table): cost scales with
        object count, never data volume — the block file IS the data."""
        self._block.flush()
        if self.sync:
            os.fsync(self._block.fileno())
        tmp = self.path / (_SNAP + ".tmp")
        with open(tmp, "wb") as f:
            pickle.dump((self.committed_seq, self.onodes, self.blobs,
                         self.next_blob), f,
                        protocol=pickle.HIGHEST_PROTOCOL)
            f.flush()
            if self.sync:
                os.fsync(f.fileno())
        os.replace(tmp, self.path / _SNAP)
        self._wal.close()
        self._wal = open(self.path / _WAL, "wb")
        self._wal_records = 0

    def close(self, checkpoint: bool = True) -> None:
        if checkpoint:
            self.checkpoint()
        self._wal.close()
        self._block.close()

    # -- blob IO ------------------------------------------------------------

    def _write_blob(self, data: bytes, new_blobs: dict[int, Blob]) -> int:
        """Store ``data`` as a fresh blob (maybe compressed); returns the
        blob id.  The bytes hit the block file NOW, before the metadata
        commits — old metadata never references them, so a crash in
        between leaks nothing and tears nothing (COW)."""
        raw_len = len(data)
        comp = None
        stored = data
        if self._compressor is not None and raw_len > self.min_alloc:
            candidate = self._compressor.compress(data)
            # worth it only if it saves at least one allocation unit
            # (bluestore_compression_required_ratio in spirit)
            if (-(-len(candidate) // self.min_alloc)
                    < -(-raw_len // self.min_alloc)):
                stored = candidate
                comp = self.compression
        poff, alloc = self.alloc.alloc(max(1, len(stored)))
        self._block.seek(poff)
        self._block.write(stored)
        bid = self.next_blob
        self.next_blob += 1
        blob = Blob(poff=poff, plen=len(stored), alloc=alloc,
                    raw_len=raw_len, csum=crc32c(0xFFFFFFFF, stored),
                    comp=comp)
        new_blobs[bid] = blob
        self.blobs[bid] = blob
        return bid

    def _read_blob(self, bid: int) -> bytes:
        b = self.blobs[bid]
        self._block.flush()
        self._block.seek(b.poff)
        stored = self._block.read(b.plen)
        if crc32c(0xFFFFFFFF, stored) != b.csum:
            raise ChecksumError(
                f"blob {bid} at {b.poff}+{b.plen}: stored crc mismatch "
                f"(bitrot at rest)")
        if b.comp is not None:
            from ..compressor import CompressorRegistry
            return CompressorRegistry.instance().create(
                b.comp).decompress(stored)
        return stored

    # -- extent-map surgery --------------------------------------------------

    @staticmethod
    def _punch(onode: Onode, off: int, length: int,
               deref: list[int], addref: list[int]) -> None:
        """Drop the logical range [off, off+length) from the extent map,
        splitting boundary extents.  Blob refs count EXTENTS: a fully
        unmapped extent collects in ``deref``; a mid-split (one extent
        becoming two remainders) collects in ``addref``."""
        end = off + length
        out: list[Extent] = []
        for e in onode.extents:
            e_end = e.loff + e.length
            if e_end <= off or e.loff >= end:
                out.append(e)
                continue
            pieces = 0
            if e.loff < off:                    # left remainder
                out.append(Extent(e.loff, off - e.loff, e.blob, e.boff))
                pieces += 1
            if e_end > end:                     # right remainder
                out.append(Extent(end, e_end - end, e.blob,
                                  e.boff + (end - e.loff)))
                pieces += 1
            if pieces == 0:
                deref.append(e.blob)
            elif pieces == 2:
                addref.append(e.blob)
        onode.extents = sorted(out, key=lambda e: e.loff)

    def _deref(self, bids, freed: list[int]) -> None:
        for bid in bids:
            b = self.blobs.get(bid)
            if b is None:
                continue
            b.refs -= 1
            if b.refs <= 0:
                del self.blobs[bid]
                self.alloc.free(b.poff, b.alloc)
                freed.append(bid)

    # -- transactions --------------------------------------------------------

    def queue_transaction(self, t: Transaction) -> int:
        """Apply atomically; journal the metadata delta; return the seq.

        Staging mirrors MemStore: copies of only the touched onodes; blob
        refcount changes are tracked and only applied on success."""
        touched: set[GObject] = set()
        for op in t.ops:
            touched.add(op[1])
            if op[0] == "clone":
                touched.add(op[2])
        staged: dict[GObject, Onode | None] = {}
        for obj in touched:
            o = self.onodes.get(obj)
            staged[obj] = o.copy() if o is not None else None
        new_blobs: dict[int, Blob] = {}
        deref: list[int] = []       # blob ids losing one reference
        addref: list[int] = []      # blob ids gaining one (clone/split)
        try:
            for op in t.ops:
                self._apply(staged, op, new_blobs, deref, addref)
        except Exception:
            # all-or-nothing: orphan the data already written for this
            # transaction (nothing references it) and free its space
            for bid, b in new_blobs.items():
                self.blobs.pop(bid, None)
                self.alloc.free(b.poff, b.alloc)
            raise
        # commit: refcounts, onode table, WAL
        for bid in addref:
            self.blobs[bid].refs += 1
        freed: list[int] = []
        self._deref(deref, freed)
        for obj, onode in staged.items():
            if onode is None:
                self.onodes.pop(obj, None)
            else:
                self.onodes[obj] = onode
        self.committed_seq += 1
        payload = pickle.dumps(
            (self.committed_seq, staged,
             {bid: self.blobs[bid] for bid in
              set(new_blobs) - set(freed)} |
             {bid: self.blobs[bid] for bid in addref + deref
              if bid in self.blobs},
             freed, self.next_blob),
            protocol=pickle.HIGHEST_PROTOCOL)
        self._block.flush()          # data precedes its metadata
        if self.sync:
            os.fsync(self._block.fileno())
        self._wal.write(_FRAME.pack(len(payload),
                                    crc32c(0xFFFFFFFF, payload)))
        self._wal.write(payload)
        self._wal.flush()
        if self.sync:
            os.fsync(self._wal.fileno())
        self._wal_records += 1
        if self._wal_records >= self.checkpoint_every:
            self.checkpoint()
        return self.committed_seq

    def _apply(self, staged, op, new_blobs, deref, addref) -> None:
        kind = op[0]
        obj = op[1]

        def node() -> Onode:
            if staged.get(obj) is None:
                staged[obj] = Onode()
            return staged[obj]

        if kind == "write":
            _, _, offset, data = op
            o = node()
            if data:
                self._punch(o, offset, len(data), deref, addref)
                bid = self._write_blob(bytes(data), new_blobs)
                o.extents.append(Extent(offset, len(data), bid, 0))
                o.extents.sort(key=lambda e: e.loff)
            o.size = max(o.size, offset + len(data))
        elif kind == "zero":
            _, _, offset, length = op
            o = node()
            self._punch(o, offset, length, deref, addref)
            o.size = max(o.size, offset + length)
        elif kind == "truncate":
            _, _, size = op
            o = node()
            if size < o.size:
                self._punch(o, size, o.size - size, deref, addref)
            o.size = size
        elif kind == "remove":
            o = staged.get(obj)
            if o is not None:
                deref.extend(e.blob for e in o.extents)
            staged[obj] = None
        elif kind == "touch":
            node()
        elif kind == "clone":
            _, src, dst = op
            so = staged.get(src)
            old = staged.get(dst)
            if old is not None:
                deref.extend(e.blob for e in old.extents)
            if so is None:
                staged[dst] = Onode()
            else:
                staged[dst] = so.copy()
                addref.extend(e.blob for e in so.extents)
        elif kind == "setattr":
            node().xattrs[op[2]] = op[3]
        elif kind == "rmattr":
            node().xattrs.pop(op[2], None)
        elif kind == "omap_setkeys":
            node().omap.update(op[2])
        elif kind == "omap_rmkeys":
            o = node()
            for key in op[2]:
                o.omap.pop(key, None)
        elif kind == "omap_clear":
            o = node()
            o.omap.clear()
            o.omap_header = b""
        elif kind == "omap_setheader":
            node().omap_header = op[2]
        else:
            raise ValueError(f"unknown op {kind}")

    # -- reads ---------------------------------------------------------------

    def _node(self, obj: GObject) -> Onode:
        o = self.onodes.get(obj)
        if o is None:
            raise FileNotFoundError(obj)
        return o

    def read(self, obj: GObject, offset: int = 0,
             length: int | None = None) -> bytes:
        o = self._node(obj)
        if length is None:
            length = max(o.size - offset, 0)
        end = min(offset + length, o.size)
        if end <= offset:
            return b""
        out = bytearray(end - offset)       # gaps read as zeros
        for e in o.extents:
            e_end = e.loff + e.length
            if e_end <= offset or e.loff >= end:
                continue
            s = max(e.loff, offset)
            t_ = min(e_end, end)
            raw = self._read_blob(e.blob)
            piece = raw[e.boff + (s - e.loff):e.boff + (t_ - e.loff)]
            out[s - offset:s - offset + len(piece)] = piece
        return bytes(out)

    def stat(self, obj: GObject) -> int:
        return self._node(obj).size

    def exists(self, obj: GObject) -> bool:
        return obj in self.onodes

    def getattr(self, obj: GObject, name: str):
        return self._node(obj).xattrs[name]

    def getattrs(self, obj: GObject):
        return dict(self._node(obj).xattrs)

    def get_omap(self, obj: GObject) -> dict[str, bytes]:
        return dict(self._node(obj).omap)

    def get_omap_header(self, obj: GObject) -> bytes:
        return self._node(obj).omap_header

    def list_objects(self) -> list[GObject]:
        return sorted(self.onodes, key=lambda g: (g.oid, g.shard))

    # -- compat: the dict-shaped objects view --------------------------------

    @property
    def objects(self) -> "_OnodeObjectsView":
        return _OnodeObjectsView(self)

    # -- introspection (admin socket / tests) --------------------------------

    def usage(self) -> dict:
        """Allocator + blob stats ('bluestore allocator dump' shape)."""
        stored = sum(b.plen for b in self.blobs.values())
        raw = sum(b.raw_len for b in self.blobs.values())
        return {
            "min_alloc": self.min_alloc,
            "blobs": len(self.blobs),
            "allocated_bytes": sum(b.alloc for b in self.blobs.values()),
            "stored_bytes": stored,
            "raw_bytes": raw,
            "compressed_blobs": sum(1 for b in self.blobs.values()
                                    if b.comp),
            "free_bytes": self.alloc.free_bytes(),
            "watermark_bytes": self.alloc.watermark * self.min_alloc,
        }


class _OnodeObjectsView:
    """Read-mostly mapping compat layer: ``store.objects[g]`` returns an
    _Object-shaped proxy (materialized data, live xattr/omap dicts) for
    the backend code paths that peek directly."""

    def __init__(self, store: BlueStoreLite):
        self._s = store

    def __getitem__(self, g: GObject) -> _Object:
        onode = self._s.onodes.get(g)
        if onode is None:
            raise KeyError(g)       # dict semantics: .get() relies on it
        return _Object(bytearray(self._s.read(g)), onode.xattrs,
                       onode.omap, onode.omap_header)

    def get(self, g: GObject, default=None):
        try:
            return self[g]
        except KeyError:
            return default

    def __contains__(self, g) -> bool:
        return g in self._s.onodes

    def __iter__(self):
        return iter(self._s.onodes)

    def __len__(self) -> int:
        return len(self._s.onodes)

    def keys(self):
        return self._s.onodes.keys()
