"""File-backed object store: write-ahead log + checkpoint snapshots.

Durable implementation of the ``ObjectStore::Transaction`` contract the EC
path uses (reference: src/os/ObjectStore.h semantics; the role BlueStore's
RocksDB WAL plays, src/os/bluestore/BlueStore.cc).  Design:

- the live state is a :class:`~ceph_tpu.backend.memstore.MemStore` in RAM
  (the page-cache model);
- every transaction appends one length+crc framed record to ``wal.log``
  BEFORE the caller sees the commit, then applies in RAM;
- every ``checkpoint_every`` transactions the whole state snapshots to
  ``objects.snap`` via write-to-temp + atomic rename, and the WAL resets —
  the FileStore/BlueFS compaction analog;
- reopening loads the snapshot and replays WAL records past its sequence
  number; a torn tail record (crash mid-append) fails its crc/length check
  and is discarded — that transaction never committed.

``sync=True`` fsyncs the WAL on every commit (the durability mode);
the default leaves flushing to the OS — the same trade
``filestore_journal_sync`` style options expose in the reference.

Records are pickled ``(seq, ops)`` tuples: an internal on-disk format, the
honest Python analog of the reference's private encoding.
"""
from __future__ import annotations

import os
import pickle
import struct
from pathlib import Path

from .ecutil import crc32c
from .memstore import GObject, MemStore, Transaction, _Object

_FRAME = struct.Struct("<II")        # payload length, crc32c(payload)
_SNAP = "objects.snap"
_WAL = "wal.log"


class FileStore:
    """Durable ObjectStore over a directory; same surface as MemStore."""

    def __init__(self, path: str | os.PathLike, sync: bool = False,
                 checkpoint_every: int = 512):
        self.path = Path(path)
        self.path.mkdir(parents=True, exist_ok=True)
        self.sync = sync
        self.checkpoint_every = checkpoint_every
        self._mem = MemStore()
        self._snap_seq = 0
        self._wal_records = 0
        self._load()
        self._wal = open(self.path / _WAL, "ab")

    # -- persistence -------------------------------------------------------

    def _load(self) -> None:
        snap = self.path / _SNAP
        if snap.exists():
            with open(snap, "rb") as f:
                seq, objects = pickle.load(f)
            self._mem.objects = objects
            self._mem.committed_seq = seq
            self._snap_seq = seq
        wal = self.path / _WAL
        if not wal.exists():
            return
        with open(wal, "rb") as f:
            buf = f.read()
        off = 0
        while off + _FRAME.size <= len(buf):
            length, crc = _FRAME.unpack_from(buf, off)
            payload = buf[off + _FRAME.size:off + _FRAME.size + length]
            if len(payload) < length or crc32c(0xFFFFFFFF, payload) != crc:
                break                 # torn tail: that txn never committed
            off += _FRAME.size + length
            seq, ops = pickle.loads(payload)
            if seq != self._mem.committed_seq + 1:
                continue              # predates the snapshot
            t = Transaction()
            t.ops = ops
            self._mem.queue_transaction(t)
            self._wal_records += 1
        if off < len(buf):
            # drop the torn tail NOW: appending new records after garbage
            # would make them unreachable on the next replay
            os.truncate(wal, off)

    def _append_wal(self, payload: bytes) -> None:
        self._wal.write(_FRAME.pack(len(payload),
                                    crc32c(0xFFFFFFFF, payload)))
        self._wal.write(payload)
        self._wal.flush()
        if self.sync:
            os.fsync(self._wal.fileno())

    def checkpoint(self) -> None:
        """Snapshot the full state atomically and reset the WAL."""
        tmp = self.path / (_SNAP + ".tmp")
        with open(tmp, "wb") as f:
            pickle.dump((self._mem.committed_seq, self._mem.objects), f,
                        protocol=pickle.HIGHEST_PROTOCOL)
            f.flush()
            if self.sync:
                os.fsync(f.fileno())
        os.replace(tmp, self.path / _SNAP)
        self._snap_seq = self._mem.committed_seq
        self._wal.close()
        self._wal = open(self.path / _WAL, "wb")
        self._wal_records = 0

    def close(self, checkpoint: bool = True) -> None:
        """Checkpoint (fast reopen) and release the WAL handle.  Pass
        ``checkpoint=False`` when the directory is about to be discarded
        (backfill to a new layout) — the snapshot would be wasted work."""
        if checkpoint:
            self.checkpoint()
        self._wal.close()

    # -- ObjectStore surface ----------------------------------------------

    @property
    def objects(self):
        return self._mem.objects

    @property
    def committed_seq(self) -> int:
        return self._mem.committed_seq

    def queue_transaction(self, t: Transaction) -> int:
        # apply first (all-or-nothing staging) so only transactions that
        # succeed reach the log; then journal before acking the caller
        seq = self._mem.queue_transaction(t)
        self._append_wal(pickle.dumps((seq, t.ops),
                                      protocol=pickle.HIGHEST_PROTOCOL))
        self._wal_records += 1
        if self._wal_records >= self.checkpoint_every:
            self.checkpoint()
        return seq

    def read(self, obj: GObject, offset: int = 0,
             length: int | None = None) -> bytes:
        return self._mem.read(obj, offset, length)

    def stat(self, obj: GObject) -> int:
        return self._mem.stat(obj)

    def exists(self, obj: GObject) -> bool:
        return self._mem.exists(obj)

    def getattr(self, obj: GObject, name: str):
        return self._mem.getattr(obj, name)

    def get_omap(self, obj: GObject) -> dict[str, bytes]:
        return self._mem.get_omap(obj)

    def get_omap_header(self, obj: GObject) -> bytes:
        return self._mem.get_omap_header(obj)

    def getattrs(self, obj: GObject):
        return self._mem.getattrs(obj)

    def list_objects(self) -> list[GObject]:
        return self._mem.list_objects()
