"""ReplicatedBackend: full-copy pools behind the PGBackend abstraction.

Analog of the reference's ``ReplicatedBackend`` (reference:
src/osd/ReplicatedBackend.cc, 2392 LoC; the other concrete PGBackend next
to ECBackend, src/osd/PGBackend.h:628).  Semantics mirrored:

- the primary applies each client transaction to its own full copy and
  fans the SAME transaction to every replica (``issue_op`` /
  ``submit_transaction`` — each replica holds identical whole objects);
- writes ack only after min_size copies are durable — inherited from
  :class:`~ceph_tpu.backend.pg_backend.PGBackend`'s gate, with the
  replicated default min_size = floor(size/2)+1;
- reads are served from the primary's copy (the reference reads locally on
  the primary, PrimaryLogPG::do_op); a non-current primary pulls from a
  current replica instead;
- recovery pushes whole-object copies from any current source
  (``prep_push``/``handle_pull`` shape);
- deep scrub compares each replica's bytes against the primary's
  (be_deep_scrub comparing object digests across replicas).

A per-object ``version`` xattr (the role object_info_t::version plays,
reference: src/osd/osd_types.h object_info_t) travels with every write and
push so scrub can tell a stale copy from a clean one even when sizes match.
"""
from __future__ import annotations

from .bluestore import ChecksumError
from .memstore import GObject, Transaction
from .messages import ECSubRead, ECSubReadReply, MessageBus
from .pg_backend import Op, OSDShard, PGBackend, RecoveryOp, shard_store
from ..osd.pg_log import OP_DELETE, OP_MODIFY

VERSION_KEY = "@version"      # object_info_t::version analog; the "@"
                              # prefix keeps it out of the user-xattr
                              # namespace ("_"+name) so a user xattr
                              # named "version" cannot collide with it


class ReplicatedBackend(PGBackend):
    """Primary-side replicated backend over full-copy shard OSDs."""

    def __init__(self, size: int, bus: MessageBus, acting: list[int],
                 whoami: int = 0, cct=None, name: str = "",
                 min_size: int = 0, store=None):
        assert len(acting) == size, f"acting set must have {size} shards"
        self.size = size
        super().__init__(bus, acting, whoami=whoami, cct=cct, name=name,
                         min_size=min_size or size // 2 + 1,
                         min_size_floor=1, store=store,
                         perf_prefix="replicated_backend")
        # remote whole-object reads in flight (non-current primary)
        self._remote_read_tids: dict[int, dict] = {}

    # -- metadata ------------------------------------------------------------

    def object_size(self, oid: str) -> int:
        try:
            return self.local_shard.store.stat(GObject(oid, self.whoami))
        except FileNotFoundError:
            return 0

    def _object_version(self, oid: str) -> int:
        try:
            return self.local_shard.store.getattr(
                GObject(oid, self.whoami), VERSION_KEY)
        except (FileNotFoundError, KeyError):
            return 0

    # -- write pipeline hooks ------------------------------------------------

    def _generate_transactions(self, op: Op):
        """Each acting shard gets the same whole-object mutation — the
        replica transactions ReplicatedBackend::issue_op ships."""
        shard_txns = {shard: Transaction() for shard in self.acting}
        log_entries = []
        for oid, objop in op.t.ops.items():
            is_delete = (objop.delete_first and not objop.buffer_updates
                         and objop.truncate is None)
            entry = self.pg_log.append(
                oid, OP_DELETE if is_delete else OP_MODIFY)
            log_entries.append(entry)
            for clone_oid in objop.clone_to:
                # clones replay independently on log repair (see the EC
                # backend's clone_to note)
                log_entries.append(self.pg_log.append(clone_oid,
                                                      OP_MODIFY))
            if objop.clone_to and oid in self.inconsistent_objects:
                # damaged state COWs into the clone (see EC note)
                self.inconsistent_objects.update(objop.clone_to)
            if objop.rollback_from is not None:
                # head state replaced by the source's — flag included
                if objop.rollback_from in self.inconsistent_objects:
                    self.inconsistent_objects.add(oid)
                else:
                    self.inconsistent_objects.discard(oid)
            elif is_delete or (objop.truncate is not None and any(
                    off == 0 and len(d) >= objop.truncate[0]
                    for off, d in objop.buffer_updates)):
                # wholesale replacement exonerates (mirrors the EC rule;
                # also covers snaptrim's clone deletes)
                self.inconsistent_objects.discard(oid)
            for shard in self.acting:
                obj = GObject(oid, shard)
                t = shard_txns[shard]
                for clone_oid in objop.clone_to:
                    t.clone(obj, GObject(clone_oid, shard))   # COW first
                if objop.rollback_from is not None:
                    t.clone(GObject(objop.rollback_from, shard), obj)
                if objop.delete_first:
                    t.remove(obj)
                if objop.truncate is not None:
                    t.truncate(obj, objop.truncate[0])
                for w_off, data in objop.buffer_updates:
                    t.write(obj, w_off, data)
                for name, value in objop.attr_updates.items():
                    if value is None:
                        t.rmattr(obj, name)
                    else:
                        t.setattr(obj, name, value)
                for oop in objop.omap_ops:
                    if oop[0] == "set":
                        t.omap_setkeys(obj, oop[1])
                    elif oop[0] == "rm":
                        t.omap_rmkeys(obj, oop[1])
                    elif oop[0] == "clear":
                        t.omap_clear(obj)
                    elif oop[0] == "header":
                        t.omap_setheader(obj, oop[1])
                    else:
                        raise ValueError(f"unknown omap op {oop[0]!r}")
                if not is_delete:
                    t.setattr(obj, VERSION_KEY, entry.version)
            self.perf.inc("stripe_bytes_encoded", sum(
                len(d) for _, d in objop.buffer_updates))
        return shard_txns, log_entries

    # -- read path -----------------------------------------------------------

    def objects_read_and_reconstruct(self, reads, on_complete,
                                     fast_read: bool = False) -> int:
        """Read extents per object.  The primary serves from its own full
        copy when current (the reference's primary-local read path); a
        stale/down primary pulls from a current replica.  Same signature
        as the EC backend so callers are pool-type agnostic."""
        self.next_tid += 1
        tid = self.next_tid
        if self.whoami in self.current_shards():
            result, errors = self._read_local(reads)
            on_complete(result, errors)
            return tid
        sources = sorted(self.current_shards())
        if not sources:
            on_complete({}, {oid: -5 for oid in reads})   # EIO: inactive
            return tid
        src = sources[0]
        self._remote_read_tids[tid] = {"reads": dict(reads),
                                       "on_complete": on_complete,
                                       "source": src}
        self.bus.send(src, ECSubRead(
            self.whoami, tid,
            {oid: [(0, None)] for oid in reads}))
        return tid

    def _read_local(self, reads):
        result: dict[str, list[tuple[int, int, bytes]]] = {}
        errors: dict[str, int] = {}
        store = self.local_shard.store
        for oid, extents in reads.items():
            obj = GObject(oid, self.whoami)
            try:
                out = []
                for off, length in extents:
                    out.append((off, length, store.read(obj, off, length)))
                result[oid] = out
            except FileNotFoundError:
                errors[oid] = -2      # ENOENT
            except ChecksumError:
                errors[oid] = -5      # EIO: rotten at rest (bluestore)
        if result:
            self.perf.inc("reads")
        if errors:
            self.perf.inc("read_errors", len(errors))
        self.perf.inc("read_bytes", sum(
            len(seg) for segs in result.values() for _, _, seg in segs))
        return result, errors

    def _handle_other_read_reply(self, reply: ECSubReadReply) -> None:
        ctx = self._remote_read_tids.pop(reply.tid, None)
        if ctx is None:
            return
        result: dict[str, list[tuple[int, int, bytes]]] = {}
        errors: dict[str, int] = dict(reply.errors)
        for oid, extents in ctx["reads"].items():
            if oid in errors:
                continue
            bufs = reply.buffers_read.get(oid)
            if bufs is None:
                errors[oid] = -5
                continue
            whole = b"".join(b for _, b in bufs)
            result[oid] = [(off, length,
                            whole[off:off + length if length is not None
                                  else None])
                           for off, length in extents]
        if result:
            self.perf.inc("reads")
        if errors:
            self.perf.inc("read_errors", len(errors))
        self.perf.inc("read_bytes", sum(
            len(seg) for segs in result.values() for _, _, seg in segs))
        ctx["on_complete"](result, errors)

    def _on_shard_down_reads(self, shard: int, chunk: int) -> None:
        # remote reads addressed to a dying source: retry elsewhere
        for tid, ctx in list(self._remote_read_tids.items()):
            if ctx["source"] == shard:
                del self._remote_read_tids[tid]
                self.objects_read_and_reconstruct(ctx["reads"],
                                                  ctx["on_complete"])

    # -- recovery hooks ------------------------------------------------------

    def is_recoverable(self, oid: str, missing: set[int]) -> bool:
        """Recoverable iff any current shard outside the missing set can
        supply a full copy (MissingLoc::readable_with_acting shape)."""
        return any(c not in missing
                   for c, s in enumerate(self.acting)
                   if s in self.current_shards())

    def _recovery_issue_reads(self, rop: RecoveryOp) -> None:
        sources = [c for c, s in enumerate(self.acting)
                   if s in self.current_shards()
                   and c not in rop.missing_shards]
        if not sources:
            raise IOError("no current source for replicated recovery")
        src_shard = self.acting[sources[0]]
        rop._pending = {src_shard}
        # "*": the push replaces the whole object, so EVERY xattr must
        # travel (a {VERSION_KEY}-only read once pushed attr-less objects
        # — invisible while only never-read replicas were repaired, data
        # loss once the shared-bus topology started repairing primaries)
        self.bus.send(src_shard, ECSubRead(
            self.whoami, rop.read_tid,
            {rop.oid: [(0, None)]}, attrs_to_read={"*"},
            include_omap=True))

    def _recovery_push_payloads(self, rop: RecoveryOp):
        (data,) = rop._read_results.values()
        attrs = next(iter(rop._read_attrs.values()), {}) or {}
        omap, header = next(iter(rop._read_omap.values()), ({}, b""))
        return {chunk: (data, dict(attrs), dict(omap), header)
                for chunk in rop.missing_shards}

    # -- deep scrub ----------------------------------------------------------

    def be_deep_scrub(self, oid: str) -> dict[int, bool]:
        """MAJORITY-vote scrub: replicas group by (bytes, version); the
        largest group is the authority and the minority is flagged.
        Trusting the primary's copy blindly would MISLOCATE rot on the
        primary itself — flagging every healthy replica and letting a
        repair push the rotten copy over them (the reference's scrub
        likewise compares maps across replicas and picks an
        authoritative object, PG::scrub_compare_maps).  A tie (e.g.
        size 2) flags everyone: detected, honestly unlocatable."""
        copies: dict[int, tuple] = {}
        out: dict[int, bool] = {}
        for chunk, shard in enumerate(self.acting):
            if shard in self.bus.down:
                continue
            store = shard_store(self.bus, shard)
            obj = GObject(oid, shard)
            try:
                # identity covers omap too: replicated pools serve omap
                # reads, so a diverged omap is user-visible corruption
                copies[chunk] = (bytes(store.read(obj)),
                                 store.getattr(obj, VERSION_KEY),
                                 tuple(sorted(store.get_omap(obj).items())),
                                 store.get_omap_header(obj))
            except (FileNotFoundError, KeyError, ChecksumError):
                # ChecksumError: bluestore-style at-rest crc failure —
                # the store itself located the rot, no vote needed
                copies[chunk] = None
        groups: dict = {}
        for chunk, ident in copies.items():
            groups.setdefault(ident, []).append(chunk)
        best = max(groups.values(), key=len)
        if len(groups) > 1 and \
                sum(1 for g in groups.values() if len(g) == len(best)) > 1:
            return {c: False for c in copies}      # tie: flag everything
        authority = next(ident for ident, cs in groups.items()
                         if cs is best)
        for chunk, ident in copies.items():
            out[chunk] = ident == authority and ident is not None
        return out


def make_replicated_cluster(size: int = 3, cct=None):
    """Primary + replica OSDs on one bus; returns (backend, bus)."""
    bus = MessageBus()
    backend = ReplicatedBackend(size, bus, acting=list(range(size)),
                                whoami=0, cct=cct)
    for shard in range(1, size):
        OSDShard(shard, bus)
    return backend, bus
