"""PGBackend: the shared core both pool types build on.

Analog of the reference's ``PGBackend`` abstraction (reference:
src/osd/PGBackend.h:628 — the interface ``ReplicatedBackend`` and
``ECBackend`` both implement), holding everything that is NOT specific to
how bytes are laid out across shards:

- the shard-side OSD (:class:`OSDShard`): transaction apply with rollback
  capture, PG log + rollback-info persistence in the pgmeta omap, reads,
  recovery pushes;
- the three-stage ordered write pipeline with the min_size availability
  gate and two-phase rollback/rollforward (ecbackend.rst:149-206);
- the recovery state machine skeleton (IDLE->READING->WRITING->COMPLETE,
  ECBackend.h:249-293) with subclass hooks for issuing reads and building
  push payloads;
- stale-shard tracking + shard repair (log catch-up / backfill, the
  PGLog::merge_log and backfill roles) and boot peering (authoritative-log
  election + witness-counted rollback, PeeringState);
- observability wiring (perf counters, op tracker, admin socket).

Subclass hooks (see :class:`~ceph_tpu.backend.ec_backend.ECBackend` and
:class:`~ceph_tpu.backend.replicated.ReplicatedBackend`):

=====================  ====================================================
``_admit_op(op)``       plan the op at pipeline admission; issue any reads
``_op_blocked(op)``     ordering block against in-flight overlapping writes
``_generate_transactions(op)``  per-shard transactions + pg_log entries
``_recovery_issue_reads(rop)``  start the READING phase (may raise IOError)
``_recovery_push_payloads(rop)``  chunk -> (bytes, attrs, omap|None,
                                  omap_header) to push (omap None =
                                  target keeps its own)
``_handle_other_read_reply(r)``  non-recovery ECSubReadReply routing
``object_size(oid)``    logical object size
``be_deep_scrub(oid)``  per-shard consistency check
=====================  ====================================================
"""
from __future__ import annotations

import pickle
from collections import deque
from dataclasses import dataclass, field
from enum import Enum

from .bluestore import ChecksumError
from .memstore import GObject, MemStore, Transaction
from .messages import (ECPartialSum, ECPartialSumAbort, ECPartialSumApplied,
                       ECPartialSumApply, ECRegenHelper, ECRegenRead,
                       ECSubRead, ECSubReadReply, ECSubWrite, ECSubWriteReply,
                       MessageBus, PGActivate, PGActivateAck, PGLogInfo,
                       PGLogQuery, PGLogUpdate,
                       PGScan, PGScanReply, PushOp, PushReply,
                       RollForward, Rollback)
from .transaction import PGTransaction
from ..common.tracer import trace_span
from ..osd.pg_log import OP_DELETE, OP_MODIFY, PGLog, dedup_latest


PG_META = "_pgmeta_"          # the reference's pgmeta object: PG log +
                              # rollback info live in its omap so they
                              # commit atomically with the data they cover


def _log_key(version: int) -> str:
    return f"log.{version:016d}"


def _rb_key(version: int) -> str:
    return f"rb.{version:016d}"


def shard_store(bus: MessageBus, shard: int):
    """The store behind a bus handler — an OSDShard's own, or the
    primary backend's local shard (ONE copy of this resolution)."""
    handler = bus.handlers[shard]
    return handler.store if isinstance(handler, OSDShard) \
        else handler.local_shard.store


class OSDShard:
    """One shard OSD: an ObjectStore plus the server side of the sub-ops
    (handle_sub_write ECBackend.cc:910-983, handle_sub_read :985-1031,
    recovery push :511-563) and a per-shard PG log that advances with
    every applied sub-write (the reference logs entries in
    handle_sub_write before queueing the transaction, ECBackend.cc:956).

    The PG log, its (head, tail) and per-write rollback info persist in
    the ``_pgmeta_`` object's omap INSIDE the same transaction as the data
    they describe — the reference stores the PG log in the pgmeta omap the
    same way — so a durable store (FileStore) survives restart with log
    and rollback state intact and boots via ``_load_pg_state``."""

    def __init__(self, shard: int, bus: MessageBus, store=None):
        self.shard = shard
        self.store = store if store is not None else MemStore()
        self.bus = bus
        self.pg_log = PGLog()
        self.peered_epoch = 0     # last PGActivate epoch (ReplicaActive)
        self.peered_head = 0      # authority log head at that activation
        self.activation_regressions = 0   # rollbacks below peered_head
        # at_version -> inverse transaction restoring the pre-write state:
        # the rollback info the reference's log entries carry until the
        # write is rolled forward (ecbackend.rst:149-174)
        self.pending_rollbacks: dict[int, Transaction] = {}
        self._load_pg_state()
        bus.register(shard, self)

    def _meta(self) -> GObject:
        return GObject(PG_META, self.shard)

    def _push_is_stale(self, msg: PushOp, obj: GObject) -> bool:
        """Is this push older than the object state already applied here?
        Compared on the per-object version attrs both pool types carry
        (EC: hinfo_key.version; replicated: @version) — each is monotone
        per object, so incoming < stored means the push predates a write
        this shard has already applied."""
        if not self.store.exists(obj):
            return False
        for key, field_ in (("hinfo_key", "version"), ("@version", None)):
            incoming = msg.attrs.get(key)
            try:
                stored = self.store.getattr(obj, key)
            except (KeyError, FileNotFoundError):
                continue
            if incoming is None:
                continue
            if field_ is not None:
                incoming = incoming.get(field_, 0)
                stored = stored.get(field_, 0) if isinstance(stored, dict) \
                    else 0
            if incoming < stored:
                return True
        return False

    def _load_pg_state(self) -> None:
        """Boot: rebuild the in-RAM log + rollback map from the pgmeta
        omap (the OSD::init superblock/PG-load path, OSD.cc:2719)."""
        if not self.store.exists(self._meta()):
            return
        omap = self.store.get_omap(self._meta())
        head, tail = pickle.loads(omap["vi"]) if "vi" in omap else (0, 0)
        self.pg_log.tail = tail
        self.pg_log.head = tail
        for key in sorted(k for k in omap if k.startswith("log.")):
            e = pickle.loads(omap[key])
            if e.version > self.pg_log.head:
                self.pg_log.record(e)
        self.pg_log.head = max(self.pg_log.head, head)
        for key in (k for k in omap if k.startswith("rb.")):
            inv = Transaction()
            inv.ops = pickle.loads(omap[key])
            self.pending_rollbacks[int(key[3:])] = inv

    def _persist_vi(self, t: Transaction) -> None:
        t.omap_setkeys(self._meta(), {"vi": pickle.dumps(
            (self.pg_log.head, self.pg_log.tail))})

    def _capture_rollback(self, t: Transaction) -> Transaction:
        """Inverse transaction: snapshot every touched object's prior state
        (chunk-sized objects make whole-object capture cheap).  The pgmeta
        object is never captured — its log/rb keys are unwound explicitly
        by _rollback, and snapshotting it would embed every prior rb blob
        in each new one."""
        touched = {op[1] for op in t.ops}
        touched |= {op[2] for op in t.ops if op[0] == "clone"}
        touched = {obj for obj in touched if obj.oid != PG_META}
        inv = Transaction()
        for obj in sorted(touched, key=lambda g: (g.oid, g.shard)):
            try:
                o = self.store.objects.get(obj)
            except ChecksumError:
                # pre-state unreadable (rotten at rest): the best honest
                # inverse is removal — a rollback leaves the object
                # missing, which scrub/recovery detect and rebuild
                o = None
            inv.remove(obj)
            if o is not None:
                inv.write(obj, 0, bytes(o.data))
                for name, value in o.xattrs.items():
                    inv.setattr(obj, name, value)
                if o.omap:
                    inv.omap_setkeys(obj, dict(o.omap))
        return inv

    def _roll_forward(self, to: int, txn: Transaction | None = None) -> None:
        """Drop rollback data for entries <= ``to``; the key removals ride
        ``txn`` when given (piggybacked roll-forward) or commit on their
        own (the standalone kick)."""
        dropped = [v for v in self.pending_rollbacks if v <= to]
        if not dropped:
            return
        for v in dropped:
            del self.pending_rollbacks[v]
        t = txn if txn is not None else Transaction()
        t.omap_rmkeys(self._meta(), [_rb_key(v) for v in dropped])
        if txn is None:
            self.store.queue_transaction(t)

    def _rollback(self, to: int) -> None:
        """Undo logged-but-not-rolled-forward entries past ``to``, newest
        first, and rewind the log — one atomic transaction."""
        t = Transaction()
        rb = sorted((v for v in self.pending_rollbacks if v > to),
                    reverse=True)
        for v in rb:
            t.append(self.pending_rollbacks.pop(v))
        dropped = self.pg_log.rewind(to)
        if not rb and not dropped:
            return
        t.omap_rmkeys(self._meta(),
                      [_rb_key(v) for v in rb] +
                      [_log_key(e.version) for e in dropped])
        self._persist_vi(t)
        self.store.queue_transaction(t)

    def handle_message(self, msg) -> None:
        if isinstance(msg, ECSubWrite):
            if msg.log_entries and msg.at_version <= self.pg_log.head:
                # duplicate delivery of an already-applied write: re-ack
                # without re-applying (reqid dedup in the reference)
                self.bus.send(msg.from_shard,
                              ECSubWriteReply(self.shard, msg.tid,
                                              gen=msg.gen))
                return
            t = msg.t
            if msg.log_entries:
                # capture rollback info FIRST — before roll-forward/meta
                # ops are appended to t — so the inverse covers only the
                # data objects; log keys are unwound explicitly by
                # _rollback
                inv = self._capture_rollback(t)
                self.pending_rollbacks[msg.at_version] = inv
                kvs = {_rb_key(msg.at_version):
                       pickle.dumps(inv.ops,
                                    protocol=pickle.HIGHEST_PROTOCOL)}
                for e in msg.log_entries:
                    if e.version > self.pg_log.head:
                        self.pg_log.record(e)
                    kvs[_log_key(e.version)] = pickle.dumps(
                        e, protocol=pickle.HIGHEST_PROTOCOL)
                t.omap_setkeys(self._meta(), kvs)
            if msg.roll_forward_to:
                self._roll_forward(msg.roll_forward_to, txn=t)
            if msg.trim_to:
                old_tail = self.pg_log.tail
                if self.pg_log.trim(msg.trim_to):
                    t.omap_rmkeys(self._meta(), [
                        _log_key(v)
                        for v in range(old_tail + 1, msg.trim_to + 1)])
                self._roll_forward(msg.trim_to, txn=t)
            if msg.log_entries or msg.trim_to:
                self._persist_vi(t)
            self.store.queue_transaction(t)
            self.bus.send(msg.from_shard,
                          ECSubWriteReply(self.shard, msg.tid, gen=msg.gen))
        elif isinstance(msg, RollForward):
            self._roll_forward(msg.to)
        elif isinstance(msg, Rollback):
            if msg.to < self.peered_head:
                # the primary is rewinding below the head it ACTIVATED us
                # at — acked state regressing.  Legitimate only in crash
                # recovery where < min_size witnesses survive; surfaced
                # as a counter so scrub/ops can tell the two apart.
                self.activation_regressions += 1
            self._rollback(msg.to)
        elif isinstance(msg, PGLogQuery):
            self.bus.send(msg.from_shard, PGLogInfo(
                self.shard, self.pg_log.head, self.pg_log.tail,
                entries=self.pg_log.entries_after(msg.since) or []))
        elif isinstance(msg, PGScan):
            self.bus.send(msg.from_shard, PGScanReply(
                self.shard, oids=sorted({g.oid for g in self.store.objects
                                         if g.shard == self.shard
                                         and g.oid != PG_META})))
        elif isinstance(msg, PGActivate):
            # Stray -> ReplicaActive: adopt the primary's epoch and the
            # authority head it activated at (a later repair rewinding
            # past this head would mean the primary regressed), then ack
            # (reference: PeeringState::ReplicaActive on MOSDPGLog)
            self.peered_epoch = msg.epoch
            self.peered_head = msg.head
            self.bus.send(msg.from_shard,
                          PGActivateAck(self.shard, msg.epoch))
        elif isinstance(msg, PGLogUpdate):
            # divergent entries past the rewind point were superseded by the
            # repair's pushes: drop their rollback data without applying it
            dropped_rb = [v for v in self.pending_rollbacks
                          if v > msg.rewind_to]
            for v in dropped_rb:
                del self.pending_rollbacks[v]
            pre = {_log_key(e.version) for e in self.pg_log.entries}
            self.pg_log.merge_authoritative(
                msg.entries, msg.last_update, msg.rewind_to, msg.trim_to)
            post = {e.version: e for e in self.pg_log.entries}
            t = Transaction()
            gone = sorted(pre - {_log_key(v) for v in post}) + \
                [_rb_key(v) for v in dropped_rb]
            if gone:
                t.omap_rmkeys(self._meta(), gone)
            # only the shipped segment can contain new/changed entries;
            # surviving pre-merge keys are already on disk
            new_kvs = {_log_key(e.version): pickle.dumps(
                           e, protocol=pickle.HIGHEST_PROTOCOL)
                       for e in msg.entries if post.get(e.version) == e}
            if new_kvs:
                t.omap_setkeys(self._meta(), new_kvs)
            self._persist_vi(t)
            self.store.queue_transaction(t)
        elif isinstance(msg, ECSubRead):
            reply = ECSubReadReply(self.shard, msg.tid)
            for oid, extents in msg.to_read.items():
                obj = GObject(oid, self.shard)
                try:
                    bufs = []
                    for ext in extents:
                        off, length = ext[0], ext[1]
                        subchunks = ext[2] if len(ext) > 2 else None
                        data = self.store.read(obj, off, length)
                        if length is not None and len(data) < length:
                            data = data + b"\0" * (length - len(data))
                        if subchunks is not None:
                            data = _slice_subchunks(data, subchunks,
                                                    msg.sub_chunk_count)
                        bufs.append((off, data))
                    reply.buffers_read[oid] = bufs
                    if msg.attrs_to_read:
                        xat = self.store.objects[obj].xattrs
                        if "*" in msg.attrs_to_read:
                            # recovery wants the FULL replicated attr set
                            # (object_info, snapset, user xattrs): pushes
                            # REPLACE the target object, so partial attr
                            # reads would wipe whatever isn't carried
                            reply.attrs_read[oid] = dict(xat)
                        else:
                            reply.attrs_read[oid] = {
                                a: self.store.getattr(obj, a)
                                for a in msg.attrs_to_read if a in xat}
                    if msg.include_omap:
                        reply.omap_read[oid] = (
                            self.store.get_omap(obj),
                            self.store.get_omap_header(obj))
                except FileNotFoundError:
                    reply.errors[oid] = -2  # ENOENT
                except ChecksumError:
                    # at-rest checksum failure (BlueStore): the shard's
                    # copy is rotten — EIO, like the reference's
                    # bluestore read path
                    reply.errors[oid] = -5
            self.bus.send(msg.from_shard, reply)
        elif isinstance(msg, PushOp):
            obj = GObject(msg.oid, self.shard)
            if self._push_is_stale(msg, obj):
                # per-object recovery serialization (the reference holds
                # recovery locks): a push reconstructed from a PRE-write
                # snapshot can already be in flight when a newer client
                # write applies on this shard — applying it would regress
                # the shard to the old state while the PG log stays at
                # the new version (observed: seed-244 soak served mixed-
                # version garbage).  Drop it; ack so the rop completes —
                # the shard already holds newer-or-equal state.
                self.bus.send(msg.from_shard, PushReply(self.shard,
                                                        msg.oid))
                return
            self._apply_push(obj, msg.data, msg.attrs, msg.omap,
                             msg.omap_header)
            self.bus.send(msg.from_shard, PushReply(self.shard, msg.oid))
        elif isinstance(msg, ECPartialSum):
            self._partial_sum_hop(msg)
        elif isinstance(msg, ECPartialSumApply):
            # a chain's final hop pushing a finished chunk: same stale
            # rule as PushOp (ack without applying so the chain
            # completes — this shard already holds newer state)
            obj = GObject(msg.oid, self.shard)
            if not self._push_is_stale(msg, obj):
                self._apply_push(obj, msg.data, msg.attrs, None, b"")
            self.bus.send(msg.coordinator,
                          ECPartialSumApplied(self.shard, msg.tid, msg.oid))
        elif isinstance(msg, ECRegenRead):
            if msg.combine:
                self._regen_prime(msg)
            else:
                self._regen_helper_leg(msg)
        elif isinstance(msg, ECRegenHelper):
            self._regen_ingest(msg)
        else:
            raise TypeError(f"shard {self.shard}: unexpected {msg!r}")

    def _apply_push(self, obj: GObject, data: bytes, attrs: dict,
                    omap, omap_header: bytes) -> None:
        """Replace this shard's copy with pushed recovery state (shared by
        PushOp and the chain's ECPartialSumApply)."""
        t = Transaction()
        # the remove wipes everything, so omap=None ("leave alone")
        # must re-apply the PRE-push omap to honour its contract
        if omap is not None:
            keep_omap, keep_header = dict(omap), omap_header
        elif self.store.exists(obj):
            keep_omap = self.store.get_omap(obj)
            keep_header = self.store.get_omap_header(obj)
        else:
            keep_omap, keep_header = {}, b""
        t.remove(obj).write(obj, 0, data)
        for name, value in attrs.items():
            t.setattr(obj, name, value)
        if keep_omap or keep_header:
            t.omap_setkeys(obj, keep_omap)
            t.omap_setheader(obj, keep_header)
        self.store.queue_transaction(t)

    def _partial_sum_hop(self, msg: ECPartialSum) -> None:
        """One leg of a chained streaming repair (recovery/chain.py):
        GF-scale the local chunk of every plan object by this hop's
        decode coefficients, XOR into the running accumulator, forward
        to the next hop — the final hop pushes finished chunks straight
        to the repair targets.  ANY validation failure aborts the WHOLE
        chain back to the coordinator, which re-drives unfinished
        objects through the centralized verified path; a hop never
        guesses around bad state."""
        from . import ecutil
        from .ecutil import HINFO_KEY, crc32c

        def abort(reason: str) -> None:
            self.bus.send(msg.coordinator,
                          ECPartialSumAbort(self.shard, msg.tid, reason))

        if not msg.hops or msg.hops[0][0] != self.shard:
            abort(f"misrouted to shard {self.shard}")
            return
        _, chunk, coeffs = msg.hops[0]
        bufs: list[bytes] = []
        for oid, length, version in zip(msg.oids, msg.lengths,
                                        msg.versions):
            obj = GObject(oid, self.shard)
            try:
                data = self.store.read(obj, 0, None)
                stored = self.store.getattr(obj, HINFO_KEY)
            except (FileNotFoundError, KeyError):
                abort(f"{oid}: no local copy")
                return
            except ChecksumError:
                # at-rest rot: centralized recovery re-verifies sources
                # and routes around the rotten shard
                abort(f"{oid}: rotten chunk")
                return
            if stored.get("version", 0) != version:
                # a write landed here after the plan was cut — the other
                # hops' contributions may predate it, so the sum would
                # mix versions; the coordinator re-drives coherently
                abort(f"{oid}: version skew")
                return
            if len(data) > length:
                abort(f"{oid}: longer than plan")
                return
            if len(data) < length:
                data = data + b"\0" * (length - len(data))
            hashes = (msg.attrs.get(oid, {}).get(HINFO_KEY) or {}).get(
                "cumulative_shard_hashes") or []
            if hashes and crc32c(0xFFFFFFFF, data) != hashes[chunk]:
                abort(f"{oid}: chunk hash mismatch")
                return
            bufs.append(data)
        stream = b"".join(bufs)
        with trace_span("recovery.chain_hop", owner="recovery",
                        objects=len(msg.oids), nbytes=len(stream)):
            acc = ecutil.partial_sum_accumulate(
                coeffs, stream, msg.acc,
                pipeline=getattr(self, "recovery_pipeline", None),
                use_device=msg.use_device)
        if len(msg.hops) > 1:
            # forward a FRESH message (the bus's dup-delivery injection
            # may still hold a reference to this one); the trace ctx
            # rides along so every leg keeps recovery attribution
            self.bus.send(msg.hops[1][0], ECPartialSum(
                from_shard=self.shard, tid=msg.tid,
                coordinator=msg.coordinator, oids=msg.oids,
                lengths=msg.lengths, versions=msg.versions,
                rows=msg.rows, targets=msg.targets, hops=msg.hops[1:],
                attrs=msg.attrs, acc=acc, use_device=msg.use_device,
                trace=msg.trace))
            return
        # final hop: slice each accumulator row per object and push the
        # finished chunks to their targets; the coordinator completes
        # each object on the targets' ECPartialSumApplied acks
        for row, target in enumerate(msg.targets):
            off = 0
            for oid, length in zip(msg.oids, msg.lengths):
                self.bus.send(target, ECPartialSumApply(
                    self.shard, msg.tid, msg.coordinator, oid,
                    acc[row][off:off + length],
                    attrs=dict(msg.attrs.get(oid, {}))))
                off += length

    # -- regenerating repair legs (recovery/regen.py) ----------------------
    #
    # Helper shards project their stored chunk down to one beta-stream
    # and ship it to the newcomer; the newcomer combines d streams into
    # the lost chunk.  Validation mirrors _partial_sum_hop: any mismatch
    # aborts the tid back to the coordinator (centralized fallback) —
    # a leg never guesses around bad state.

    # bounded stash for beta-streams arriving before this shard's own
    # ECRegenRead prime (cross-sender delivery order is not guaranteed)
    REGEN_ORPHAN_CAP = 32
    # newcomer-side in-flight repair cap: aborted/fallen-back tids are
    # evicted oldest-first rather than leaking
    REGEN_PENDING_CAP = 64

    def _regen_abort(self, msg, reason: str) -> None:
        self.bus.send(msg.coordinator,
                      ECPartialSumAbort(self.shard, msg.tid, reason))
        pend = getattr(self, "_regen_pending", None)
        if pend is not None:
            pend.pop(msg.tid, None)
        orph = getattr(self, "_regen_orphans", None)
        if orph is not None:
            orph.pop(msg.tid, None)

    def _regen_read_local(self, msg, oid: str, length: int,
                          version: int) -> bytes | None:
        """Read + validate one plan object's local stored chunk (the
        _partial_sum_hop ladder); None means the tid was aborted."""
        from .ecutil import HINFO_KEY, crc32c
        obj = GObject(oid, self.shard)
        try:
            data = self.store.read(obj, 0, None)
            stored = self.store.getattr(obj, HINFO_KEY)
        except (FileNotFoundError, KeyError):
            self._regen_abort(msg, f"{oid}: no local copy")
            return None
        except ChecksumError:
            self._regen_abort(msg, f"{oid}: rotten chunk")
            return None
        if stored.get("version", 0) != version:
            self._regen_abort(msg, f"{oid}: version skew")
            return None
        if len(data) > length:
            self._regen_abort(msg, f"{oid}: longer than plan")
            return None
        if len(data) < length:
            data = data + b"\0" * (length - len(data))
        hashes = (msg.attrs.get(oid, {}).get(HINFO_KEY) or {}).get(
            "cumulative_shard_hashes") or []
        if hashes and crc32c(0xFFFFFFFF, data) != hashes[msg.chunk]:
            self._regen_abort(msg, f"{oid}: chunk hash mismatch")
            return None
        return data

    def _regen_helper_leg(self, msg: ECRegenRead) -> None:
        """Helper leg: project every plan object's stored chunk by the
        1 x alpha coefficient row and ship the beta-streams to the
        newcomer in ONE ECRegenHelper."""
        from . import ecutil
        if len(msg.proj) != msg.sub_count:
            self._regen_abort(msg, "sub-chunk mismatch")
            return
        streams: dict[str, bytes] = {}
        total = 0
        for oid, length, version in zip(msg.oids, msg.lengths,
                                        msg.versions):
            if length % max(msg.sub_count, 1):
                self._regen_abort(msg, f"{oid}: sub-chunk mismatch")
                return
            data = self._regen_read_local(msg, oid, length, version)
            if data is None:
                return
            total += len(data)
            with trace_span("recovery.regen_hop", owner="recovery",
                            nbytes=len(data)):
                streams[oid] = ecutil.regen_project(
                    msg.proj, data, msg.sub_count,
                    pipeline=getattr(self, "recovery_pipeline", None),
                    use_device=msg.use_device)
        self.bus.send(msg.target, ECRegenHelper(
            from_shard=self.shard, tid=msg.tid,
            coordinator=msg.coordinator, chunk=msg.chunk,
            streams=streams, trace=msg.trace))

    def _regen_prime(self, msg: ECRegenRead) -> None:
        """Newcomer leg: remember the plan (combine matrix, helper
        stream order, per-oid lengths/attrs) and drain any beta-streams
        that arrived before it."""
        pend = getattr(self, "_regen_pending", None)
        if pend is None:
            pend = self._regen_pending = {}
        if msg.sub_count < 1 or len(msg.combine) != \
                msg.sub_count * len(msg.helpers):
            self._regen_abort(msg, "sub-chunk mismatch")
            return
        while len(pend) >= self.REGEN_PENDING_CAP:
            pend.pop(next(iter(pend)))
        pend[msg.tid] = {"msg": msg, "streams": {}}
        orphans = getattr(self, "_regen_orphans", None)
        for early in (orphans.pop(msg.tid, []) if orphans else []):
            self._regen_ingest(early)

    def _regen_ingest(self, msg: ECRegenHelper) -> None:
        """One helper's beta-streams landing on the newcomer; combine +
        verify + apply once all d helpers reported."""
        pend = getattr(self, "_regen_pending", None)
        rec = pend.get(msg.tid) if pend else None
        if rec is None:
            orphans = getattr(self, "_regen_orphans", None)
            if orphans is None:
                orphans = self._regen_orphans = {}
            stash = orphans.setdefault(msg.tid, [])
            stash.append(msg)
            while sum(len(v) for v in orphans.values()) > \
                    self.REGEN_ORPHAN_CAP:
                orphans.pop(next(iter(orphans)))
            return
        plan: ECRegenRead = rec["msg"]
        if msg.chunk not in plan.helpers:
            self._regen_abort(plan, f"stream from non-helper {msg.chunk}")
            return
        rec["streams"][msg.chunk] = msg.streams
        if len(rec["streams"]) < len(plan.helpers):
            return
        self._regen_complete(plan, rec["streams"])

    def _regen_complete(self, plan: ECRegenRead,
                        streams: dict[int, dict]) -> None:
        from types import SimpleNamespace

        from . import ecutil
        from .ecutil import HINFO_KEY, crc32c
        pend = getattr(self, "_regen_pending", {})
        pend.pop(plan.tid, None)
        beta_per = {oid: length // plan.sub_count
                    for oid, length in zip(plan.oids, plan.lengths)}
        for oid, length in zip(plan.oids, plan.lengths):
            rows = []
            for h in plan.helpers:          # combine-matrix stream order
                s = streams[h].get(oid)
                if s is None or len(s) != beta_per[oid]:
                    self._regen_abort(plan, f"{oid}: sub-chunk mismatch")
                    return
                rows.append(s)
            with trace_span("recovery.regen_hop", owner="recovery",
                            nbytes=length):
                data = ecutil.regen_combine(
                    plan.combine, rows, plan.sub_count,
                    pipeline=getattr(self, "recovery_pipeline", None),
                    use_device=plan.use_device)
            oattrs = dict(plan.attrs.get(oid, {}))
            hashes = (oattrs.get(HINFO_KEY) or {}).get(
                "cumulative_shard_hashes") or []
            if hashes and crc32c(0xFFFFFFFF, data) != hashes[plan.chunk]:
                # the regenerated chunk must match the newcomer's own
                # recorded hash chain bit-for-bit — the end-to-end
                # verification a decode-and-push repair gets for free
                self._regen_abort(plan, f"{oid}: combined hash mismatch")
                return
            obj = GObject(oid, self.shard)
            if not self._push_is_stale(SimpleNamespace(attrs=oattrs), obj):
                self._apply_push(obj, data, oattrs, None, b"")
            self.bus.send(plan.coordinator,
                          ECPartialSumApplied(self.shard, plan.tid, oid))


def _slice_subchunks(data: bytes, runs: list[tuple[int, int]],
                     sub_chunk_count: int) -> bytes:
    """Extract (offset, count) sub-chunk runs out of ``sub_chunk_count``
    equal sub-chunks (clay fractional reads, ECBackend.cc:1002-1024)."""
    sub_size = len(data) // max(sub_chunk_count, 1)
    return b"".join(data[off * sub_size:(off + c) * sub_size]
                    for off, c in runs)


class RecoveryState(Enum):
    IDLE = "IDLE"
    READING = "READING"
    WRITING = "WRITING"
    COMPLETE = "COMPLETE"
    # a push target died before acking: the object is still degraded there
    # (the reference's _failed_push path, ECBackend.cc:211-248)
    FAILED = "FAILED"


@dataclass
class RecoveryOp:
    """ECBackend::RecoveryOp (ECBackend.h:249-293)."""
    oid: str
    missing_shards: set[int]
    state: RecoveryState = RecoveryState.IDLE
    read_tid: int | None = None
    # pg_log version of the object when the recovery read was issued; a
    # bump while the read was in flight means a write landed and the
    # reconstructed bytes are stale — re-read instead of pushing them
    # (the reference serializes this with per-object recovery locks)
    at_version: int = 0
    pending_pushes: set[int] = field(default_factory=set)
    # sources whose copy failed its at-rest checksum (EIO from the
    # store): excluded from further reads AND added to missing_shards so
    # the rebuild repairs them too
    bad_sources: set[int] = field(default_factory=set)
    # sticky: a push target died before acking; even if the remaining
    # pushes ack, the op must finish FAILED (reference _failed_push fails
    # the whole op for any dead push target)
    failed: bool = False
    on_complete: object = None


class RepairState(Enum):
    QUERY = "QUERY"               # waiting for the shard's PGLogInfo
    SCAN = "SCAN"                 # backfill: waiting for the object list
    RECOVERING = "RECOVERING"     # pushes/deletes in flight
    COMPLETE = "COMPLETE"
    FAILED = "FAILED"


@dataclass
class ShardRepairOp:
    """Catch one stale/revived shard up, cheapest plan first: log equality
    (free) -> log replay (O(missed writes), PGLog.cc semantics) -> full
    backfill (O(objects), only past the log horizon)."""
    shard: int
    chunk: int
    state: RepairState = RepairState.QUERY
    plan: str = ""                # "clean" | "log" | "backfill"
    rewind_to: int = 0
    # authority log head when the repair's todo set was computed; writes
    # committing past it mid-repair skipped the stale target and must be
    # caught up before the shard is declared current
    caught_up_to: int = 0
    pending: set = field(default_factory=set)   # ("recover"|"delete", oid)
    objects_repaired: int = 0
    failed: bool = False
    on_complete: object = None
    # scheduler hand-off (ceph_tpu/recovery): with a driver attached the
    # repair planner OFFERS the missing-object list instead of recovering
    # inline; the driver paces it in waves through repair_wave and the
    # not-yet-dispatched remainder parks here
    driver: object = None
    deferred: list = field(default_factory=list)


@dataclass
class Op:
    """In-flight client write (ECBackend::Op, ECBackend.h:390-440)."""
    tid: int
    t: PGTransaction
    on_commit: object
    # computed at pipeline admission (_admit_op) so a rolled-back op
    # re-plans against the restored object state when re-admitted
    plan: object | None = None
    pending_read_shards: set[int] = field(default_factory=set)
    remote_reads: dict[str, dict[int, bytes]] = field(default_factory=dict)  # oid -> {logical off: stripe data}
    pending_commit_shards: set[int] = field(default_factory=set)
    acked_shards: set[int] = field(default_factory=set)
    cache_claims: list[tuple[str, int]] = field(default_factory=list)
    # version span (first_version, at_version] of this op's log entries,
    # recorded at fan-out; rollback rewinds to first_version - 1
    first_version: int = 0
    at_version: int = 0
    # dispatch generation: bumped each fan-out so stale acks from a
    # rolled-back dispatch are ignored
    gen: int = 0
    # reads unrecoverable with current up set; re-driven by on_shard_up
    _rmw_stalled: bool = False
    tracked: object = None      # OpTracker request (mark_event timeline)


class PGBackend:
    """Shared primary-side machinery; see module docstring for the hook
    surface each pool type implements."""

    def __init__(self, bus: MessageBus, acting: list[int], whoami: int = 0,
                 cct=None, name: str = "", min_size: int = 0,
                 min_size_floor: int = 1, store=None,
                 perf_prefix: str = "pg_backend"):
        # `name` disambiguates observability registrations when several
        # backends (e.g. one per PG) share a Context and a primary OSD id
        self.bus = bus
        self.acting = list(acting)
        self.whoami = whoami
        # write availability floor: a write is never acked with fewer than
        # min_size current shards holding it (the pool min_size the
        # reference's PeeringState enforces by going inactive).  The floor
        # is k for EC (below it the data is unreadable) and 1 for
        # replicated.
        self.min_size = max(min_size or 0, min_size_floor)
        self.local_shard = OSDShard(whoami, bus, store=store)
        bus.handlers[whoami] = self  # primary intercepts its own queue
        self.next_tid = 0
        # write pipeline (ECBackend.h:562-564)
        self.waiting_state: deque[Op] = deque()
        self.waiting_reads: deque[Op] = deque()
        self.waiting_commit: deque[Op] = deque()
        self.tid_to_op: dict[int, Op] = {}
        # recovery
        self.recovery_ops: dict[str, RecoveryOp] = {}
        self._recovery_read_tids: dict[int, RecoveryOp] = {}
        self._stalled_recoveries: list[RecoveryOp] = []
        # The authority log advances at fan-out; the local shard's own log
        # advances only when its self-delivered sub-write APPLIES.  Keeping
        # them separate is what lets a revived primary detect its own
        # staleness (writes committed by the other shards while it was
        # down) and repair itself through the same query/replay machinery.
        # On boot from a durable store, the local shard's persisted log IS
        # the authority (the reference elects the authoritative log during
        # peering; the primary's own is the single-primary analog) — half-
        # applied writes it logged roll FORWARD by repairing the peers.
        # objects with detected-but-unlocatable inconsistency (see the EC
        # backend's verified recovery; replicated majority-vote ties could
        # populate it too): surfaced by scrub/health until exonerated
        self.inconsistent_objects: set[str] = set()
        self.pg_log = PGLog()
        self.pg_log.tail = self.local_shard.pg_log.tail
        self.pg_log.head = self.local_shard.pg_log.tail
        for e in self.local_shard.pg_log.entries:
            self.pg_log.record(e)
        self.pg_log.head = max(self.pg_log.head,
                               self.local_shard.pg_log.head)
        # two-phase commit bookkeeping: committed_to = newest version acked
        # by >= min_size shards (the roll-forward point); _rolled_forward_to
        # = the point already announced to the shards
        self.committed_to = self.pg_log.head
        self._rolled_forward_to = self.pg_log.head
        self._rollback_pending = 0
        # shards that revived but have not been repaired yet: excluded from
        # reads AND from write fan-out until a shard repair completes (the
        # reference keeps stale shards out of the acting set until
        # recovery/backfill, PeeringState.cc)
        self.stale: set[int] = set()
        # boot peering (crash recovery): shard -> PGLogInfo while collecting
        self._boot_peering: dict[int, PGLogInfo] | None = None
        self._boot_peering_expect: set[int] = set()
        self.shard_repairs: dict[int, "ShardRepairOp"] = {}
        # tid -> (rop, oid, on_done|None) for in-flight repair deletes
        self._repair_write_tids: dict[int, tuple] = {}
        self._scan_waiters: dict[int, "ShardRepairOp"] = {}
        # background repair orchestration (ceph_tpu/recovery): when a
        # scheduler is attached, shard revival and stalled-recovery
        # re-drives route through its reservation gate instead of firing
        # inline; None keeps the pre-scheduler inline behavior
        self.recovery_scheduler = None
        # oid -> batched recovery wave with pushes in flight (the EC
        # backend's decode_many-fused recovery path; empty elsewhere)
        self._wave_pushes: dict[str, object] = {}
        bus.down_listeners.append(self.on_shard_down)
        bus.up_listeners.append(self.on_shard_up)
        # observability (SURVEY.md §5): counters + op tracking + admin cmds
        from ..common import OpTracker, PerfCountersBuilder, default_context
        self.cct = cct if cct is not None else default_context()
        self.instance_name = name or str(whoami)
        self.perf = (
            PerfCountersBuilder(f"{perf_prefix}.{self.instance_name}")
            .add_u64_counter("writes", "client writes committed")
            .add_u64_counter("write_rollbacks",
                             "in-flight writes rolled back (min_size)")
            .add_u64_counter("reads", "client reads completed")
            .add_u64_counter("read_errors", "per-object read failures (EIO)")
            .add_u64_counter("write_bytes", "client bytes written")
            .add_u64_counter("stripe_bytes_encoded",
                             "stripe-aligned bytes through encode (>= "
                             "write_bytes: RMW pads to whole stripes)")
            .add_u64_counter("read_bytes", "logical bytes returned")
            .add_u64_counter("recoveries", "recovery ops completed")
            .add_u64_counter("recovery_bytes",
                             "chunk bytes pushed to recovery targets "
                             "(the mgr digest's recovery B/s source)")
            .add_u64_counter("recovery_failures", "recovery ops failed")
            .add_u64_counter("chain_repairs",
                             "partial-sum chain waves completed")
            .add_u64_counter("chain_objects",
                             "objects repaired via streaming chains")
            .add_u64_counter("chain_fallbacks",
                             "chains aborted to centralized repair")
            .add_u64_counter("regen_repairs",
                             "regenerating-code repair rounds completed")
            .add_u64_counter("regen_objects",
                             "objects repaired from helper inner products")
            .add_u64_counter("regen_fallbacks",
                             "regen repairs aborted to centralized repair")
            .add_u64_counter("log_repairs_clean",
                             "shard repairs satisfied by log equality alone")
            .add_u64_counter("log_repairs", "log-based shard catch-ups")
            .add_u64_counter("log_repair_objects",
                             "objects replayed by log catch-up")
            .add_u64_counter("shard_backfills",
                             "repairs past the log horizon (full backfill)")
            .add_u64_counter("backfill_objects",
                             "objects moved by shard backfill")
            .add_u64_counter("slow_ops",
                             "ops exceeding osd_op_complaint_time")
            .add_time_avg("encode_time", "batched encode wall time")
            .add_time_avg("decode_time", "batched decode wall time")
            .add_u64("pipeline_depth", "ops across the three wait lists")
            .create_perf_counters())
        self.cct.perf.add(self.perf)
        self.op_tracker = OpTracker(conf=self.cct.conf, perf=self.perf)
        for cmd, fn in ((f"dump_ops_in_flight.{self.instance_name}",
                         lambda **kw: self.op_tracker.dump_ops_in_flight()),
                        (f"dump_historic_ops.{self.instance_name}",
                         lambda **kw: self.op_tracker.dump_historic_ops())):
            # a re-created backend with the same name takes over the hook
            # (leaving the old registration would serve — and pin — the
            # dead backend's tracker)
            self.cct.admin_socket.unregister(cmd)
            self.cct.admin_socket.register(cmd, fn)

    # -- subclass hook surface ---------------------------------------------

    def _admit_op(self, op: Op) -> None:
        """Plan the op and issue any pre-commit reads; default: nothing."""
        op.plan = op.plan or True

    def _op_blocked(self, op: Op) -> bool:
        return False

    def _generate_transactions(self, op: Op):
        raise NotImplementedError

    def _recovery_issue_reads(self, rop: RecoveryOp) -> None:
        raise NotImplementedError

    def _recovery_push_payloads(self, rop: RecoveryOp
                                ) -> dict[int, tuple[bytes, dict, dict | None, bytes]]:
        raise NotImplementedError

    def _handle_other_read_reply(self, reply: ECSubReadReply) -> None:
        pass

    def _on_shard_down_reads(self, shard: int, chunk: int) -> None:
        pass

    def _redrive_reads(self) -> None:
        pass

    def _on_local_rollback(self) -> None:
        pass

    def _op_reset_extra(self, op: Op) -> None:
        pass

    def object_size(self, oid: str) -> int:
        raise NotImplementedError

    def be_deep_scrub(self, oid: str) -> dict[int, bool]:
        raise NotImplementedError

    def is_recoverable(self, oid: str, missing: set[int]) -> bool:
        raise NotImplementedError

    # -- helpers -----------------------------------------------------------

    def up_shards(self) -> set[int]:
        return {s for s in self.acting if s not in self.bus.down}

    def current_shards(self) -> set[int]:
        """Up AND repaired: the shards that may serve reads and receive
        write fan-out (the reference's acting set after peering; stale
        revived shards rejoin once their shard repair completes)."""
        return {s for s in self.acting
                if s not in self.bus.down and s not in self.stale}

    def is_active(self) -> bool:
        """Writes proceed only while >= min_size current shards exist (the
        PG-active gate of PeeringState; below it client writes park in
        waiting_state until shards return — never acked, never lost).
        NOTE: a bus-down primary is gated at the DAEMON dispatch layer
        (a dead OSD accepts no client ops), not here — the backend
        coordinator running with its own shard down is a legitimate
        divergence scenario (it commits on peers and self-repairs)."""
        return len(self.current_shards()) >= self.min_size

    # -- message dispatch --------------------------------------------------

    def handle_message(self, msg) -> None:
        if isinstance(msg, ECSubWriteReply):
            self.handle_sub_write_reply(msg)
        elif isinstance(msg, ECSubReadReply):
            self.handle_sub_read_reply(msg)
        elif isinstance(msg, PushReply):
            self.handle_push_reply(msg)
        elif isinstance(msg, PGLogInfo):
            self.handle_pg_log_info(msg)
        elif isinstance(msg, PGActivateAck):
            peering = getattr(self, "peering", None)
            if peering is not None:
                peering.on_activate_ack(msg)
        elif isinstance(msg, PGScanReply):
            self.handle_pg_scan_reply(msg)
        elif isinstance(msg, Rollback):
            # primary's own shard rolls back; subclass caches of the rolled-
            # back state must refresh before re-queued ops re-plan
            self.local_shard.handle_message(msg)
            self._on_local_rollback()
            self._rollback_pending = max(0, self._rollback_pending - 1)
            self.check_ops()
        else:
            self.local_shard.handle_message(msg)

    def handle_sub_read_reply(self, reply: ECSubReadReply) -> None:
        rop_rec = self._recovery_read_tids.get(reply.tid)
        if rop_rec is not None:
            self.handle_recovery_read_reply(rop_rec, reply)
            return
        self._handle_other_read_reply(reply)

    def shutdown(self, checkpoint_store: bool = True) -> None:
        """Unhook from the shared Context and bus so a discarded backend is
        collectable (registration without teardown pins the backend — and
        its trackers/stores — for the context's lifetime)."""
        self.cct.perf.remove(self.perf.name)
        self.cct.admin_socket.unregister(
            f"dump_ops_in_flight.{self.instance_name}")
        self.cct.admin_socket.unregister(
            f"dump_historic_ops.{self.instance_name}")
        for lst in (self.bus.down_listeners, self.bus.up_listeners):
            for cb in list(lst):
                if getattr(cb, "__self__", None) is self:
                    lst.remove(cb)
        # hand the shard queue back to the plain shard handler so the bus
        # no longer references this backend
        if self.bus.handlers.get(self.whoami) is self:
            self.bus.handlers[self.whoami] = self.local_shard
        if hasattr(self.local_shard.store, "close"):
            self.local_shard.store.close(checkpoint=checkpoint_store)

    # -- failure handling --------------------------------------------------

    def on_shard_down(self, shard: int) -> None:
        """Route around a shard that died with requests outstanding — the
        analog of the reference's on_change/check_recovery_sources paths
        re-driving in-flight ops when the acting set changes
        (ECBackend.cc check_recovery_sources, _failed_push)."""
        if shard not in set(self.acting):
            return
        chunk = self.acting.index(shard)
        self._on_shard_down_reads(shard, chunk)
        # recovery reads: restart the op's READING phase from live shards
        from ..common.tracer import root_or_ambient
        for tid, rop in list(self._recovery_read_tids.items()):
            if shard in rop._pending:
                del self._recovery_read_tids[tid]
                rop.state = RecoveryState.IDLE
                try:
                    # re-planned reads are still recovery traffic (wire
                    # accounting / device ledger), same as recover_object
                    with root_or_ambient("recovery"):
                        self.continue_recovery_op(rop)
                except IOError:
                    # too few survivors: park; re-driven by on_shard_up
                    self._stalled_recoveries.append(rop)
        # recovery pushes: a dead target never acks and is still degraded —
        # the op FAILS (the reference's _failed_push), it is not COMPLETE
        for oid, rop in list(self.recovery_ops.items()):
            if shard in rop.pending_pushes:
                rop.pending_pushes.discard(shard)
                rop.failed = True
                if not rop.pending_pushes and \
                        rop.state == RecoveryState.WRITING:
                    self._finish_recovery_op(rop, failed=True)
        # a shard under repair that dies again: the repair fails (its
        # revival restarts it via the boot path)
        srop = self.shard_repairs.get(shard)
        if srop is not None:
            srop.failed = True
            srop.deferred = []
            self._repair_write_tids = {
                tid: v for tid, v in self._repair_write_tids.items()
                if v[0] is not srop}
            srop.pending.clear()
            self._finish_shard_repair(srop)
        self.try_finish_rmw()
        self.check_ops()

    def on_shard_up(self, shard: int) -> None:
        """A revived shard is stale — it missed every write since it died —
        so it is kept out of reads and write fan-out and a shard repair
        starts automatically (the reference re-peers on the osdmap epoch
        bump, which drives log-based recovery the same way).  Parked work
        re-drives now and again when the repair completes."""
        if shard in self.acting:
            # stale until repair completes: serving reads could return old
            # bytes; receiving new writes would make its log head current
            # while mid-history entries are missing, defeating log catch-up
            self.stale.add(shard)
            if shard not in self.shard_repairs:
                if self.recovery_scheduler is not None:
                    # reservation-gated: the repair starts when the
                    # scheduler grants this PG its local+remote slots
                    self.recovery_scheduler.schedule_backend(
                        self, targets=[shard])
                else:
                    self.start_shard_repair(shard)
        self._redrive_parked()

    def _redrive_parked(self) -> None:
        """Re-drive ops parked by unrecoverable shard loss (called on shard
        revival and on repair completion, when current_shards() grows)."""
        self._redrive_reads()
        stalled, self._stalled_recoveries = self._stalled_recoveries, []
        if stalled and self.recovery_scheduler is not None:
            # stalled recoveries must RE-ENTER via the scheduler
            # (reservation-gated), not bypass it on shard revival
            self.recovery_scheduler.requeue_stalled(self, stalled)
        else:
            from ..common.tracer import root_or_ambient
            for rop in stalled:
                try:
                    # re-driven repair bytes stay recovery-class (the
                    # ambient ctx here is usually a peering/up event's,
                    # not a recovery root)
                    with root_or_ambient("recovery"):
                        self.continue_recovery_op(rop)
                except IOError:
                    self._stalled_recoveries.append(rop)
        # a stale shard whose repair FAILED (a peer died mid-repair) gets a
        # fresh repair on the next cluster event — the role re-peering on
        # a map change plays in the reference
        for shard in sorted(self.stale & self.up_shards()):
            if shard not in self.shard_repairs:
                if self.recovery_scheduler is not None:
                    self.recovery_scheduler.schedule_backend(
                        self, targets=[shard])
                else:
                    self.start_shard_repair(shard)
        self.check_ops()

    # -- write pipeline ----------------------------------------------------

    def submit_transaction(self, t: PGTransaction, on_commit=None) -> int:
        """Client entry point (ECBackend.cc:1477 -> start_rmw :1830).

        While the PG is inactive (< min_size current shards) the op parks
        in waiting_state — queued, unacked, unapplied — and is re-driven
        when shards return (the reference blocks I/O on an inactive PG)."""
        self.next_tid += 1
        tid = self.next_tid
        op = Op(tid=tid, t=t, on_commit=on_commit)
        op.tracked = self.op_tracker.create_request(
            f"osd_op(write tid={tid} objects={sorted(t.ops)})")
        op.tracked.mark_event("queued_for_pg")
        self.tid_to_op[tid] = op
        self.waiting_state.append(op)
        self._update_pipeline_depth()
        self.check_ops()
        return tid

    def _update_pipeline_depth(self) -> None:
        self.perf.set("pipeline_depth",
                      len(self.waiting_state) + len(self.waiting_reads) +
                      len(self.waiting_commit))

    def check_ops(self) -> None:
        """Advance each pipeline stage's head as far as possible
        (ECBackend.cc:2137-2145).  Re-loops because an op reaching the
        commit stage pins its result in the extent cache, which can unblock
        a stalled overlapping op behind it.  Gated on the PG being active
        (min_size current shards) and on no rollback being mid-flight (a
        re-queued op must re-plan against the restored state)."""
        if not self.is_active() or self._rollback_pending:
            return
        progress = True
        while progress:
            progress = False
            if self.waiting_state and self.try_state_to_reads():
                progress = True
            if self.waiting_reads and self.try_reads_to_commit():
                progress = True

    def try_state_to_reads(self) -> bool:
        """(ECBackend.cc:1856-1928): plan, satisfy cached reads, issue
        remote reads (all via the _admit_op hook)."""
        op = self.waiting_state[0]
        self._admit_op(op)
        if self._op_blocked(op):
            return False
        self.waiting_state.popleft()
        self.waiting_reads.append(op)
        self._start_op_reads(op)
        return True

    def _start_op_reads(self, op: Op) -> None:
        pass

    def try_reads_to_commit(self) -> bool:
        """(ECBackend.cc:1930-2087): generate per-shard transactions (the
        subclass hook encodes/replicates) and fan them out to every current
        shard with the piggybacked roll-forward point."""
        op = self.waiting_reads[0]
        if op.pending_read_shards:
            return False
        self.waiting_reads.popleft()
        self.waiting_commit.append(op)
        op.first_version = self.pg_log.head + 1
        with trace_span("pg.generate_transactions", tid=op.tid,
                        backend=self.instance_name):
            shard_txns, log_entries = self._generate_transactions(op)
        # fan out to every current shard (down/stale shards miss the write
        # and are repaired later by the log — the reference's peering
        # likewise keeps them out of the acting set)
        cur = self.current_shards()
        op.at_version = self.pg_log.head
        op.gen += 1
        op.acked_shards = set()
        op.pending_commit_shards = set(cur)
        trim_to = self.pg_log.trim_target()
        for shard in self.acting:
            if shard in cur:
                self.bus.send(shard, ECSubWrite(
                    self.whoami, op.tid, shard_txns[shard],
                    at_version=op.at_version, trim_to=trim_to,
                    log_entries=list(log_entries),
                    roll_forward_to=self.committed_to, gen=op.gen))
        self._rolled_forward_to = max(self._rolled_forward_to,
                                      self.committed_to)
        self.pg_log.maybe_trim()
        return True

    def handle_sub_write_reply(self, reply: ECSubWriteReply) -> None:
        """(ECBackend.cc:1120-1152) -> try_finish_rmw (:2089)."""
        rep = self._repair_write_tids.pop(reply.tid, None)
        if rep is not None:                 # a shard-repair delete acked
            rop, oid, on_done = rep
            rop.pending.discard(("delete", oid))
            if on_done:
                on_done()
            self._maybe_finish_shard_repair(rop)
            return
        op = self.tid_to_op.get(reply.tid)
        if op is None or reply.gen != op.gen:
            return                      # stale ack from a rolled-back dispatch
        op.acked_shards.add(reply.from_shard)
        op.pending_commit_shards.discard(reply.from_shard)
        self.try_finish_rmw()

    def try_finish_rmw(self) -> None:
        while self.waiting_commit:
            op = self.waiting_commit[0]
            # shards that died after dispatch can never ack
            op.pending_commit_shards &= self.up_shards()
            if op.pending_commit_shards:
                return
            # write-availability gate (ecbackend.rst:149-174): the write is
            # durable only if >= min_size shards hold it.  Shards that died
            # after acking still hold it on disk but can't serve; count
            # only live acks.  Below the floor the write — and every later
            # in-flight write — rolls back; nothing was ever acked to the
            # client, so nothing is lost.
            live_acked = op.acked_shards & self.up_shards()
            if len(live_acked) < self.min_size:
                self._rollback_incomplete()
                return
            self.waiting_commit.popleft()
            self.committed_to = max(self.committed_to, op.at_version)
            self._op_reset_extra(op)
            del self.tid_to_op[op.tid]
            self.perf.inc("writes")
            self.perf.inc("write_bytes", sum(
                len(d) for objop in op.t.ops.values()
                for _, d in objop.buffer_updates))
            self._update_pipeline_depth()
            if op.tracked:
                op.tracked.mark_event("commit_sent")
                op.tracked.finish()
            if op.on_commit:
                op.on_commit(op.tid)
        # pipeline drained with an unannounced roll-forward point: kick it
        # to the shards so they drop rollback data (the reference's dummy
        # transaction, ECBackend.cc:2106-2120)
        if self.committed_to > self._rolled_forward_to:
            self._rolled_forward_to = self.committed_to
            for shard in sorted(self.current_shards()):
                self.bus.send(shard, RollForward(self.whoami,
                                                 self.committed_to))

    def _rollback_incomplete(self) -> None:
        """Undo every in-flight commit-stage write (head first failed; all
        later ones have higher versions and must unwind with it), rewind
        the authority log, and re-queue the ops at the pipeline head to
        re-plan and re-execute once the PG is active again.

        Ops still in waiting_reads / waiting_state are reset too: their
        plans and reads were computed against state of the writes being
        rolled back."""
        ops = list(self.waiting_commit)
        self.waiting_commit.clear()
        to = ops[0].first_version - 1
        self.perf.inc("write_rollbacks", len(ops))
        read_ops = list(self.waiting_reads)
        self.waiting_reads.clear()
        state_ops = list(self.waiting_state)
        self.waiting_state.clear()
        ops = ops + read_ops + state_ops    # original pipeline order
        for shard in sorted(self.up_shards()):
            # FIFO per-shard queues order the Rollback after any still-
            # undelivered sub-writes of these ops, so every shard unwinds
            # exactly what it applied
            if shard == self.whoami:
                self._rollback_pending += 1
            self.bus.send(shard, Rollback(self.whoami, to))
        if self.whoami not in self.up_shards():
            # local shard marked down: its queue was cleared, so no sub-
            # write can race a synchronous local unwind
            self.local_shard._rollback(to)
            self._on_local_rollback()
        self.pg_log.rewind(to)
        self.committed_to = min(self.committed_to, to)
        for op in ops:
            self._op_reset_extra(op)
            op.plan = None
            op.pending_read_shards.clear()
            op.remote_reads.clear()
            op.pending_commit_shards.clear()
            op.acked_shards.clear()
            op._rmw_stalled = False
            if op.tracked:
                op.tracked.mark_event("rolled_back")
        self.waiting_state.extend(ops)
        self._update_pipeline_depth()

    # -- recovery (ECBackend.cc:565-732; state ECBackend.h:249-293) --------

    def recover_object(self, oid: str, missing_chunks: set[int],
                       on_complete=None) -> RecoveryOp:
        rop = RecoveryOp(oid=oid, missing_shards=set(missing_chunks),
                         on_complete=on_complete)
        self.recovery_ops[oid] = rop
        # the recovery conversation (reads -> replies -> pushes) rides
        # the root context stamped HERE: an ambient one (scrub repair,
        # a scheduler wave) is adopted, otherwise a fresh recovery root
        # — so every byte it moves attributes to the recovery op class
        # in the wire accounting and device ledger
        from ..common.tracer import root_or_ambient
        with root_or_ambient("recovery"):
            try:
                self.continue_recovery_op(rop)
            except IOError:
                # too few current shards right now: park; re-driven when
                # a shard returns (the reference defers recovery the same
                # way when sources are missing)
                self._stalled_recoveries.append(rop)
        return rop

    def continue_recovery_op(self, rop: RecoveryOp) -> None:
        if rop.state == RecoveryState.IDLE:
            self.next_tid += 1
            rop.read_tid = self.next_tid
            rop.at_version = self.pg_log.last_version_of(rop.oid)
            rop._read_results = {}
            rop._read_attrs = {}
            rop._read_omap = {}            # chunk -> (omap kvs, header)
            self._recovery_issue_reads(rop)   # may raise IOError (parked)
            rop.state = RecoveryState.READING
            self._recovery_read_tids[rop.read_tid] = rop

    def handle_recovery_read_reply(self, rop: RecoveryOp,
                                   reply: ECSubReadReply) -> None:
        if rop.state != RecoveryState.READING:
            return                      # stale/duplicate reply
        if rop.oid in reply.errors:
            if reply.errors[rop.oid] == -5:
                # the source's copy is ROTTEN at rest (store checksum):
                # don't fail the op — drop the source, mark its shard for
                # rebuild too, and restart the read from the remaining
                # clean sources (mirrors the hash-present rotten-source
                # drop in _recovery_push_payloads)
                chunk = {s: c for c, s in
                         enumerate(self.acting)}[reply.from_shard]
                rop.bad_sources.add(chunk)
                rop.missing_shards = set(rop.missing_shards) | {chunk}
                self._recovery_read_tids.pop(rop.read_tid, None)
                rop.state = RecoveryState.IDLE
                try:
                    self.continue_recovery_op(rop)
                except IOError:
                    self._finish_recovery_op(rop, failed=True)
                return
            # the source no longer has the object (e.g. a delete committed
            # while the read was in flight): the op fails cleanly; a later
            # repair pass re-plans from the log
            self._recovery_read_tids.pop(rop.read_tid, None)
            self._finish_recovery_op(rop, failed=True)
            return
        chunk_of_shard = {s: c for c, s in enumerate(self.acting)}
        chunk = chunk_of_shard[reply.from_shard]
        # recovery reads exactly ONE oid: key every slot by rop.oid so a
        # hypothetical multi-oid reply cannot last-oid-wins overwrite
        if rop.oid in reply.buffers_read:
            rop._read_results[chunk] = b"".join(
                b for _, b in reply.buffers_read[rop.oid])
        if rop.oid in reply.attrs_read:
            rop._read_attrs[chunk] = reply.attrs_read[rop.oid]
        if rop.oid in reply.omap_read:
            rop._read_omap[chunk] = reply.omap_read[rop.oid]
        rop._pending.discard(reply.from_shard)
        if rop._pending:
            return
        self._recovery_read_tids.pop(rop.read_tid, None)
        if self.pg_log.last_version_of(rop.oid) != rop.at_version:
            # a write to this oid committed between the recovery read and
            # now: the reconstructed bytes predate it.  Re-read (the new
            # data is on the survivors) instead of pushing stale bytes.
            rop.state = RecoveryState.IDLE
            self.continue_recovery_op(rop)
            return
        # READING -> WRITING: build the payloads, push them
        payloads = self._recovery_push_payloads(rop)
        rop.state = RecoveryState.WRITING
        up = self.up_shards()
        for chunk in rop.missing_shards:
            shard = self.acting[chunk]
            if shard not in up:
                # target died while the reads were in flight: a push would
                # drop silently and never ack — fail now exactly as
                # on_shard_down fails an already-sent push (_failed_push)
                rop.failed = True
                continue
            data, attrs, omap, header = payloads[chunk]
            rop.pending_pushes.add(shard)
            self.perf.inc("recovery_bytes", len(data))
            self.bus.send(shard, PushOp(self.whoami, rop.oid, data,
                                        attrs=attrs, omap=omap,
                                        omap_header=header))
        if not rop.pending_pushes:
            self._finish_recovery_op(rop, failed=rop.failed)

    def handle_push_reply(self, reply: PushReply) -> None:
        wave = self._wave_pushes.get(reply.oid)
        if wave is not None and reply.from_shard in \
                wave.pending_pushes.get(reply.oid, ()):
            # a batched recovery wave's push.  The from_shard check
            # disambiguates against a CONCURRENT per-object RecoveryOp
            # for the same oid (e.g. scrub repair): replies the wave is
            # not waiting on fall through to the per-object path below
            self._wave_push_reply(wave, reply)
            return
        rop = self.recovery_ops.get(reply.oid)
        if rop is None:
            return
        rop.pending_pushes.discard(reply.from_shard)
        if not rop.pending_pushes and rop.state == RecoveryState.WRITING:
            self._finish_recovery_op(rop, failed=rop.failed)

    def _finish_recovery_op(self, rop: RecoveryOp, failed: bool = False) -> None:
        """COMPLETE (or FAILED) + drop tracking state so late replies are
        inert (the reference erases the RecoveryOp from recovery_ops on
        on_global_recover; failures go through _failed_push)."""
        rop.state = RecoveryState.FAILED if failed else RecoveryState.COMPLETE
        self.recovery_ops.pop(rop.oid, None)
        self._recovery_read_tids.pop(rop.read_tid, None)
        self.perf.inc("recovery_failures" if failed else "recoveries")
        if rop.on_complete:
            rop.on_complete(rop)

    # -- shard repair: log catch-up or backfill ----------------------------
    # (the role PGLog::merge_log + log-based recovery + backfill play in the
    # reference, src/osd/PGLog.cc)

    def start_shard_repair(self, shard: int, on_complete=None,
                           driver=None) -> ShardRepairOp:
        """Bring a revived/stale shard current.  Queries its log; replays
        exactly the missed entries when they are within the horizon, falls
        back to a scan+push backfill when not.  COMPLETE means the shard's
        data AND log match the authority's.  Works for the primary's own
        shard too: its local log lags the authority log by exactly the
        writes that committed while it was down, and the recovery pushes
        self-deliver over the bus.

        ``driver`` (a recovery-scheduler job) turns the repair into a
        PACED one: the planner hands the missing-object list to
        ``driver.offer_work`` and the driver dispatches it in waves via
        :meth:`repair_wave` instead of recovering everything inline."""
        existing = self.shard_repairs.get(shard)
        if existing is not None:
            # one repair per shard at a time: revival auto-starts one, an
            # explicit caller joins it
            if on_complete is not None:
                prev = existing.on_complete

                def chained(r, _prev=prev, _cb=on_complete):
                    if _prev:
                        _prev(r)
                    _cb(r)
                existing.on_complete = chained
            return existing
        chunk = self.acting.index(shard)
        rop = ShardRepairOp(shard=shard, chunk=chunk,
                            on_complete=on_complete, driver=driver)
        self.shard_repairs[shard] = rop
        # root the repair conversation on a recovery-class trace (see
        # recover_object): the log query, its reply, and every replay/
        # backfill push it triggers stitch — and account — as recovery
        from ..common.tracer import root_or_ambient
        with root_or_ambient("recovery"):
            self.bus.send(shard, PGLogQuery(self.whoami,
                                            since=self.pg_log.tail))
        return rop

    # -- boot peering (crash recovery) -------------------------------------

    def start_boot_peering(self) -> None:
        """After a restart from durable stores, decide what survived BEFORE
        serving: query every up peer's persisted log, adopt the best
        (furthest-ahead witnessed) log as the authority, and roll back any
        entry persisted on fewer than min_size shards — such a write was
        never acked, and repairing peers toward it would push never-acked
        state (for EC it would even mix chunk versions into garbage).
        This is the single-primary analog of the reference's peering
        (PeeringState GetInfo/GetLog; authoritative-log election +
        divergent-entry rollback)."""
        peers = {s for s in self.acting
                 if s != self.whoami and s not in self.bus.down}
        if not peers:
            return
        self._boot_peering = {}
        self._boot_peering_expect = peers
        for shard in sorted(peers):
            self.bus.send(shard, PGLogQuery(self.whoami, since=0))

    def _finish_boot_peering(self) -> None:
        infos = self._boot_peering
        self._boot_peering = None
        self._boot_peering_expect = set()
        self.elect_and_adopt_authority(infos)

    def elect_and_adopt_authority(self, infos: dict[int, PGLogInfo]) -> int:
        """Authoritative-log election + divergent-entry rollback: adopt the
        furthest-ahead witnessed log and roll back entries persisted on
        < min_size shards (never acked).  Shared by boot peering and the
        live peering statechart (osd/peering.py GetLog); returns the
        commit boundary.  Reference: PeeringState GetLog merge +
        ecbackend rollback semantics."""
        # adopt the furthest-ahead log: the primary may itself have been
        # down while peers committed (its RAM authority died with it)
        local = self.local_shard.pg_log
        best_shard, best_head = self.whoami, self.pg_log.head
        for shard, info in infos.items():
            if info.last_update > best_head:
                best_shard, best_head = shard, info.last_update
        if best_shard != self.whoami:
            binfo = infos[best_shard]
            if binfo.tail > self.pg_log.head:
                # our persisted log is beyond the best peer's horizon:
                # adopt its log wholesale (the data repairs via backfill)
                self.pg_log = PGLog()
                self.pg_log.tail = self.pg_log.head = binfo.tail
            for e in sorted(binfo.entries, key=lambda e: e.version):
                if e.version > self.pg_log.head:
                    self.pg_log.record(e)
            self.pg_log.head = max(self.pg_log.head, binfo.last_update)
        # witness count per version: a shard witnesses v if its log
        # provably contains the authority's entry at v
        auth = {e.version: e for e in self.pg_log.entries}
        shard_logs = {self.whoami: (local.head, local.tail,
                                    {e.version: e for e in local.entries})}
        for shard, info in infos.items():
            shard_logs[shard] = (info.last_update, info.tail,
                                 {e.version: e for e in info.entries})

        def witnesses(v: int) -> int:
            n = 0
            for head, tail, by_v in shard_logs.values():
                if head < v:
                    continue
                if v > tail and by_v.get(v) != auth.get(v):
                    continue
                n += 1
            return n

        boundary = self.pg_log.head
        if len(shard_logs) >= self.min_size:
            while boundary > self.pg_log.tail and \
                    witnesses(boundary) < self.min_size:
                boundary -= 1
        # roll back everything past the boundary, everywhere (FIFO-safe:
        # nothing else is in flight during boot), then roll the kept
        # prefix forward so stale rollback data drops
        if boundary < self.pg_log.head:
            for shard in sorted(self.up_shards()):
                if shard == self.whoami:
                    self._rollback_pending += 1
                self.bus.send(shard, Rollback(self.whoami, boundary))
            if self.whoami not in self.up_shards():
                self.local_shard._rollback(boundary)
            self.pg_log.rewind(boundary)
            self._on_local_rollback()
        self.committed_to = boundary
        self._rolled_forward_to = boundary
        for shard in sorted(self.up_shards()):
            self.bus.send(shard, RollForward(self.whoami, boundary))
        return boundary

    def handle_pg_log_info(self, info: PGLogInfo) -> None:
        if self._boot_peering is not None and \
                info.from_shard in self._boot_peering_expect:
            self._boot_peering[info.from_shard] = info
            if set(self._boot_peering) == self._boot_peering_expect:
                self._finish_boot_peering()
            return
        # The live peering statechart and a shard-repair op may BOTH be
        # waiting on this shard's log state (PGLogQuery carries no
        # correlation id, and the answer is identical either way), so the
        # reply feeds both: peering collects it AND the repair planner
        # still sees it — consuming it exclusively would stall whichever
        # consumer asked second.
        peering = getattr(self, "peering", None)
        if peering is not None:
            peering.offer_pg_log_info(info)
        rop = self.shard_repairs.get(info.from_shard)
        if rop is None or rop.state != RepairState.QUERY:
            return
        divergent, div_rewind = self.pg_log.divergent_oids(info.entries)
        plan, entries = self.pg_log.catch_up_plan(info.last_update)
        # the rewind point: last shard version consistent with our log
        rop.rewind_to = min(info.last_update, self.pg_log.head, div_rewind)
        rop.caught_up_to = self.pg_log.head
        if plan == "backfill":
            rop.plan = "backfill"
            rop.state = RepairState.SCAN
            self.perf.inc("shard_backfills")
            self._start_scan(rop)
            return
        rop.plan = plan
        todo: dict[str, str] = {}          # oid -> op
        for e in entries:
            todo[e.oid] = e.op
        for oid in divergent:
            # authority wins: re-push our state, or delete what we lack
            todo[oid] = OP_MODIFY if self._object_exists(oid) else OP_DELETE
        if not todo:
            self.perf.inc("log_repairs_clean")
            self._finish_shard_repair(rop)
            return
        self.perf.inc("log_repairs")
        rop.state = RepairState.RECOVERING
        if rop.driver is not None:
            # scheduler-paced: the driver dispatches repair_wave batches
            rop.driver.offer_work(self, rop, sorted(todo.items()))
            return
        for oid, op in sorted(todo.items()):
            self._repair_one(rop, oid, op)
        self._maybe_finish_shard_repair(rop)

    def _start_scan(self, rop: ShardRepairOp) -> None:
        """Backfill needs the authoritative object list.  Repairing a
        replica: the primary's own store is the authority, scan the stale
        target for extras.  Repairing the primary itself: any other up
        (hence current) shard supplies the authority list, and the stale
        local store supplies the extras."""
        target = rop.shard
        if rop.shard == self.whoami:
            others = [s for s in self.acting
                      if s != self.whoami and s in self.current_shards()]
            if not others:
                rop.failed = True
                self._finish_shard_repair(rop)
                return
            target = others[0]
        self._scan_waiters[target] = rop
        self.bus.send(target, PGScan(self.whoami))

    def handle_pg_scan_reply(self, reply: PGScanReply) -> None:
        rop = self._scan_waiters.pop(reply.from_shard, None)
        if rop is None or rop.state != RepairState.SCAN:
            return
        if rop.shard == self.whoami:
            authority = set(reply.oids)        # a current replica's list
            target_list = self._local_oids()   # the stale local store
        else:
            authority = self._local_oids()
            target_list = set(reply.oids)
        # the object lists reflect this moment: writes after it are the
        # delta _maybe_finish_shard_repair catches up
        rop.caught_up_to = self.pg_log.head
        rop.state = RepairState.RECOVERING
        items = [(oid, OP_MODIFY) for oid in sorted(authority)] + \
            [(oid, OP_DELETE) for oid in sorted(target_list - authority)]
        if rop.driver is not None and items:
            rop.driver.offer_work(self, rop, items)
            return
        for oid, op in items:
            self._repair_one(rop, oid, op)
        self._maybe_finish_shard_repair(rop)

    def _local_oids(self) -> set[str]:
        return {g.oid for g in self.local_shard.store.objects
                if g.shard == self.whoami and g.oid != PG_META}

    def _object_exists(self, oid: str) -> bool:
        return GObject(oid, self.whoami) in self.local_shard.store.objects

    def _repair_one(self, rop: ShardRepairOp, oid: str, op: str) -> None:
        if op == OP_DELETE:
            self._repair_delete(rop, oid)
        else:
            self._repair_recover_one(rop, oid)

    def _repair_delete(self, rop: ShardRepairOp, oid: str,
                       on_done=None) -> None:
        rop.objects_repaired += 1
        self.next_tid += 1
        tid = self.next_tid
        rop.pending.add(("delete", oid))
        self._repair_write_tids[tid] = (rop, oid, on_done)
        t = Transaction().remove(GObject(oid, rop.shard))
        self.bus.send(rop.shard, ECSubWrite(self.whoami, tid, t))

    def _repair_bookkeeping(self, rop: ShardRepairOp, oid: str,
                            ok: bool, on_done=None) -> None:
        """ONE copy of the per-object completion accounting shared by the
        chained per-object path and the batched wave path."""
        rop.pending.discard(("recover", oid))
        if not ok:
            rop.failed = True
        if on_done:
            on_done()
        self._maybe_finish_shard_repair(rop)

    def _chain_or_recover(self, oid: str, missing: set[int],
                          on_done) -> None:
        """ONE RecoveryOp per object at a time: start the recovery, or
        chain behind the in-flight op and re-issue when it completes —
        the per-object serialization rule every repair path shares."""
        existing = self.recovery_ops.get(oid)
        if existing is None:
            self.recover_object(oid, set(missing), on_complete=on_done)
            return
        prev = existing.on_complete

        def chained(rec, _prev=prev, _oid=oid, _missing=frozenset(missing),
                    _done=on_done):
            if _prev:
                _prev(rec)
            self.recover_object(_oid, set(_missing), on_complete=_done)
        existing.on_complete = chained

    def _repair_recover_one(self, rop: ShardRepairOp, oid: str,
                            on_done=None) -> None:
        rop.objects_repaired += 1
        rop.pending.add(("recover", oid))

        def done(rec, _rop=rop, _oid=oid, _cb=on_done):
            self._repair_bookkeeping(
                _rop, _oid, rec.state == RecoveryState.COMPLETE, _cb)

        self._chain_or_recover(oid, {rop.chunk}, done)

    # -- paced repair waves (driven by ceph_tpu/recovery) ------------------

    def repair_wave(self, rop: ShardRepairOp, items, on_done=None) -> None:
        """Dispatch ONE wave of repair work: deletes go per-object (they
        are cheap sub-writes), recovers batch through the subclass's
        :meth:`_recover_many` (the EC backend fuses them into one
        ``decode_shards_many`` dispatch).  ``on_done`` fires when every
        item of THIS wave completed — the scheduler's cue to queue the
        next wave (overall repair completion still flows through
        ``_maybe_finish_shard_repair``)."""
        remaining = {"n": 0}

        def _item_done():
            remaining["n"] -= 1
            if remaining["n"] == 0 and on_done:
                on_done()
        recovers: list[str] = []
        for oid, op in items:
            remaining["n"] += 1
            if op == OP_DELETE:
                self._repair_delete(rop, oid, on_done=_item_done)
            else:
                recovers.append(oid)
        if recovers:
            self._repair_recover_many(rop, recovers, _item_done)
        elif remaining["n"] == 0 and on_done:
            on_done()

    def _repair_recover_many(self, rop: ShardRepairOp, oids: list[str],
                             each_done) -> None:
        """Wave recovers: objects already mid-recovery (or mid-wave) take
        the chained per-object path; the rest batch via _recover_many."""
        batch: dict[str, set[int]] = {}
        for oid in oids:
            if oid in self.recovery_ops or oid in self._wave_pushes:
                self._repair_recover_one(rop, oid, on_done=each_done)
            else:
                rop.objects_repaired += 1
                rop.pending.add(("recover", oid))
                batch[oid] = {rop.chunk}
        if batch:
            self._recover_many(
                batch,
                lambda oid, ok, _rop=rop, _cb=each_done:
                    self._repair_bookkeeping(_rop, oid, ok, _cb))

    def _recover_many(self, oids: dict[str, set[int]], on_each) -> None:
        """Recover several objects; ``on_each(oid, ok)`` per object.  The
        default is the per-object path (replicated pools have nothing to
        batch); the EC backend overrides with the decode-fused wave."""
        for oid, missing in sorted(oids.items()):
            def done(rec, _oid=oid):
                on_each(_oid, rec.state == RecoveryState.COMPLETE)
            self.recover_object(oid, set(missing), on_complete=done)

    def _wave_push_reply(self, wave, reply) -> None:
        """Only the EC backend creates waves; a stray reply here means a
        lifecycle bug, not a silent drop."""
        raise TypeError(f"wave push reply for {reply.oid!r} on a backend "
                        f"without a batched recovery path")

    def _maybe_finish_shard_repair(self, rop: ShardRepairOp) -> None:
        if rop.state != RepairState.RECOVERING or rop.pending or \
                rop.deferred:
            return                  # driver still holds undispatched waves
        # writes that committed while the repair was in flight skipped the
        # stale target (it is out of the fan-out): repair the delta before
        # declaring it current, else its log would claim writes whose data
        # it never received
        if not rop.failed and self.pg_log.head > rop.caught_up_to:
            delta = dedup_latest([e for e in self.pg_log.entries
                                  if e.version > rop.caught_up_to])
            rop.caught_up_to = self.pg_log.head
            for e in delta:
                self._repair_one(rop, e.oid, e.op)
            if rop.pending:
                return
        self._finish_shard_repair(rop)

    def _finish_shard_repair(self, rop: ShardRepairOp) -> None:
        self.shard_repairs.pop(rop.shard, None)
        if rop.failed:
            rop.state = RepairState.FAILED
        else:
            # repaired: the shard is current again — it rejoins reads and
            # write fan-out, and its return may reactivate a parked PG
            self.stale.discard(rop.shard)
            # data is current: ship the authoritative log segment so the
            # shard's next repair takes the clean fast path
            self.bus.send(rop.shard, PGLogUpdate(
                self.whoami,
                entries=self.pg_log.entries_after(rop.rewind_to) or [],
                last_update=self.pg_log.head,
                rewind_to=rop.rewind_to,
                trim_to=self.pg_log.tail))
            rop.state = RepairState.COMPLETE
            self.perf.inc("log_repair_objects" if rop.plan != "backfill"
                          else "backfill_objects", rop.objects_repaired)
        if rop.on_complete:
            rop.on_complete(rop)
        if not rop.failed:
            self._redrive_parked()
