"""Write-pinned stripe cache for the EC RMW pipeline.

Analog of the reference's ``ExtentCache`` (reference:
src/osd/ExtentCache.{h,cc}; design in
doc/dev/osd_internals/erasure_coding/ecbackend.rst:176-188): stripes written
by in-flight ops stay pinned so an overlapping later write reads them from
cache instead of re-reading shards — the pipeline never sees stale data and
never stalls on its own writes.
"""
from __future__ import annotations

from .extent import ExtentSet


class ExtentCache:
    def __init__(self):
        # oid -> {stripe-aligned offset interval: bytes}, flat byte map
        self._pinned: dict[str, dict[int, bytes]] = {}
        # oid -> tid -> extents pinned by that op
        self._by_op: dict[str, dict[int, ExtentSet]] = {}

    def present(self, oid: str) -> ExtentSet:
        es = ExtentSet()
        for off, buf in self._pinned.get(oid, {}).items():
            es.union_insert(off, len(buf))
        return es

    def claim(self, oid: str, tid: int, offset: int, data: bytes) -> None:
        """Pin [offset, offset+len(data)) with op tid's freshly-written bytes."""
        self._pinned.setdefault(oid, {})
        self._merge(oid, offset, bytes(data))
        self._by_op.setdefault(oid, {}).setdefault(tid, ExtentSet()) \
            .union_insert(offset, len(data))

    def _merge(self, oid: str, offset: int, data: bytes) -> None:
        spans = self._pinned[oid]
        end = offset + len(data)
        merged_off, merged = offset, bytearray(data)
        for off in sorted(list(spans)):
            buf = spans[off]
            if off + len(buf) < merged_off or off > end:
                continue
            # overlap/adjacency: splice (new data wins on overlap)
            del spans[off]
            new_off = min(off, merged_off)
            new_end = max(off + len(buf), merged_off + len(merged))
            out = bytearray(new_end - new_off)
            out[off - new_off:off - new_off + len(buf)] = buf
            out[merged_off - new_off:merged_off - new_off + len(merged)] = merged
            merged_off, merged = new_off, out
            end = merged_off + len(merged)
        spans[merged_off] = bytes(merged)

    def read(self, oid: str, offset: int, length: int) -> bytes | None:
        """The cached bytes for [offset, offset+length), or None if not fully pinned."""
        for off, buf in self._pinned.get(oid, {}).items():
            if off <= offset and offset + length <= off + len(buf):
                return buf[offset - off:offset - off + length]
        return None

    def release(self, oid: str, tid: int) -> None:
        """Drop op tid's pins; bytes stay until no op covers them."""
        ops = self._by_op.get(oid)
        if not ops or tid not in ops:
            return
        del ops[tid]
        still = ExtentSet()
        for es in ops.values():
            still = still.union(es)
        spans = self._pinned.get(oid, {})
        for off in sorted(list(spans)):
            buf = spans[off]
            del spans[off]
            # keep only sub-ranges still pinned by a live op
            for s, ln in still.intersection(
                    ExtentSet([(off, len(buf))])):
                spans[s] = buf[s - off:s - off + ln]
        if not ops:
            self._by_op.pop(oid, None)
        if not spans:
            self._pinned.pop(oid, None)
