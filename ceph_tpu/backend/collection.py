"""Per-PG collections over ONE shared per-OSD ObjectStore.

The reference OSD hosts every PG against a single ObjectStore, with each
PG's objects living in their own collection (coll_t): boot iterates the
store's collections to rediscover PGs (reference: src/osd/OSD.cc:3971
load_pgs; src/os/ObjectStore.h Collection).  :class:`Collection` gives
this framework the same topology: it exposes the full ObjectStore API of
MemStore/FileStore but namespaces every GObject into its collection, so
N PG shards on one OSD share ONE store — one WAL, one checkpoint, one
restart — while the PG backends stay collection-oblivious.
"""
from __future__ import annotations

from collections.abc import Mapping

from .memstore import GObject, Transaction


class _ObjectsView(Mapping):
    """Dict-shaped view of one collection's slice of the shared store's
    ``objects`` map, with collection prefixes stripped.  Deletion is
    supported for the fault-injection paths (tests vaporise an object to
    model silent loss)."""

    def __init__(self, coll: "Collection"):
        self._c = coll

    def __getitem__(self, g: GObject):
        return self._c.base.objects[self._c._in(g)]

    def __delitem__(self, g: GObject) -> None:
        del self._c.base.objects[self._c._in(g)]

    def __contains__(self, g) -> bool:
        return isinstance(g, GObject) and \
            self._c._in(g) in self._c.base.objects

    def __iter__(self):
        p = self._c._p
        for g in self._c.base.objects:
            if g.oid.startswith(p):
                yield self._c._out(g)

    def __len__(self) -> int:
        return sum(1 for _ in self)

# oid namespace separator: NUL-delimited like the clone oids' SNAP_SEP so
# no user-visible object name can collide with a collection prefix
COLL_SEP = "\x00c\x00"


def collection_names(store) -> set[str]:
    """Collections present in a store (OSD::load_pgs discovery: which
    PGs does this store host?)."""
    out = set()
    for g in store.list_objects():
        if COLL_SEP in g.oid:
            out.add(g.oid.split(COLL_SEP, 1)[0])
    return out


class Collection:
    """One PG's namespace inside a shared store.

    Implements the ObjectStore read/write surface the PG backends use
    (queue_transaction, read/stat/exists, attrs, omap, list_objects) by
    rewriting oids to '<cname>\\x00c\\x00<oid>'.  ``close`` is a no-op:
    the OSD daemon owns the underlying store's lifecycle.
    """

    def __init__(self, store, cname: str):
        if COLL_SEP in cname:
            raise ValueError(f"collection name {cname!r} contains the "
                             f"namespace separator")
        self.base = store
        self.cname = cname
        self._p = cname + COLL_SEP

    # -- oid mapping --------------------------------------------------------

    def _in(self, obj: GObject) -> GObject:
        return GObject(self._p + obj.oid, obj.shard)

    def _out(self, obj: GObject) -> GObject:
        return GObject(obj.oid[len(self._p):], obj.shard)

    # -- writes -------------------------------------------------------------

    def queue_transaction(self, t: Transaction) -> int:
        nt = Transaction()
        nt.ops = [tuple(self._in(x) if isinstance(x, GObject) else x
                        for x in op)
                  for op in t.ops]
        return self.base.queue_transaction(nt)

    # -- reads --------------------------------------------------------------

    def read(self, obj: GObject, offset: int = 0,
             length: int | None = None) -> bytes:
        return self.base.read(self._in(obj), offset, length)

    def stat(self, obj: GObject) -> int:
        return self.base.stat(self._in(obj))

    def exists(self, obj: GObject) -> bool:
        return self.base.exists(self._in(obj))

    def getattr(self, obj: GObject, name: str):
        return self.base.getattr(self._in(obj), name)

    def getattrs(self, obj: GObject):
        return self.base.getattrs(self._in(obj))

    def get_omap(self, obj: GObject):
        return self.base.get_omap(self._in(obj))

    def get_omap_header(self, obj: GObject) -> bytes:
        return self.base.get_omap_header(self._in(obj))

    def list_objects(self) -> list[GObject]:
        return [self._out(g) for g in self.base.list_objects()
                if g.oid.startswith(self._p)]

    @property
    def objects(self) -> "_ObjectsView":
        """Mapping view over this collection's objects (the backends use
        ``store.objects`` for direct xattr peeks and membership)."""
        return _ObjectsView(self)

    # -- lifecycle ----------------------------------------------------------

    @property
    def committed_seq(self) -> int:
        return getattr(self.base, "committed_seq", 0)

    def close(self, checkpoint: bool = True) -> None:
        """No-op: the daemon owns the shared store (PGGroup teardown must
        not checkpoint/close a store other PGs are still using)."""

    def destroy(self) -> None:
        """Remove every object in this collection from the base store
        (ObjectStore::remove_collection): a remapped PG's outgoing
        incarnation must leave nothing — a later incarnation reopening
        the same collection name would otherwise boot from the stale
        pgmeta/pg-log it left behind."""
        t = Transaction()
        for g in self.base.list_objects():
            if g.oid.startswith(self._p):
                t.remove(g)
        if not t.empty():
            self.base.queue_transaction(t)
