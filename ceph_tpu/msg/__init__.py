"""ceph_tpu.msg — the async messenger (reference: src/msg/async).

A readiness-driven transport replacing thread-per-connection serving:

- :mod:`~ceph_tpu.msg.reactor` — the event loop (selectors + timers);
- :mod:`~ceph_tpu.msg.parser` — zero-copy incremental v2-frame parsing;
- :mod:`~ceph_tpu.msg.connection` — per-socket state: framed sends with
  write-queue backpressure, readiness callbacks, fault hooks;
- :mod:`~ceph_tpu.msg.proto` — session-multiplexing frame types;
- :mod:`~ceph_tpu.msg.server` — accept + cephx handshake state machines
  + dmClock-ordered dispatch with a bounded worker pool;
- :mod:`~ceph_tpu.msg.client` — MuxClient: thousands of logical
  sessions over few connections;
- :mod:`~ceph_tpu.msg.shed` — overload shedding by dmClock op class;
- :mod:`~ceph_tpu.msg.frontend` — sharded serving engines behind
  striper-aware routing.
"""
from .connection import AsyncConnection
from .client import MuxCall, MuxClient, MuxSession
from .frontend import FrontendBusy, ShardedFrontend
from .parser import StreamParser
from .proto import RpcBatch, RpcResultBatch
from .reactor import Reactor, client_reactor
from .server import AsyncServerTransport, Dispatcher
from .shed import DEFAULT_SHED_FRACTIONS, EBUSY, ShedPolicy

__all__ = [
    "AsyncConnection", "AsyncServerTransport", "DEFAULT_SHED_FRACTIONS",
    "Dispatcher", "EBUSY", "FrontendBusy", "MuxCall", "MuxClient",
    "MuxSession", "Reactor", "RpcBatch", "RpcResultBatch", "ShardedFrontend",
    "ShedPolicy", "StreamParser", "client_reactor",
]
