"""AsyncServerTransport: the reactor-driven serving front door.

Replaces ``ClusterServer``'s thread-per-connection accept loop
(reference analog: AsyncMessenger's Processor + Worker pool replacing
SimpleMessenger's Pipe threads):

- ONE reactor thread owns the listener and every accepted connection;
  accept, banner, the full cephx handshake, frame reassembly, and
  reply writes are readiness callbacks — no per-connection threads, no
  per-request threads;
- the cephx exchange runs as a per-connection STATE MACHINE.  Because
  the KeyServer holds a single challenge slot per entity
  (``auth/cephx.py _pending``), concurrent handshakes serialize through
  a FIFO token — the async form of the old ``_auth_lock``, held across
  the exchange but never blocking the loop;
- decoded calls land in a dmClock-ordered dispatch queue drained by a
  SMALL fixed worker pool (``ms_async_op_threads``) that executes
  against the cluster and sends replies with write-queue backpressure;
- when ingest outruns dispatch, arrivals shed by op class
  (:class:`~ceph_tpu.msg.shed.ShedPolicy`): background classes bounce
  with EBUSY while client ops still queue, and nothing buffers without
  bound.

Fault semantics are bitwise-compatible with the threaded transport:
hooks arm only post-auth via the provider pattern (disarming applies to
live connections), recv-side faults (blackhole/reset) are consulted per
inner call, and a truncated/reset reply surfaces to the peer as a cut
frame + EOF.
"""
from __future__ import annotations

import socket
import threading
import time

from ..osd.mclock import (CLIENT_OP, ClientInfo, DEFAULT_OP_CLASS_INFO,
                          MClockOpClassQueue)
from .connection import AsyncConnection
from .reactor import Reactor
from .shed import EBUSY, ShedPolicy

AUTH_TIMEOUT = 10.0

# dispatch-queue QoS: keep the weights/reservations of the engine's
# class info but drop the rate LIMITS — at the dispatch tier, overload
# control is the shed ladder, not stranding queued ops on limit tags
DISPATCH_CLASS_INFO = {
    cls: ClientInfo(reservation=info.reservation, weight=info.weight,
                    limit=0.0)
    for cls, info in DEFAULT_OP_CLASS_INFO.items()
}

# handshake phases
WAIT_BEGIN = "wait_begin"
WAIT_AUTHENTICATE = "wait_authenticate"
WAIT_AUTHORIZE = "wait_authorize"
OPEN = "open"


class _AuthState:
    __slots__ = ("phase", "name", "now", "timer", "holds_token")

    def __init__(self):
        self.phase = WAIT_BEGIN
        self.name = ""
        self.now = 0.0
        self.timer = None
        self.holds_token = False


class _Listener:
    """Readiness handler for the accept socket."""

    def __init__(self, transport):
        self.transport = transport

    def wants_write(self) -> bool:
        return False

    def on_writable(self) -> None:
        pass

    def on_readable(self) -> None:
        while True:
            try:
                sock, _addr = self.transport.listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return                    # listener closed by stop()
            self.transport._accept(sock)

    def on_io_error(self, exc) -> None:
        pass


class Dispatcher:
    """dmClock-ordered dispatch queue + a bounded worker pool."""

    def __init__(self, core, n_threads: int, shed: ShedPolicy,
                 name: str = "msgr"):
        self.core = core
        self.shed = shed
        self.q = MClockOpClassQueue(DISPATCH_CLASS_INFO)
        self._cond = threading.Condition()
        self._depth = 0
        self._stopping = False
        self._n = max(1, int(n_threads))
        self._threads: list[threading.Thread] = []
        self._name = name

    def start(self) -> None:
        # the ONLY thread spawns in the serving path: a fixed pool,
        # sized by config, started once — never per connection/request
        for i in range(self._n):
            t = threading.Thread(target=self._worker,
                                 name=f"{self._name}.dispatch.{i}",
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        for t in self._threads:
            t.join(5.0)

    @property
    def depth(self) -> int:
        with self._cond:
            return self._depth

    def ingest(self, conn, msg, op_class: str) -> bool:
        """Reactor-thread arrival: queue under dmClock order, or shed by
        class with an immediate EBUSY refusal.  Never blocks.  Depth is
        measured in LOGICAL OPS (a batch frame counts its calls), so the
        shed thresholds mean the same thing batched or not."""
        n = len(msg.calls) if hasattr(msg, "calls") else 1
        with self._cond:
            depth = self._depth
        if self.shed.should_shed(op_class, depth, n=n):
            reply = self._shed_reply(msg, op_class)
            try:
                conn.send_from_reactor(reply)
            except (ConnectionError, OSError):
                pass
            return False
        with self._cond:
            self.q.enqueue(op_class, (conn, msg, n), now=time.monotonic(),
                           cost=float(n))
            self._depth += n
            self._cond.notify()
        return True

    @staticmethod
    def _shed_reply(msg, op_class: str):
        from .. import net
        from .proto import RpcResultBatch

        def one(call):
            return net.RpcResult(
                call.rid, False, None,
                f"EBUSY: shed ({op_class}) — dispatch queue over the "
                f"class threshold", EBUSY,
                trace=getattr(call, "trace", None))
        if hasattr(msg, "calls"):
            return RpcResultBatch([one(c) for c in msg.calls])
        return one(msg)

    @staticmethod
    def _stamp_batch_reply(calls, wall: float, dur: float) -> None:
        """Wire-phase spans for replies riding a batched RpcResultBatch
        frame: each riding call's trace gets one ``mux.batch_reply``
        child covering the coalesced reply serialize+enqueue (the send
        the per-method ``rpc.*`` server spans end before)."""
        from ..common import instruments
        if not instruments.enabled():
            return
        from ..common.tracer import default_tracer
        tr = default_tracer()
        for c in calls:
            ctx = getattr(c, "trace", None)
            if getattr(ctx, "trace_id", None):
                tr.complete("mux.batch_reply", wall, dur, cat="mux",
                            ctx=ctx, batched_calls=len(calls))

    def _worker(self) -> None:
        from .. import net
        from .proto import RpcResultBatch
        while True:
            with self._cond:
                item = None
                while item is None:
                    if self._depth:
                        item = self.q.dequeue(time.monotonic())
                        if item is not None:
                            self._depth -= item[2]
                            break
                        # everything queued is tag-ineligible right now
                        self._cond.wait(0.005)
                    elif self._stopping:
                        return
                    else:
                        self._cond.wait(0.5)
            conn, msg, _n = item
            if hasattr(msg, "calls"):     # RpcBatch: one worker, one frame
                reply = RpcResultBatch(
                    [self.core._dispatch(conn, c) for c in msg.calls])
            else:
                reply = self.core._dispatch(conn, msg)
            try:
                t0 = time.monotonic()
                wall = time.time()
                conn.send(reply)
                if hasattr(msg, "calls"):
                    self._stamp_batch_reply(msg.calls, wall,
                                            time.monotonic() - t0)
            except (ConnectionError, OSError):
                # link died (or an injected fault) before the reply got
                # out: results are cached under their reqids — the
                # client's resend on the next connection collects them
                pass
            # dispatcher completion boundary: fold this worker's pending
            # span batch into the ring once per frame, not per span
            from ..common.tracer import default_tracer
            default_tracer().flush()


class AsyncServerTransport:
    """Reactor + handshake state machines + dispatcher for one server.

    ``core`` is the RPC brain (``net.ClusterServer``): it provides
    ``keyserver``/``handler`` for cephx, ``_dispatch`` for execution,
    ``fault_hooks`` for injection, ``wire`` for accounting, and
    ``_note_ack``/``_conn_closed`` for notify bookkeeping.
    """

    def __init__(self, core, listener: socket.socket, *, cct=None,
                 name: str | None = None):
        self.core = core
        self.listener = listener
        port = listener.getsockname()[1]
        self.name = name or f"srv.{port}"
        conf = cct.conf if cct is not None else None

        def opt(key, default):
            return conf.get(key) if conf is not None else default
        if conf is not None:
            from .. import net
            net.wire_zero_copy_config(conf)
        # server connections land request sidebands in the pooled
        # staging buffers (the one sanctioned copy: wire -> staging)
        from .staging import default_pool
        self.staging = default_pool()
        self.reactor = Reactor(name=self.name)
        self.write_queue_bytes = int(opt("ms_async_write_queue_bytes",
                                         4 << 20))
        self.shed = ShedPolicy(int(opt("ms_async_dispatch_queue_max",
                                       1024)))
        self.dispatcher = Dispatcher(
            core, int(opt("ms_async_op_threads", 3)), self.shed,
            name=self.name)
        self._conns: set[AsyncConnection] = set()
        self._conns_lock = threading.Lock()
        # the async _auth_lock: a FIFO token serializing full cephx
        # exchanges (single challenge slot per entity in the KeyServer)
        self._auth_holder: AsyncConnection | None = None
        self._auth_fifo: list[tuple[AsyncConnection, object]] = []
        self._accepts = 0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "AsyncServerTransport":
        self.listener.setblocking(False)
        self.reactor.start()
        self.reactor.register(self.listener, _Listener(self))
        self.dispatcher.start()
        return self

    def stop(self) -> None:
        try:
            self.listener.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            c.close()
        self.dispatcher.stop()
        self.reactor.stop()

    def connections(self) -> int:
        with self._conns_lock:
            return len(self._conns)

    # -- accept + handshake state machine (reactor thread) -------------------

    def _accept(self, sock: socket.socket) -> None:
        self._accepts += 1
        conn = AsyncConnection(
            sock, self.reactor, expect_banner=True, send_banner=True,
            name=f"{self.name}.c{self._accepts}",
            on_message=self._on_message, on_closed=self._on_closed,
            write_queue_bytes=self.write_queue_bytes,
            staging=self.staging)
        conn.acct = self.core.wire
        conn.auth = _AuthState()
        conn.auth.timer = self.reactor.call_later(
            AUTH_TIMEOUT, lambda c=conn: self._auth_timeout(c))
        with self._conns_lock:
            self._conns.add(conn)

    def _auth_timeout(self, conn: AsyncConnection) -> None:
        if conn.auth.phase != OPEN:
            conn.close(ConnectionError("handshake timeout"))

    def _on_closed(self, conn: AsyncConnection, exc) -> None:
        with self._conns_lock:
            self._conns.discard(conn)
        # auth promotion mutates reactor-affine state (_auth_holder /
        # _auth_fifo, next-waiter handshake sends): off-loop closes
        # (stop(), client-thread aborts) trampoline like register()
        # does instead of racing the in-flight _auth_step
        if self.reactor.in_reactor() or not self.reactor.running:
            self._release_auth(conn)
        else:
            self.reactor.call_soon(lambda: self._release_auth(conn))
        if conn.auth.timer is not None:
            conn.auth.timer.cancel()
        self.core._conn_closed(conn)

    def _release_auth(self, conn: AsyncConnection) -> None:
        self._auth_fifo = [(c, m) for c, m in self._auth_fifo
                           if c is not conn]
        if self._auth_holder is not conn:
            return
        self._auth_holder = None
        while self._auth_fifo:
            nxt, begin = self._auth_fifo.pop(0)
            if nxt.closed:
                continue
            self._auth_holder = nxt
            self._auth_begin(nxt, begin)
            break

    def _on_message(self, conn: AsyncConnection, msg) -> None:
        from ..backend.wire import WireError
        if conn.auth.phase != OPEN:
            self._auth_step(conn, msg)
            return
        self._route(conn, msg)

    def _auth_step(self, conn: AsyncConnection, msg) -> None:
        from ..auth.cephx import AuthError
        from ..backend.wire import WireError
        try:
            self._auth_step_inner(conn, msg)
        except (WireError, AuthError, KeyError, ValueError) as e:
            conn.close(e if isinstance(e, (WireError,))
                       else ConnectionError(f"auth failed: {e}"))

    def _auth_step_inner(self, conn: AsyncConnection, msg) -> None:
        from .. import net
        from ..backend.wire import WireError
        st = conn.auth
        if st.phase == WAIT_BEGIN:
            if not isinstance(msg, net.CephxBegin):
                raise WireError("expected CephxBegin")
            if self._auth_holder is not None and \
                    self._auth_holder is not conn:
                self._auth_fifo.append((conn, msg))
                return
            self._auth_holder = conn
            self._auth_begin(conn, msg)
        elif st.phase == WAIT_AUTHENTICATE:
            if not isinstance(msg, net.CephxAuthenticate):
                raise WireError("expected CephxAuthenticate")
            env = self.core.keyserver.issue_session_key(
                st.name, msg.client_challenge, msg.proof, st.now)
            ticket_env = self.core.keyserver.issue_service_ticket(
                st.name, net.SERVICE, st.now)
            conn.send_from_reactor(net.CephxSession(env, ticket_env))
            st.phase = WAIT_AUTHORIZE
        elif st.phase == WAIT_AUTHORIZE:
            if not isinstance(msg, net.CephxAuthorize):
                raise WireError("expected CephxAuthorize")
            _name, reply = self.core.handler.verify_authorizer(
                msg.authorizer, st.now)
            _, secret = self.core.keyserver.service_secret(
                net.SERVICE, msg.authorizer.secret_id)
            from ..auth.cephx import unseal
            session_key = unseal(secret, msg.authorizer.blob)[
                "session_key"]
            # Done rides the LAST crc-mode frame; both ends switch to
            # HMAC under the service session key right after it
            conn.send_from_reactor(net.CephxDone(reply))
            conn.secure(session_key)
            st.phase = OPEN
            if st.timer is not None:
                st.timer.cancel()
            # fault injection arms only POST-auth, via a provider so
            # disarming mid-run applies to live connections too
            conn.faults = lambda: self.core.fault_hooks
            self._release_auth(conn)
        else:                             # pragma: no cover — state error
            raise WireError(f"auth message in phase {st.phase}")

    def _auth_begin(self, conn: AsyncConnection, msg) -> None:
        from .. import net
        st = conn.auth
        st.name = msg.name
        st.now = time.time()
        conn.send_from_reactor(net.CephxChallenge(
            self.core.keyserver.get_challenge(msg.name)))
        st.phase = WAIT_AUTHENTICATE

    # -- post-auth routing (reactor thread) ----------------------------------

    def _route(self, conn: AsyncConnection, msg) -> None:
        from .. import net
        from ..backend.wire import WireError
        if isinstance(msg, net.NotifyAck):
            self.core._note_ack(msg)
            return
        calls = None
        if isinstance(msg, net.RpcCall):
            calls = [msg]
        elif hasattr(msg, "calls") and type(msg).__name__ == "RpcBatch":
            calls = list(msg.calls)
        if calls is None:
            conn.close(WireError(f"unexpected {type(msg).__name__}"))
            return
        hooks = self.core.fault_hooks
        if hooks is not None:
            from ..failure.transport import RECV_BLACKHOLE, RECV_RESET
            kept = []
            for call in calls:
                act = hooks.on_recv(type(call).__name__,
                                    target=call.method)
                if act == RECV_BLACKHOLE:
                    continue              # swallowed: no reply, ever
                if act == RECV_RESET:
                    conn.close(ConnectionError("injected recv reset"))
                    return
                kept.append(call)
            calls = kept
        if not calls:
            return
        op_class = getattr(calls[0], "op_class", "") or CLIENT_OP
        if len(calls) == 1 and isinstance(msg, net.RpcCall):
            self.dispatcher.ingest(conn, calls[0], op_class)
        else:
            from .proto import RpcBatch
            self.dispatcher.ingest(conn, RpcBatch(calls), op_class)
