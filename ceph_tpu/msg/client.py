"""MuxClient: many logical sessions multiplexed over few connections.

``net.TcpRados`` is one-session-per-connection: a reader thread, a
correlation table, and a socket per client object.  That shape cannot
express 10k concurrent closed-loop clients — 10k sockets, 10k reader
threads.  MuxClient inverts it (reference analog: librados clients
sharing an AsyncMessenger worker pool):

- a :class:`MuxSession` is a LOGICAL client: a reqid namespace
  (``session`` uuid) and nothing else — thousands are cheap;
- all sessions' calls funnel through one submission queue, coalesce
  into :class:`~ceph_tpu.msg.proto.RpcBatch` frames (one pickle, one
  MAC, one syscall per admission window) and spread round-robin over a
  small fixed set of :class:`AsyncConnection`\\ s on the shared client
  reactor;
- replies correlate by globally-unique rid on the reactor thread;
  completion either sets the caller's event (sync :meth:`MuxSession.call`)
  or fires the ``cb`` (closed-loop async drivers);
- per-attempt timers (reactor ``call_later``) resend black-holed calls
  within the same ``ms_rpc_timeout`` deadline budget as TcpRados, and
  reqid-dedup on the server keeps those resends exactly-once;
- a dead connection is re-dialed by the single sender thread under
  bounded full-jitter backoff (``ms_reconnect_*``); in-flight calls
  ride their timers onto the fresh socket.

The blocking dial + cephx handshake lives in ``net.py``
(``net.dial_and_handshake``) — inside ``ceph_tpu/msg/`` sockets are
only ever touched from readiness callbacks (tests/test_no_blocking_socket
pins that), so the one legitimately-blocking step stays outside.
"""
from __future__ import annotations

import pickle
import threading
import time
import uuid

from ..common import copy_ledger, instruments
from ..osd.mclock import CLIENT_OP
from .connection import AsyncConnection
from .proto import RpcBatch
from .reactor import client_reactor
from .shed import EBUSY


class MuxCall:
    """One in-flight logical call: correlation + completion state."""

    __slots__ = ("rid", "session", "method", "args", "op_class", "trace",
                 "event", "result", "timer", "attempts", "deadline",
                 "per_attempt", "queued", "done", "cb", "t_submit")

    def __init__(self, rid, session, method, args, op_class, trace, cb):
        self.rid = rid
        self.session = session
        self.method = method
        self.args = args
        self.op_class = op_class
        self.trace = trace
        self.cb = cb
        self.event = threading.Event() if cb is None else None
        self.result = None               # RpcResult | exception
        self.timer = None
        self.attempts = 0
        self.deadline = 0.0
        self.per_attempt = 0.0
        self.queued = False
        self.done = False
        self.t_submit = 0.0

    def value(self):
        """Unwrap: the RPC's value, or raise what the call raised —
        ConnectionError/TimeoutError from the transport, IOError with
        the server's errno (EBUSY for a shed) otherwise."""
        r = self.result
        if isinstance(r, BaseException):
            raise r
        if not r.ok:
            raise IOError(r.errno or 0, r.error)
        return r.value


class MuxSession:
    """A logical client: one reqid namespace over the shared transport."""

    __slots__ = ("client", "session")

    def __init__(self, client: "MuxClient", session: str):
        self.client = client
        self.session = session

    def call_async(self, method: str, args: dict | None = None, *,
                   op_class: str = CLIENT_OP, timeout: float | None = None,
                   trace=None, cb=None) -> MuxCall:
        return self.client._submit(self.session, method, args or {},
                                   op_class=op_class, timeout=timeout,
                                   trace=trace, cb=cb)

    def call(self, method: str, args: dict | None = None, *,
             op_class: str = CLIENT_OP, timeout: float | None = None,
             trace=None):
        c = self.call_async(method, args, op_class=op_class,
                            timeout=timeout, trace=trace)
        c.event.wait(c.deadline - time.monotonic() + 1.0)
        if not c.done:
            raise TimeoutError(f"rpc {method} timed out")
        return c.value()


class MuxClient:
    """The shared transport: submission queue, batcher, connections."""

    def __init__(self, host: str, port: int, keyring, *, cct=None,
                 n_conns: int = 2, name: str = "mux"):
        from ..common import default_context
        from .. import net
        self._conf = (cct if cct is not None else default_context()).conf
        net.wire_zero_copy_config(self._conf)
        self._host, self._port = host, port
        with open(keyring, "rb") as f:
            self._key = pickle.load(f)["key"]
        self.name = name
        self.reactor = client_reactor()
        self._cond = threading.Condition()
        self._pending: dict[int, MuxCall] = {}
        self._out: list[MuxCall] = []
        self._rid = 0
        self._closed = False
        self._conns: list[AsyncConnection | None] = \
            [None] * max(1, int(n_conns))
        self._rr = 0
        self._batch_max = int(self._conf.get("ms_async_batch_max"))
        self._batch_delay = \
            float(self._conf.get("ms_async_batch_delay_ms")) / 1000.0
        self._rpc_timeout = float(self._conf.get("ms_rpc_timeout"))
        self._max_attempts = max(
            1, int(self._conf.get("ms_rpc_retry_attempts")))
        self.sessions_opened = 0
        self.reconnects = 0              # successful re-dials
        self.resends = 0                 # rpc attempts after the first
        self.timeouts = 0
        self.completed = 0
        self.sheds_seen = 0              # EBUSY refusals observed
        self.batches_sent = 0
        self.calls_sent = 0
        self._sender = threading.Thread(target=self._sender_loop,
                                        name=f"{name}.sender", daemon=True)
        self._sender.start()

    # -- sessions ------------------------------------------------------------

    def session(self) -> MuxSession:
        with self._cond:
            self.sessions_opened += 1
        return MuxSession(self, uuid.uuid4().hex)

    # -- submission ----------------------------------------------------------

    def _submit(self, session, method, args, *, op_class, timeout,
                trace, cb) -> MuxCall:
        total = self._rpc_timeout if timeout is None else float(timeout)
        with self._cond:
            if self._closed:
                raise ConnectionError("mux client closed")
            self._rid += 1
            call = MuxCall(self._rid, session, method, args, op_class,
                           trace, cb)
            call.per_attempt = max(0.05, total / self._max_attempts)
            now = time.monotonic()
            call.t_submit = now
            call.deadline = now + total
            self._pending[call.rid] = call
            call.queued = True
            self._out.append(call)
            self._cond.notify()
        call.timer = self.reactor.call_later(
            call.per_attempt, lambda: self._on_attempt_timeout(call))
        return call

    def _on_attempt_timeout(self, call: MuxCall) -> None:
        """Reactor timer: the attempt produced no reply (black-holed
        request or reply, dead link).  Resend within the deadline
        budget; reqid dedup makes the resend exactly-once."""
        rearm = False
        with self._cond:
            if call.done or self._closed:
                return
            call.attempts += 1
            now = time.monotonic()
            if now >= call.deadline or call.attempts >= self._max_attempts:
                self.timeouts += 1
                self._finish_locked(call, TimeoutError(
                    f"rpc {call.method} timed out "
                    f"after {call.attempts + 1} attempts"))
            else:
                self.resends += 1
                if not call.queued:
                    call.queued = True
                    self._out.append(call)
                    self._cond.notify()
                rearm = True
        if rearm:
            call.timer = self.reactor.call_later(
                call.per_attempt, lambda: self._on_attempt_timeout(call))
        else:
            self._signal(call)

    def _finish_locked(self, call: MuxCall, result) -> None:
        call.done = True
        call.result = result
        self._pending.pop(call.rid, None)
        if call.timer is not None:
            call.timer.cancel()

    def _signal(self, call: MuxCall) -> None:
        if call.event is not None:
            call.event.set()
        if call.cb is not None:
            try:
                call.cb(call)
            except Exception:            # noqa: BLE001 — driver callback
                pass

    # -- reply path (reactor thread) -----------------------------------------

    def _on_message(self, conn, msg) -> None:
        from .. import net
        if isinstance(msg, net.RpcResult):
            results = (msg,)
        elif type(msg).__name__ == "RpcResultBatch":
            results = msg.results
        else:
            return                       # pushes etc.: not a mux concern
        finished = []
        with self._cond:
            for r in results:
                call = self._pending.get(r.rid)
                if call is None or call.done:
                    continue             # late duplicate after a resend
                if not r.ok and r.errno == EBUSY:
                    self.sheds_seen += 1
                self.completed += 1
                self._finish_locked(call, r)
                finished.append(call)
        if instruments.enabled():
            # copy-ledger denominator: result payload bytes landing in
            # their consumer's completion (pairs with the server-side
            # request tally at dispatch)
            served = sum(len(r.value) for r in results
                         if net._sb_eligible(r.value))
            if served:
                copy_ledger.count_served(served)
        for call in finished:
            self._signal(call)

    def _on_closed(self, conn, exc) -> None:
        with self._cond:
            for i, c in enumerate(self._conns):
                if c is conn:
                    self._conns[i] = None
            # wake the sender so queued work re-dials promptly instead
            # of waiting out a batch window on a dead socket
            self._cond.notify()

    # -- sender thread -------------------------------------------------------

    def _sender_loop(self) -> None:
        from .. import net
        while True:
            with self._cond:
                while not self._out and not self._closed:
                    self._cond.wait(0.5)
                if self._closed:
                    return
                if len(self._out) < self._batch_max \
                        and self._batch_delay > 0:
                    self._cond.wait(self._batch_delay)  # coalesce window
                batch = self._out[:self._batch_max]
                del self._out[:len(batch)]
                for c in batch:
                    c.queued = False
            live = [c for c in batch if not c.done]
            if not live:
                continue
            calls = []
            for c in live:
                rc = net.RpcCall(c.rid, c.method, c.args, trace=c.trace,
                                 session=c.session)
                rc.op_class = c.op_class
                calls.append(rc)
            msg = RpcBatch(calls) if len(calls) > 1 else calls[0]
            conn = self._conn_for_send()
            if conn is None:
                # reconnect budget exhausted (or client closed): every
                # owner learns, none hangs
                self._fail_all(ConnectionError("reconnect exhausted"))
                continue
            try:
                t_send = time.monotonic()
                wall = time.time()
                conn.send(msg)
                if len(calls) > 1:
                    self._stamp_batch(live, wall,
                                      time.monotonic() - t_send,
                                      len(calls))
                with self._cond:
                    self.batches_sent += 1
                    self.calls_sent += len(calls)
            except (ConnectionError, OSError):
                # link died under the send (or an injected fault): the
                # calls stay pending; requeue them for the next socket
                with self._cond:
                    for c in live:
                        if not c.done and not c.queued:
                            c.queued = True
                            self._out.append(c)
                    self._cond.notify()

    @staticmethod
    def _stamp_batch(live, wall: float, dur: float, n: int) -> None:
        """Wire-phase spans for calls riding a batched RpcBatch frame:
        each riding call's trace gets one ``mux.batch_send`` child
        covering the coalesced serialize+enqueue, so critical-path
        attribution sees frame time the per-call rpc spans cannot."""
        from ..common import instruments
        if not instruments.enabled():
            return
        from ..common.tracer import default_tracer
        tr = default_tracer()
        for c in live:
            if getattr(c.trace, "trace_id", None):
                tr.complete("mux.batch_send", wall, dur, cat="mux",
                            ctx=c.trace, batched_calls=n)
        # sender-loop completion boundary: fold this thread's pending
        # batch into the ring once per frame, not once per riding call
        tr.flush()

    def _conn_for_send(self) -> AsyncConnection | None:
        with self._cond:
            if self._closed:
                return None
            self._rr += 1
            order = list(range(self._rr, self._rr + len(self._conns)))
        for i in order:
            slot = i % len(self._conns)
            with self._cond:
                conn = self._conns[slot]
            if conn is not None and not conn.closed:
                return conn
        # every slot is down: re-dial ONE under bounded backoff (the
        # sender is the only dialer, so this cannot stampede)
        return self._redial(order[0] % len(self._conns))

    def _redial(self, slot: int) -> AsyncConnection | None:
        from .. import net
        from ..auth.cephx import AuthError
        from ..backend.wire import WireError
        from ..failure.backoff import ExponentialBackoff, RetriesExhausted

        def dial():
            sock, session_key = net.dial_and_handshake(
                self._host, self._port, self._key)
            conn = AsyncConnection(
                sock, self.reactor, secret=session_key,
                name=f"{self.name}.{slot}",
                on_message=self._on_message, on_closed=self._on_closed)
            with self._cond:
                if self._closed:
                    conn.close()
                    raise ConnectionError("mux client closed")
                self._conns[slot] = conn
                self.reconnects += 1
            return conn
        try:
            return ExponentialBackoff(
                base=float(self._conf.get("ms_reconnect_backoff_base")),
                cap=float(self._conf.get("ms_reconnect_backoff_cap")),
                max_attempts=int(
                    self._conf.get("ms_reconnect_max_attempts")),
            ).run(dial, retry_on=(ConnectionError, OSError, AuthError,
                                  WireError))
        except (RetriesExhausted, ConnectionError, OSError, AuthError,
                WireError):
            return None

    def _fail_all(self, exc: BaseException) -> None:
        with self._cond:
            victims = [c for c in self._pending.values() if not c.done]
            for c in victims:
                self._finish_locked(c, exc)
            self._out.clear()
        for c in victims:
            self._signal(c)

    # -- stats / teardown ----------------------------------------------------

    def connect(self) -> None:
        """Eagerly dial every connection slot (optional: the sender
        dials lazily on first send otherwise)."""
        for slot in range(len(self._conns)):
            with self._cond:
                have = self._conns[slot]
            if have is None or have.closed:
                conn = self._redial(slot)
                if conn is None:
                    raise ConnectionError(
                        f"dial {self._host}:{self._port} failed")

    def live_connections(self) -> int:
        with self._cond:
            return sum(1 for c in self._conns
                       if c is not None and not c.closed)

    def stats(self) -> dict:
        with self._cond:
            return {"sessions": self.sessions_opened,
                    "pending": len(self._pending),
                    "connections": sum(
                        1 for c in self._conns
                        if c is not None and not c.closed),
                    "reconnects": self.reconnects,
                    "resends": self.resends,
                    "timeouts": self.timeouts,
                    "completed": self.completed,
                    "sheds_seen": self.sheds_seen,
                    "batches_sent": self.batches_sent,
                    "calls_sent": self.calls_sent}

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._fail_all(ConnectionError("mux client closed"))
        with self._cond:
            conns = [c for c in self._conns if c is not None]
            self._conns = [None] * len(self._conns)
        for c in conns:
            c.close()
        self._sender.join(5.0)
