"""Reactor: a readiness-driven event loop over ``selectors``.

The AsyncMessenger core (reference: src/msg/async/EventEpoll.cc,
AsyncMessenger's worker loop in src/msg/async/Stack.h): ONE thread
multiplexes every registered connection through a level-triggered
selector, so concurrency is bounded by file descriptors — not OS
threads.  Handlers are plain objects exposing readiness callbacks:

- ``on_readable()``  — the fd has bytes (or EOF) to consume;
- ``on_writable()``  — the fd can absorb queued bytes;
- ``wants_write()``  — whether EVENT_WRITE interest should be armed;
- ``on_io_error(e)`` — a callback raised; the reactor quarantines the
  handler (unregisters it) instead of dying.

Cross-thread work enters through :meth:`call_soon` (a self-pipe wakes
the selector, the reference's EventCenter::wakeup) and timed work
through :meth:`call_later` (a heap of monotonic deadlines, the
EventCenter time-event list).  Everything else — parsing, dispatch,
backpressure — lives in the handlers; the loop only moves readiness.
"""
from __future__ import annotations

import heapq
import itertools
import os
import selectors
import threading
import time


class Timer:
    """A cancellable :meth:`Reactor.call_later` handle."""

    __slots__ = ("when", "fn", "cancelled")

    def __init__(self, when: float, fn):
        self.when = when
        self.fn = fn
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class Reactor:
    """One event-loop thread over a ``selectors.DefaultSelector``."""

    def __init__(self, name: str = "msgr"):
        self.name = name
        self._sel = selectors.DefaultSelector()
        self._lock = threading.Lock()
        self._soon: list = []
        self._timers: list = []                  # heap of (when, seq, Timer)
        self._seq = itertools.count()
        self._stop = threading.Event()
        self._started = threading.Event()
        self._thread: threading.Thread | None = None
        # self-pipe: call_soon from another thread interrupts select()
        self._wake_r, self._wake_w = os.pipe()
        os.set_blocking(self._wake_r, False)
        os.set_blocking(self._wake_w, False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, None)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Reactor":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name=f"reactor.{self.name}", daemon=True)
            self._thread.start()
            self._started.wait(5.0)
        return self

    def stop(self, join: bool = True) -> None:
        self._stop.set()
        self._wakeup()
        t = self._thread
        if join and t is not None and t is not threading.current_thread():
            t.join(5.0)

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive() and not self._stop.is_set()

    def in_reactor(self) -> bool:
        return threading.current_thread() is self._thread

    # -- registration (reactor-thread-affine; routed via call_soon) ----------

    def register(self, sock, handler) -> None:
        """Arm readiness callbacks for ``sock``.  Safe from any thread:
        off-loop callers are trampolined through :meth:`call_soon` so the
        selector is only mutated on the loop."""
        if self.in_reactor() or not self.running:
            self._register(sock, handler)
        else:
            self.call_soon(lambda: self._register(sock, handler))

    def _register(self, sock, handler) -> None:
        mask = selectors.EVENT_READ
        if handler.wants_write():
            mask |= selectors.EVENT_WRITE
        try:
            self._sel.register(sock, mask, handler)
        except KeyError:                  # re-register = interest update
            self._sel.modify(sock, mask, handler)

    def unregister(self, sock) -> None:
        if self.in_reactor() or not self.running:
            self._unregister(sock)
        else:
            self.call_soon(lambda: self._unregister(sock))

    def _unregister(self, sock) -> None:
        try:
            self._sel.unregister(sock)
        except (KeyError, ValueError, OSError):
            pass

    def update_interest(self, sock, handler) -> None:
        """Re-derive the EVENT_WRITE mask from ``handler.wants_write()``
        (called after a send queues bytes or a flush drains them)."""
        if self.in_reactor() or not self.running:
            self._update(sock, handler)
        else:
            self.call_soon(lambda: self._update(sock, handler))

    def _update(self, sock, handler) -> None:
        mask = selectors.EVENT_READ
        if handler.wants_write():
            mask |= selectors.EVENT_WRITE
        try:
            self._sel.modify(sock, mask, handler)
        except (KeyError, ValueError, OSError):
            pass

    # -- cross-thread entry points -------------------------------------------

    def call_soon(self, fn) -> None:
        with self._lock:
            self._soon.append(fn)
        self._wakeup()

    def call_later(self, delay: float, fn) -> Timer:
        t = Timer(time.monotonic() + max(0.0, delay), fn)
        with self._lock:
            heapq.heappush(self._timers, (t.when, next(self._seq), t))
        self._wakeup()
        return t

    def _wakeup(self) -> None:
        try:
            os.write(self._wake_w, b"x")
        except (BlockingIOError, OSError):
            pass                          # pipe full = wakeup already queued

    # -- the loop ------------------------------------------------------------

    def _poll_timeout(self) -> float | None:
        with self._lock:
            if self._soon:
                return 0.0
            while self._timers and self._timers[0][2].cancelled:
                heapq.heappop(self._timers)
            if not self._timers:
                return None
            return max(0.0, self._timers[0][0] - time.monotonic())

    def _run(self) -> None:
        self._started.set()
        while not self._stop.is_set():
            try:
                events = self._sel.select(self._poll_timeout())
            except OSError:
                continue                  # an fd closed under select()
            for key, mask in events:
                if key.data is None:      # the wake pipe
                    try:
                        while os.read(self._wake_r, 4096):
                            pass
                    except (BlockingIOError, OSError):
                        pass
                    continue
                handler = key.data
                try:
                    if mask & selectors.EVENT_READ:
                        handler.on_readable()
                    if mask & selectors.EVENT_WRITE:
                        handler.on_writable()
                except Exception as e:     # noqa: BLE001 — loop must live
                    self._unregister(key.fileobj)
                    try:
                        handler.on_io_error(e)
                    except Exception:      # noqa: BLE001
                        pass
            self._run_ready()
        self._drain_on_stop()

    def _run_ready(self) -> None:
        now = time.monotonic()
        due, soon = [], []
        with self._lock:
            while self._timers and self._timers[0][0] <= now:
                _, _, t = heapq.heappop(self._timers)
                if not t.cancelled:
                    due.append(t)
            soon, self._soon = self._soon, []
        for t in due:
            try:
                t.fn()
            except Exception:              # noqa: BLE001 — loop must live
                pass
        for fn in soon:
            try:
                fn()
            except Exception:              # noqa: BLE001
                pass

    def _drain_on_stop(self) -> None:
        """Final sweep so close callbacks queued behind stop() still run."""
        self._run_ready()
        try:
            self._sel.close()
        except OSError:
            pass
        for fd in (self._wake_r, self._wake_w):
            try:
                os.close(fd)
            except OSError:
                pass


# -- shared client reactor ---------------------------------------------------
#
# Client handles (TcpRados, MuxClient) share ONE process-wide reactor:
# a process holding N client connections costs one loop thread, not N
# reader threads (the bounded-thread contract tests pin).

_client_reactor: Reactor | None = None
_client_reactor_lock = threading.Lock()


def client_reactor() -> Reactor:
    global _client_reactor
    with _client_reactor_lock:
        if _client_reactor is None or not _client_reactor.running:
            _client_reactor = Reactor(name="client").start()
        return _client_reactor
