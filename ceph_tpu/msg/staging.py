"""Pooled staging buffers: where sideband payloads make their ONE copy.

The zero-copy receive path (ISSUE 20 layer a) lands a batch frame's raw
payload segment here: the :class:`~ceph_tpu.msg.parser.StreamParser`'s
memoryviews die at the next ``feed``, so anything that crosses the
reactor -> dispatch-worker boundary must move into a buffer the parser
does not own.  That move is the one sanctioned copy between socket and
device — it reports to the copy ledger as ``staging`` — and everything
downstream (dispatch handlers, the codec pack, the echoed reply's
write-queue splice) works on memoryview slices of the staged buffer.

Lifetime is GC-owned, deliberately: a staged buffer may simultaneously
be aliased by a dispatch handler's args, by the reqid-dedup cache's
retained RpcResult, and by a reply frame sitting in a connection write
queue behind a slow peer.  Each alias is a memoryview holding the
underlying bytearray alive, so dropping the last view frees the buffer
— whereas an explicit recycle would have to prove none of those aliases
remain (the classic reuse-after-splice corruption).  The pool therefore
recycles only buffers a caller *explicitly* hands back via
:meth:`recycle` after severing every view, and the hot path never does;
the size-class freelist exists for bounded, provably-single-owner uses
(the coalescer's pack scratch), not for wire payloads.
"""
from __future__ import annotations

import threading

from ..common import copy_ledger

# freelist size classes: powers of two from 4 KiB to 1 MiB; larger
# buffers always allocate fresh (rare, and pinning MiBs in a freelist
# is worse than the malloc)
_MIN_CLASS = 12
_MAX_CLASS = 20
_PER_CLASS = 8


class StagingPool:
    """Size-classed bytearray lease pool with copy-ledger accounting."""

    def __init__(self, name: str = "staging"):
        self.name = name
        self._lock = threading.Lock()
        self._free: dict[int, list[bytearray]] = {}
        self.stats = {"staged_bytes": 0, "staged_buffers": 0,
                      "reused": 0, "allocated": 0}

    def _class_of(self, n: int) -> int | None:
        if n <= 0:
            return None
        c = max((n - 1).bit_length(), _MIN_CLASS)
        return c if c <= _MAX_CLASS else None

    def lease(self, n: int) -> bytearray:
        """A writable buffer of exactly ``n`` bytes (sliced view of a
        size-class buffer when one is free)."""
        c = self._class_of(n)
        if c is not None:
            with self._lock:
                bucket = self._free.get(c)
                if bucket:
                    self.stats["reused"] += 1
                    buf = bucket.pop()
                    # bytearray resize is O(1) shrink within capacity;
                    # safe: recycled buffers have no exported views
                    del buf[n:]
                    return buf
        with self._lock:
            self.stats["allocated"] += 1
        return bytearray(n)

    def recycle(self, buf: bytearray) -> None:
        """Return a buffer whose every view has been severed.  Callers
        must be the provable sole owner — see the module docstring."""
        c = self._class_of(len(buf))
        if c is None:
            return
        try:
            buf += b"\x00" * ((1 << c) - len(buf))   # restore capacity
        except BufferError:
            return                       # a view survives: not reusable
        with self._lock:
            bucket = self._free.setdefault(c, [])
            if len(bucket) < _PER_CLASS:
                bucket.append(buf)

    def stage(self, view, source: str = "staging") -> memoryview:
        """Copy one wire segment into a staged buffer (THE copy) and
        return a read-write memoryview over it."""
        n = len(view)
        buf = self.lease(n)
        buf[:] = view
        with self._lock:
            self.stats["staged_bytes"] += n
            self.stats["staged_buffers"] += 1
        copy_ledger.count_copy(source, n)
        return memoryview(buf)


_DEFAULT = StagingPool()


def default_pool() -> StagingPool:
    """The process-global pool the async server's connections stage
    request sidebands into."""
    return _DEFAULT
