"""AsyncConnection: one non-blocking framed socket on a reactor.

The per-connection half of the async messenger (reference:
src/msg/async/AsyncConnection.cc): the reactor delivers readiness, this
object turns it into frames —

- **receive**: ``on_readable`` drains the socket into the zero-copy
  :class:`~ceph_tpu.msg.parser.StreamParser`; each decoded message is
  handed to ``on_message(conn, msg)`` ON the reactor thread (keep those
  callbacks non-blocking: correlation-table pokes, queue enqueues);
- **send**: any thread may :meth:`send`; the encoded frame enters a
  bounded write queue whose byte budget is an ``exec/throttle.Throttle``
  — a slow or dead peer therefore backpressures senders through the
  SAME admission primitive the serving engine throttles with, instead
  of buffering without bound.  ``on_writable`` flushes queued
  memoryviews with partial-send slicing and releases throttle budget as
  bytes reach the kernel;
- **faults**: the ``faults`` zero-arg provider mirrors ``net.Channel``
  exactly (armed post-auth by the server; delay/truncate/reset on send
  consult the same seeded streams), so chaos campaigns see identical
  semantics on the async stack.

Sends from the reactor thread itself (handshake replies, shed
refusals) use :meth:`send_from_reactor`: unthrottled and fault-exempt,
because the loop must never block on its own write budget.
"""
from __future__ import annotations

import socket
import threading

from ..backend.wire import WireError, frame_encode  # noqa: F401
from ..common import wire_accounting
from ..exec.throttle import Throttle
from .parser import StreamParser

RECV_SIZE = 256 * 1024
DEFAULT_WRITE_QUEUE_BYTES = 4 << 20
SEND_TIMEOUT = 5.0

# vectored drain: gather up to this many queue entries / bytes into one
# sendmsg(2) — a sideband frame is several unjoined views (head, payload
# splices, tail), and per-entry send() would pay one syscall per view
_SENDMSG_MAX_BUFS = 64
_SENDMSG_MAX_BYTES = 1 << 20
_HAS_SENDMSG = hasattr(socket.socket, "sendmsg")


class AsyncConnection:
    """One framed, reactor-driven socket endpoint (Channel's async twin:
    same ``stats``/``acct``/``faults``/``secret`` surface)."""

    def __init__(self, sock: socket.socket, reactor, *,
                 secret: bytes | None = None, expect_banner: bool = False,
                 name: str = "conn", on_message=None, on_closed=None,
                 write_queue_bytes: int = DEFAULT_WRITE_QUEUE_BYTES,
                 send_banner: bool = False, register: bool = True,
                 staging=None):
        self.sock = sock
        self.reactor = reactor
        self.name = name
        self.secret = secret
        # sideband landing policy (net._decode): a msg/staging pool on
        # server connections (handlers get pooled views), None on
        # client/handshake connections (completions get owned bytes)
        self.staging = staging
        self.parser = StreamParser(secret, expect_banner=expect_banner)
        self.on_message = on_message
        self.on_closed = on_closed
        self.stats = {"tx_msgs": 0, "tx_bytes": 0,
                      "rx_msgs": 0, "rx_bytes": 0}
        self.acct = None
        self.faults = None
        self._wlock = threading.Lock()
        self._wq: list = []              # [[memoryview, throttled_left]]
        self._close_after_flush = False
        self._closed = False
        self._close_exc: BaseException | None = None
        self.wthrottle = Throttle(f"msgr.wq.{name}",
                                  int(write_queue_bytes))
        sock.setblocking(False)
        if send_banner:
            from ..backend.wire import BANNER
            self._enqueue_locked_entry(memoryview(BANNER), 0)
        if register:
            reactor.register(sock, self)

    # -- protocol state ------------------------------------------------------

    def secure(self, key: bytes) -> None:
        """Post-auth switch to HMAC frames, both directions."""
        self.secret = key
        self.parser.set_secret(key)

    @property
    def closed(self) -> bool:
        return self._closed

    # -- send path -----------------------------------------------------------

    def _encode(self, msg) -> bytes:
        from .. import net
        return net._encode(msg, self.secret)

    def _encode_parts(self, msg):
        from .. import net
        return net._encode_parts(msg, self.secret)

    def _send_parts(self, msg, parts: list, timeout: float) -> None:
        """Enqueue one frame as multiple write-queue entries (payload
        views unjoined).  Entries land atomically under _wlock, so
        concurrent senders cannot interleave mid-frame; each entry
        carries its own byte count as throttle budget, so partial-send
        release and close-time accounting stay exact per entry."""
        total = sum(len(p) for p in parts)
        if not self.wthrottle.get(total, timeout=timeout):
            self.close(ConnectionError(
                f"{self.name}: write backpressure timeout"))
            raise ConnectionError(f"{self.name}: write queue full")
        if self._closed:
            self.wthrottle.put(total)
            raise ConnectionError(f"{self.name}: connection closed")
        with self._wlock:
            self._stats_tx(total)
            for p in parts:
                self._enqueue_locked_entry(
                    p if isinstance(p, memoryview) else memoryview(p),
                    len(p))
        self._account_tx(msg, total)
        self.reactor.update_interest(self.sock, self)

    def _stats_tx(self, nbytes: int) -> None:
        # plain-dict read-modify-write: callers hold _wlock (pairs with
        # the rx bumps in on_readable)
        self.stats["tx_msgs"] += 1
        self.stats["tx_bytes"] += nbytes

    def _account_tx(self, msg, nbytes: int) -> None:
        # the accountant path runs OUTSIDE _wlock: perf-counter updates
        # need no caller lock (sharded cells), and instrument work under
        # the write lock is the contention class ceph-lint's
        # instrument-under-lock rule exists to keep out
        if self.acct is not None:
            ctx = getattr(msg, "trace", None)
            if ctx is None and type(msg).__name__ in (
                    "RpcBatch", "RpcResultBatch"):
                from .proto import batch_trace_ctx
                ctx = batch_trace_ctx(msg)
            if ctx is None:
                from ..common.tracer import default_tracer
                ctx = default_tracer().current_ctx()
            self.acct.account_msg(msg, nbytes=nbytes, ctx=ctx)

    def send(self, msg, timeout: float = SEND_TIMEOUT) -> None:
        """Thread-safe framed send with write-queue backpressure.  May
        block up to ``timeout`` for throttle budget; raises
        ConnectionError on a closed link, an injected transport fault,
        or exhausted backpressure budget (peer stopped reading)."""
        if self._closed:
            raise ConnectionError(f"{self.name}: connection closed")
        hooks = self.faults() if self.faults is not None else None
        if hooks is None:
            # zero-copy fast path: payload-bearing frames splice their
            # payload views into the write queue unjoined (ISSUE 20
            # layer d).  Fault campaigns (hooks armed) keep the single-
            # buffer frame so truncate/reset see one contiguous image.
            parts = self._encode_parts(msg)
            if parts is not None:
                self._send_parts(msg, parts, timeout)
                return
        data = self._encode(msg)
        action = "ok"
        if hooks is not None:
            action = hooks.on_send(type(msg).__name__, len(data),
                                   target=type(msg).__name__)
        if not self.wthrottle.get(len(data), timeout=timeout):
            # the peer stopped draining for a whole budget window: the
            # link is as good as dead — close so readers learn too
            self.close(ConnectionError(
                f"{self.name}: write backpressure timeout"))
            raise ConnectionError(f"{self.name}: write queue full")
        if self._closed:
            self.wthrottle.put(len(data))
            raise ConnectionError(f"{self.name}: connection closed")
        from ..failure.transport import SEND_TRUNCATE
        if action == "ok":
            with self._wlock:
                self._stats_tx(len(data))
                self._enqueue_locked_entry(memoryview(data), len(data))
            self._account_tx(msg, len(data))
            self.reactor.update_interest(self.sock, self)
            return
        # injected transport failure: partial frame (truncate) or
        # nothing, then an abrupt close — the peer must reconnect+resend
        self.wthrottle.put(len(data))
        if action == SEND_TRUNCATE:
            half = data[:max(1, len(data) // 2)]
            with self._wlock:
                self._stats_tx(len(data))
                self._enqueue_locked_entry(memoryview(half), 0)
                self._close_after_flush = True
            self._account_tx(msg, len(data))
            self.reactor.update_interest(self.sock, self)
        else:
            self.close(ConnectionError("injected connection reset"))
        raise ConnectionError(f"injected connection {action}")

    def send_from_reactor(self, msg) -> None:
        """Unthrottled, fault-exempt enqueue for the reactor's own frames
        (handshake steps, shed refusals): the loop must never block on
        its own write budget, and a reconnecting peer's handshake is
        never faulted."""
        if self._closed:
            raise ConnectionError(f"{self.name}: connection closed")
        data = self._encode(msg)
        with self._wlock:
            self._stats_tx(len(data))
            self._enqueue_locked_entry(memoryview(data), 0)
        self._account_tx(msg, len(data))
        self.reactor.update_interest(self.sock, self)

    def _enqueue_locked_entry(self, mv: memoryview, throttled: int) -> None:
        self._wq.append([mv, throttled])

    def wants_write(self) -> bool:
        return bool(self._wq)

    # -- readiness callbacks (reactor thread) --------------------------------

    def on_readable(self) -> None:
        try:
            data = self.sock.recv(RECV_SIZE)
        except (BlockingIOError, InterruptedError):
            return
        except OSError as e:
            self.close(ConnectionError(f"recv failed: {e}"))
            return
        if not data:
            self.close(ConnectionError("peer closed"))
            return
        try:
            frames = self.parser.feed(data)
        except WireError as e:
            self.close(e)
            return
        sizes = self.parser.frame_sizes
        self.parser.frame_sizes = []
        for i, (tag, segs) in enumerate(frames):
            try:
                msg = self._decode(tag, segs)
            except WireError as e:
                self.close(e)
                return
            nbytes = sizes[i] if i < len(sizes) else \
                sum(len(s) for s in segs) + wire_accounting.MSG_OVERHEAD
            # tx bumps run under _wlock on sender threads; take it here
            # too so the read-modify-write pairs can't lose updates
            with self._wlock:
                self.stats["rx_msgs"] += 1
                self.stats["rx_bytes"] += nbytes
            if self.acct is not None:
                self.acct.account_rx(type(msg).__name__, nbytes,
                                     ctx=getattr(msg, "trace", None))
            if self.on_message is not None:
                self.on_message(self, msg)
            if self._closed:
                return

    def _decode(self, tag, segs):
        from .. import net
        return net._decode(tag, segs, authed=self.secret is not None,
                           staging=self.staging)

    def on_writable(self) -> None:
        released = 0
        err: BaseException | None = None
        with self._wlock:
            while self._wq:
                if _HAS_SENDMSG:
                    bufs, cap = [], 0
                    for e in self._wq:
                        bufs.append(e[0])
                        cap += len(e[0])
                        if len(bufs) >= _SENDMSG_MAX_BUFS or \
                                cap >= _SENDMSG_MAX_BYTES:
                            break
                    send = lambda: self.sock.sendmsg(bufs)  # noqa: E731
                else:
                    cap = len(self._wq[0][0])
                    send = lambda: self.sock.send(self._wq[0][0])  # noqa: E731
                try:
                    n = send()
                except (BlockingIOError, InterruptedError):
                    break
                except OSError as e:
                    err = ConnectionError(f"send failed: {e}")
                    break
                full = n >= cap
                # walk the sent count across entries (a gathered send
                # can complete several and split the last)
                while self._wq:
                    mv, throttled = self._wq[0]
                    take = min(n, len(mv))
                    if throttled:
                        rel = min(take, throttled)
                        self._wq[0][1] -= rel
                        released += rel
                    if take == len(mv):
                        self._wq.pop(0)
                    else:
                        self._wq[0][0] = mv[take:]
                    n -= take
                    if n <= 0:
                        break
                if not full:
                    break
            drained = not self._wq
        if released:
            self.wthrottle.put(released)
        if err is not None:
            self.close(err)
            return
        if drained:
            self.reactor.update_interest(self.sock, self)
            if self._close_after_flush:
                self.close(ConnectionError("injected connection truncate"))

    def on_io_error(self, exc: BaseException) -> None:
        self.close(exc if isinstance(exc, (ConnectionError, WireError))
                   else ConnectionError(f"io error: {exc!r}"))

    # -- teardown ------------------------------------------------------------

    def close(self, exc: BaseException | None = None) -> None:
        """Idempotent, any-thread teardown: shut the socket down NOW (the
        peer sees EOF immediately), release queued write budget, then
        let the reactor drop its registration."""
        with self._wlock:
            if self._closed:
                return
            self._closed = True
            self._close_exc = exc
            held = sum(t for _, t in self._wq)
            self._wq.clear()
        if held:
            self.wthrottle.put(held)
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        if self.reactor.running and not self.reactor.in_reactor():
            self.reactor.call_soon(self._finish_close)
        else:
            self._finish_close()
        cb, self.on_closed = self.on_closed, None
        if cb is not None:
            cb(self, exc)

    def _finish_close(self) -> None:
        self.reactor.unregister(self.sock)
        try:
            self.sock.close()
        except OSError:
            pass
