"""Zero-copy incremental frame parsing for the async messenger.

Bitwise-compatible with ``backend/wire.py``'s v2 framing (same preamble
struct, crc32c epilogue in crc mode, truncated HMAC-SHA256 in secure
mode) but built for a readiness-driven receive path:

- bytes accumulate in ONE growable buffer consumed by an offset head
  pointer, so a frame spanning many ``recv`` chunks is never re-copied
  per feed (``FrameParser`` re-slices its bytearray on every parse);
- segments come back as ``memoryview`` slices into that buffer —
  valid until the next :meth:`feed` — so decode paths (``bytes.decode``
  on the type-name segment, ``pickle.loads`` on the payload) read the
  receive buffer in place;
- the connection banner is part of the stream state (state machine
  step 0), not a caller-side special case.

The buffer compacts only when the consumed head outgrows half the
buffer — amortized O(bytes), no per-frame copies.
"""
from __future__ import annotations

import hmac
from hashlib import sha256

import numpy as np

from ..backend.ecutil import crc32c
from ..backend.wire import (BANNER, MAX_SEGMENTS, WireError, _CRC,
                            _MAC_LEN, _PREAMBLE)
from ..common import copy_ledger

_COMPACT_MIN = 1 << 16


def _crc(data) -> int:
    # np.frombuffer is a zero-copy view of the receive buffer — the
    # segment checksum never materializes payload bytes (the native
    # crc kernel reads pointer+length in place)
    return crc32c(0xFFFFFFFF,
                  np.frombuffer(data, dtype=np.uint8)) ^ 0xFFFFFFFF


class StreamParser:
    """Incremental v2-frame parser with an offset-consumed buffer.

    ``feed(data)`` returns ``[(tag, [memoryview, ...]), ...]``; the
    memoryviews alias the internal buffer and must be consumed before
    the next ``feed``.  ``frame_sizes`` mirrors ``FrameParser``'s
    ``track_sizes`` contract: real on-wire length per parsed frame, in
    order, drained by the caller.
    """

    def __init__(self, secret: bytes | None = None, *,
                 expect_banner: bool = False):
        self.secret = secret
        self._buf = bytearray()
        self._pos = 0
        self._banner_pending = expect_banner
        self.frame_sizes: list[int] = []

    def set_secret(self, key: bytes | None) -> None:
        """Switch crc mode <-> secure mode mid-stream (the post-auth
        handoff).  Buffered-but-unparsed bytes are KEPT — the strictly
        request/response handshake leaves the buffer empty here, but a
        pipelined peer's first secure frame must not be dropped."""
        self.secret = key

    def pending(self) -> int:
        return len(self._buf) - self._pos

    def feed(self, data) -> list:
        # compact BEFORE handing out new views: last feed's memoryviews
        # are dead by now, so the resize is safe — and if a caller
        # retained one anyway, fall back to a fresh buffer rather than
        # surfacing BufferError on the hot path
        self._maybe_compact()
        try:
            self._buf += data
        except BufferError:
            # retained views pin the buffer: rebuild.  This copies the
            # unconsumed tail AND the new bytes — report both to the
            # copy ledger so bytes_copied_per_byte_served cannot
            # undercount the parser's own copies (ISSUE 20 satellite 1)
            copy_ledger.count_copy(
                "fallback", (len(self._buf) - self._pos) + len(data))
            self._buf = self._buf[self._pos:] + bytes(data)
            self._pos = 0
        frames = []
        while True:
            f = self._try_parse()
            if f is None:
                break
            frames.append(f)
        return frames

    def _maybe_compact(self) -> None:
        if self._pos > _COMPACT_MIN and self._pos * 2 > len(self._buf):
            try:
                moved = len(self._buf) - self._pos
                del self._buf[:self._pos]
                self._pos = 0
                # amortized head-trim moves the unconsumed tail down —
                # the parser's only steady-state copy; count it so the
                # ledger's ratio carries the true parser overhead
                copy_ledger.count_copy("compaction", moved)
            except BufferError:
                pass                     # retained views pin the buffer

    def _try_parse(self):
        if self._banner_pending:
            if len(self._buf) - self._pos < len(BANNER):
                return None
            view = memoryview(self._buf)
            if view[self._pos:self._pos + len(BANNER)] != BANNER:
                raise WireError("banner mismatch")
            self._pos += len(BANNER)
            self._banner_pending = False
        head = _PREAMBLE.size + _CRC.size
        avail = len(self._buf) - self._pos
        if avail < head:
            return None
        view = memoryview(self._buf)
        pre = view[self._pos:self._pos + _PREAMBLE.size]
        (want_crc,) = _CRC.unpack_from(view, self._pos + _PREAMBLE.size)
        if _crc(pre) != want_crc:
            raise WireError("preamble crc mismatch")
        tag, nseg, _flags, *lens = _PREAMBLE.unpack(pre)
        if not 1 <= nseg <= MAX_SEGMENTS:
            raise WireError(f"bad segment count {nseg}")
        seg_lens = lens[:nseg]
        body = sum(seg_lens)
        tail = _MAC_LEN if self.secret is not None else _CRC.size * nseg
        total = head + body + tail
        if avail < total:
            return None
        segs, off = [], self._pos + head
        for ln in seg_lens:
            segs.append(view[off:off + ln])
            off += ln
        if self.secret is None:
            for i, s in enumerate(segs):
                (want,) = _CRC.unpack_from(view, off + i * _CRC.size)
                if _crc(s) != want:
                    raise WireError(f"segment {i} crc mismatch")
        else:
            want = bytes(view[off:off + _MAC_LEN])
            h = hmac.new(self.secret, pre, sha256)
            for s in segs:               # incremental: no segment join
                h.update(s)
            if not hmac.compare_digest(want, h.digest()[:_MAC_LEN]):
                raise WireError("frame MAC mismatch")
        self._pos += total
        self.frame_sizes.append(total)
        return tag, segs
