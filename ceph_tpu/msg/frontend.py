"""Sharded serving front end: per-OSD engines behind one admission door.

One :class:`~ceph_tpu.exec.engine.ServingEngine` per OSD shard
(reference analog: the OSD's sharded op work queue — osd_op_num_shards),
so codec work for different placement targets batches and throttles
independently instead of convoying through one queue.  The front end
adds:

- **striper-aware routing**: a striped logical object's pieces
  (``piece_name(soid, idx)``) route by the SAME placement the data
  plane uses — a locate callable (normally the cluster's
  ``object_pg(...).acting[0]``) — so a stripe fans its pieces across
  shards and a whole-object write becomes per-shard batched encodes;
- **overload shedding by dmClock class** on the way IN: each shard's
  dispatch depth is measured against the shed ladder
  (:class:`~ceph_tpu.msg.shed.ShedPolicy`), and over-threshold arrivals
  raise :class:`FrontendBusy` (EBUSY) instead of queuing — background
  classes bounce first, client ops only at the hard limit.  The
  engine's own throttles still backpressure admitted work; the ladder
  is the REFUSAL tier above them.
"""
from __future__ import annotations

import threading

from ..backend.ecutil import crc32c
from ..client.striper import piece_name
from ..osd.mclock import CLIENT_OP
from .shed import EBUSY, ShedPolicy


class FrontendBusy(IOError):
    """An arrival shed by class: explicit EBUSY refusal, queue untouched."""

    def __init__(self, shard, op_class: str, depth: int, threshold: int):
        super().__init__(
            EBUSY,
            f"shard {shard}: shed {op_class} (depth {depth} >= "
            f"threshold {threshold})")
        self.shard = shard
        self.op_class = op_class


class ShardedFrontend:
    """Route + shed + submit over ``{shard_id: ServingEngine}``."""

    def __init__(self, shards: dict, locate=None, *,
                 queue_limit: int = 256, shed_fractions: dict | None = None):
        if not shards:
            raise ValueError("frontend needs at least one shard")
        self.shards = dict(shards)
        self._ids = sorted(self.shards)
        self._locate = locate
        self._lock = threading.Lock()
        self.shed = {sid: ShedPolicy(queue_limit, shed_fractions)
                     for sid in self._ids}
        self.routed = {sid: 0 for sid in self._ids}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ShardedFrontend":
        for eng in self.shards.values():
            eng.start()
        return self

    def stop(self) -> None:
        for eng in self.shards.values():
            eng.stop()

    def flush(self, timeout: float | None = 60.0) -> None:
        for eng in self.shards.values():
            eng.flush(timeout)

    # -- routing -------------------------------------------------------------

    def shard_for(self, name: str):
        """The shard owning ``name``: the data plane's placement when a
        locate callable is wired (``object_pg(...).acting[0]``), else a
        stable crc32c hash over the shard set."""
        if self._locate is not None:
            sid = self._locate(name)
            if sid in self.shards:
                return sid
        h = crc32c(0, name.encode()) if isinstance(name, str) \
            else crc32c(0, bytes(name))
        return self._ids[h % len(self._ids)]

    def stripe_routes(self, soid: str, length: int, *,
                      stripe_unit: int = 65536, stripe_count: int = 4,
                      object_size: int = 1 << 20) -> list:
        """[(piece name, shard id, [(piece off, logical off, n)])] for a
        striped object of ``length`` bytes — the striper's layout math
        joined with this front end's placement."""
        from ..client.striper import RadosStriper
        lay = RadosStriper(_NullIo(), stripe_unit, stripe_count,
                           object_size)
        return [(piece_name(soid, idx), self.shard_for(piece_name(soid, idx)),
                 extents)
                for idx, extents in lay._piece_extents(length)]

    # -- admission -----------------------------------------------------------

    def _admit(self, name: str, op_class: str):
        sid = self.shard_for(name)
        eng = self.shards[sid]
        depth = eng.depths()["_total"]
        policy = self.shed[sid]
        if policy.should_shed(op_class, depth):
            raise FrontendBusy(sid, op_class, depth,
                               policy.threshold(op_class))
        with self._lock:
            self.routed[sid] += 1
        return sid, eng

    def serve_read(self, name: str, reader, op_class: str = CLIENT_OP):
        """Admit one already-resident read (a cache-tier hit) through
        the owning shard's shed ladder, then run ``reader()`` inline;
        returns ``(shard_id, reader())``.  The hit costs no codec
        dispatch, but it still competes for admission — an overloaded
        shard sheds tier hits by class exactly like codec work (raises
        :class:`FrontendBusy`) instead of letting the "free" path
        bypass overload control."""
        sid, _eng = self._admit(name, op_class)
        return sid, reader()

    def submit_encode(self, name: str, buf, op_class: str = CLIENT_OP,
                      **kw):
        """Admit one encode on the owning shard; returns
        ``(shard_id, BatchFuture)``.  Raises :class:`FrontendBusy` when
        the class is over its shed threshold."""
        sid, eng = self._admit(name, op_class)
        return sid, eng.submit_encode(buf, op_class, **kw)

    def submit_decode(self, name: str, chunks: dict,
                      op_class: str = CLIENT_OP, **kw):
        sid, eng = self._admit(name, op_class)
        return sid, eng.submit_decode(chunks, op_class, **kw)

    def submit_striped_encode(self, soid: str, data, *,
                              op_class: str = CLIENT_OP,
                              stripe_unit: int = 65536,
                              stripe_count: int = 4,
                              object_size: int = 1 << 20, **kw) -> list:
        """Stripe ``data`` and submit each piece's encode on ITS shard;
        returns ``[(piece name, shard id, BatchFuture)]``.  A shed on
        any piece aborts the whole submission (no partial stripes) —
        callers retry the object, not a piece."""
        data = bytes(data)
        routes = self.stripe_routes(soid, len(data),
                                    stripe_unit=stripe_unit,
                                    stripe_count=stripe_count,
                                    object_size=object_size)
        out = []
        for pname, sid, extents in routes:
            buf = bytearray()
            for p_off, l_off, n in extents:
                if len(buf) < p_off + n:
                    buf.extend(b"\0" * (p_off + n - len(buf)))
                buf[p_off:p_off + n] = data[l_off:l_off + n]
            sid2, eng = self._admit(pname, op_class)
            out.append((pname, sid2, eng.submit_encode(
                bytes(buf), op_class, **kw)))
        return out

    # -- observability -------------------------------------------------------

    def pressures(self) -> dict:
        """Per-shard admission occupancy (0..1+): the overload signal."""
        return {sid: eng.pressure() for sid, eng in self.shards.items()}

    def stats(self) -> dict:
        with self._lock:
            routed = dict(self.routed)
        return {"shards": len(self.shards),
                "routed": routed,
                "pressures": self.pressures(),
                "shed": {sid: p.snapshot() for sid, p in self.shed.items()}}


class _NullIo:
    """Layout-math-only stand-in: RadosStriper never touches it here."""
