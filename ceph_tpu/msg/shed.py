"""Overload shedding by dmClock op class.

When the event loop's ingest outruns dispatch admission, SOMETHING must
absorb the excess; an unbounded queue just converts overload into
latency for everyone.  The shedding ladder refuses work instead, lowest
QoS class first (the reference's mClock never starves client ops to
feed scrub; this is the admission-side complement): each class may
occupy the dispatch queue only up to its fraction of the configured
limit, so background classes start bouncing while client ops still
have headroom, and client ops themselves bounce only at the hard
limit.

A shed is an explicit, cheap refusal — the caller gets ``EBUSY``
immediately (no queue time burned) and may back off and retry; counters
record sheds per class so the bench can report shed-rate under
overload.
"""
from __future__ import annotations

import errno
import threading

from ..osd.mclock import (BG_RECOVERY, BG_SCRUB, BG_SNAPTRIM, CLIENT_OP,
                          OSD_SUBOP)

# fraction of the dispatch-queue limit each class may fill before its
# arrivals shed: background work yields headroom to client ops long
# before the hard limit (CLIENT_OP sheds only when the queue is FULL)
DEFAULT_SHED_FRACTIONS = {
    BG_SCRUB: 0.50,
    BG_SNAPTRIM: 0.60,
    BG_RECOVERY: 0.70,
    OSD_SUBOP: 0.85,
    CLIENT_OP: 1.00,
}

EBUSY = getattr(errno, "EBUSY", 16)


class ShedPolicy:
    """Class-fraction shedding ladder over one queue-depth limit."""

    def __init__(self, limit: int, fractions: dict | None = None):
        if limit <= 0:
            raise ValueError("shed limit must be > 0")
        self.limit = int(limit)
        self.fractions = dict(DEFAULT_SHED_FRACTIONS)
        if fractions:
            self.fractions.update(fractions)
        self._lock = threading.Lock()
        self.shed_counts: dict[str, int] = {}
        self.admitted = 0

    def threshold(self, op_class: str) -> int:
        frac = self.fractions.get(op_class, 1.0)
        return max(1, int(self.limit * frac))

    def should_shed(self, op_class: str, depth: int, n: int = 1) -> bool:
        """Decide for one arrival of ``n`` logical ops (a mux batch
        frame sheds or admits as a unit) given the current queue depth
        IN OPS; the verdict is recorded per op in the counters."""
        if depth < self.threshold(op_class):
            with self._lock:
                self.admitted += n
            return False
        with self._lock:
            self.shed_counts[op_class] = \
                self.shed_counts.get(op_class, 0) + n
        return True

    @property
    def shed_total(self) -> int:
        with self._lock:
            return sum(self.shed_counts.values())

    def shed_rate(self) -> float:
        """Sheds as a fraction of all arrivals seen so far."""
        with self._lock:
            shed = sum(self.shed_counts.values())
            total = shed + self.admitted
        return shed / total if total else 0.0

    def snapshot(self) -> dict:
        with self._lock:
            return {"limit": self.limit,
                    "admitted": self.admitted,
                    "shed": dict(self.shed_counts),
                    "shed_total": sum(self.shed_counts.values())}
