"""Session-multiplexing frame types for the async messenger.

``net.py`` owns the base RPC vocabulary (cephx handshake frames,
RpcCall/RpcResult, watch/notify).  This module adds the frames the
multiplexed transport introduces — carrying MANY logical sessions'
calls per TCP connection in one frame, the reference messenger's
out-queue coalescing made explicit on the wire:

- :class:`RpcBatch`   — client->server: a vector of RpcCalls (possibly
  from many logical sessions) submitted as one frame: one pickle, one
  MAC, one send for a whole admission window;
- :class:`RpcResultBatch` — server->client: the results a dispatch
  worker produced for one batch, returned as one frame.

Both register wire-accounting sizers (test_wire_guard's no-unmetered-
types contract) and join ``net._TYPES`` so the shared codec
(``net._encode``/``net._decode``) carries them: they are post-auth
pickle frames, never valid before the HMAC session.

Reqid-dedup semantics are untouched: every inner call keeps its own
``(session, rid)``, so a resent batch (or a single resent call from a
dead batch) dedups per call, exactly like the unbatched path.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from ..common import wire_accounting
from .. import net


@dataclass
class RpcBatch:
    """A vector of :class:`~ceph_tpu.net.RpcCall` riding one frame."""
    calls: list = field(default_factory=list)


@dataclass
class RpcResultBatch:
    """The :class:`~ceph_tpu.net.RpcResult` vector for one RpcBatch."""
    results: list = field(default_factory=list)


_blob = wire_accounting.blob_size
wire_accounting.register_wire_sizes({
    RpcBatch: lambda m: sum(
        len(c.method) + _blob(c.args) + 16 for c in m.calls) + 8,
    RpcResultBatch: lambda m: sum(
        _blob(r.value) + len(r.error) + 16 for r in m.results) + 8,
})

# join the shared RPC registry: the codec resolves frame type names
# through net._TYPES, and test_wire_guard pins that every name in it is
# individually metered
net._TYPES.update({
    "RpcBatch": RpcBatch,
    "RpcResultBatch": RpcResultBatch,
})


def batch_trace_ctx(msg):
    """The trace context a batch frame's wire bytes charge to: batches
    are client-op vectors, so the first traced member speaks for the
    frame (the per-class byte partition stays exact — one frame, one
    class — while mixed-class batches are a documented approximation)."""
    items = getattr(msg, "calls", None) or getattr(msg, "results", None) \
        or ()
    for m in items:
        ctx = getattr(m, "trace", None)
        if ctx is not None:
            return ctx
    return None
