"""Session-multiplexing frame types for the async messenger.

``net.py`` owns the base RPC vocabulary (cephx handshake frames,
RpcCall/RpcResult, watch/notify).  This module adds the frames the
multiplexed transport introduces — carrying MANY logical sessions'
calls per TCP connection in one frame, the reference messenger's
out-queue coalescing made explicit on the wire:

- :class:`RpcBatch`   — client->server: a vector of RpcCalls (possibly
  from many logical sessions) submitted as one frame: one pickle, one
  MAC, one send for a whole admission window;
- :class:`RpcResultBatch` — server->client: the results a dispatch
  worker produced for one batch, returned as one frame.

Both register wire-accounting sizers (test_wire_guard's no-unmetered-
types contract) and join ``net._TYPES`` so the shared codec
(``net._encode``/``net._decode``) carries them: they are post-auth
pickle frames, never valid before the HMAC session.

Reqid-dedup semantics are untouched: every inner call keeps its own
``(session, rid)``, so a resent batch (or a single resent call from a
dead batch) dedups per call, exactly like the unbatched path.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from ..common import wire_accounting
from .. import net


@dataclass
class RpcBatch:
    """A vector of :class:`~ceph_tpu.net.RpcCall` riding one frame."""
    calls: list = field(default_factory=list)


@dataclass
class RpcResultBatch:
    """The :class:`~ceph_tpu.net.RpcResult` vector for one RpcBatch."""
    results: list = field(default_factory=list)


_blob = wire_accounting.blob_size
wire_accounting.register_wire_sizes({
    RpcBatch: lambda m: sum(
        len(c.method) + _blob(c.args) + 16 for c in m.calls) + 8,
    RpcResultBatch: lambda m: sum(
        _blob(r.value) + len(r.error) + 16 for r in m.results) + 8,
})

# join the shared RPC registry: the codec resolves frame type names
# through net._TYPES, and test_wire_guard pins that every name in it is
# individually metered
net._TYPES.update({
    "RpcBatch": RpcBatch,
    "RpcResultBatch": RpcResultBatch,
})


# ---- sideband codecs (ISSUE 20: zero-copy batch frames) ------------------
#
# Batch frames are where bulk payloads actually ride the mux transport,
# so both batch types register extract/reattach hooks with the shared
# codec: eligible args/value blobs lift out of the pickled control
# header into the frame's raw third segment (net._encode_parts), and
# land on the far side with one staged copy (net._sideband_payloads).
# Extraction copies the CONTAINERS only (a fresh calls list + args
# dicts, never payload bytes): retries resend the same RpcCall objects,
# which must keep their real payloads.

def _batch_extract(msg):
    views: list = []
    calls, dirty = [], False
    for c in msg.calls:
        repl = net._call_extract_args(c, views)
        if repl is not None:
            dirty = True
            c = net.RpcCall(c.rid, c.method, repl, trace=c.trace,
                            session=c.session, op_class=c.op_class)
        calls.append(c)
    if not dirty:
        return None
    return RpcBatch(calls), views


def _batch_reattach(msg, payloads) -> None:
    for c in msg.calls:
        net._call_reattach_args(c, payloads)


def _batch_payload_bytes(msg) -> int:
    return sum(len(v) for c in msg.calls for v in c.args.values()
               if net._sb_eligible(v))


def _result_batch_extract(msg):
    views: list = []
    results, dirty = [], False
    for r in msg.results:
        if net._sb_splice(r.value):
            dirty = True
            v = r.value
            views.append(v if isinstance(v, memoryview)
                         else memoryview(v))
            r = net.RpcResult(r.rid, r.ok,
                              net.SidebandRef(len(views) - 1),
                              r.error, r.errno, trace=r.trace)
        results.append(r)
    if not dirty:
        return None
    return RpcResultBatch(results), views


def _result_batch_reattach(msg, payloads) -> None:
    for r in msg.results:
        net._rpc_result_reattach(r, payloads)


def _result_batch_payload_bytes(msg) -> int:
    return sum(len(r.value) for r in msg.results
               if net._sb_eligible(r.value))


net._SIDEBAND_CODECS.update({
    "RpcBatch": net._SidebandCodec(
        _batch_extract, _batch_reattach, _batch_payload_bytes),
    "RpcResultBatch": net._SidebandCodec(
        _result_batch_extract, _result_batch_reattach,
        _result_batch_payload_bytes),
})


def batch_trace_ctx(msg):
    """The trace context a batch frame's wire bytes charge to: batches
    are client-op vectors, so the first traced member speaks for the
    frame (the per-class byte partition stays exact — one frame, one
    class — while mixed-class batches are a documented approximation)."""
    items = getattr(msg, "calls", None) or getattr(msg, "results", None) \
        or ()
    for m in items:
        ctx = getattr(m, "trace", None)
        if ctx is not None:
            return ctx
    return None
