"""TCP transport for the v2 wire: a live cluster served over sockets.

The missing messenger half (r4 VERDICT #4): the in-process MessageBus
carries intra-cluster traffic deterministically, and THIS module carries
client↔cluster traffic over real loopback/LAN sockets using the same v2
framing (reference: src/msg/async/AsyncMessenger.h:74, ProtocolV2.cc):

- banner + HELLO exchange in crc mode (wire.py frames);
- a REAL cephx handshake over the socket — server challenge, session
  key, service ticket, authorizer with mutual-auth reply (auth/cephx.py,
  the full KDC flow with the server embedding the key server the way a
  mon does) — after which both ends switch the connection to SECURE
  (HMAC) mode keyed by the negotiated service session key, exactly the
  cephx→wire-secure handoff ProtocolV2 performs;
- RPC frames against the cluster (put/get/operate-style calls), plus
  server→client watch/notify pushes with blocking acks, so two client
  PROCESSES can watch and notify each other through the cluster.

Secret distribution matches deployment practice: the server writes
``client.admin.keyring`` into the cluster's data dir; clients read it
from the shared filesystem.

Threading (post-ISSUE-14): the server runs the async messenger (msg/):
ONE reactor thread owns the listener and every connection — accept,
handshake state machines, frame reassembly, reply writes — and a small
fixed dmClock-ordered worker pool executes RPCs against the cluster
(every cluster call still serializes through one lock; the MiniCluster
is a single-threaded construct).  No per-connection or per-request
threads exist on either side: the client's replies arrive as readiness
callbacks on a shared client reactor.  NotifyAcks are handled inline on
the reactor so a notify blocked on remote acks can never deadlock
against the acking client's queued work.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path

from .auth.cephx import (AuthError, Authorizer, CephxClient,
                         CephxServiceHandler, KeyServer)
from .backend.wire import (BANNER, FrameParser, TAG_HELLO, TAG_MESSAGE,
                           WireError, frame_encode, frame_encode_parts)
from .common import copy_ledger, instruments, wire_accounting
from .common.tracer import default_tracer

SERVICE = "osd"
KEYRING = "client.admin.keyring"
NOTIFY_TIMEOUT = 10.0

# interned "rpc.<method>" span names: dispatch records one tracer event
# per op, and building the name fresh each time is measurable at that rate
_RPC_SPAN_NAMES: dict[str, str] = {}


# -- socket RPC messages (own registry: these never ride the PG bus) ---------

@dataclass
class CephxBegin:
    name: str


@dataclass
class CephxChallenge:
    challenge: bytes


@dataclass
class CephxAuthenticate:
    client_challenge: bytes
    proof: bytes


@dataclass
class CephxSession:
    env: bytes                   # sealed session key envelope
    ticket_env: bytes            # sealed service-ticket envelope


@dataclass
class CephxAuthorize:
    authorizer: Authorizer


@dataclass
class CephxDone:
    reply: bytes                 # mutual-auth nonce+1 blob


@dataclass
class RpcCall:
    rid: int
    method: str
    args: dict
    # distributed-trace context (common/tracer.TraceContext): rides the
    # post-auth frame so the server's spans stitch under the remote
    # client's trace id — the cross-PROCESS half of trace propagation
    trace: object = None
    # client session id: (session, rid) is the reqid the server dedups
    # resent calls by, so a resend after a connection reset (or a
    # black-holed request) never re-applies a non-idempotent op — the
    # reference's reqid dedup for 'ms inject socket failures' resends
    session: str = ""
    # dmClock op class (osd/mclock constants): orders the async server's
    # dispatch queue and picks the overload-shedding threshold; absent on
    # frames from older peers — readers use getattr with this default
    op_class: str = "client_op"


@dataclass
class RpcResult:
    rid: int
    ok: bool
    value: object = None
    error: str = ""
    errno: int = 0
    # echo of the call's trace ctx: the reply frame's wire bytes charge
    # to the op class that asked (the send happens on the reader thread,
    # outside the dispatch activation)
    trace: object = None


@dataclass
class SidebandRef:
    """Placeholder left in a pickled control header where a bulk payload
    was extracted to the frame's raw sideband segment (ISSUE 20): ``i``
    indexes the sideband's length table.  Decode replaces every ref with
    its staged payload before the message reaches any consumer, so refs
    are never visible outside the codec."""
    i: int


@dataclass
class NotifyPush:
    cookie: int
    notify_id: int
    payload: bytes


@dataclass
class NotifyAck:
    cookie: int
    notify_id: int
    value: object = None


_TYPES = {c.__name__: c for c in (
    CephxBegin, CephxChallenge, CephxAuthenticate, CephxSession,
    CephxAuthorize, CephxDone, RpcCall, RpcResult, NotifyPush, NotifyAck)}

# wire accounting sizers (common/wire_accounting.py): the sockets have
# REAL frame lengths, so these estimates only serve the no-unmetered-
# types guard and non-framed callers; weigh the payload-bearing fields
_blob = wire_accounting.blob_size
wire_accounting.register_wire_sizes({
    CephxBegin: lambda m: len(m.name),
    CephxChallenge: lambda m: len(m.challenge),
    CephxAuthenticate: lambda m: len(m.client_challenge) + len(m.proof),
    CephxSession: lambda m: len(m.env) + len(m.ticket_env),
    CephxAuthorize: lambda m: _blob(m.authorizer.blob) + 48,
    CephxDone: lambda m: len(m.reply),
    RpcCall: lambda m: len(m.method) + _blob(m.args),
    RpcResult: lambda m: _blob(m.value) + len(m.error),
    # a sideband placeholder is one u32 index on the wire; the payload
    # it stands for is metered by the frame's real byte length
    SidebandRef: lambda m: 4,
    NotifyPush: lambda m: len(m.payload) + 16,
    NotifyAck: lambda m: _blob(m.value) + 16,
})

# ---- pre-auth codec: NO pickle before the peer is authenticated ----------
#
# Everything that arrives before the HMAC session is established is
# attacker-controlled, and unpickling attacker bytes is remote code
# execution.  The six handshake message types therefore serialize as
# plain length-prefixed primitive fields (str/bytes/int only); pickle is
# allowed ONLY for post-auth frames, whose HMAC a peer without the
# session key cannot forge (the same trust line ProtocolV2 draws at its
# auth-done frame).

_HANDSHAKE_FIELDS = {
    "CephxBegin": ("name",),
    "CephxChallenge": ("challenge",),
    "CephxAuthenticate": ("client_challenge", "proof"),
    "CephxSession": ("env", "ticket_env"),
    "CephxDone": ("reply",),
    # Authorizer flattened: the only nested handshake payload
    "CephxAuthorize": ("service", "blob", "secret_id", "nonce", "proof"),
}
_LEN = struct.Struct("<I")


def _pack_field(v) -> bytes:
    if isinstance(v, str):
        tag, payload = b"s", v.encode()
    elif isinstance(v, (bytes, bytearray)):
        tag, payload = b"b", bytes(v)
    elif isinstance(v, int):
        tag, payload = b"i", str(int(v)).encode()
    else:
        raise WireError(f"unsupported handshake field {type(v)}")
    return tag + _LEN.pack(len(payload)) + payload


def _unpack_fields(blob: bytes) -> list:
    out, off = [], 0
    while off < len(blob):
        tag = blob[off:off + 1]
        (ln,) = _LEN.unpack_from(blob, off + 1)
        payload = blob[off + 1 + _LEN.size:off + 1 + _LEN.size + ln]
        if len(payload) != ln:
            raise WireError("truncated handshake field")
        off += 1 + _LEN.size + ln
        if tag == b"s":
            out.append(payload.decode())
        elif tag == b"b":
            out.append(payload)
        elif tag == b"i":
            out.append(int(payload))
        else:
            raise WireError(f"bad handshake field tag {tag!r}")
    return out


def _handshake_dumps(msg) -> bytes:
    name = type(msg).__name__
    fields = _HANDSHAKE_FIELDS[name]
    if name == "CephxAuthorize":
        a = msg.authorizer
        values = [a.service, a.blob, a.secret_id, a.nonce, a.proof]
    else:
        values = [getattr(msg, f) for f in fields]
    return b"".join(_pack_field(v) for v in values)


def _handshake_loads(name: str, blob: bytes):
    values = _unpack_fields(blob)
    if len(values) != len(_HANDSHAKE_FIELDS[name]):
        raise WireError(f"bad field count for {name}")
    if name == "CephxAuthorize":
        return CephxAuthorize(Authorizer(*values))
    return _TYPES[name](*values)


def _encode(msg, secret: bytes | None) -> bytes:
    name = type(msg).__name__
    if name in _HANDSHAKE_FIELDS:
        payload = _handshake_dumps(msg)
    else:
        if secret is None:
            raise WireError(f"{name} may not ride an unauthenticated "
                            f"connection")
        payload = pickle.dumps(msg)
        if instruments.enabled():
            codec = _SIDEBAND_CODECS.get(name)
            if codec is not None:
                pb = codec.payload_bytes(msg)
                if pb:
                    # the legacy path's two tx-side payload copies:
                    # pickle.dumps above and frame_encode's b"".join
                    copy_ledger.count_copy("pickle", pb)
                    copy_ledger.count_copy("join", pb)
    return frame_encode(TAG_MESSAGE, [name.encode(), payload],
                        secret=secret)


# ---- raw-payload sideband (ISSUE 20: zero-copy batch frames) -------------
#
# A payload-bearing post-auth message may serialize as a THREE-segment
# frame: [type name, pickled control header, raw sideband].  Bulk
# bytes-like values are lifted out of the header before pickling (a
# SidebandRef marks each slot) and ride the third segment length-
# prefixed, so the encode side never pickles payload bytes (the views
# splice straight into the connection's write queue) and the decode
# side lands them with ONE copy — into a pooled staging buffer (server)
# or owned bytes (client/blocking channel).  Frames dispatch on segment
# count, so both formats decode regardless of ms_zero_copy: the option
# gates only the encode side and mixed peers interoperate.

_SB_MIN = copy_ledger.PAYLOAD_MIN
# encode-side splice threshold: lifting a value costs a header rewrite,
# a table entry, and an extra write-queue part — worth it only once the
# value dwarfs that overhead.  Smaller eligible values stay pickled
# (and still weigh in the ledger as legacy copies via _sb_eligible)
_SB_SPLICE_MIN = 1024
_SB_LEN = struct.Struct("<I")

_zero_copy = True


def zero_copy_enabled() -> bool:
    return _zero_copy


def set_zero_copy(on: bool) -> None:
    global _zero_copy
    _zero_copy = bool(on)


def wire_zero_copy_config(conf) -> None:
    """Adopt ``ms_zero_copy`` from a ConfigProxy and follow live
    updates (the transports call this; the switch is process-wide like
    the instruments kill-switch, and gates only the encode side)."""
    if "ms_zero_copy" not in conf.schema:
        return
    set_zero_copy(bool(conf.get("ms_zero_copy")))
    conf.add_observer("ms_zero_copy",
                      lambda _name, v: set_zero_copy(bool(v)))


def _sb_eligible(v) -> bool:
    return isinstance(v, (bytes, bytearray, memoryview)) \
        and len(v) >= _SB_MIN


def _sb_splice(v) -> bool:
    return isinstance(v, (bytes, bytearray, memoryview)) \
        and len(v) >= _SB_SPLICE_MIN


class _SidebandCodec:
    """One message type's sideband hooks: ``extract(msg)`` returns
    ``(header_msg, views)`` or None (nothing to lift — caller falls back
    to the pickled frame); ``reattach(msg, payloads)`` swaps every
    SidebandRef in a freshly-unpickled header for its landed payload;
    ``payload_bytes(msg)`` sizes the eligible payloads (the legacy
    path's ledger weights)."""

    __slots__ = ("extract", "reattach", "payload_bytes")

    def __init__(self, extract, reattach, payload_bytes):
        self.extract = extract
        self.reattach = reattach
        self.payload_bytes = payload_bytes


_SIDEBAND_CODECS: dict[str, _SidebandCodec] = {}


def _call_extract_args(call, views: list):
    """Lift eligible args values; returns a replacement args dict or
    None.  Never mutates the caller's dict — retries resend the same
    RpcCall objects, which must keep their real payloads."""
    repl = None
    for k, v in call.args.items():
        if _sb_splice(v):
            if repl is None:
                repl = dict(call.args)
            repl[k] = SidebandRef(len(views))
            views.append(v if isinstance(v, memoryview) else memoryview(v))
    return repl


def _call_reattach_args(call, payloads: list) -> None:
    for k, v in call.args.items():
        if type(v) is SidebandRef:
            call.args[k] = payloads[v.i]


def _rpc_call_extract(msg):
    views: list = []
    repl = _call_extract_args(msg, views)
    if repl is None:
        return None
    return RpcCall(msg.rid, msg.method, repl, trace=msg.trace,
                   session=msg.session, op_class=msg.op_class), views


def _rpc_call_payload_bytes(msg) -> int:
    return sum(len(v) for v in msg.args.values() if _sb_eligible(v))


def _rpc_result_extract(msg):
    if not _sb_splice(msg.value):
        return None
    v = msg.value
    return RpcResult(msg.rid, msg.ok, SidebandRef(0), msg.error,
                     msg.errno, trace=msg.trace), \
        [v if isinstance(v, memoryview) else memoryview(v)]


def _rpc_result_reattach(msg, payloads) -> None:
    if type(msg.value) is SidebandRef:
        msg.value = payloads[msg.value.i]


_SIDEBAND_CODECS["RpcCall"] = _SidebandCodec(
    _rpc_call_extract, _call_reattach_args, _rpc_call_payload_bytes)
_SIDEBAND_CODECS["RpcResult"] = _SidebandCodec(
    _rpc_result_extract, _rpc_result_reattach,
    lambda m: len(m.value) if _sb_eligible(m.value) else 0)


def _encode_parts(msg, secret: bytes | None) -> list | None:
    """Sideband encode: the frame as an ordered list of write buffers
    (payload views UNJOINED), or None when the message cannot or need
    not sideband — the caller falls back to :func:`_encode`."""
    if secret is None or not _zero_copy:
        return None
    codec = _SIDEBAND_CODECS.get(type(msg).__name__)
    if codec is None:
        return None
    ex = codec.extract(msg)
    if ex is None:
        return None
    header_msg, views = ex
    table = _SB_LEN.pack(len(views)) + b"".join(
        _SB_LEN.pack(len(v)) for v in views)
    return frame_encode_parts(
        TAG_MESSAGE,
        [type(msg).__name__.encode(), pickle.dumps(header_msg),
         [table, *views]],
        secret=secret)


def _sideband_payloads(seg, staging) -> list:
    """Land a sideband segment's payloads with ONE copy each: staged
    into a pooled buffer (views) when ``staging`` is a pool, or
    materialized to owned bytes otherwise (client completions and the
    reqid-dedup cache outlive the parser buffer)."""
    mv = seg if isinstance(seg, memoryview) else memoryview(seg)
    if len(mv) < _SB_LEN.size:
        raise WireError("truncated sideband table")
    (n,) = _SB_LEN.unpack_from(mv, 0)
    head = _SB_LEN.size * (1 + n)
    if len(mv) < head:
        raise WireError("truncated sideband table")
    lens = [_SB_LEN.unpack_from(mv, _SB_LEN.size * (1 + i))[0]
            for i in range(n)]
    body = mv[head:]
    if sum(lens) != len(body):
        raise WireError("sideband length mismatch")
    out: list = []
    off = 0
    if staging is not None:
        base = staging.stage(body)          # THE copy (ledger: staging)
        for ln in lens:
            out.append(base[off:off + ln])
            off += ln
    else:
        for ln in lens:
            b = bytes(body[off:off + ln])
            off += ln
            copy_ledger.count_copy("materialize", len(b))
            out.append(b)
    return out


def _decode(tag: int, segs: list[bytes], *, authed: bool, staging=None):
    # segs may be bytes (FrameParser) or memoryviews into the async
    # stream parser's receive buffer; only the tiny name/handshake
    # segments materialize — the pickle payload decodes in place
    if tag != TAG_MESSAGE or len(segs) not in (2, 3):
        raise WireError(f"unexpected frame tag {tag}")
    name = bytes(segs[0]).decode()
    klass = _TYPES.get(name)
    if klass is None:
        raise WireError(f"unknown rpc type {name!r}")
    if name in _HANDSHAKE_FIELDS:
        if len(segs) != 2:
            raise WireError(f"{name} cannot carry a sideband")
        return _handshake_loads(name, bytes(segs[1]))
    if not authed:
        # pickle is reachable ONLY behind the HMAC (pre-auth unpickling
        # of peer bytes would be remote code execution)
        raise WireError(f"{name} before authentication")
    msg = pickle.loads(segs[1])
    if type(msg) is not klass:
        raise WireError("rpc type name mismatch")
    codec = _SIDEBAND_CODECS.get(name)
    if len(segs) == 3:
        if codec is None:
            raise WireError(f"{name} cannot carry a sideband")
        try:
            codec.reattach(msg, _sideband_payloads(segs[2], staging))
        except (IndexError, AttributeError, TypeError) as e:
            raise WireError(f"bad sideband refs in {name}: {e}") from e
    elif codec is not None and instruments.enabled():
        pb = codec.payload_bytes(msg)
        if pb:
            copy_ledger.count_copy("unpickle", pb)
    return msg


class Channel:
    """One framed socket endpoint.  Starts in crc mode; ``secure(key)``
    switches both directions to HMAC mode (called at the same protocol
    point on both ends, like ProtocolV2's post-auth session switch)."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.parser = FrameParser(None)
        self.parser.track_sizes = True
        self.secret: bytes | None = None
        self._wlock = threading.Lock()
        self._banner_seen = False
        self._banner_buf = bytearray()
        # per-connection byte/op counters (the reference's per-Connection
        # messenger stats) + optional shared WireAccounting the server
        # attaches so every connection rolls up into wire.net.<port>
        self.stats = {"tx_msgs": 0, "tx_bytes": 0,
                      "rx_msgs": 0, "rx_bytes": 0}
        self.acct = None
        # transport fault hooks (failure/transport.py): a ZERO-ARG
        # PROVIDER returning the current hooks (or None), attached by
        # the server AFTER auth — a provider rather than a snapshot so
        # arming/disarming mid-run applies to live connections, and the
        # handshake is never faulted (reconnects always get back in)
        self.faults = None
        with self._wlock:
            self.sock.sendall(BANNER)

    def secure(self, key: bytes) -> None:
        self.secret = key
        self.parser = FrameParser(key)
        self.parser.track_sizes = True

    def send(self, msg) -> None:
        data = _encode(msg, self.secret)
        action = "ok"
        hooks = self.faults() if self.faults is not None else None
        if hooks is not None:
            # target is the MESSAGE TYPE, not the peer address: ephemeral
            # ports differ between runs and would break the same-seed
            # event-digest guarantee
            from .failure.transport import SEND_TRUNCATE
            action = hooks.on_send(
                type(msg).__name__, len(data),
                target=type(msg).__name__)
        if self.acct is not None:
            # real framed bytes; the op class comes from the riding
            # trace ctx (RpcCall) or the sender's active context.
            # Accounting is sharded per thread now — it needs no lock,
            # and keeping it OUT of _wlock keeps concurrent senders
            # from serializing on an instrument
            self.acct.account_msg(
                msg, nbytes=len(data),
                ctx=getattr(msg, "trace", None)
                or default_tracer().current_ctx())
        with self._wlock:
            # the plain stats dict still rides the lock that serializes
            # concurrent senders (dispatch reply vs notify push):
            # counting it outside would lose increments and drift from
            # the peer's rx side
            self.stats["tx_msgs"] += 1
            self.stats["tx_bytes"] += len(data)
            if action == "ok":
                self.sock.sendall(data)
        if action != "ok":
            # injected transport failure: a PARTIAL frame on the wire
            # (truncate) or nothing at all, then an abrupt close — the
            # peer sees a cut-off frame / RST and must reconnect+resend
            if action == SEND_TRUNCATE:
                try:
                    self.sock.sendall(data[:max(1, len(data) // 2)])
                except OSError:
                    pass
            self.close()
            raise ConnectionError(f"injected connection {action}")

    def recv_msgs(self) -> list:
        """Blocking read; returns >=1 decoded messages or raises
        ConnectionError on EOF."""
        while True:
            data = self.sock.recv(65536)
            if not data:
                raise ConnectionError("peer closed")
            if not self._banner_seen:
                self._banner_buf += data
                if len(self._banner_buf) < len(BANNER):
                    continue
                if self._banner_buf[:len(BANNER)] != BANNER:
                    raise WireError("banner mismatch")
                data = bytes(self._banner_buf[len(BANNER):])
                self._banner_seen = True
                self._banner_buf.clear()
            frames = self.parser.feed(data)
            if frames:
                # the parser reports each frame's REAL on-wire length
                # (preamble + crc/mac + body), so rx_bytes matches the
                # peer's tx_bytes for the same conversation; the segment
                # sum is only the fallback for a parser swapped mid-read
                sizes = self.parser.frame_sizes
                self.parser.frame_sizes = []
                out = []
                for i, (t, s) in enumerate(frames):
                    msg = _decode(t, s, authed=self.secret is not None)
                    nbytes = sizes[i] if i < len(sizes) else \
                        sum(len(seg) for seg in s) + \
                        wire_accounting.MSG_OVERHEAD
                    self.stats["rx_msgs"] += 1
                    self.stats["rx_bytes"] += nbytes
                    if self.acct is not None:
                        self.acct.account_rx(
                            type(msg).__name__, nbytes,
                            ctx=getattr(msg, "trace", None))
                    out.append(msg)
                return out

    def recv_one(self):
        msgs = self.recv_msgs()
        if len(msgs) != 1:
            raise WireError(f"expected one message, got {len(msgs)}")
        return msgs[0]

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


# -- server ------------------------------------------------------------------

class ClusterServer:
    """Serve a MiniCluster over TCP with cephx-authenticated, HMAC-secured
    connections.  ``port=0`` binds an ephemeral port (see ``.port``)."""

    def __init__(self, cluster, host: str = "127.0.0.1", port: int = 0):
        self.cluster = cluster
        self.lock = threading.Lock()          # ONE cluster at a time
        self.keyserver = KeyServer()
        self._load_or_create_keys()
        self.handler = CephxServiceHandler(SERVICE, self.keyserver)
        self._listener = socket.create_server((host, port))
        self.port = self._listener.getsockname()[1]
        # server-wide wire accounting: every connection's frames roll up
        # into ONE wire.net.<port> perf collection (per-message-type
        # bytes, per-op-class bytes, RPC latency histogram)
        self.wire = wire_accounting.WireAccounting(
            cct=getattr(cluster, "cct", None), name=f"net.{self.port}")
        self._stop = threading.Event()
        # the serving front door: reactor + handshake state machines +
        # dmClock dispatch (msg/server.py), created by start().  The
        # KeyServer's single per-entity challenge slot is serialized by
        # the transport's auth FIFO (the old _auth_lock, made async)
        self._transport = None
        # cookie -> connection for remote watchers
        self._watchers: dict[int, object] = {}
        self._watch_lock = threading.Lock()
        self._pending_acks: dict[tuple[int, int], list] = {}
        self._ack_cond = threading.Condition()
        # transport fault injection (failure/): hooks attached to every
        # authenticated connection once inject_faults() arms them —
        # explicitly, or auto-armed from the ms_inject_* options (the
        # reference's 'ms inject socket failures' config surface)
        self.fault_hooks = None
        self._maybe_auto_inject()
        # resend dedup: (client session, rid) -> cached RpcResult, so a
        # retried call after a reset/black-hole returns the FIRST
        # execution's answer instead of re-applying (reqid dedup)
        self._rpc_cache: "dict[tuple[str, int], RpcResult]" = {}
        self._rpc_cache_order: list[tuple[str, int]] = []
        self._rpc_cache_lock = threading.Lock()
        # reqids currently EXECUTING: a resend that arrives while the
        # original is still running waits for that execution instead of
        # starting a second one (slow notify + eager client resend)
        self._rpc_inflight: "dict[tuple[str, int], threading.Event]" = {}
        self.rpc_dedup_hits = 0

    RPC_CACHE_MAX = 4096

    # side-effect-free methods are safe to simply RE-EXECUTE on a
    # resend: caching them would pin every read payload in the dedup
    # cache (4 MiB gets x 4096 entries) for hits that barely happen
    IDEMPOTENT_RPCS = frozenset(
        {"get", "stat", "ls", "pools", "status", "health", "getxattr",
         "ping", "tier_read"})

    def inject_faults(self, injector) -> None:
        """Arm (or, with None, disarm) transport-plane fault injection:
        every authenticated connection consults the injector's seeded
        streams for resets, black-holes, truncations and delays."""
        from .failure.transport import TransportFaultHooks
        self.fault_hooks = TransportFaultHooks(injector) \
            if injector is not None else None

    def _maybe_auto_inject(self) -> None:
        """The ms_inject_* options arm the hooks without code: a reset
        roughly every ``ms_inject_socket_failures`` post-auth messages
        plus ``ms_inject_delay_prob``/``ms_inject_delay_ms`` stalls."""
        cct = getattr(self.cluster, "cct", None)
        if cct is None:
            return
        n = int(cct.conf.get("ms_inject_socket_failures"))
        dprob = float(cct.conf.get("ms_inject_delay_prob"))
        if n <= 0 and dprob <= 0:
            return
        from .failure import (FaultInjector, FaultPlan, TransportFaults)
        plan = FaultPlan(transport=TransportFaults(
            reset_prob=(1.0 / n) if n > 0 else 0.0,
            delay_prob=dprob,
            delay_ms=float(cct.conf.get("ms_inject_delay_ms"))))
        self._own_injector = FaultInjector(plan, cct=cct,
                                           name=f"net.{self.port}")
        self.inject_faults(self._own_injector)

    # -- keyring -------------------------------------------------------------

    SERVER_KEYS = "mon.keyserver"     # server-only: rotating secrets

    def _load_or_create_keys(self) -> None:
        """The CLIENT keyring carries ONLY the entity key (a real cephx
        keyring's content); the rotating service secrets stay in a
        separate server-only file — a keyring holder must never be able
        to seal ticket blobs and impersonate entities."""
        data_dir = getattr(self.cluster, "data_dir", None)
        base = Path(data_dir) if data_dir is not None else None
        if base is not None and (base / self.SERVER_KEYS).exists():
            with open(base / self.SERVER_KEYS, "rb") as f:
                saved = pickle.load(f)
            self.keyserver.entity_keys.update(saved["entity_keys"])
            self.keyserver.rotating = saved["rotating"]
            return
        self.keyserver.create_entity("client.admin")
        self.keyserver.rotate(SERVICE)
        if base is not None:
            with open(base / self.SERVER_KEYS, "wb") as f:
                pickle.dump({"entity_keys":
                             dict(self.keyserver.entity_keys),
                             "rotating": self.keyserver.rotating}, f)
            with open(base / KEYRING, "wb") as f:
                pickle.dump({"key":
                             self.keyserver.entity_keys["client.admin"]},
                            f)

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        """Bring up the async serving transport (idempotent).  The
        listener, every connection's handshake, frame reassembly and
        reply writes all live on ONE reactor thread; dispatch runs on a
        small fixed worker pool — no per-connection or per-request
        thread is ever spawned."""
        if self._transport is None:
            from .msg.server import AsyncServerTransport
            self._transport = AsyncServerTransport(
                self, self._listener,
                cct=getattr(self.cluster, "cct", None),
                name=f"net.{self.port}")
            self._transport.start()
        return self._transport

    def serve_forever(self) -> None:
        """Blocking form (rados_cli serve): start + wait for stop()."""
        self.start()
        self._stop.wait()

    def stop(self) -> None:
        self._stop.set()
        if self._transport is not None:
            self._transport.stop()
            self._transport = None
        try:
            self._listener.close()
        except OSError:
            pass
        self.wire.close()
        if getattr(self, "_own_injector", None) is not None:
            # only the auto-armed injector is ours to close; an operator-
            # supplied one (inject_faults) belongs to its campaign
            self._own_injector.close()
            self._own_injector = None

    # -- transport callbacks (msg/server.py) ---------------------------------

    def _note_ack(self, msg: "NotifyAck") -> None:
        """A remote watcher's NotifyAck arrived: wake the notify that is
        blocked on it.  Runs INLINE on the reactor (never queued behind
        dispatch): the notify holding the cluster lock is what a queued
        ack would be stuck behind."""
        with self._ack_cond:
            key = (msg.cookie, msg.notify_id)
            self._pending_acks.setdefault(key, []).append(msg.value)
            self._ack_cond.notify_all()

    def _conn_closed(self, conn) -> None:
        """Connection teardown: drop the watches registered on it.  Under
        its own small lock, NOT the cluster lock — this runs on the
        reactor thread, which must never wait on a dispatch in flight."""
        with self._watch_lock:
            dead = [c for c, w in self._watchers.items() if w is conn]
            for cookie in dead:
                del self._watchers[cookie]

    # -- RPC dispatch --------------------------------------------------------

    def _dispatch(self, ch: Channel, call: RpcCall) -> RpcResult:
        t0 = time.perf_counter()
        if instruments.enabled():
            # copy-ledger denominator: request payload bytes reaching
            # their consumer (the handler) — pairs with the client-side
            # tally of result payloads at completion
            served = sum(len(v) for v in call.args.values()
                         if _sb_eligible(v))
            if served:
                copy_ledger.count_served(served)
        # resend dedup by reqid: a session-stamped call already answered
        # returns its FIRST execution's cached result — the property that
        # makes reset/black-hole resends safe for non-idempotent ops
        key = (call.session, call.rid) \
            if getattr(call, "session", "") \
            and call.method not in self.IDEMPOTENT_RPCS else None
        if key is not None:
            with self._rpc_cache_lock:
                hit = self._rpc_cache.get(key)
                running = None
                if hit is None:
                    running = self._rpc_inflight.get(key)
                    if running is None:
                        self._rpc_inflight[key] = threading.Event()
            if hit is not None:
                self.rpc_dedup_hits += 1
                return hit
            if running is not None:
                # the original execution is still on the cluster lock:
                # wait for ITS answer rather than double-applying
                self.rpc_dedup_hits += 1
                running.wait(NOTIFY_TIMEOUT * 6)
                with self._rpc_cache_lock:
                    hit = self._rpc_cache.get(key)
                if hit is not None:
                    return hit
                return RpcResult(call.rid, False, None,
                                 "duplicate of an execution that never "
                                 "finished", 0,
                                 trace=getattr(call, "trace", None))
        try:
            fn = getattr(self, f"_rpc_{call.method}", None)
            if fn is None:
                raise ValueError(f"unknown method {call.method!r}")
            tr = default_tracer()
            trace = getattr(call, "trace", None)
            sname = _RPC_SPAN_NAMES.get(call.method)
            if sname is None:
                sname = _RPC_SPAN_NAMES[call.method] = "rpc." + call.method
            if trace is not None:
                with self.lock, tr.activate(trace, track="server"), \
                        tr.span(sname, cat="rpc"):
                    value = fn(ch, **call.args)
            else:
                # untraced op: no context/track to adopt and nothing to
                # link — record through the allocation-light observe()
                # path instead of the full Span protocol
                with self.lock:
                    t0_span = time.perf_counter()
                    value = fn(ch, **call.args)
                    tr.observe(sname, t0_span, cat="rpc")
            return self._rpc_remember(
                key, RpcResult(call.rid, True, value,
                               trace=getattr(call, "trace", None)))
        except Exception as e:                 # noqa: BLE001 — RPC boundary
            return self._rpc_remember(
                key, RpcResult(call.rid, False, None,
                               f"{type(e).__name__}: {e}",
                               getattr(e, "errno", 0) or 0,
                               trace=getattr(call, "trace", None)))
        finally:
            # RPC latency lands in the wire histogram whether the call
            # succeeded or not — a failing method is still served time
            self.wire.observe_rpc(call.method,
                                  time.perf_counter() - t0)

    def _rpc_remember(self, key, res: RpcResult) -> RpcResult:
        if key is None:
            return res
        with self._rpc_cache_lock:
            if key not in self._rpc_cache:
                self._rpc_cache_order.append(key)
                while len(self._rpc_cache_order) > self.RPC_CACHE_MAX:
                    self._rpc_cache.pop(self._rpc_cache_order.pop(0),
                                        None)
            self._rpc_cache[key] = res
            ev = self._rpc_inflight.pop(key, None)
        if ev is not None:
            ev.set()
        return res

    def _rpc_mkpool(self, ch, name, profile=None, pg_num=8,
                    replicated=False, size=3):
        c = self.cluster
        if name in c.pool_ids:
            raise ValueError(f"pool {name!r} exists")
        if replicated:
            return c.create_replicated_pool(name, size=size, pg_num=pg_num)
        return c.create_ec_pool(name, profile or {}, pg_num=pg_num)

    def _rpc_pools(self, ch):
        return dict(self.cluster.pool_ids)

    def _rpc_put(self, ch, pool, oid, data):
        from .osd.osd_ops import ObjectOperation
        pid = self.cluster.pool_ids[pool]
        self.cluster.operate(pid, oid,
                             ObjectOperation().write_full(bytes(data)))
        return len(data)

    def _rpc_get(self, ch, pool, oid):
        from .osd.osd_ops import ObjectOperation
        pid = self.cluster.pool_ids[pool]
        r = self.cluster.operate(pid, oid, ObjectOperation().stat()
                                 .read(0, 0))
        size, _mtime = r.outdata(0)
        return bytes(r.outdata(1)[:size])

    def _rpc_stat(self, ch, pool, oid):
        from .osd.osd_ops import ObjectOperation
        pid = self.cluster.pool_ids[pool]
        r = self.cluster.operate(pid, oid, ObjectOperation().stat())
        return tuple(r.outdata(0))           # (size, mtime), like local

    def _rpc_remove(self, ch, pool, oid):
        from .osd.osd_ops import ObjectOperation
        pid = self.cluster.pool_ids[pool]
        self.cluster.operate(pid, oid, ObjectOperation().remove())
        return True

    def _rpc_ls(self, ch, pool):
        from .osd.hit_set import is_hit_set_oid
        from .osd.primary_log_pg import is_clone_oid
        pid = self.cluster.pool_ids[pool]
        # internal oids (snapshot clones, hit-set archives) stay hidden,
        # like the local IoCtx listing
        return sorted(o for o in self.cluster.objects.get(pid, set())
                      if not is_clone_oid(o) and not is_hit_set_oid(o))

    def _rpc_setxattr(self, ch, pool, oid, name, value):
        from .osd.osd_ops import ObjectOperation
        pid = self.cluster.pool_ids[pool]
        self.cluster.operate(pid, oid,
                             ObjectOperation().setxattr(name, value))
        return True

    def _rpc_getxattr(self, ch, pool, oid, name):
        from .osd.osd_ops import ObjectOperation
        pid = self.cluster.pool_ids[pool]
        return self.cluster.operate(
            pid, oid, ObjectOperation().getxattr(name)).outdata(0)

    def _rpc_status(self, ch):
        return self.cluster.status()

    def _rpc_health(self, ch):
        return self.cluster.health()

    def _rpc_ping(self, ch, payload=None, key=None):
        """Echo: the serving-path microbenchmark op (rados_bench mux
        mode) — round-trips the transport without touching the cluster.
        ``key`` carries the workload generator's object key (zipf /
        flash-crowd streams) so key-addressed load shapes ride the real
        wire format; the echo ignores it."""
        return payload

    def _rpc_tier_read(self, ch, pool, key):
        """Tiered read: when ``pool`` is a cache tier, serve ``key``
        through it (hit / proxy / recency-gated promote — the
        flash-crowd serving op); otherwise read straight from the pool
        with the tier's own base op vector, so the tiering bench's cold
        arm measures the exact path a miss proxies to.  Idempotent: a
        promotion is a copy-up, so re-executing on a resend is safe."""
        c = self.cluster
        pid = c.pool_ids[pool]
        tier = c.tiers.get(pid)
        if tier is not None:
            return tier[0].read(key)
        from .osd.osd_ops import ObjectOperation
        r = c.operate(pid, key, ObjectOperation().read(0, 0).getxattrs())
        return bytes(r.ops[0].outdata)

    def _rpc_tier_write(self, ch, pool, key, payload):
        """Tiered write: absorbed by the cache tier bound over ``pool``
        (writeback marks dirty, proxy forwards, readonly refuses) or
        written straight to the pool when no tier is bound — the cold
        arm's EC full-stripe write, encode and all.  Replay-deduped
        like ``put`` (NOT in IDEMPOTENT_RPCS)."""
        c = self.cluster
        pid = c.pool_ids[pool]
        tier = c.tiers.get(pid)
        if tier is not None:
            tier[0].write(key, bytes(payload))
            return len(payload)
        from .osd.osd_ops import ObjectOperation
        c.operate(pid, key, ObjectOperation().write_full(bytes(payload)))
        return len(payload)

    def _rpc_watch(self, ch, pool, oid, cookie):
        from .osd.osd_ops import ObjectOperation
        pid = self.cluster.pool_ids[pool]
        with self._watch_lock:
            self._watchers[cookie] = ch

        def on_notify(notify_id, ck, payload, _ch=ch, _cookie=cookie):
            # push OUTSIDE the ack wait; the remote client answers on its
            # own reader thread via NotifyAck
            _ch.send(NotifyPush(_cookie, notify_id, payload))
            deadline = time.monotonic() + NOTIFY_TIMEOUT
            key = (_cookie, notify_id)
            with self._ack_cond:
                while not self._pending_acks.get(key):
                    left = deadline - time.monotonic()
                    if left <= 0:
                        return TimeoutError("notify ack timeout")
                    self._ack_cond.wait(left)
                return self._pending_acks.pop(key)[0]
        self.cluster.operate(pid, oid,
                             ObjectOperation().watch(cookie, on_notify))
        return True

    def _rpc_unwatch(self, ch, pool, oid, cookie):
        from .osd.osd_ops import ObjectOperation
        pid = self.cluster.pool_ids[pool]
        self.cluster.operate(pid, oid, ObjectOperation().unwatch(cookie))
        with self._watch_lock:
            self._watchers.pop(cookie, None)
        return True

    def _rpc_notify(self, ch, pool, oid, payload):
        from .osd.osd_ops import ObjectOperation
        pid = self.cluster.pool_ids[pool]
        r = self.cluster.operate(pid, oid,
                                 ObjectOperation().notify(bytes(payload)))
        acks = r.outdata(0)
        # exceptions don't pickle reliably; stringify them
        return {ck: (repr(v) if isinstance(v, Exception) else v)
                for ck, v in acks.items()}


# -- CLI helper --------------------------------------------------------------

def cli_connect(connect: str, keyring: str | None, data_dir: str | None):
    """Shared --connect preamble for the rados/ceph CLIs: parse
    HOST:PORT, resolve the keyring (explicit or <data-dir>/keyring), and
    open an authenticated TcpRados.  Raises ValueError/IOError/AuthError
    with operator-readable messages; the CLIs map those to 'error: ...'
    + exit 2."""
    host, _, port_s = connect.rpartition(":")
    if not host or not port_s.isdigit():
        raise ValueError(f"--connect wants HOST:PORT, got {connect!r}")
    keyring = keyring or (os.path.join(data_dir, KEYRING)
                          if data_dir else None)
    if keyring is None:
        raise ValueError("--keyring (or --data-dir) required with "
                         "--connect")
    return TcpRados(host, int(port_s), keyring)


# -- client ------------------------------------------------------------------

def _client_handshake(ch: "Channel", cx: CephxClient) -> bytes:
    """Client side of the cephx exchange over a blocking Channel; fills
    ``cx`` with the session key + service ticket, switches ``ch`` to
    secure mode, and returns the service session key."""
    from .auth.cephx import Ticket, _proof, unseal
    now = time.time()
    ch.send(CephxBegin(cx.name))
    challenge = ch.recv_one()
    if not isinstance(challenge, CephxChallenge):
        raise AuthError("expected CephxChallenge")
    client_challenge = os.urandom(16)
    proof = _proof(cx.key, challenge.challenge, client_challenge)
    ch.send(CephxAuthenticate(client_challenge, proof))
    sess = ch.recv_one()
    if not isinstance(sess, CephxSession):
        raise AuthError("expected CephxSession")
    cx.session_key = unseal(cx.key, sess.env)["session_key"]
    t = unseal(cx.session_key, sess.ticket_env)
    cx.tickets[SERVICE] = Ticket(
        service=SERVICE, blob=t["blob"], secret_id=t["secret_id"],
        session_key=t["session_key"], expires=t["expires"])
    authz = cx.build_authorizer(SERVICE, now)
    ch.send(CephxAuthorize(authz))
    done = ch.recv_one()
    if not isinstance(done, CephxDone):
        raise AuthError("expected CephxDone")
    cx.verify_reply(SERVICE, done.reply, authz.nonce)  # mutual auth
    # both ends switch to HMAC frames under the service session key
    key = cx.tickets[SERVICE].session_key
    ch.secure(key)
    return key


def dial_and_handshake(host: str, port: int, key: bytes,
                       timeout: float = 10.0):
    """Blocking dial + full cephx handshake; returns the authenticated
    ``(socket, session_key)`` ready to hand to an async connection.
    This is the msg/ package's entry point for new connections — the
    only legitimately-blocking socket work stays HERE, outside the
    reactor's readiness discipline."""
    cx = CephxClient("client.admin", key)
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.settimeout(None)
    ch = Channel(sock)
    try:
        session_key = _client_handshake(ch, cx)
    except BaseException:
        ch.close()
        raise
    return sock, session_key


class TcpRados:
    """A remote cluster handle: cephx-authenticated, HMAC-secured RPC.

    ``keyring`` is the path the server wrote (client.admin.keyring) —
    reading it from the shared filesystem IS the secret distribution.

    Self-healing (ISSUE 9): the link dropping (reset, truncated frame,
    server bounce) no longer kills the handle — :meth:`call` reconnects
    with bounded full-jitter exponential backoff and RESENDS the rpc
    under its original (session, rid) reqid, which the server dedups, so
    a reset between send and reply is neither a lost op nor a double
    apply.  A per-RPC deadline (``ms_rpc_timeout``) bounds the whole
    dance; a black-holed request times out per attempt and resends.
    """

    def __init__(self, host: str, port: int, keyring: str | os.PathLike,
                 cct=None):
        from .common import default_context
        self._conf = (cct if cct is not None else default_context()).conf
        self._host, self._port = host, port
        with open(keyring, "rb") as f:
            saved = pickle.load(f)
        self._key = saved["key"]
        import uuid
        self._session = uuid.uuid4().hex    # the reqid namespace
        self._rid = 0
        self._lock = threading.Lock()
        self._pending: dict[int, list] = {}
        # rids a call() is actively waiting on: the reader DROPS replies
        # for anything else (a late duplicate reply after a resend must
        # not recreate a popped _pending entry and pin its payload)
        self._waiting: set[int] = set()
        self._cond = threading.Condition()
        self._watch_cbs: dict[int, object] = {}
        self._watch_pools: dict[int, tuple] = {}   # cookie -> (pool, oid)
        self._dead = True
        self._closed = False
        # serializes reconnect attempts: two callers seeing _dead at
        # once must not dial two connections and clobber self.ch
        self._conn_lock = threading.Lock()
        self.reconnects = 0                 # successful re-dials
        self.resends = 0                    # rpc attempts after the first
        # one AsyncConnection on the shared client reactor (msg/): the
        # old per-client reader THREAD is gone — replies and pushes
        # arrive as readiness callbacks.  Same surface as before:
        # .ch.secret, .ch.stats, .ch.send(), .ch.close()
        self.ch = None
        self._connect()

    def _connect(self) -> None:
        """Dial + blocking cephx handshake, then hand the authenticated
        socket to the shared client reactor (one connection's worth).
        The new connection is PUBLISHED only after the handshake
        succeeds, so concurrent senders never see a half-authenticated
        ``self.ch`` (the old, closed connection stays in place until
        then — their sends fail with OSError and their retry loops come
        back around)."""
        self._cephx = CephxClient("client.admin", self._key)
        sock = socket.create_connection((self._host, self._port),
                                        timeout=10.0)
        sock.settimeout(None)
        ch = Channel(sock)
        try:
            self._handshake(ch)
        except BaseException:
            ch.close()
            raise
        # the Channel wrapper retires; the socket lives on, secured,
        # readiness-driven, on the shared reactor
        from .msg.connection import AsyncConnection
        from .msg.reactor import client_reactor
        self.ch = AsyncConnection(
            sock, client_reactor(),
            secret=self._cephx.tickets[SERVICE].session_key,
            name=f"rados.{self._session[:8]}",
            on_message=self._on_message,
            on_closed=self._on_conn_closed)
        with self._cond:
            self._dead = False

    def _reconnect(self) -> None:
        """Bounded reconnect: full-jitter exponential backoff between
        attempts (failure/backoff.py), then re-register watches.  Raises
        RetriesExhausted when the budget runs out.  Serialized: a second
        caller blocks on the lock and returns as soon as the first
        caller's fresh connection is up."""
        from .failure.backoff import ExponentialBackoff
        with self._conn_lock:
            if self._closed:
                # a concurrent close() must not be raced back to life by
                # an in-flight call's retry loop
                raise ConnectionError("client closed")
            with self._cond:
                if not self._dead:
                    return              # someone else already re-dialed
            old = self.ch
            if old is not None:
                old.close()
            ExponentialBackoff(
                base=float(self._conf.get("ms_reconnect_backoff_base")),
                cap=float(self._conf.get("ms_reconnect_backoff_cap")),
                max_attempts=int(
                    self._conf.get("ms_reconnect_max_attempts")),
            ).run(self._connect, retry_on=(ConnectionError, OSError,
                                           AuthError, WireError))
            self.reconnects += 1
        # watches live server-side per CONNECTION: re-arm them on the new
        # one (one shot each; a failure here just surfaces on the next
        # call's own retry loop)
        for cookie in list(self._watch_cbs):
            try:
                self._call_once(self._next_rid(), "watch",
                                {"pool": self._watch_pools[cookie][0],
                                 "oid": self._watch_pools[cookie][1],
                                 "cookie": cookie},
                                timeout=NOTIFY_TIMEOUT)
            except (KeyError, ConnectionError, OSError, IOError,
                    TimeoutError):
                pass

    def _handshake(self, ch: Channel) -> None:
        _client_handshake(ch, self._cephx)

    # -- reply / push callbacks (reactor thread) -----------------------------

    def _on_message(self, conn, msg) -> None:
        if isinstance(msg, RpcResult):
            with self._cond:
                if msg.rid in self._waiting:
                    self._pending.setdefault(msg.rid, []).append(msg)
                    self._cond.notify_all()
                # else: a late duplicate of an answered call — drop it,
                # don't pin its payload
        elif isinstance(msg, NotifyPush):
            # the watch callback is user code and may block (it often
            # answers with its own RPCs): off the reactor thread
            threading.Thread(target=self._run_watch_cb,
                             args=(msg,), daemon=True).start()

    def _on_conn_closed(self, conn, exc) -> None:
        # the link died (reset, truncated frame, server gone): flag it
        # and wake every waiter — call() reconnects and resends
        with self._cond:
            if self.ch is conn:           # not already superseded
                self._dead = True
            self._cond.notify_all()

    def _run_watch_cb(self, push: NotifyPush) -> None:
        cb = self._watch_cbs.get(push.cookie)
        value = None
        if cb is not None:
            try:
                value = cb(push.notify_id, push.cookie, push.payload)
            except Exception as e:             # noqa: BLE001
                value = repr(e)
        try:
            self.ch.send(NotifyAck(push.cookie, push.notify_id, value))
        except (ConnectionError, OSError, AttributeError):
            # link died under the ack (or is mid-reconnect): the server's
            # notify times out and reports it — nothing to heal here
            pass

    def _next_rid(self) -> int:
        with self._lock:
            self._rid += 1
            return self._rid

    def _call_once(self, rid: int, method: str, args: dict,
                   timeout: float):
        """One send + one bounded wait on the CURRENT connection.
        Raises ConnectionError (link died) or TimeoutError (no reply —
        e.g. a black-holed request) for the retry loop to handle."""
        tr = default_tracer()
        ctx = tr.current_ctx() or tr.new_trace("client")
        self.ch.send(RpcCall(rid, method, args, trace=ctx,
                             session=self._session))
        deadline = time.monotonic() + timeout
        with self._cond:
            while not self._pending.get(rid):
                if self._dead:
                    raise ConnectionError("link down")
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"rpc {method} rid={rid}: no reply within "
                        f"{timeout:.1f}s")
                self._cond.wait(left)
            return self._pending.pop(rid)[0]

    def call(self, method: str, timeout: float | None = None, **args):
        """One RPC under the self-healing contract: bounded resends
        (``ms_rpc_retry_attempts``) within one overall deadline
        (``ms_rpc_timeout``), reconnecting with backoff as needed; the
        stable (session, rid) reqid makes every resend dedup-safe."""
        if self._closed:
            raise ConnectionError("client closed")
        total = float(self._conf.get("ms_rpc_timeout")
                      if timeout is None else timeout)
        attempts = int(self._conf.get("ms_rpc_retry_attempts"))
        per_attempt = max(0.05, total / attempts)
        deadline = time.monotonic() + total
        rid = self._next_rid()
        with self._cond:
            self._waiting.add(rid)
        # every RPC is (part of) a client op: adopt the caller's trace
        # or root one, so resend/backoff time below stamps into a trace
        # the critical-path ledger can attribute to `retry`
        tr = default_tracer()
        ctx = tr.current_ctx() or tr.new_trace("client")
        try:
            with tr.activate(ctx, track="client"), \
                    tr.span("client.rpc", cat="client", method=method):
                # the INNER ctx (child of the client.rpc span): resend
                # events must nest UNDER the rpc span, or the span-tree
                # overlap clamp treats them as clipped sibling roots
                # and their time files under the span's self time
                return self._call_with_retries(rid, method, args, total,
                                               attempts, per_attempt,
                                               deadline,
                                               tr.current_ctx() or ctx)
        finally:
            with self._cond:
                self._waiting.discard(rid)
                self._pending.pop(rid, None)   # no ghost replies later

    def _call_with_retries(self, rid, method, args, total, attempts,
                           per_attempt, deadline, ctx=None):
        tr = default_tracer()
        last: BaseException | None = None
        timeouts = 0
        last_mark = time.monotonic()
        for attempt in range(attempts):
            if attempt:
                self.resends += 1
                # time burned since the previous attempt started (the
                # failed attempt + any reconnect backoff) is retry
                # overhead: stamp it into the op's trace
                now = time.monotonic()
                if ctx is not None:
                    tr.complete("net.resend",
                                time.time() - (now - last_mark),
                                now - last_mark, ctx=ctx,
                                method=method, attempt=attempt)
                last_mark = now
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                if self._dead:
                    self._reconnect()
                res = self._call_once(rid, method, args,
                                      min(per_attempt, remaining))
            except TimeoutError as e:
                last = e                  # black-holed: resend, same rid
                timeouts += 1
                if timeouts >= 2:
                    # two silent attempts on one connection: suspect a
                    # HALF-OPEN link (peer died without RST) — force a
                    # re-dial rather than shouting into the void again
                    with self._cond:
                        self._dead = True
                continue
            except (ConnectionError, OSError) as e:
                last = e                  # link died mid-call: mark it
                with self._cond:          # dead so the next attempt
                    self._dead = True     # re-dials instead of resending
                continue                  # on the same broken channel
            if not res.ok:
                err = IOError(res.error)
                err.errno = res.errno
                raise err
            return res.value
        if isinstance(last, TimeoutError):
            raise TimeoutError(f"rpc {method}: no reply within "
                               f"{total:.1f}s ({attempts} attempts)") \
                from last
        raise ConnectionError(
            f"rpc {method}: link down after {attempts} attempts") \
            from last

    # -- convenience surface -------------------------------------------------

    def mkpool(self, name, profile=None, pg_num=8, replicated=False,
               size=3):
        return self.call("mkpool", name=name, profile=profile,
                         pg_num=pg_num, replicated=replicated, size=size)

    def put(self, pool, oid, data):
        return self.call("put", pool=pool, oid=oid, data=bytes(data))

    def get(self, pool, oid) -> bytes:
        return self.call("get", pool=pool, oid=oid)

    def stat(self, pool, oid) -> int:
        return self.call("stat", pool=pool, oid=oid)

    def remove(self, pool, oid):
        return self.call("remove", pool=pool, oid=oid)

    def ls(self, pool):
        return self.call("ls", pool=pool)

    def pools(self):
        return self.call("pools")

    def status(self):
        return self.call("status")

    def setxattr(self, pool, oid, name, value):
        return self.call("setxattr", pool=pool, oid=oid, name=name,
                         value=value)

    def getxattr(self, pool, oid, name):
        return self.call("getxattr", pool=pool, oid=oid, name=name)

    def watch(self, pool, oid, cookie: int, on_notify):
        self._watch_cbs[cookie] = on_notify
        self._watch_pools[cookie] = (pool, oid)
        return self.call("watch", pool=pool, oid=oid, cookie=cookie)

    def unwatch(self, pool, oid, cookie: int):
        self._watch_cbs.pop(cookie, None)
        self._watch_pools.pop(cookie, None)
        return self.call("unwatch", pool=pool, oid=oid, cookie=cookie)

    def notify(self, pool, oid, payload: bytes) -> dict:
        return self.call("notify", pool=pool, oid=oid,
                         payload=bytes(payload))

    def close(self) -> None:
        self._closed = True
        with self._cond:
            self._dead = True
            self._cond.notify_all()
        # under the conn lock: any reconnect in flight finishes first,
        # then we close whatever channel is current — _closed above
        # keeps later retry loops from dialing again
        with self._conn_lock:
            if self.ch is not None:
                self.ch.close()
