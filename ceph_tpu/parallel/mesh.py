"""Device-mesh sharding of codec batches.

The TPU-native equivalent of the reference's cluster fan-out: where Ceph's
primary OSD fans ECSubWrites out to shard OSDs over the async messenger
(reference: src/osd/ECBackend.cc:2036-2070), a multi-chip TPU deployment
shards the stripe batch over a `jax.sharding.Mesh` and lets XLA insert ICI
collectives (SURVEY.md §5 "distributed communication backend").

Mesh axes:
  dp   data parallel over stripes  — independent stripes on different chips
  sp   "sequence" parallel over chunk bytes — one huge stripe split along
       its byte axis (the long-context analog: stripes too big for one chip)

The encode step runs the GF(2) bitslice matmul on each chip's local block,
then reduces a placement checksum over sp (psum) and rotates parity shards
around the dp ring (ppermute) the way the primary hands sub-writes to its
peers.  All collectives ride ICI; nothing touches the host.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import rs_kernels

try:                                    # jax >= 0.8 moved it out of
    from jax import shard_map as _shard_map   # experimental
except ImportError:                     # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map


def make_mesh(n_devices: int | None = None, dp: int | None = None) -> Mesh:
    """A (dp, sp) mesh over the first n_devices devices."""
    devices = jax.devices()
    n = n_devices or len(devices)
    if n > len(devices):
        # an undersized reshape below would raise an opaque numpy error;
        # name the real problem (the serving pipeline gates on this
        # before building a mesh, ad-hoc callers may not)
        raise ValueError(
            f"mesh wants {n} devices, only {len(devices)} present")
    if dp is None:
        dp = 1
        for cand in range(int(np.sqrt(n)), 0, -1):
            if n % cand == 0:
                dp = cand
                break
    sp = n // dp
    arr = np.array(devices[:n]).reshape(dp, sp)
    return Mesh(arr, axis_names=("dp", "sp"))


def sharded_encode_step(mesh: Mesh, parity_mat: np.ndarray):
    """Build a jit'd multi-chip encode step.

    Returns step(data) where data is [B, k, N] uint8, sharded
    [B@dp, k, N@sp].  Output: (parity [B, m, N] with the same sharding,
    checksum [B] int32 psum'd over sp, rotated parity from the dp ring).
    """
    mat = jnp.asarray(parity_mat, dtype=jnp.uint8)
    m, k = parity_mat.shape

    def local_step(data_blk):
        # data_blk: [B/dp, k, N/sp] on this chip.  Restack into the
        # VERTICAL stripe layout and run the PRODUCTION kernel selector
        # (gf_apply_stripes: pallas on TPU, XLA bitslice elsewhere) — the
        # single-chip bench and the sharded path must exercise ONE kernel,
        # so shard_map-over-pallas is exactly what multi-chip runs.
        b, kk, n = data_blk.shape
        vert = data_blk.reshape(b * kk, n)
        parity = rs_kernels.gf_apply_stripes(mat, vert, b)
        parity = parity.reshape(b, m, n)                    # [B/dp, m, N/sp]
        # placement checksum: reduce over the byte axis, then over sp —
        # the integrity cross-check a deep-scrub would do per shard
        # (reference: src/osd/ECBackend.cc:2461 be_deep_scrub crc recompute)
        local_sum = parity.astype(jnp.int32).sum(axis=(1, 2))
        checksum = jax.lax.psum(local_sum, axis_name="sp")
        # sub-write fan-out analog: hand this chip's parity to the next
        # dp-ring neighbour (primary -> shard OSD hop over ICI)
        ndp = jax.lax.psum(1, axis_name="dp")
        rotated = jax.lax.ppermute(
            parity, axis_name="dp",
            perm=[(i, (i + 1) % ndp) for i in range(ndp)])
        return parity, checksum, rotated

    step = _shard_map(
        local_step, mesh=mesh,
        in_specs=(P("dp", None, "sp"),),
        out_specs=(P("dp", None, "sp"), P("dp"), P("dp", None, "sp")))
    return jax.jit(step)


def sharded_batch_encode_step(mesh: Mesh, parity_mat: np.ndarray):
    """Parity-only multi-chip encode for the SERVING batch path: the same
    dp/sp sharding and production kernel selector as
    :func:`sharded_encode_step`, WITHOUT the placement checksum psum and
    the dp-ring ppermute — those model scrub/fan-out for the MULTICHIP
    dryrun, and a serving dispatch that discards them would still pay
    their ICI traffic (jitted outputs cannot be dead-code-eliminated).

    Returns step(data [B, k, N] sharded [B@dp, k, N@sp]) -> parity
    [B, m, N], same sharding.
    """
    mat = jnp.asarray(parity_mat, dtype=jnp.uint8)
    m, _k = parity_mat.shape

    def local_step(data_blk):
        b, kk, n = data_blk.shape
        vert = data_blk.reshape(b * kk, n)
        parity = rs_kernels.gf_apply_stripes(mat, vert, b)
        return parity.reshape(b, m, n)

    step = _shard_map(local_step, mesh=mesh,
                      in_specs=(P("dp", None, "sp"),),
                      out_specs=P("dp", None, "sp"))
    return jax.jit(step)


def sharded_decode_step(mesh: Mesh):
    """Distributed reconstruction: survivors sharded over chips, partial
    GF products reduced over ICI.

    The reference rebuilds a lost shard by pulling chunks from helper OSDs
    over the messenger and combining them on the primary
    (src/osd/ECBackend.cc:565-732 recovery, clay's fractional helper reads).
    The TPU-native shape: survivor chunks live chunk-sharded on the mesh's
    dp axis; each chip applies its columns of the decode matrix to its
    local chunks (a partial GF(2^8) product = XOR-accumulable), and one
    ``psum`` over the axis IS the helper->rebuilder transfer, riding ICI.
    GF addition is XOR, which is exactly bitwise-reduce-able: psum over
    bit-planes mod 2 keeps the math exact.

    Returns step(D, chunks) with D [r, n_survivors] uint8 (replicated) and
    chunks [n_survivors, N] uint8 sharded [n@dp, N@sp]; output [r, N]
    sharded [None, N@sp] (fully reconstructed on every dp row).  Survivor
    counts that don't divide over dp are zero-padded internally (zero
    chunks contribute nothing to the XOR sum).
    """
    ndp = mesh.shape["dp"]

    def local_step(D_blk, chunks_blk):
        # D_blk: [r, n/dp] this chip's columns; chunks_blk: [n/dp, N/sp]
        partial = rs_kernels.gf_apply_lookup(D_blk, chunks_blk)  # [r, N/sp]
        # XOR-reduce over dp: unpack to bit-planes, psum, mod 2, repack —
        # exact because XOR == addition mod 2 per bit; the per-bit sum is
        # bounded by ndp, so uint16 keeps the ICI payload small
        bits = jnp.unpackbits(partial, axis=0, bitorder="little")
        summed = jax.lax.psum(bits.astype(jnp.uint16), axis_name="dp")
        rec_bits = (summed & 1).astype(jnp.uint8)
        return jnp.packbits(rec_bits, axis=0, bitorder="little")

    jitted = jax.jit(_shard_map(
        local_step, mesh=mesh,
        in_specs=(P(None, "dp"), P("dp", "sp")),
        out_specs=P(None, "sp")))

    def step(D, chunks):
        D = jnp.asarray(D, dtype=jnp.uint8)
        chunks = jnp.asarray(chunks, dtype=jnp.uint8)
        n = chunks.shape[0]
        if D.shape[1] != n:
            raise ValueError(
                f"D has {D.shape[1]} columns for {n} survivor chunks")
        pad = (-n) % ndp
        if pad:
            D = jnp.pad(D, ((0, 0), (0, pad)))
            chunks = jnp.pad(chunks, ((0, pad), (0, 0)))
        return jitted(D, chunks)
    return step


def sharded_placement_step(mesh: Mesh, bulk, ruleno: int, n_osds: int,
                           reweights=None, result_max: int = 0):
    """Distributed bulk placement: the multi-chip ParallelPGMapper.

    The reference maps every PG of every pool on a host thread pool
    (reference: src/osd/OSDMapMapping.h:18 ParallelPGMapper); here the
    placement-seed vector shards over the ``dp`` axis, every device runs
    the jitted CRUSH kernel on its block, and the per-OSD utilization
    histogram — what the mon's mapping job exists to produce — reduces
    over the ICI ring with ONE psum.  Returns
    ``step(xs [N]) -> (out [N, numrep] dp-sharded, hist [n_osds]
    replicated)``.
    """
    CRUSH_ITEM_NONE = 0x7FFFFFFF

    def local(xs_blk):
        out, placed = bulk.map_rule(ruleno, xs_blk,
                                    reweights=reweights,
                                    result_max=result_max)
        # holes are CRUSH_ITEM_NONE (a positive int32): mask them like
        # every host consumer does, or they corrupt the scatter index
        valid = (out >= 0) & (out != CRUSH_ITEM_NONE)
        hist = jnp.zeros((n_osds,), jnp.int32).at[
            jnp.where(valid, out, 0)].add(valid.astype(jnp.int32))
        hist = jax.lax.psum(hist, axis_name="dp")     # ICI all-reduce
        return out, hist

    # Disable the replication/varying-axes checker: the CRUSH kernel's
    # bounded-retry loops initialise carries from literals (unvarying)
    # and update them from the dp-varying seeds — sound, but unprovable
    # for the checker.  The kwarg is check_vma on jax >= 0.8 and
    # check_rep on the experimental fallback import.
    import inspect
    kw = ("check_vma" if "check_vma" in
          inspect.signature(_shard_map).parameters else "check_rep")
    return jax.jit(_shard_map(local, mesh=mesh,
                              in_specs=(P("dp"),),
                              out_specs=(P("dp"), P(None)),
                              **{kw: False}))
