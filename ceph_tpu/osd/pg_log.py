"""Per-PG operation log: bounded history for log-based recovery.

Analog of the reference's ``PGLog`` (reference: src/osd/PGLog.{h,cc} ~3k
LoC; EC rollback-entry semantics described in
doc/dev/osd_internals/erasure_coding/ecbackend.rst:8-26): every committed
write appends an entry ``(version, oid, op)``; the log covers the window
``(tail, head]`` and is trimmed as it grows.  A shard that missed writes
is caught up by replaying exactly the entries past its ``last_update``
(O(missed writes)); only a shard whose ``last_update`` predates the tail
needs backfill (O(objects)).  Divergence — a shard holding entries the
authority does not — is detected by comparing entry streams from the
common point, like ``PGLog::merge_log``'s rewind.

The reference keys entries by ``eversion_t(epoch, version)``; here the
single-writer-per-PG pipeline makes the version counter alone total, and
the epoch lives in the map layer (osdmap/mon), so entries carry a plain
monotonic ``version``.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

OP_MODIFY = "modify"
OP_DELETE = "delete"


@dataclass(frozen=True)
class PGLogEntry:
    """pg_log_entry_t (reference: src/osd/osd_types.h pg_log_entry_t)."""
    version: int
    oid: str
    op: str = OP_MODIFY           # OP_MODIFY | OP_DELETE
    prior_version: int = 0        # last version that touched this oid


class PGLog:
    """Bounded ordered log; ``(tail, head]`` are the covered versions."""

    def __init__(self, max_entries: int = 1500):
        self.max_entries = max_entries
        self.entries: deque[PGLogEntry] = deque()
        self.head = 0                 # last_update.version
        self.tail = 0                 # horizon: entries start at tail+1
        self._last_by_oid: dict[str, int] = {}

    # -- append/trim -------------------------------------------------------

    def append(self, oid: str, op: str = OP_MODIFY) -> PGLogEntry:
        self.head += 1
        e = PGLogEntry(self.head, oid, op,
                       prior_version=self._last_by_oid.get(oid, 0))
        self.entries.append(e)
        self._last_by_oid[oid] = self.head
        return e

    def record(self, e: PGLogEntry) -> None:
        """Append a remotely-authored entry (shard side of ECSubWrite)."""
        assert e.version > self.head, f"out of order: {e} after {self.head}"
        self.entries.append(e)
        self.head = e.version
        self._last_by_oid[e.oid] = e.version

    def trim(self, to: int) -> int:
        """Drop entries with version <= ``to``; returns how many."""
        n = 0
        while self.entries and self.entries[0].version <= to:
            e = self.entries.popleft()
            if self._last_by_oid.get(e.oid) == e.version:
                del self._last_by_oid[e.oid]
            n += 1
        self.tail = max(self.tail, to)
        return n

    def rewind(self, to: int) -> list[PGLogEntry]:
        """Drop entries with version > ``to`` (the rollback half of the
        reference's two-phase EC write: entries past the roll-forward point
        are undone when a write fails to reach min_size — the divergent-
        entry rewind of PGLog::merge_log applied locally).  Returns the
        dropped entries, newest first."""
        dropped: list[PGLogEntry] = []
        while self.entries and self.entries[-1].version > to:
            dropped.append(self.entries.pop())
        self.head = max(min(self.head, to), self.tail)
        self._last_by_oid = {e.oid: e.version for e in self.entries}
        return dropped

    def trim_target(self) -> int:
        """Version the followers should trim to (primary piggybacks this on
        sub-writes the way the reference ships ``trim_to``)."""
        return max(0, self.head - self.max_entries)

    def maybe_trim(self) -> None:
        if len(self.entries) > self.max_entries:
            self.trim(self.trim_target())

    # -- queries -----------------------------------------------------------

    def last_version_of(self, oid: str) -> int:
        """Version of the newest in-window entry touching ``oid`` (0 when
        none): the recovery-vs-write race check compares this before and
        after a recovery read to detect an interleaved write."""
        return self._last_by_oid.get(oid, 0)

    def entries_after(self, v: int) -> list[PGLogEntry] | None:
        """Entries with version > v, or None when v predates the tail
        (past the horizon: log cannot catch this follower up)."""
        if v < self.tail:
            return None
        return [e for e in self.entries if e.version > v]

    def catch_up_plan(self, follower_last_update: int
                      ) -> tuple[str, list[PGLogEntry]]:
        """("clean"|"log"|"backfill", entries-to-replay).

        log: replay exactly the missed entries, newest-per-oid
        (PGLog-based recovery); backfill: follower is beyond the horizon.
        """
        if follower_last_update >= self.head:
            return ("clean", [])
        missed = self.entries_after(follower_last_update)
        if missed is None:
            return ("backfill", [])
        return ("log", dedup_latest(missed))

    def divergent_oids(self, follower_entries: list[PGLogEntry]
                       ) -> tuple[set[str], int]:
        """(divergent objects, rewind point) for a follower's log segment.

        Divergent = follower entries past our head, or disagreeing at a
        shared version (merge_log's divergent set); the rewind point is
        the last follower version consistent with this log — the follower
        must drop everything after it."""
        by_version = {e.version: e for e in self.entries}
        out: set[str] = set()
        rewind_to = self.head
        for e in sorted(follower_entries, key=lambda e: e.version):
            if e.version > self.head or (
                    e.version > self.tail and
                    by_version.get(e.version) != e):
                out.add(e.oid)
                rewind_to = min(rewind_to, e.version - 1)
        return out, rewind_to

    def merge_authoritative(self, entries: list[PGLogEntry],
                            last_update: int, rewind_to: int,
                            trim_to: int = 0) -> None:
        """Adopt an authority's segment (the follower half of merge_log):
        drop everything past ``rewind_to``, append the shipped entries,
        advance head to ``last_update``."""
        self.rewind(rewind_to)
        for e in entries:
            if e.version > self.head:
                self.record(e)
        self.head = max(self.head, last_update)
        if trim_to:
            self.trim(trim_to)


def dedup_latest(entries: list[PGLogEntry]) -> list[PGLogEntry]:
    """Collapse to one entry per oid, keeping the newest, in version
    order — replaying the final state per object is sufficient because
    recovery pushes whole current chunks, not deltas."""
    latest: dict[str, PGLogEntry] = {}
    for e in entries:
        latest[e.oid] = e
    return sorted(latest.values(), key=lambda e: e.version)
