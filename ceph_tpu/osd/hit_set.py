"""Hit sets: bloom-filter records of recently accessed objects.

Analog of the reference's HitSet machinery (reference: src/osd/HitSet.h —
BloomHitSet over a compressible bloom filter with fpp/target_size/seed
params; PrimaryLogPG.h:952-966 accumulates one per period and persists an
archive ring).  The tiering agent estimates object "temperature" from how
many recent hit sets contain the object (PrimaryLogPG::agent_estimate_temp)
and evicts cold objects.

Divergence note: the reference's period is wall-clock
(hit_set_period seconds); here the period counts OPS so the in-process
cluster stays deterministic — same ring semantics, testable boundaries.
"""
from __future__ import annotations

import math
import struct

from ..backend.ecutil import crc32c

_HDR = struct.Struct("<IIQ")      # nbits, nhash, inserts


class BloomHitSet:
    """Bloom filter over object names (HitSet.h:323 BloomHitSet).

    Sized from ``target_size`` expected insertions at ``fpp`` false
    positive probability: m = -n*ln(p)/ln(2)^2 bits, k = m/n*ln(2)
    hashes — the standard construction the reference's
    compressible_bloom_filter uses.
    """

    def __init__(self, target_size: int = 1000, fpp: float = 0.05,
                 seed: int = 0):
        n = max(1, int(target_size))
        p = min(max(fpp, 1e-6), 0.5)
        self.nbits = max(8, int(-n * math.log(p) / (math.log(2) ** 2)))
        self.nhash = max(1, round(self.nbits / n * math.log(2)))
        self.seed = seed
        self.bits = bytearray((self.nbits + 7) // 8)
        self.inserts = 0

    def _positions(self, oid: str):
        data = oid.encode()
        h1 = crc32c(0xFFFFFFFF ^ (self.seed & 0xFFFFFFFF), data)
        h2 = crc32c(h1, data) | 1          # odd stride: full period
        for i in range(self.nhash):
            yield (h1 + i * h2) % self.nbits

    def insert(self, oid: str) -> None:
        for pos in self._positions(oid):
            self.bits[pos >> 3] |= 1 << (pos & 7)
        self.inserts += 1

    def contains(self, oid: str) -> bool:
        return all(self.bits[pos >> 3] & (1 << (pos & 7))
                   for pos in self._positions(oid))

    def is_full(self) -> bool:
        return self.inserts >= max(1, int(
            self.nbits * (math.log(2) ** 2) / -math.log(0.05)))

    # -- (de)serialisation (the archive object payload) ---------------------

    def to_bytes(self) -> bytes:
        return _HDR.pack(self.nbits, self.nhash, self.inserts) + \
            bytes(self.bits)

    @classmethod
    def from_bytes(cls, blob: bytes) -> "BloomHitSet":
        nbits, nhash, inserts = _HDR.unpack_from(blob)
        hs = cls.__new__(cls)
        hs.nbits, hs.nhash, hs.inserts = nbits, nhash, inserts
        hs.seed = 0
        hs.bits = bytearray(blob[_HDR.size:_HDR.size + (nbits + 7) // 8])
        return hs


# internal archive objects live outside the user namespace (NUL-embedded,
# like clone oids' SNAP_SEP)
HIT_SET_PREFIX = "hit_set\x00"


def archive_oid(n: int) -> str:
    return f"{HIT_SET_PREFIX}{n:08d}"


def is_hit_set_oid(oid: str) -> bool:
    return oid.startswith(HIT_SET_PREFIX)
