"""The OSD daemon shell: boot, sharded op queue, dispatch.

Analog of the reference's ``OSD`` daemon skeleton (reference:
src/osd/OSD.cc — ``init`` boot at :2719, ``ms_fast_dispatch`` at :6877,
sharded ``enqueue_op``/``dequeue_op`` at :9490,9543): the layer between
the messenger and the PGs.  What the reference spreads over a 10k-LoC
daemon collapses here to the load-bearing pieces:

- **superblock + boot**: the daemon persists ``{whoami, epoch, pgids}``
  in its meta store and on boot re-registers every PG it hosted
  (OSD::init reads the superblock then loads PGs;
  src/osd/OSD.cc:2719,3306).
- **epoch gate**: ops stamped with an older epoch than the PG's are
  bounced back to the client for a resend with a newer map
  (require_same_or_newer_map; the Objecter handles the resend).
- **sharded op queue with mClock QoS**: ops land in one of N shard
  queues picked by pgid hash — the reference's ShardedOpWQ — and each
  shard dequeues in dmClock order over op CLASSES (client ops vs
  recovery vs scrub), so background work cannot starve clients
  (src/osd/OSD.cc:9490-9600, src/osd/mClockOpClassQueue.h).
- **dispatch**: a dequeued client op runs through the PG's op engine
  (PrimaryLogPG.do_op); a dequeued background item is just a thunk.

The event loop is cooperative (``drain``), matching the framework's
deterministic single-thread design; shard count shapes ORDER (ops on one
PG stay FIFO within their class), not parallelism.
"""
from __future__ import annotations

import time
import weakref
from dataclasses import dataclass, field
from typing import Callable

from .mclock import (
    BG_RECOVERY, BG_SCRUB, CLIENT_OP, MClockOpClassQueue,
)
from .osd_ops import MOSDOp, MOSDOpReply
from ..common.device_attribution import canonical_owner
from ..common.tracer import default_tracer

# live daemons, for the prometheus mclock-depth gauge export
_DAEMONS: "weakref.WeakSet[OSDDaemon]" = weakref.WeakSet()


def live_daemons() -> list["OSDDaemon"]:
    return list(_DAEMONS)


@dataclass
class _QueuedOp:
    pgid: object
    run: Callable[[], None]
    cost: float = 1.0
    t_enqueue: float = 0.0          # daemon-clock stamp for queue-wait
    throttled: int = 0              # op-throttle units to release on run


class OSDDaemon:
    """One OSD's daemon shell hosting the PGs whose primary it is."""

    def __init__(self, whoami: int, num_shards: int = 2, clock=None,
                 meta_store=None, op_throttle=None):
        self.whoami = whoami
        self.num_shards = max(1, num_shards)
        self.clock = clock          # VirtualClock or None (monotonic int)
        self._ticks = 0.0
        self.pgs: dict = {}         # pgid -> PGGroup (engine + backend)
        self.epoch = 0
        self.meta_store = meta_store    # FileStore/MemStore for superblock
        self.shards = [MClockOpClassQueue() for _ in range(self.num_shards)]
        self.booted = False
        # optional admission throttle (exec.Throttle over op count): past
        # the bound, ms_dispatch answers ('throttled', epoch) and the
        # client backs off — the daemon-queue face of the same
        # backpressure the serving engine applies at the codec
        self.op_throttle = op_throttle
        # queue accounting for the exporter: enqueued/dequeued totals and
        # summed queue wait (daemon-clock seconds)
        self.queue_stats = {"enqueued": 0, "dequeued": 0,
                            "throttled_rejects": 0, "wait_sum": 0.0}
        _DAEMONS.add(self)

    # -- superblock (OSDSuperblock; src/osd/OSD.cc read_superblock) --------

    SUPERBLOCK = "osd_superblock"

    def _now(self) -> float:
        if self.clock is not None:
            return self.clock.now()
        self._ticks += 1e-3
        return self._ticks

    def advance_clock(self, dt: float) -> None:
        """Consume ``dt`` seconds of virtual time — how 'sleeping' works
        in the cooperative model.  The recovery scheduler uses this for
        ``osd_recovery_sleep`` and token-bucket debt between waves: the
        pacing is real on the daemon clock (queue-wait accounting, mClock
        tags) without ever blocking the single thread."""
        if dt <= 0:
            return
        if self.clock is not None:
            self.clock.advance(dt)
        else:
            self._ticks += dt

    def write_superblock(self) -> None:
        if self.meta_store is None:
            return
        from ..backend.memstore import GObject, Transaction
        t = Transaction().setattr(
            GObject(self.SUPERBLOCK), "sb",
            {"whoami": self.whoami, "epoch": self.epoch,
             "pgids": sorted(self.pgs, key=repr)})
        self.meta_store.queue_transaction(t)

    def read_superblock(self) -> dict | None:
        if self.meta_store is None:
            return None
        from ..backend.memstore import GObject
        try:
            return dict(self.meta_store.getattr(GObject(self.SUPERBLOCK),
                                                "sb"))
        except (FileNotFoundError, KeyError):
            return None

    def boot(self, pg_loader: Callable[[object], object] | None = None
             ) -> list:
        """OSD::init: read the superblock, re-register every hosted PG via
        ``pg_loader(pgid) -> PGGroup`` (the caller owns store opening /
        peering — MiniCluster.load's boot path), bump to booted."""
        sb = self.read_superblock()
        loaded = []
        if sb is not None:
            self.epoch = max(self.epoch, int(sb["epoch"]))
            if pg_loader is not None:
                for pgid in sb["pgids"]:
                    g = pg_loader(pgid)
                    if g is not None:
                        self.pgs[pgid] = g
                        loaded.append(pgid)
        self.booted = True
        return loaded

    # -- PG registry -------------------------------------------------------

    def register_pg(self, pgid, group) -> None:
        self.pgs[pgid] = group
        self.epoch = max(self.epoch, getattr(group, "epoch", 0))
        self.write_superblock()

    def advance_epoch(self, epoch: int) -> None:
        self.epoch = max(self.epoch, epoch)
        self.write_superblock()

    # -- op intake (ms_fast_dispatch + enqueue_op) -------------------------

    def _shard_for(self, pgid) -> MClockOpClassQueue:
        return self.shards[hash(pgid) % self.num_shards]

    def ms_dispatch(self, pgid, m: MOSDOp,
                    on_reply: Callable[[MOSDOpReply], None],
                    op_class: str = CLIENT_OP):
        """Accept a client op for a hosted PG.  Returns None when queued,
        or ``("stale", epoch)`` when the op's epoch predates the PG's
        acting set (client must refresh its map and resend)."""
        g = self.pgs.get(pgid)
        if g is None or g.backend.whoami != self.whoami:
            return ("stale", self.epoch)
        if m.epoch < g.epoch:
            return ("stale", self.epoch)
        if self.op_throttle is not None and \
                not self.op_throttle.get_or_fail(1):
            # bounded daemon queue: refuse instead of growing (the
            # reference's messenger policy throttles the same way; the
            # client treats it like a transient and resends with backoff)
            self.queue_stats["throttled_rejects"] += 1
            return ("throttled", self.epoch)
        cost = 1.0 + sum(len(op.params.get("data", b""))
                         for op in m.ops) / 65536.0
        now = self._now()
        self.queue_stats["enqueued"] += 1

        t_enq_mono = time.monotonic()   # real clock: _now() may be virtual

        def run(m=m, g=g, on_reply=on_reply, op_class=op_class,
                t_enq_mono=t_enq_mono):
            # the queued op runs much later (drain), on whatever thread
            # drives the bus: re-activate the context the CLIENT stamped
            # on the MOSDOp so this daemon's spans stitch under it, with
            # this OSD as their track
            tr = default_tracer()
            ctx = getattr(m, "trace", None)
            wait = max(0.0, time.monotonic() - t_enq_mono)
            if ctx is not None:
                # the op's daemon-queue wait, stamped into its trace —
                # the critical-path ledger's `queue` phase
                tr.complete("osd.queue_wait", time.time() - wait, wait,
                            ctx=ctx, osd=self.whoami)
            with tr.activate(ctx, track=f"osd.{self.whoami}"), \
                    tr.span("osd.op", oid=m.oid,
                            owner=canonical_owner(op_class)):
                g.engine.do_op(m, on_reply)
        self._shard_for(pgid).enqueue(
            op_class,
            _QueuedOp(pgid, run, cost, t_enqueue=now,
                      throttled=1 if self.op_throttle is not None else 0),
            now, cost=cost)
        return None

    def queue_background(self, pgid, fn: Callable[[], None],
                         op_class: str = BG_RECOVERY,
                         cost: float = 1.0) -> None:
        """Recovery/scrub work rides the same queue under its own QoS
        class (the reference queues PGRecovery/PGScrub items alongside
        client ops, src/osd/OSD.cc:9700+)."""
        now = self._now()
        self.queue_stats["enqueued"] += 1
        # background items run under their own root trace whose op class
        # is the dmClock class: every span (and device dispatch) below
        # them attributes to recovery/scrub instead of masquerading as
        # client work — unless the caller already carries a context
        # (e.g. the recovery scheduler's wave trace)
        owner = canonical_owner(op_class)
        # the ENQUEUING thread's context (e.g. the recovery scheduler's
        # wave trace) rides along; drain-time ambient context must not —
        # a client op draining the queue would misattribute the backlog
        ctx = default_tracer().current_ctx()

        t_enq_mono = time.monotonic()   # real clock: _now() may be virtual

        def run(fn=fn, owner=owner, ctx=ctx, t_enq_mono=t_enq_mono):
            tr = default_tracer()
            actx = ctx if ctx is not None else tr.new_trace(owner)
            wait = max(0.0, time.monotonic() - t_enq_mono)
            # background work pays queue wait too (scrub behind client
            # bursts): stamped so its class's attribution carries it
            tr.complete("osd.queue_wait", time.time() - wait, wait,
                        ctx=actx, osd=self.whoami)
            with tr.activate(actx, track=f"osd.{self.whoami}"), \
                    tr.span(f"osd.{owner}", owner=owner):
                fn()
        self._shard_for(pgid).enqueue(
            op_class, _QueuedOp(pgid, run, cost, t_enqueue=now), now,
            cost=cost)

    def queue_depths(self) -> dict:
        """Per-shard mClock depths (the prometheus gauge surface)."""
        return {i: s.depths() for i, s in enumerate(self.shards)
                if not s.empty()}

    def _run_item(self, item: _QueuedOp) -> None:
        self.queue_stats["dequeued"] += 1
        self.queue_stats["wait_sum"] += max(
            0.0, self._now() - item.t_enqueue)
        try:
            item.run()
        finally:
            if item.throttled and self.op_throttle is not None:
                self.op_throttle.put(item.throttled)

    # -- dispatch loop (dequeue_op) ----------------------------------------

    def drain(self, max_ops: int | None = None) -> int:
        """Dequeue in mClock order until empty (or max_ops); returns the
        number dispatched.  Items whose QoS limit pushes them into the
        future still run — 'limited' classes yield to eligible ones but a
        drain must not leave work behind (the reference blocks the shard
        thread on next_eligible_time the same way)."""
        ran = 0
        while max_ops is None or ran < max_ops:
            progressed = False
            for shard in self.shards:
                if shard.empty():
                    continue
                now = self._now()
                item = shard.dequeue(now)
                if item is None:
                    nxt = shard.next_eligible_time(now)
                    if nxt is None:
                        continue
                    if self.clock is not None:
                        self.clock.advance(nxt - now)
                    else:
                        self._ticks = nxt
                    item = shard.dequeue(self._now())
                    if item is None:
                        continue
                self._run_item(item)
                ran += 1
                progressed = True
                if max_ops is not None and ran >= max_ops:
                    break
            if not progressed:
                break
        return ran

    def pending(self) -> int:
        return sum(0 if s.empty() else 1 for s in self.shards)
