"""OSD-side subsystems: PG log, peering, scheduling.

The distributed-systems spine around the EC backend — the analog of the
reference's src/osd/ beyond the EC slice (PGLog.cc, PeeringState.cc,
mClock queues).
"""
from .pg_log import PGLog, PGLogEntry

__all__ = ["PGLog", "PGLogEntry"]
