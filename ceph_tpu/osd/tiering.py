"""Cache tiering: a writeback cache pool in front of a base pool.

Analog of the reference's cache-tier machinery (reference:
src/osd/PrimaryLogPG.h:971-992 TierAgentState/agent_work/agent_maybe_flush/
agent_maybe_evict; osd_types.h cache_mode_t CACHEMODE_WRITEBACK and
object_info_t FLAG_DIRTY):

- :class:`CacheTier` is the IO facade (the role the OSD's cache-mode
  dispatch plays when the OSDMap overlays a base pool with its tier):
  reads and writes go to the CACHE pool; a read miss promotes the object
  from the base pool first (promote_object), then serves from cache.
  Every cache write sets the DIRTY flag atomically in the same op vector
  — the object_info_t FLAG_DIRTY the reference's OSD sets internally.
- :class:`TieringAgent` is the background worker: it flushes DIRTY cache
  objects down to the base pool (clearing the flag) and evicts COLD
  clean objects — temperature 0 across the cache PG's hit sets
  (agent_estimate_temp) — so the cache holds only the working set.
"""
from __future__ import annotations

from .hit_set import is_hit_set_oid
from .osd_ops import ObjectOperation

DIRTY_ATTR = "tier.dirty"            # object_info_t FLAG_DIRTY analog


class CacheTier:
    """Writeback cache-mode IO facade over (cache pool, base pool)."""

    def __init__(self, cluster, cache_pool: int, base_pool: int):
        self.c = cluster
        self.cache = cache_pool
        self.base = base_pool

    # -- promote (PrimaryLogPG::promote_object) -----------------------------

    def _promote(self, oid: str) -> bool:
        """Copy base -> cache on a miss; False when the object exists in
        neither tier.  A fresh promote is CLEAN (no dirty flag): it is
        byte-identical to the base copy."""
        try:
            r = self.c.operate(self.base, oid,
                               ObjectOperation().read(0, 0).getxattrs())
        except IOError:
            return False
        data, attrs = r.outdata(0), r.outdata(1)
        op = ObjectOperation().write_full(bytes(data))
        for name, value in sorted(attrs.items()):
            op.setxattr(name, value)
        self.c.operate(self.cache, oid, op)
        return True

    # -- client IO ----------------------------------------------------------

    def read(self, oid: str) -> bytes:
        try:
            return bytes(self.c.operate(
                self.cache, oid, ObjectOperation().read(0, 0)).outdata(0))
        except IOError as e:
            if getattr(e, "errno", None) != -2:
                raise
        if not self._promote(oid):
            raise FileNotFoundError(oid)
        return bytes(self.c.operate(self.cache, oid,
                                    ObjectOperation().read(0, 0))
                     .outdata(0))

    def write(self, oid: str, data: bytes) -> None:
        """CACHEMODE_WRITEBACK: the write lands in the cache only, with
        the dirty flag riding the SAME atomic op vector; the agent
        flushes to the base pool later."""
        self.c.operate(self.cache, oid, ObjectOperation()
                       .write_full(bytes(data)).setxattr(DIRTY_ATTR, True))


class TieringAgent:
    """The background flush/evict worker (agent_work)."""

    def __init__(self, cluster, cache_pool: int, base_pool: int):
        self.c = cluster
        self.cache = cache_pool
        self.base = base_pool
        self.stats = {"flushes": 0, "evictions": 0, "skipped_hot": 0}

    def is_dirty(self, oid: str) -> bool:
        try:
            self.c.operate(self.cache, oid,
                           ObjectOperation().getxattr(DIRTY_ATTR),
                           internal=True)
            return True
        except IOError:
            return False              # no flag (or no object): clean

    def temperature(self, oid: str) -> int:
        return self.c.pg_group(self.cache, oid).engine.object_temperature(
            oid)

    # -- agent work (agent_maybe_flush / agent_maybe_evict) -----------------

    def flush(self, oid: str) -> None:
        """Copy the cache object down to the base pool, then clear the
        dirty flag (agent_maybe_flush)."""
        r = self.c.operate(self.cache, oid,
                           ObjectOperation().read(0, 0).getxattrs(),
                           internal=True)
        data, attrs = r.outdata(0), r.outdata(1)
        op = ObjectOperation().write_full(bytes(data))
        for name, value in sorted(attrs.items()):
            if name != DIRTY_ATTR:
                op.setxattr(name, value)
        self.c.operate(self.base, oid, op, internal=True)
        self.c.operate(self.cache, oid,
                       ObjectOperation().rmxattr(DIRTY_ATTR),
                       internal=True)
        self.stats["flushes"] += 1

    def evict(self, oid: str) -> None:
        """Drop a CLEAN object from the cache (agent_maybe_evict)."""
        self.c.operate(self.cache, oid, ObjectOperation().remove(),
                       internal=True)
        self.stats["evictions"] += 1

    def age(self) -> None:
        """Roll every cache PG's hit-set ring forward one slot.  The
        reference ages by wall-clock (hit_set_period seconds); with this
        framework's deterministic op-count periods an idle PG would
        never age, so the agent's periodic pass IS the clock — one
        ``age()`` per pass makes 'cold' mean 'untouched for the last
        hit_set_count agent periods'."""
        for g in self.c.pools[self.cache]["pgs"].values():
            if g.engine.hit_set_params is not None:
                g.engine.hit_set_persist()

    def agent_work(self, max_ops: int = 1 << 30,
                   age: bool = False) -> dict:
        """One agent pass: flush every dirty object; evict the clean AND
        cold (temperature 0) ones.  ``age=True`` rolls the hit-set rings
        first (see :meth:`age`).  Returns cumulative stats."""
        if age:
            self.age()
        done = 0
        for oid in sorted(self.c.objects.get(self.cache, set())):
            if is_hit_set_oid(oid) or done >= max_ops:
                continue
            if self.is_dirty(oid):
                self.flush(oid)
                done += 1
            if self.temperature(oid) == 0:
                if not self.is_dirty(oid):
                    self.evict(oid)
                    done += 1
            else:
                self.stats["skipped_hot"] += 1
        return dict(self.stats)
