"""The primary's object-op engine: PrimaryLogPG's do_osd_ops analog.

Executes a client op vector (``MOSDOp``) against one object, atomically:
reads resolve against the (possibly degraded) PG via the backend's
reconstructing read path, mutations stage into ONE ``PGTransaction`` that
rides the backend's ordered write pipeline (min_size gate, rollback,
recovery — all below this layer).

Reference call stack (SURVEY §3.1): PrimaryLogPG::do_request → do_op →
execute_ctx → do_osd_ops (the giant opcode switch,
src/osd/PrimaryLogPG.cc:5577) → prepare_transaction → issue_repop →
PGBackend::submit_transaction (src/osd/PrimaryLogPG.cc:1565,1756,3709,
8319,10422).  Object metadata is an ``object_info_t`` xattr "_" on every
shard and user xattrs are stored "_"-prefixed, both exactly like the
reference (src/osd/osd_types.h OI_ATTR).

Implemented surfaces: data/metadata reads, the write family, xattr and
omap ops with guards, object classes (cls registry), snapshots
(SnapContext COW + snap reads + rollback + list_snaps) and watch/notify.

Scope notes (deliberate divergences, all returning clean errors):
- cache tiering lives in osd/hit_set.py (per-period bloom hit sets
  accumulated here, archived as internal PG objects) + osd/tiering.py
  (writeback CacheTier facade + flush/evict TieringAgent); the in-engine
  proxy/flush OPS of the reference (COPY_FROM, CACHE_FLUSH/EVICT
  opcodes) stay out of the opcode switch — the facade + agent carry the
  same semantics at pool level;
- data READs inside a *write* vector are rejected with -EINVAL on EC
  pools (the reference queues them as pending_async_reads; here a vector
  is either data-reading or mutating — metadata reads work in both);
- CEPH_OSD_OP_ZERO never extends the object (the reference's behavior
  with the default truncate_seq handling);
- ROLLBACK must be the only mutation in its vector.

Ordering: mutating vectors take a per-object in-flight slot; any later op
on the same object queues until the commit callback fires — the obc
rw-lock ordering of the reference collapsed to its observable effect.
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable

from ..backend.memstore import GObject
from ..backend.transaction import PGTransaction
from .osd_ops import (
    CMPXATTR_EQ, CMPXATTR_GT, CMPXATTR_GTE, CMPXATTR_LT, CMPXATTR_LTE,
    CMPXATTR_MODE_STRING, CMPXATTR_MODE_U64, CMPXATTR_NE, DATA_READ_OPS,
    MOSDOp, MOSDOpReply, OP_APPEND, OP_CALL, OP_CMPEXT, OP_CMPXATTR,
    OP_CREATE, OP_DELETE, OP_GETXATTR, OP_GETXATTRS, OP_OMAPCLEAR,
    OP_OMAPGETHEADER, OP_OMAPGETKEYS, OP_OMAPGETVALS, OP_OMAPGETVALSBYKEYS,
    OP_LIST_SNAPS, OP_LIST_WATCHERS, OP_NOTIFY, OP_OMAPRMKEYS,
    OP_OMAPSETHEADER, OP_OMAPSETVALS,
    OP_OMAP_CMP, OP_READ, OP_RMXATTR, OP_ROLLBACK, OP_SETXATTR,
    OP_SPARSE_READ, OP_STAT, OP_TRUNCATE, OP_UNWATCH, OP_WATCH,
    OP_WRITE, OP_WRITEFULL, OP_ZERO, OSDOp, WRITE_OPS,
)

# errnos, negated like the reference's rvals
ENOENT, EEXIST, EINVAL = -2, -17, -22
ENODATA = -61
EOPNOTSUPP = -95
ECANCELED = -125
EROFS = -30
ENOTSUP_COMBINED = -22    # rollback combined with other mutations
MAX_ERRNO = 4095          # cmpext mismatch: -(MAX_ERRNO + offset)

OI_ATTR = "_"             # object_info_t xattr (src/osd/osd_types.h)
SS_ATTR = "snapset"       # SnapSet xattr (src/osd/osd_types.h SS_ATTR)
USER_PREFIX = "_"         # user xattr "foo" is stored as "_foo"
SNAP_SEP = "\x00snap\x00"  # clone object namespace (ghobject snap field
                           # analog; NUL keeps user oids collision-free)


def clone_oid(oid: str, snapid: int) -> str:
    return f"{oid}{SNAP_SEP}{snapid}"


def is_clone_oid(oid: str) -> bool:
    return SNAP_SEP in oid


def split_clone_oid(oid: str) -> tuple[str, int] | None:
    """(head, snapid) for a clone oid, None for a head."""
    if SNAP_SEP not in oid:
        return None
    head, _, cid = oid.rpartition(SNAP_SEP)
    return head, int(cid)


def empty_snapset() -> dict:
    # lbs[c] = snapset.seq at clone c's creation: clone c covers exactly
    # the snaps in (lbs[c], c] — the analog of the reference SnapSet's
    # per-clone clone_snaps list (src/osd/osd_types.h SnapSet), which is
    # what lets reads at PRE-creation snaps resolve to ENOENT even after
    # later clones exist
    return {"seq": 0, "clones": [], "sizes": {}, "lbs": {}}


def clone_lower_bound(ss: dict, c: int) -> int:
    """The oldest snap NOT covered by clone c (0 = covers everything
    below c; legacy snapsets without lbs keep the old semantics)."""
    lbs = ss.get("lbs", {})
    return lbs.get(c, lbs.get(str(c), 0))
# non-user attrs that share the "_" prefix (internal attrs otherwise use
# non-"_" prefixes — e.g. the replicated backend's "@version" — so they
# cannot collide with any user name)
INTERNAL_ATTRS = frozenset({OI_ATTR})


class OpError(Exception):
    def __init__(self, rval: int):
        self.rval = rval


@dataclass
class ClsMethod:
    fn: Callable
    mutates: bool


class ClsRegistry:
    """Object-class method registry (the reference's loadable cls plugins,
    src/cls/ + PrimaryLogPG's CEPH_OSD_OP_CALL dispatch)."""

    _methods: dict[tuple[str, str], ClsMethod] = {}

    @classmethod
    def register(cls, cls_name: str, method: str, fn: Callable,
                 mutates: bool = False) -> None:
        cls._methods[(cls_name, method)] = ClsMethod(fn, mutates)

    @classmethod
    def get(cls, cls_name: str, method: str) -> ClsMethod | None:
        return cls._methods.get((cls_name, method))


class ClsContext:
    """What a cls method sees: the op's staged object state."""

    def __init__(self, ectx: "_ExecCtx", indata: bytes):
        self._ctx = ectx
        self.indata = indata
        self.oid = ectx.m.oid

    def exists(self) -> bool:
        return self._ctx.exists

    def size(self) -> int:
        return self._ctx.size

    def getxattr(self, name: str):
        return self._ctx.get_attr(USER_PREFIX + name)

    # mutations stage into the surrounding op vector's transaction
    def setxattr(self, name: str, value) -> None:
        self._ctx.stage_attr(USER_PREFIX + name, value)

    def write_full(self, data: bytes) -> None:
        self._ctx.stage_write_full(data)

    def append(self, data: bytes) -> None:
        self._ctx.stage_write(self._ctx.size, data)


@dataclass
class _ExecCtx:
    """Mutable execute state: the reference's OpContext (new_obs + op_t)."""
    m: MOSDOp
    engine: "PrimaryLogPG"
    exists: bool
    size: int
    attrs: dict = field(default_factory=dict)       # overlay: name -> v|None
    attrs_cleared: bool = False     # staged delete dropped the base attrs
    omap: dict = field(default_factory=dict)        # overlay: key -> v|None
    omap_cleared: bool = False
    omap_header: bytes | None = None
    t: PGTransaction = field(default_factory=PGTransaction)
    mutated: bool = False
    user_modify: bool = False
    # watch/unwatch effects staged until the vector SUCCEEDS (the
    # reference's do_osd_op_effects runs only on success)
    watch_effects: list = field(default_factory=list)

    # -- staged-state readers ---------------------------------------------

    def _gobj(self) -> GObject:
        return GObject(self.m.oid, self.engine.backend.whoami)

    def get_attr(self, name: str):
        """Committed attr overlaid with this vector's staged updates."""
        if name in self.attrs:
            if self.attrs[name] is None:
                raise KeyError(name)
            return self.attrs[name]
        if self.attrs_cleared:      # staged delete: base attrs are gone
            raise KeyError(name)
        store = self.engine.backend.local_shard.store
        gobj = self._gobj()
        if not store.exists(gobj):
            raise KeyError(name)
        return store.getattr(gobj, name)

    def get_attrs(self) -> dict:
        store = self.engine.backend.local_shard.store
        gobj = self._gobj()
        base = ({} if self.attrs_cleared or not store.exists(gobj)
                else store.getattrs(gobj))
        base.update({k: v for k, v in self.attrs.items() if v is not None})
        for k, v in self.attrs.items():
            if v is None:
                base.pop(k, None)
        return base

    def get_omap(self) -> dict:
        store = self.engine.backend.local_shard.store
        gobj = self._gobj()
        base = ({} if self.omap_cleared or not store.exists(gobj)
                else store.get_omap(gobj))
        base.update({k: v for k, v in self.omap.items() if v is not None})
        for k, v in self.omap.items():
            if v is None:
                base.pop(k, None)
        return base

    def get_omap_header(self) -> bytes:
        if self.omap_header is not None:
            return self.omap_header
        if self.omap_cleared:
            return b""
        store = self.engine.backend.local_shard.store
        gobj = self._gobj()
        return store.get_omap_header(gobj) if store.exists(gobj) else b""

    # -- staged-state writers ----------------------------------------------

    def objop(self):
        return self.t.touch(self.m.oid)

    def stage_attr(self, name: str, value) -> None:
        self.attrs[name] = value
        if value is None:
            self.objop().rmattr(name)
        else:
            self.objop().setattr(name, value)
        self.mutated = True

    def stage_write(self, offset: int, data: bytes) -> None:
        self.objop().write(offset, data)
        self.size = max(self.size, offset + len(data))
        self.exists = True
        self.mutated = self.user_modify = True

    def stage_write_full(self, data: bytes) -> None:
        op = self.objop()
        op.buffer_updates = [(0, bytes(data))]
        op.truncate = (len(data), len(data))
        self.size = len(data)
        self.exists = True
        self.mutated = self.user_modify = True

    def stage_truncate(self, size: int) -> None:
        op = self.objop()
        # clip staged writes beyond the new size so a write-then-truncate
        # vector ends at exactly `size` (the reference applies ops in
        # order inside one transaction)
        clipped = []
        for off, data in op.buffer_updates:
            if off >= size:
                continue
            clipped.append((off, data[:size - off]) if off + len(data) > size
                           else (off, data))
        op.buffer_updates = clipped
        op.truncate = (size, size)
        self.size = size
        self.exists = True
        self.mutated = self.user_modify = True

    def stage_omap(self, kind: str, *args) -> None:
        self.objop().omap_ops.append((kind, *args))
        self.mutated = self.user_modify = True


class PrimaryLogPG:
    """The op engine bound to one PG's backend."""

    def __init__(self, backend, pool_type: str = "ec"):
        self.backend = backend
        self.pool_type = pool_type
        self.version = 0            # pg op version (eversion_t analog)
        self.user_version = 0
        self._busy: set[str] = set()
        self._waiting: dict[str, deque] = {}
        # watch/notify state (the obc watchers map, src/osd/Watch.cc)
        self.watchers: dict[str, dict[int, object]] = {}
        self.notify_id = 0
        # hit-set accumulation (PrimaryLogPG.h:952-966); configured by
        # the pool's hit_set_* params via configure_hit_sets
        self.hit_set = None
        self.hit_set_params: dict | None = None
        self.hit_set_archive_n = 0
        self._hit_set_ops = 0

    # -- hit sets (hit_set_setup/persist/trim, PrimaryLogPG.h:957-961) ------

    def configure_hit_sets(self, count: int, period: int,
                           target_size: int = 1000,
                           fpp: float = 0.05) -> None:
        """hit_set_setup: start accumulating per-period bloom hit sets,
        archived as internal PG objects in a ring of ``count``.  The
        period counts OPS (deterministic in-process; the reference uses
        wall-clock seconds — see osd/hit_set.py)."""
        from .hit_set import HIT_SET_PREFIX, BloomHitSet, is_hit_set_oid
        self.hit_set_params = {"count": int(count), "period": int(period),
                               "target_size": int(target_size),
                               "fpp": float(fpp)}
        self.hit_set = BloomHitSet(target_size, fpp)
        self._hit_set_ops = 0
        # restart: resume the archive ring after the persisted ones
        store = self.backend.local_shard.store
        ns = [int(g.oid[len(HIT_SET_PREFIX):])
              for g in store.list_objects()
              if g.shard == self.backend.whoami and is_hit_set_oid(g.oid)]
        self.hit_set_archive_n = max(ns, default=-1) + 1

    def _hit_set_record(self, oid: str) -> None:
        from .hit_set import is_hit_set_oid
        if self.hit_set is None or is_hit_set_oid(oid):
            return
        parsed = split_clone_oid(oid)
        self.hit_set.insert(parsed[0] if parsed else oid)
        self._hit_set_ops += 1
        if self._hit_set_ops >= self.hit_set_params["period"]:
            self.hit_set_persist()

    def hit_set_persist(self) -> None:
        """Archive the accumulating set as an internal PG object and trim
        the ring past hit_set_count (hit_set_persist + hit_set_trim)."""
        from .hit_set import BloomHitSet, archive_oid
        p = self.hit_set_params
        n = self.hit_set_archive_n
        self.hit_set_archive_n += 1
        t = PGTransaction().write(archive_oid(n), 0,
                                  self.hit_set.to_bytes())
        old = n - p["count"]
        if old >= 0:
            t.delete(archive_oid(old))
        self.backend.submit_transaction(t)
        self._hit_set_ops = 0
        self.hit_set = BloomHitSet(p["target_size"], p["fpp"])

    def hit_set_archives(self) -> list:
        """The persisted ring, oldest first (agent_load_hit_sets)."""
        from .hit_set import BloomHitSet, archive_oid
        if self.hit_set_params is None:
            return []
        store = self.backend.local_shard.store
        out = []
        lo = max(0, self.hit_set_archive_n - self.hit_set_params["count"])
        for n in range(lo, self.hit_set_archive_n):
            gobj = GObject(archive_oid(n), self.backend.whoami)
            if store.exists(gobj):
                out.append(BloomHitSet.from_bytes(bytes(
                    store.read(gobj))))
        return out

    def object_temperature(self, oid: str) -> int:
        """How many recent hit sets (current + archives) saw this object
        (agent_estimate_temp: 0 = cold, eviction candidate)."""
        temp = 0
        if self.hit_set is not None and self.hit_set.contains(oid):
            temp += 1
        for hs in self.hit_set_archives():
            if hs.contains(oid):
                temp += 1
        return temp

    # -- entry -------------------------------------------------------------

    def do_op(self, m: MOSDOp, on_reply: Callable[[MOSDOpReply], None]):
        """Execute one client op vector; on_reply fires with the reply —
        immediately for pure reads, at commit for mutations."""
        if not m.internal:
            self._hit_set_record(m.oid)
        if m.oid in self._busy:
            self._waiting.setdefault(m.oid, deque()).append((m, on_reply))
            return
        self._start(m, on_reply)

    def _op_mutates(self, op: OSDOp) -> bool:
        if op.op in WRITE_OPS:
            return True
        if op.op == OP_CALL:
            meth = ClsRegistry.get(op.params["cls"], op.params["method"])
            return bool(meth and meth.mutates)
        return False

    def _load_snapset(self, oid: str) -> dict:
        """The head's SnapSet.  An existing head without the attr simply
        has no clones (cheap).  Only a MISSING head (deleted under
        snapshots — the reference keeps a snapdir object for this case)
        pays a store scan to rediscover its clones."""
        store = self.backend.local_shard.store
        gobj = GObject(oid, self.backend.whoami)
        if store.exists(gobj):
            try:
                return dict(store.getattr(gobj, SS_ATTR))
            except KeyError:
                return empty_snapset()
        prefix = oid + SNAP_SEP
        clones = sorted(
            int(g.oid[len(prefix):]) for g in store.list_objects()
            if g.shard == self.backend.whoami and g.oid.startswith(prefix))
        ss = empty_snapset()
        ss["seq"] = max(clones, default=0)
        ss["clones"] = clones
        # per-clone lower bounds survive head deletion because each clone
        # is a copy of the PRE-COW head, whose own SS_ATTR recorded the
        # snapset.seq of that moment — exactly lbs[c].  (The reference
        # keeps a snapdir object for the deleted-head case instead.)
        for c in clones:
            try:
                old_ss = dict(store.getattr(
                    GObject(clone_oid(oid, c), self.backend.whoami),
                    SS_ATTR))
                ss["lbs"][c] = int(old_ss.get("seq", 0))
            except KeyError:
                pass                 # clone predates lbs / no snap context
        return ss

    def _resolve_snap(self, oid: str, snapid: int) -> str | None:
        """find_object_context's snap resolution: clone c covers the snap
        interval (lbs[c], c]; a read at snap s hits the oldest clone >= s
        IF s falls inside its coverage, else the head.  None = the object
        did not exist at that snap (it postdates the creation seq stamped
        on the snapset, or falls below the covering clone's lower bound)
        -> ENOENT."""
        ss = self._load_snapset(oid)
        for c in sorted(ss["clones"]):
            if c >= snapid:
                if snapid <= clone_lower_bound(ss, c):
                    # the clone postdates the object's creation at snapid
                    # (e.g. snap taken, THEN object created, THEN cloned):
                    # no state existed at snapid
                    return None
                return clone_oid(oid, c)
        if snapid <= ss["seq"]:
            return None
        return oid

    def _start(self, m: MOSDOp, on_reply) -> None:
        has_write = any(self._op_mutates(op) for op in m.ops)
        if m.snapid is not None:
            # snaps are read-only; resolve the whole vector onto the
            # covering clone (or the head)
            if has_write:
                on_reply(MOSDOpReply(EROFS, m.ops))
                return
            if any(op.op in (OP_WATCH, OP_UNWATCH, OP_NOTIFY,
                             OP_LIST_WATCHERS) for op in m.ops):
                # watches live on the HEAD; registering one under a
                # resolved clone oid would leak an unreachable entry
                on_reply(MOSDOpReply(EINVAL, m.ops))
                return
            resolved = self._resolve_snap(m.oid, m.snapid)
            if resolved is None:        # object postdates the snap
                on_reply(MOSDOpReply(ENOENT, m.ops))
                return
            m.oid = resolved
        if has_write:
            # take the per-object write slot BEFORE any async hop: a
            # second vector arriving while this one's data read is in
            # flight must queue, or both would read the same pre-state
            # and commit out of order (the obc write-lock ordering)
            self._busy.add(m.oid)
        data_reads = [op for op in m.ops if op.op in DATA_READ_OPS]
        oi = self._load_oi(m.oid)
        if data_reads:
            if has_write and self.pool_type == "ec":
                for op in m.ops:
                    op.rval = EINVAL
                self._finish(m, MOSDOpReply(EINVAL, m.ops),
                             has_write, on_reply)
                return
            if oi is None:
                self._finish(m, MOSDOpReply(ENOENT, m.ops),
                             has_write, on_reply)
                return
            extents = []
            for op in data_reads:
                off = op.params["offset"]
                length = op.params.get("length",
                                       len(op.params.get("data", b"")))
                if length == 0 and op.op != OP_CMPEXT:
                    length = max(oi["size"] - off, 0)   # len 0 = to end
                extents.append((off, length))

            def _got(result, errors):
                if errors:
                    self._finish(m, MOSDOpReply(EINVAL, m.ops),
                                 has_write, on_reply)
                    return
                got = {(off, ln): data
                       for off, ln, data in result.get(m.oid, [])}
                self._execute(m, oi, got, has_write, on_reply)
            self.backend.objects_read_and_reconstruct(
                {m.oid: extents}, lambda result, errors: _got(result, errors))
        else:
            self._execute(m, oi, {}, has_write, on_reply)

    # -- the opcode switch (do_osd_ops) ------------------------------------

    def _execute(self, m: MOSDOp, oi, readdata, has_write, on_reply) -> None:
        ctx = _ExecCtx(m=m, engine=self,
                       exists=oi is not None,
                       size=oi["size"] if oi else 0)
        # make_writable (PrimaryLogPG::make_writable): first mutation of
        # an existing head under a NEWER snap context clones the pre-op
        # state to <oid>@<newest snap> — copy-on-write at snap boundaries
        if has_write and m.snapc is not None and not is_clone_oid(m.oid):
            if ctx.exists:
                ss = self._load_snapset(m.oid)
                if m.snapc.seq > ss["seq"] and m.snapc.snaps:
                    newest = max(m.snapc.snaps)
                    ctx.objop().clone_to.append(clone_oid(m.oid, newest))
                    ss["clones"] = sorted(set(ss["clones"]) | {newest})
                    ss["sizes"] = dict(ss["sizes"])
                    ss["sizes"][newest] = ctx.size
                    # the clone covers (old seq, newest]: snaps at or
                    # below the pre-clone seq belong to older clones (or
                    # predate the object entirely)
                    ss["lbs"] = dict(ss.get("lbs", {}))
                    ss["lbs"][newest] = ss["seq"]
                    ss["seq"] = m.snapc.seq
                    ctx.stage_attr(SS_ATTR, ss)
            else:
                # creation under a snap context stamps the seq so reads
                # at PRE-creation snaps resolve to ENOENT, not to the
                # head (the reference stamps snapset.seq the same way).
                # _load_snapset DISCOVERS orphaned clones of a deleted
                # head, so re-creation keeps its snap history (snapdir).
                ss = self._load_snapset(m.oid)
                ss["seq"] = max(ss["seq"], m.snapc.seq)
                ctx.stage_attr(SS_ATTR, ss)
        result = 0
        try:
            for op in m.ops:
                op.rval = self._do_one(ctx, op, oi, readdata)
        except OpError as e:
            result = e.rval
        if result != 0 or not ctx.mutated:
            if result == 0:
                self._apply_watch_effects(ctx)    # do_osd_op_effects
            self._finish(m, MOSDOpReply(result, m.ops), has_write, on_reply)
            return
        # prepare_transaction: persist object_info on every shard with the
        # data (atomically — it rides the same PGTransaction)
        self.version += 1
        if ctx.user_modify:
            self.user_version += 1
        objop = ctx.t.touch(m.oid)
        if ctx.exists:
            objop.setattr(OI_ATTR, {
                "size": ctx.size, "version": self.version,
                "user_version": self.user_version, "mtime": time.time()})
        version = self.version
        deleted = not ctx.exists

        def _committed(tid):
            if deleted:
                # a deleted object loses its watchers (Watch.cc discard)
                self.watchers.pop(m.oid, None)
            self._apply_watch_effects(ctx)        # do_osd_op_effects
            self._finish(m, MOSDOpReply(0, m.ops, version=version),
                         has_write, on_reply)
        self.backend.submit_transaction(ctx.t, on_commit=_committed)

    def _apply_watch_effects(self, ctx: _ExecCtx) -> None:
        for eff in ctx.watch_effects:
            if eff[0] == "watch":
                self.watchers.setdefault(ctx.m.oid, {})[eff[1]] = eff[2]
            elif eff[0] == "unwatch":
                self.watchers.get(ctx.m.oid, {}).pop(eff[1], None)
            else:                                   # notify
                _, payload, notify_op = eff
                self.notify_id += 1
                acks = {}
                for cookie, fn in sorted(self.watchers.get(ctx.m.oid,
                                                           {}).items()):
                    try:
                        acks[cookie] = fn(self.notify_id, cookie, payload)
                    except Exception as e:  # one bad watcher can't block
                        acks[cookie] = e    # the notify (timeout analog)
                notify_op.outdata = acks

    def _finish(self, m, reply, has_write, on_reply) -> None:
        if has_write:
            self._busy.discard(m.oid)
        on_reply(reply)
        q = self._waiting.get(m.oid)
        while q and m.oid not in self._busy:
            nm, cb = q.popleft()
            self._start(nm, cb)
        if q is not None and not q:
            self._waiting.pop(m.oid, None)

    def _load_oi(self, oid: str) -> dict | None:
        store = self.backend.local_shard.store
        gobj = GObject(oid, self.backend.whoami)
        if not store.exists(gobj):
            return None
        try:
            return dict(store.getattr(gobj, OI_ATTR))
        except KeyError:
            # object written below the op-engine layer (e.g. MiniCluster.put)
            return {"size": self.backend.object_size(oid),
                    "version": 0, "user_version": 0, "mtime": 0.0}

    def _require(self, ctx: _ExecCtx) -> None:
        if not ctx.exists:
            raise OpError(ENOENT)

    def _do_one(self, ctx: _ExecCtx, op: OSDOp, oi, readdata) -> int:
        p = op.params
        kind = op.op

        # ---- data reads (pre-fetched through the reconstructing path)
        if kind in (OP_READ, OP_SPARSE_READ):
            self._require(ctx)
            off = p["offset"]
            length = p["length"] or max((oi["size"] if oi else 0) - off, 0)
            data = readdata.get((off, length), b"")[:length]
            op.outdata = ({off: bytes(data)} if kind == OP_SPARSE_READ
                          else bytes(data))
            return len(data)
        if kind == OP_CMPEXT:
            self._require(ctx)
            off, want = p["offset"], p["data"]
            got = bytes(readdata.get((off, len(want)), b""))
            got = got.ljust(len(want), b"\0")
            if got != want:
                mism = next(i for i in range(len(want)) if got[i] != want[i])
                raise OpError(-(MAX_ERRNO + mism))
            return len(want)

        # ---- metadata reads
        if kind == OP_STAT:
            self._require(ctx)
            op.outdata = (ctx.size, (oi or {}).get("mtime", 0.0))
            return 0
        if kind == OP_GETXATTR:
            if not p["name"]:
                raise OpError(EINVAL)   # "" would alias OI_ATTR
            self._require(ctx)
            try:
                op.outdata = ctx.get_attr(USER_PREFIX + p["name"])
            except KeyError:
                raise OpError(ENODATA)
            return 0
        if kind == OP_GETXATTRS:
            self._require(ctx)
            op.outdata = {k[len(USER_PREFIX):]: v
                          for k, v in ctx.get_attrs().items()
                          if k.startswith(USER_PREFIX)
                          and k not in INTERNAL_ATTRS}
            return 0
        if kind == OP_CMPXATTR:
            if not p["name"]:
                raise OpError(EINVAL)
            self._require(ctx)
            try:
                have = ctx.get_attr(USER_PREFIX + p["name"])
            except KeyError:
                raise OpError(ECANCELED if p["mode"] == CMPXATTR_MODE_STRING
                              else ENODATA)
            if p["mode"] == CMPXATTR_MODE_U64:
                try:
                    have = int(have)
                except (TypeError, ValueError):
                    raise OpError(EINVAL)
            ok = {CMPXATTR_EQ: have == p["value"],
                  CMPXATTR_NE: have != p["value"],
                  CMPXATTR_GT: have > p["value"],
                  CMPXATTR_GTE: have >= p["value"],
                  CMPXATTR_LT: have < p["value"],
                  CMPXATTR_LTE: have <= p["value"]}.get(p["cmp"])
            if ok is None:
                raise OpError(EINVAL)
            if not ok:
                raise OpError(ECANCELED)
            return 1

        # ---- omap (replicated pools only, like the reference)
        if kind.startswith("omap"):
            if self.pool_type == "ec":
                raise OpError(EOPNOTSUPP)
            return self._do_omap(ctx, op)

        # ---- mutations
        if kind == OP_CREATE:
            if ctx.exists and p.get("exclusive"):
                raise OpError(EEXIST)
            if not ctx.exists:
                ctx.stage_write(0, b"")     # touch
                ctx.size = 0
            return 0
        if kind == OP_WRITE:
            ctx.stage_write(p["offset"], p["data"])
            return 0
        if kind == OP_WRITEFULL:
            ctx.stage_write_full(p["data"])
            return 0
        if kind == OP_APPEND:
            ctx.stage_write(ctx.size, p["data"])
            return 0
        if kind == OP_ZERO:
            self._require(ctx)
            off = p["offset"]
            length = min(p["length"], max(ctx.size - off, 0))
            if length > 0:
                ctx.stage_write(off, b"\0" * length)
            return 0
        if kind == OP_TRUNCATE:
            self._require(ctx)
            ctx.stage_truncate(p["size"])
            return 0
        if kind == OP_DELETE:
            self._require(ctx)
            op_obj = ctx.objop()
            op_obj.delete_first = True
            op_obj.buffer_updates = []
            op_obj.truncate = None
            op_obj.attr_updates = {}
            op_obj.omap_ops = []
            ctx.exists = False
            ctx.size = 0
            ctx.attrs = {}
            ctx.attrs_cleared = True     # later reads must not see base
            ctx.omap = {}
            ctx.omap_cleared = True
            ctx.omap_header = None
            ctx.mutated = ctx.user_modify = True
            return 0
        if kind == OP_SETXATTR:
            if not p["name"]:
                raise OpError(EINVAL)   # "" would alias OI_ATTR
            if not ctx.exists:
                ctx.stage_write(0, b"")
            ctx.stage_attr(USER_PREFIX + p["name"], p["value"])
            return 0
        if kind == OP_RMXATTR:
            if not p["name"]:
                raise OpError(EINVAL)
            self._require(ctx)
            ctx.stage_attr(USER_PREFIX + p["name"], None)
            return 0

        # ---- watch/notify (PrimaryLogPG::do_osd_op_effects + Watch.cc:
        # watchers live on the primary; notifies fan to every watcher and
        # collect acks.  In-process, a watcher is a callback.)
        if kind == OP_WATCH:
            self._require(ctx)
            ctx.watch_effects.append(("watch", p["cookie"], p["on_notify"]))
            return 0
        if kind == OP_UNWATCH:
            ws = dict(self.watchers.get(ctx.m.oid, {}))
            for eff in ctx.watch_effects:     # staged view for validation
                if eff[0] == "watch":
                    ws[eff[1]] = eff[2]
                else:
                    ws.pop(eff[1], None)
            if p["cookie"] not in ws:
                raise OpError(ENOENT)
            ctx.watch_effects.append(("unwatch", p["cookie"]))
            return 0
        if kind == OP_NOTIFY:
            self._require(ctx)
            # staged like watch/unwatch: a FAILED vector must not have
            # delivered anything (do_osd_op_effects fires on success);
            # the effect fills op.outdata before the reply is sent
            ctx.watch_effects.append(("notify", p["payload"], op))
            return 0
        if kind == OP_LIST_WATCHERS:
            self._require(ctx)
            op.outdata = sorted(self.watchers.get(ctx.m.oid, {}))
            return 0

        # ---- snapshots
        if kind == OP_LIST_SNAPS:
            ss = self._load_snapset(ctx.m.oid)
            op.outdata = {"seq": ss["seq"],
                          "clones": [{"snapid": c,
                                      "size": ss["sizes"].get(c)}
                                     for c in sorted(ss["clones"])]}
            return 0
        if kind == OP_ROLLBACK:
            if any(o is not op and self._op_mutates(o) for o in ctx.m.ops):
                # rollback replaces the object wholesale at the store
                # level; mixing it with other mutations in one vector is
                # rejected (the reference serializes it through its own
                # transaction machinery instead)
                raise OpError(ENOTSUP_COMBINED)
            # the STAGED snapset wins: make_writable may have just COWed
            # the pre-rollback head in this very vector (rollback after a
            # newer snap) — re-reading the store would clobber that
            # update and orphan the fresh clone
            try:
                ss = dict(ctx.get_attr(SS_ATTR))
            except KeyError:
                ss = self._load_snapset(ctx.m.oid)
            cands = [c for c in sorted(ss["clones"]) if c >= p["snapid"]]
            if cands and p["snapid"] <= clone_lower_bound(ss, cands[0]):
                # the covering clone postdates the object's creation at
                # this snap: the object did not exist then — fall through
                # to the delete-the-head branch, matching what a read at
                # the snap reports (ENOENT)
                cands = []
            if not cands:
                self._require(ctx)
                if p["snapid"] <= ss["seq"]:
                    # the object did not exist at that snap (creation
                    # postdates it): rollback REMOVES the head — exactly
                    # what a read at that snap reports (the reference's
                    # _rollback_to on ENOENT deletes the head)
                    objop = ctx.objop()
                    objop.delete_first = True
                    objop.buffer_updates = []
                    objop.truncate = None
                    objop.attr_updates = {}
                    ctx.exists = False
                    ctx.size = 0
                    ctx.attrs = {}
                    ctx.attrs_cleared = True
                    ctx.omap = {}
                    ctx.omap_cleared = True
                    ctx.mutated = ctx.user_modify = True
                    return 0
                return 0    # snap postdates the head state: no-op
            src = clone_oid(ctx.m.oid, cands[0])
            snap = cands[0]
            objop = ctx.objop()
            objop.rollback_from = src
            # the clone's attrs replace the head's, EXCEPT the SnapSet:
            # the head keeps knowing all its clones (the reference's
            # snapset stays on the head/snapdir through rollback)
            objop.attr_updates[SS_ATTR] = ss
            fallback = ss["sizes"].get(snap, ss["sizes"].get(str(snap)))
            store = self.backend.local_shard.store
            try:
                src_oi = dict(store.getattr(
                    GObject(src, self.backend.whoami), OI_ATTR))
                ctx.size = src_oi["size"]
            except (FileNotFoundError, KeyError):
                ctx.size = fallback if fallback is not None else ctx.size
            ctx.exists = True             # a deleted head is recreated
            ctx.attrs_cleared = True      # head attrs replaced by clone's
            ctx.attrs = {}
            ctx.mutated = ctx.user_modify = True
            return 0

        # ---- object classes
        if kind == OP_CALL:
            meth = ClsRegistry.get(p["cls"], p["method"])
            if meth is None:
                raise OpError(EOPNOTSUPP)
            rval, out = meth.fn(ClsContext(ctx, p["indata"]))
            op.outdata = out
            if rval < 0:
                raise OpError(rval)
            return rval

        raise OpError(EOPNOTSUPP)

    def _do_omap(self, ctx: _ExecCtx, op: OSDOp) -> int:
        p = op.params
        kind = op.op
        if kind == OP_OMAPGETKEYS:
            self._require(ctx)
            keys = sorted(k for k in ctx.get_omap()
                          if k > p["start_after"])[:p["max_return"]]
            op.outdata = keys
            return 0
        if kind == OP_OMAPGETVALS:
            self._require(ctx)
            omap = ctx.get_omap()
            keys = sorted(k for k in omap if k > p["start_after"]
                          and k.startswith(p["filter_prefix"]))
            keys = keys[:p["max_return"]]
            op.outdata = {k: omap[k] for k in keys}
            return 0
        if kind == OP_OMAPGETVALSBYKEYS:
            self._require(ctx)
            omap = ctx.get_omap()
            op.outdata = {k: omap[k] for k in p["keys"] if k in omap}
            return 0
        if kind == OP_OMAPGETHEADER:
            self._require(ctx)
            op.outdata = ctx.get_omap_header()
            return 0
        if kind == OP_OMAP_CMP:
            self._require(ctx)
            omap = ctx.get_omap()
            for key, (value, cmp_op) in sorted(p["assertions"].items()):
                have = omap.get(key)
                if have is None:
                    raise OpError(ECANCELED)
                ok = {CMPXATTR_EQ: have == value, CMPXATTR_NE: have != value,
                      CMPXATTR_GT: have > value, CMPXATTR_GTE: have >= value,
                      CMPXATTR_LT: have < value, CMPXATTR_LTE: have <= value,
                      }.get(cmp_op)
                if not ok:
                    raise OpError(ECANCELED)
            return 0
        # mutations
        if not ctx.exists:
            ctx.stage_write(0, b"")
        if kind == OP_OMAPSETVALS:
            for k, v in p["kvs"].items():
                ctx.omap[k] = v
            ctx.stage_omap("set", dict(p["kvs"]))
            return 0
        if kind == OP_OMAPSETHEADER:
            ctx.omap_header = p["header"]
            ctx.stage_omap("header", p["header"])
            return 0
        if kind == OP_OMAPRMKEYS:
            for k in p["keys"]:
                ctx.omap[k] = None
            ctx.stage_omap("rm", list(p["keys"]))
            return 0
        if kind == OP_OMAPCLEAR:
            ctx.omap = {}
            ctx.omap_cleared = True
            ctx.omap_header = b""
            ctx.stage_omap("clear")
            return 0
        raise OpError(EOPNOTSUPP)


# -- built-in object classes (the reference ships src/cls/hello) -----------

def _hello_say_hello(ctx: ClsContext):
    who = ctx.indata.decode() if ctx.indata else "world"
    return 0, f"Hello, {who}!".encode()


def _hello_record_hello(ctx: ClsContext):
    who = ctx.indata.decode() if ctx.indata else "world"
    greeting = f"Hello, {who}!".encode()
    ctx.write_full(greeting)
    ctx.setxattr("recorded", b"1")
    return 0, b""


ClsRegistry.register("hello", "say_hello", _hello_say_hello, mutates=False)
ClsRegistry.register("hello", "record_hello", _hello_record_hello,
                     mutates=True)


# -- cls_lock: advisory object locks (the reference's src/cls/lock, the
# -- coordination primitive RBD/RGW build on).  Lock state lives in an
# -- object xattr and mutates atomically with the op vector.

LOCK_ATTR = "lock"              # per-object lock table xattr
LOCK_EXCLUSIVE = "exclusive"
LOCK_SHARED = "shared"
EBUSY = -16


def _locks(ctx: ClsContext) -> dict:
    """DEEP copy of the lock table: the stored xattr's inner dicts must
    never leak — in-place mutation would bypass the transaction (a failed
    vector would still release locks) and get_info callers could corrupt
    committed state through the returned aliases."""
    try:
        stored = ctx.getxattr(LOCK_ATTR)
    except KeyError:
        return {}
    return {name: {"type": lk["type"], "holders": list(lk["holders"])}
            for name, lk in stored.items()}


def _lock_lock(ctx: ClsContext):
    """indata: {name, cookie, type} — take/renew the lock.  EBUSY when an
    exclusive holder exists, or on a shared lock being taken exclusively
    (cls_lock lock_obj semantics; re-locking your own cookie renews)."""
    import pickle
    req = pickle.loads(ctx.indata)
    name, cookie = req["name"], req["cookie"]
    ltype = req.get("type", LOCK_EXCLUSIVE)
    locks = _locks(ctx)
    lk = locks.get(name)
    if lk is not None:
        if cookie in lk["holders"]:
            if lk["type"] != ltype:
                # no silent up/downgrade: an exclusive request against a
                # shared hold must not report success while the lock
                # stays shared (cls_lock refuses conflicting types)
                return EBUSY, b""
            # renewal: state unchanged
        else:
            if lk["type"] == LOCK_EXCLUSIVE or ltype == LOCK_EXCLUSIVE:
                return EBUSY, b""
            lk = {"type": lk["type"],
                  "holders": sorted(set(lk["holders"]) | {cookie})}
    else:
        lk = {"type": ltype, "holders": [cookie]}
    locks[name] = lk
    ctx.setxattr(LOCK_ATTR, locks)
    return 0, b""


def _lock_unlock(ctx: ClsContext):
    """indata: {name, cookie} — release; ENOENT when not held."""
    import pickle
    req = pickle.loads(ctx.indata)
    locks = _locks(ctx)
    lk = locks.get(req["name"])
    if lk is None or req["cookie"] not in lk["holders"]:
        return ENOENT, b""
    lk["holders"] = [h for h in lk["holders"] if h != req["cookie"]]
    if lk["holders"]:
        locks[req["name"]] = lk
    else:
        del locks[req["name"]]
    ctx.setxattr(LOCK_ATTR, locks)
    return 0, b""


def _lock_break(ctx: ClsContext):
    """indata: {name, cookie} — forcibly evict another client's cookie
    (cls_lock break_lock: recovery from dead lockers)."""
    return _lock_unlock(ctx)


def _lock_info(ctx: ClsContext):
    import pickle
    req = pickle.loads(ctx.indata) if ctx.indata else {}
    locks = _locks(ctx)          # deep copy: safe to hand to the caller
    if "name" in req:
        return 0, locks.get(req["name"])
    return 0, locks


ClsRegistry.register("lock", "lock", _lock_lock, mutates=True)
ClsRegistry.register("lock", "unlock", _lock_unlock, mutates=True)
ClsRegistry.register("lock", "break_lock", _lock_break, mutates=True)
ClsRegistry.register("lock", "get_info", _lock_info, mutates=False)
