"""mClock/dmClock QoS op scheduling.

Analog of the reference's mClock queues (reference:
src/osd/mClockOpClassQueue.{h,cc} + src/osd/mClockClientQueue.{h,cc}
bridging into the dmclock library, src/dmclock/ — the Gulati et al.
"mClock: Handling Throughput Variability for Hypervisor IO Scheduling"
algorithm).  Semantics mirrored:

- every client (or op CLASS — the mClockOpClassQueue adapter treats the
  op type as the client) has a QoS triple (reservation, weight, limit)
  in ops/sec;
- each request gets three tags at enqueue: R (reservation), P
  (proportional/weight), L (limit), each ``max(now, prev + 1/param)``;
- dequeue serves in two phases: the CONSTRAINT phase picks the smallest
  R tag <= now (reservations are hard guarantees), else the WEIGHT phase
  picks the smallest P tag among clients whose L tag <= now (limits are
  hard caps); a weight-phase pick credits the client's remaining R tags
  by 1/r so reservations are not double-counted (paper §III-B);
- strict-priority ops (peering messages etc.) bypass QoS entirely, like
  the reference's enqueue_strict path (OpQueue semantics).

Time is a virtual clock so tests drive deterministic schedules; the OSD
op-class defaults mirror ``osd_op_queue_mclock_*`` options
(src/common/options.cc).
"""
from __future__ import annotations

import heapq
import itertools
from collections import deque
from dataclasses import dataclass, field


@dataclass(frozen=True)
class ClientInfo:
    """dmclock ClientInfo: QoS triple in ops/sec (0 = unused)."""
    reservation: float = 0.0
    weight: float = 1.0
    limit: float = 0.0          # 0 => unlimited


@dataclass
class _Request:
    item: object
    r_tag: float
    p_tag: float
    l_tag: float
    cost: float


@dataclass
class _ClientRec:
    info: ClientInfo
    queue: deque = field(default_factory=deque)
    # -inf so a client's FIRST request tags at now (paper: a newly
    # active client starts fresh; max(now, prev + 1/param) handles both
    # the first request and the return-from-idle reset)
    last_r: float = float("-inf")
    last_p: float = float("-inf")
    last_l: float = float("-inf")


class MClockQueue:
    """Two-phase dmclock scheduler + strict-priority bypass."""

    def __init__(self, client_info_fn):
        """``client_info_fn(client) -> ClientInfo`` (the reference's
        op_class_client_info_f / ClientInfoFunc)."""
        self.client_info_fn = client_info_fn
        self.clients: dict[object, _ClientRec] = {}
        self._strict: list = []          # (-priority, seq, item)
        self._seq = itertools.count()
        self.served_reservation = 0
        self.served_weight = 0

    # -- enqueue -------------------------------------------------------------

    def enqueue_strict(self, priority: int, item) -> None:
        """Priority ops bypass QoS (OpQueue::enqueue_strict)."""
        heapq.heappush(self._strict, (-priority, next(self._seq), item))

    def enqueue(self, client, item, now: float, cost: float = 1.0) -> None:
        rec = self.clients.get(client)
        if rec is None:
            rec = self.clients[client] = _ClientRec(
                info=self.client_info_fn(client))
        info = rec.info
        r = max(now, rec.last_r + cost / info.reservation) \
            if info.reservation > 0 else float("inf")
        p = max(now, rec.last_p + cost / info.weight) \
            if info.weight > 0 else float("inf")
        l = max(now, rec.last_l + cost / info.limit) \
            if info.limit > 0 else 0.0
        rec.queue.append(_Request(item, r, p, l, cost))
        if info.reservation > 0:
            rec.last_r = r
        if info.weight > 0:
            rec.last_p = p
        if info.limit > 0:
            rec.last_l = l

    # -- dequeue -------------------------------------------------------------

    def empty(self) -> bool:
        return not self._strict and \
            all(not rec.queue for rec in self.clients.values())

    def dequeue(self, now: float):
        """Next item, or None when everything queued is over its limit
        and nothing is reservation-eligible (caller advances the clock;
        the reference's queue blocks on the same condition)."""
        if self._strict:
            return heapq.heappop(self._strict)[2]
        # constraint phase: hard reservations first
        best = None
        for client, rec in self.clients.items():
            if rec.queue and rec.queue[0].r_tag <= now:
                if best is None or rec.queue[0].r_tag < \
                        self.clients[best].queue[0].r_tag:
                    best = client
        if best is not None:
            self.served_reservation += 1
            return self.clients[best].queue.popleft().item
        # weight phase: proportional among clients under their limit
        best = None
        for client, rec in self.clients.items():
            if rec.queue and rec.queue[0].l_tag <= now:
                if best is None or rec.queue[0].p_tag < \
                        self.clients[best].queue[0].p_tag:
                    best = client
        if best is None:
            return None
        rec = self.clients[best]
        req = rec.queue.popleft()
        # credit the client's remaining reservation tags (paper §III-B:
        # a weight-phase grant must not also consume reservation budget)
        if rec.info.reservation > 0:
            delta = req.cost / rec.info.reservation
            for pending in rec.queue:
                pending.r_tag -= delta
            rec.last_r -= delta
        self.served_weight += 1
        return req.item

    def depths(self) -> dict:
        """Queue depth per client/class (+ strict-priority backlog) — the
        gauge surface the prometheus exporter renders as
        ``ceph_tpu_mclock_queue_depth``."""
        d = {str(client): len(rec.queue)
             for client, rec in self.clients.items() if rec.queue}
        if self._strict:
            d["strict"] = len(self._strict)
        return d

    def next_eligible_time(self, now: float) -> float | None:
        """Earliest future time anything becomes servable (for clock
        advancement in tests/ticks)."""
        t = None
        for rec in self.clients.values():
            if not rec.queue:
                continue
            head = rec.queue[0]
            cand = min(head.r_tag, max(head.l_tag, now))
            if cand > now and (t is None or cand < t):
                t = cand
            elif cand <= now:
                return now
        return t


# -- the op-class adapter (mClockOpClassQueue) --------------------------------

CLIENT_OP = "client_op"
OSD_SUBOP = "osd_subop"
BG_SNAPTRIM = "bg_snaptrim"
BG_RECOVERY = "bg_recovery"
BG_SCRUB = "bg_scrub"

# defaults mirroring osd_op_queue_mclock_* (src/common/options.cc):
# client ops dominate by weight; background classes are limited so they
# cannot starve clients, recovery keeps a small reservation so it always
# makes progress
DEFAULT_OP_CLASS_INFO = {
    CLIENT_OP: ClientInfo(reservation=0.0, weight=500.0, limit=0.0),
    OSD_SUBOP: ClientInfo(reservation=0.0, weight=500.0, limit=0.0),
    BG_SNAPTRIM: ClientInfo(reservation=0.0, weight=1.0, limit=0.001),
    BG_RECOVERY: ClientInfo(reservation=1.0, weight=5.0, limit=10.0),
    BG_SCRUB: ClientInfo(reservation=0.0, weight=1.0, limit=0.001),
}


class MClockOpClassQueue(MClockQueue):
    """QoS by op CLASS: the adapter the reference wraps around dmclock
    (mClockOpClassQueue.h: 'the class is osd_op_type_t')."""

    def __init__(self, class_info: dict | None = None):
        info = dict(DEFAULT_OP_CLASS_INFO)
        if class_info:
            info.update(class_info)
        super().__init__(lambda op_class: info[op_class])
