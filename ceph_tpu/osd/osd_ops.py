"""Client-visible object operations: the RADOS op vector.

Analog of the reference's ``OSDOp``/``ceph_osd_op`` op vector carried by
``MOSDOp`` (reference: src/osd/osd_types.h, src/messages/MOSDOp.h) and the
librados ``ObjectReadOperation``/``ObjectWriteOperation`` builders
(src/librados/librados_cxx.cc).  One MOSDOp holds an ordered vector of ops
executed atomically by the primary's op engine
(PrimaryLogPG::do_osd_ops — see primary_log_pg.py).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

# -- opcodes (CEPH_OSD_OP_* — src/include/rados.h) --------------------------

OP_READ = "read"
OP_SPARSE_READ = "sparse_read"
OP_STAT = "stat"
OP_CMPEXT = "cmpext"
OP_CREATE = "create"
OP_WRITE = "write"
OP_WRITEFULL = "writefull"
OP_APPEND = "append"
OP_ZERO = "zero"
OP_TRUNCATE = "truncate"
OP_DELETE = "delete"
OP_GETXATTR = "getxattr"
OP_GETXATTRS = "getxattrs"
OP_SETXATTR = "setxattr"
OP_RMXATTR = "rmxattr"
OP_CMPXATTR = "cmpxattr"
OP_OMAPGETKEYS = "omap_get_keys"
OP_OMAPGETVALS = "omap_get_vals"
OP_OMAPGETVALSBYKEYS = "omap_get_vals_by_keys"
OP_OMAPGETHEADER = "omap_get_header"
OP_OMAPSETVALS = "omap_set_vals"
OP_OMAPSETHEADER = "omap_set_header"
OP_OMAPRMKEYS = "omap_rm_keys"
OP_OMAPCLEAR = "omap_clear"
OP_OMAP_CMP = "omap_cmp"
OP_CALL = "call"
OP_ROLLBACK = "rollback"
OP_LIST_SNAPS = "list_snaps"
OP_WATCH = "watch"
OP_UNWATCH = "unwatch"
OP_NOTIFY = "notify"
OP_LIST_WATCHERS = "list_watchers"

# ops that mutate object state (CEPH_OSD_FLAG_WRITE classification)
WRITE_OPS = frozenset({
    OP_CREATE, OP_WRITE, OP_WRITEFULL, OP_APPEND, OP_ZERO, OP_TRUNCATE,
    OP_DELETE, OP_SETXATTR, OP_RMXATTR, OP_OMAPSETVALS, OP_OMAPSETHEADER,
    OP_OMAPRMKEYS, OP_OMAPCLEAR, OP_ROLLBACK,
})
# ops that need object DATA from the (possibly degraded) store
DATA_READ_OPS = frozenset({OP_READ, OP_SPARSE_READ, OP_CMPEXT})

# CEPH_OSD_CMPXATTR_OP_* (src/include/rados.h:305-312)
CMPXATTR_EQ, CMPXATTR_NE = 1, 2
CMPXATTR_GT, CMPXATTR_GTE = 3, 4
CMPXATTR_LT, CMPXATTR_LTE = 5, 6
# CEPH_OSD_CMPXATTR_MODE_*
CMPXATTR_MODE_STRING, CMPXATTR_MODE_U64 = 1, 2


@dataclass
class OSDOp:
    """One op of the vector: opcode + params + (after execution) result."""
    op: str
    params: dict[str, Any] = field(default_factory=dict)
    rval: int = 0
    outdata: Any = None


class ObjectOperation:
    """Ordered op-vector builder (librados ObjectRead/WriteOperation)."""

    def __init__(self):
        self.ops: list[OSDOp] = []

    def _add(self, op: str, **params) -> "ObjectOperation":
        self.ops.append(OSDOp(op, params))
        return self

    # reads
    def read(self, offset: int, length: int):
        return self._add(OP_READ, offset=offset, length=length)

    def sparse_read(self, offset: int, length: int):
        return self._add(OP_SPARSE_READ, offset=offset, length=length)

    def stat(self):
        return self._add(OP_STAT)

    def cmpext(self, offset: int, data: bytes):
        return self._add(OP_CMPEXT, offset=offset, data=bytes(data))

    def getxattr(self, name: str):
        return self._add(OP_GETXATTR, name=name)

    def getxattrs(self):
        return self._add(OP_GETXATTRS)

    def cmpxattr(self, name: str, op: int, value, mode: int | None = None):
        if mode is None:
            mode = (CMPXATTR_MODE_U64 if isinstance(value, int)
                    else CMPXATTR_MODE_STRING)
        return self._add(OP_CMPXATTR, name=name, cmp=op, mode=mode,
                         value=value)

    def omap_get_keys(self, start_after: str = "", max_return: int = 1 << 30):
        return self._add(OP_OMAPGETKEYS, start_after=start_after,
                         max_return=max_return)

    def omap_get_vals(self, start_after: str = "", filter_prefix: str = "",
                      max_return: int = 1 << 30):
        return self._add(OP_OMAPGETVALS, start_after=start_after,
                         filter_prefix=filter_prefix, max_return=max_return)

    def omap_get_vals_by_keys(self, keys):
        return self._add(OP_OMAPGETVALSBYKEYS, keys=list(keys))

    def omap_get_header(self):
        return self._add(OP_OMAPGETHEADER)

    def omap_cmp(self, assertions: dict):
        """assertions: key -> (value, cmp op) — all must hold."""
        return self._add(OP_OMAP_CMP, assertions=dict(assertions))

    # writes
    def create(self, exclusive: bool = False):
        return self._add(OP_CREATE, exclusive=exclusive)

    def write(self, offset: int, data: bytes):
        return self._add(OP_WRITE, offset=offset, data=bytes(data))

    def write_full(self, data: bytes):
        return self._add(OP_WRITEFULL, data=bytes(data))

    def append(self, data: bytes):
        return self._add(OP_APPEND, data=bytes(data))

    def zero(self, offset: int, length: int):
        return self._add(OP_ZERO, offset=offset, length=length)

    def truncate(self, size: int):
        return self._add(OP_TRUNCATE, size=size)

    def remove(self):
        return self._add(OP_DELETE)

    def setxattr(self, name: str, value):
        return self._add(OP_SETXATTR, name=name, value=value)

    def rmxattr(self, name: str):
        return self._add(OP_RMXATTR, name=name)

    def omap_set(self, kvs: dict):
        return self._add(OP_OMAPSETVALS, kvs=dict(kvs))

    def omap_set_header(self, header: bytes):
        return self._add(OP_OMAPSETHEADER, header=bytes(header))

    def omap_rm_keys(self, keys):
        return self._add(OP_OMAPRMKEYS, keys=list(keys))

    def omap_clear(self):
        return self._add(OP_OMAPCLEAR)

    # object classes
    def call(self, cls: str, method: str, indata: bytes = b""):
        return self._add(OP_CALL, cls=cls, method=method,
                         indata=bytes(indata))

    # watch/notify (librados watch2/notify2 shape)
    def watch(self, cookie: int, on_notify):
        """Register a watch: ``on_notify(notify_id, cookie, payload) ->
        reply bytes`` fires for every notify on the object."""
        return self._add(OP_WATCH, cookie=cookie, on_notify=on_notify)

    def unwatch(self, cookie: int):
        return self._add(OP_UNWATCH, cookie=cookie)

    def notify(self, payload: bytes = b""):
        """Deliver ``payload`` to every watcher; outdata maps each
        watcher cookie to its reply (notify_ack collection)."""
        return self._add(OP_NOTIFY, payload=bytes(payload))

    def list_watchers(self):
        return self._add(OP_LIST_WATCHERS)

    # snapshots
    def rollback(self, snapid: int):
        """CEPH_OSD_OP_ROLLBACK: restore the object to its state at
        ``snapid`` (must be the only mutation in the vector)."""
        return self._add(OP_ROLLBACK, snapid=snapid)

    def list_snaps(self):
        return self._add(OP_LIST_SNAPS)


@dataclass
class SnapContext:
    """The write-time snap context (SnapContext, src/include/rados.h):
    ``seq`` is the newest snap id the client knows, ``snaps`` the live
    snap ids newest-first."""
    seq: int = 0
    snaps: tuple = ()


@dataclass
class MOSDOp:
    """Client op message (src/messages/MOSDOp.h shape, trimmed)."""
    oid: str
    ops: list[OSDOp]
    epoch: int = 0
    client: str = "client"
    tid: int = 0
    snapid: int | None = None          # read AT this snap (None = head)
    snapc: SnapContext | None = None   # write-time snap context
    # internal ops (tiering agent, scrub helpers) must not count as
    # client accesses — they would keep every object artificially hot in
    # the hit sets (the reference's agent IO bypasses hit_set tracking)
    internal: bool = False
    # distributed-trace context (common/tracer.TraceContext): stamped at
    # dispatch, activated by the daemon when the queued op actually runs,
    # so the primary's spans stitch under the client's trace id
    trace: object = None


@dataclass
class MOSDOpReply:
    """(src/messages/MOSDOpReply.h): overall result + per-op rval/outdata."""
    result: int
    ops: list[OSDOp]
    version: int = 0

    def outdata(self, i: int = 0):
        return self.ops[i].outdata
