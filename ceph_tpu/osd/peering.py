"""The PG peering statechart: acting-set negotiation over the bus.

Analog of the reference's boost::statechart peering machine (reference:
src/osd/PeeringState.{h,cc} — states at PeeringState.h:604-774,
GetInfo/GetLog/GetMissing/Activating flow in PeeringState.cc).  The
reference encodes ~6600 LoC of statechart; what survives the redesign is
the OBSERVABLE protocol:

    AdvMap ──▶ GetInfo ──(all infos)──▶ GetLog ──(authority adopted)──▶
    GetMissing ──(missing computed)──▶ Activating ──(all acks)──▶ Active

- **GetInfo**: the primary queries every up member of the acting set for
  its pg_info (log head/tail + entries) — `PGLogQuery` fan-out.
- **choose_acting / find_best_info**: the authority is the info with the
  max last_update, ties broken by the longer log (lower tail) then the
  lower shard id (PeeringState::find_best_info semantics).  Peers whose
  logs can catch up by replay join the acting set; peers past the log
  horizon are marked backfill targets (PeeringState::choose_acting's
  "needs backfill" split).
- **GetLog**: if the authority is a peer, its log is merged and entries
  witnessed by < min_size shards roll back (never acked — the shared
  election in PGBackend.elect_and_adopt_authority).
- **GetMissing**: per-peer catch-up plans derived from log divergence;
  stale peers get shard-repair ops queued (log replay or backfill).
- **Activating**: `PGActivate` fans to every up peer; each replica moves
  Stray→ReplicaActive, stamps the epoch, and acks.  When every ack is in,
  the PG is **Active**: parked writes re-drive and last_epoch_started
  advances.

A peer dying mid-peering (bus down event) just shrinks the expectation
set — peering completes with the survivors, exactly like the reference
restarting GetInfo on prior-set changes.

The machine records every transition in ``history`` (epoch, state) — the
`pg_state` the reference exposes via `ceph pg dump`.
"""
from __future__ import annotations

from dataclasses import dataclass
from enum import Enum

from ..backend.messages import PGActivate, PGActivateAck, PGLogInfo, \
    PGLogQuery


class PState(Enum):
    """State names mirror PeeringState.h:604-774's nesting."""
    INITIAL = "Initial"
    GET_INFO = "Started/Primary/Peering/GetInfo"
    GET_LOG = "Started/Primary/Peering/GetLog"
    GET_MISSING = "Started/Primary/Peering/GetMissing"
    ACTIVATING = "Started/Primary/Active/Activating"
    ACTIVE = "Started/Primary/Active"


@dataclass
class PeerInfo:
    """pg_info_t subset the negotiation runs on."""
    shard: int
    last_update: int
    tail: int


class PeeringCoordinator:
    """The primary-side peering machine bound to one PG backend."""

    def __init__(self, backend):
        self.backend = backend
        backend.peering = self
        self.state = PState.INITIAL
        self.epoch = 0
        self.last_epoch_started = 0
        self.history: list[tuple[int, str]] = [(0, PState.INITIAL.value)]
        self._expect_infos: set[int] = set()
        self._infos: dict[int, PGLogInfo] = {}
        self._expect_acks: set[int] = set()
        self.acting_set: list[int] = list(backend.acting)
        self.backfill_targets: set[int] = set()
        self.repair_targets: set[int] = set()
        backend.bus.down_listeners.append(self._on_peer_down)

    # -- bookkeeping -------------------------------------------------------

    def _enter(self, state: PState) -> None:
        self.state = state
        self.history.append((self.epoch, state.value))

    # -- events ------------------------------------------------------------

    def advance_map(self, epoch: int) -> None:
        """AdvMap: the map changed (shard died/revived, acting set
        touched) — restart peering from GetInfo.  Reference: the Peering
        super-state's AdvMap reaction."""
        self.epoch = max(self.epoch, epoch)
        b = self.backend
        sched = getattr(b, "recovery_scheduler", None)
        if sched is not None:
            # map change preempts background repair cleanly: the job's
            # reservations release and the re-activation below queues a
            # fresh one against the new acting-set reality
            sched.cancel_pg(b)
        peers = {s for s in b.acting if s != b.whoami and s not in b.bus.down}
        self._infos = {}
        self._expect_infos = set(peers)
        self._expect_acks = set()
        self._enter(PState.GET_INFO)
        if not peers:
            self._got_all_infos()
            return
        for shard in sorted(peers):
            # entries below our tail are trimmed cluster-wide, so the
            # reply only ships the segment election/repair can use (the
            # same bound start_shard_repair queries with)
            b.bus.send(shard, PGLogQuery(b.whoami, since=b.pg_log.tail))

    def offer_pg_log_info(self, info: PGLogInfo) -> bool:
        """MNotifyRec: a peer's info arrived.  Returns False when this
        machine is not collecting (the reply belongs to a repair op)."""
        if self.state != PState.GET_INFO or \
                info.from_shard not in self._expect_infos:
            return False
        self._infos[info.from_shard] = info
        if set(self._infos) >= self._expect_infos:
            self._got_all_infos()
        return True

    def on_activate_ack(self, ack: PGActivateAck) -> None:
        if self.state != PState.ACTIVATING or ack.epoch != self.epoch:
            return
        self._expect_acks.discard(ack.from_shard)
        if not self._expect_acks:
            self._activate_done()

    def _on_peer_down(self, shard: int) -> None:
        """A peer died mid-peering: shrink the expectation set (the
        reference restarts GetInfo when the prior set changes; with a
        fixed acting set, dropping the dead peer is equivalent)."""
        if self.state == PState.GET_INFO and shard in self._expect_infos:
            self._expect_infos.discard(shard)
            self._infos.pop(shard, None)
            if set(self._infos) >= self._expect_infos:
                self._got_all_infos()
        elif self.state == PState.ACTIVATING and shard in self._expect_acks:
            self._expect_acks.discard(shard)
            if not self._expect_acks:
                self._activate_done()

    # -- the flow ----------------------------------------------------------

    def _got_all_infos(self) -> None:
        b = self.backend
        infos = {b.whoami: PeerInfo(b.whoami, b.pg_log.head, b.pg_log.tail)}
        for shard, info in self._infos.items():
            infos[shard] = PeerInfo(shard, info.last_update, info.tail)
        # find_best_info: max last_update, then longer log, then low shard
        best = max(infos.values(),
                   key=lambda i: (i.last_update, -i.tail, -i.shard))
        self._enter(PState.GET_LOG)
        if best.shard != b.whoami and self._infos:
            # adopt the authority peer's log (+ witness-count rollback)
            b.elect_and_adopt_authority(dict(self._infos))
        self._enter(PState.GET_MISSING)
        # choose_acting: who serves, who repairs, who backfills
        self.acting_set = [b.whoami]
        self.backfill_targets = set()
        self.repair_targets = set()
        head = b.pg_log.head
        for shard, info in sorted(self._infos.items()):
            if info.last_update == head:
                self.acting_set.append(shard)
            elif info.last_update >= b.pg_log.tail:
                self.repair_targets.add(shard)      # log replay suffices
            else:
                self.backfill_targets.add(shard)    # past the log horizon
        self._enter(PState.ACTIVATING)
        up_peers = sorted(set(self._infos))
        self._expect_acks = set(up_peers)
        for shard in up_peers:
            b.bus.send(shard, PGActivate(b.whoami, self.epoch, head))
        if not up_peers:
            self._activate_done()

    def _activate_done(self) -> None:
        b = self.backend
        self._enter(PState.ACTIVE)
        self.last_epoch_started = self.epoch
        # queue recovery for stale/backfill peers: through the recovery
        # scheduler's reservation gate when one is attached (priorities,
        # osd_max_backfills, wave pacing), else inline through the repair
        # machinery (GetMissing's product; the repair op itself picks
        # log-replay vs backfill from the peer's reply)
        targets = [shard
                   for shard in sorted(self.repair_targets |
                                       self.backfill_targets)
                   if shard not in b.bus.down]
        sched = getattr(b, "recovery_scheduler", None)
        if sched is not None and targets:
            sched.schedule_backend(
                b, targets=targets,
                backfill=frozenset(self.backfill_targets))
        else:
            for shard in targets:
                b.start_shard_repair(shard)
        # an Active PG serves: re-drive writes parked while peering
        b._redrive_parked()
