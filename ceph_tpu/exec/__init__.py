"""Serving subsystem (SURVEY north-star: heavy traffic, not just fast
kernels): admission throttles, dmClock-ordered queues, the deadline-driven
op coalescer that fuses concurrent submissions into single device
dispatches, and completion futures/finishers — the reference's
``Throttle``/``WorkQueue``/``Finisher`` trio rebuilt around
inference-style dynamic batching."""
from .throttle import Throttle, ThrottleFull
from .finisher import Finisher
from .batcher import BatchFuture, dispatch_batch, bucket_pad_stripes
from .engine import ServingEngine, live_engines

__all__ = [
    "Throttle", "ThrottleFull", "Finisher", "BatchFuture",
    "dispatch_batch", "bucket_pad_stripes", "ServingEngine",
    "live_engines",
]
