"""Open/closed-loop workload generation over a ServingEngine.

The measurement half of the serving subsystem (the role ``rados bench``'s
ObjBencher plays for the reference, src/common/obj_bencher.cc — but aimed
at the SERVING question: what does coalescing buy at a given concurrency,
and what does the tail look like?):

- **closed loop**: a fixed number of logical clients, each submitting its
  next op the moment the previous completes (completion-callback driven,
  so it needs no thread per client).  Throughput is demand-limited; this
  is the mode the "coalesced >= 3x unbatched at concurrency 64"
  acceptance gate uses.
- **open loop**: ops arrive on a fixed schedule regardless of completions
  (the honest way to measure tail latency under load — closed loops
  self-throttle and hide queueing delay; see the coordinated-omission
  literature).  Requires a started (threaded) engine.

Both report throughput and p50/p95/p99 latency.  Works with a threaded
engine (deadline batching across arrivals) or the deterministic
single-thread engine (the driver pumps ``step()``).
"""
from __future__ import annotations

import threading
import time

import numpy as np

from ..common.percentile import nearest_rank as percentile  # noqa: F401
from ..osd.mclock import CLIENT_OP
from .engine import ServingEngine
from .throttle import ThrottleFull

# `percentile` is THE shared nearest-rank helper (common/percentile.py):
# the deliberately-duplicated copies this module and tools/trace_report.py
# once carried are unified there, and tests/test_critpath.py's AST guard
# keeps anyone from growing a local redefinition that could let bench p99
# and trace p99 drift apart again.


def _latency_stats(lat_s: list[float]) -> dict:
    s = sorted(lat_s)
    return {
        "p50_ms": round(percentile(s, 50) * 1e3, 3),
        "p95_ms": round(percentile(s, 95) * 1e3, 3),
        "p99_ms": round(percentile(s, 99) * 1e3, 3),
        "mean_ms": round(sum(s) / len(s) * 1e3, 3) if s else 0.0,
        "max_ms": round(s[-1] * 1e3, 3) if s else 0.0,
    }


def make_payloads(op_bytes: int, n_distinct: int = 8, seed: int = 0
                  ) -> list[np.ndarray]:
    """A small rotation of distinct payloads (identical buffers would let
    clever caches lie; distinct-per-op would spend the run on RNG)."""
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 256, size=op_bytes, dtype=np.uint8)
            for _ in range(max(1, n_distinct))]


def _engine_deltas(engine: ServingEngine, before: dict) -> dict:
    after = {k: engine.perf.get(k)
             for k in ("batches", "ops_coalesced", "ops_rejected")}
    d = {k: int(after[k] - before[k]) for k in after}
    d["mean_batch_size"] = round(
        d["ops_coalesced"] / d["batches"], 2) if d["batches"] else 0.0
    return d


def _perf_snapshot(engine: ServingEngine) -> dict:
    return {k: engine.perf.get(k)
            for k in ("batches", "ops_coalesced", "ops_rejected")}


def closed_loop(engine: ServingEngine, n_ops: int, concurrency: int,
                payloads: list[np.ndarray] | None = None,
                op_bytes: int = 4096, op_class: str = CLIENT_OP,
                timeout: float = 300.0) -> dict:
    """``concurrency`` logical clients, each resubmitting on completion,
    until ``n_ops`` complete.  Returns throughput + latency percentiles.

    Throttle note: the engine's op throttle must admit ``concurrency``
    ops (a closed loop with demand above the admission bound would just
    deadlock its own completions)."""
    if payloads is None:
        payloads = make_payloads(op_bytes)
    if engine.op_throttle.max < concurrency:
        raise ValueError(
            f"op throttle {engine.op_throttle.max} < concurrency "
            f"{concurrency}: the closed loop would block itself")
    width = engine.sinfo.stripe_width if engine.sinfo is not None else 1
    padded = -(-int(payloads[0].nbytes) // width) * width
    if engine.byte_throttle.max < concurrency * padded:
        raise ValueError(
            f"byte throttle {engine.byte_throttle.max} < concurrency * "
            f"op bytes {concurrency * padded}: the closed loop would "
            f"block itself")
    lock = threading.Lock()
    all_done = threading.Event()
    lat: list[float] = []
    state = {"submitted": 0}
    before = _perf_snapshot(engine)

    def submit_next() -> None:
        with lock:
            i = state["submitted"]
            if i >= n_ops:
                return
            state["submitted"] = i + 1
        fut = engine.submit_encode(payloads[i % len(payloads)],
                                   op_class=op_class)
        fut.add_done_callback(on_done)

    def on_done(fut) -> None:
        with lock:
            lat.append(fut.t_done - fut.t_submit)
            finished = len(lat) >= n_ops
        if finished:
            all_done.set()
        else:
            submit_next()

    t0 = time.monotonic()
    for _ in range(min(concurrency, n_ops)):
        submit_next()
    if engine.running:
        if not all_done.wait(timeout):
            raise TimeoutError(f"closed loop incomplete after {timeout}s: "
                               f"{len(lat)}/{n_ops}")
    else:
        while not all_done.is_set():
            if time.monotonic() - t0 > timeout:
                raise TimeoutError(
                    f"closed loop incomplete after {timeout}s: "
                    f"{len(lat)}/{n_ops}")
            engine.step()
    elapsed = time.monotonic() - t0
    op_nbytes = int(payloads[0].nbytes)
    out = {
        "mode": "closed", "ops": n_ops, "concurrency": concurrency,
        "op_bytes": op_nbytes,
        "elapsed_s": round(elapsed, 4),
        "ops_s": round(n_ops / elapsed, 1) if elapsed else 0.0,
        "mb_s": round(n_ops * op_nbytes / elapsed / 1e6, 2)
        if elapsed else 0.0,
    }
    out.update(_latency_stats(lat))
    out.update(_engine_deltas(engine, before))
    return out


def open_loop(engine: ServingEngine, rate_ops_s: float, seconds: float,
              payloads: list[np.ndarray] | None = None,
              op_bytes: int = 4096, op_class: str = CLIENT_OP,
              timeout: float = 300.0) -> dict:
    """Fixed arrival rate for ``seconds``; latency includes queueing
    delay (no coordinated omission).  Fail-fast engines count rejected
    arrivals instead of blocking the arrival process."""
    if not engine.running:
        raise ValueError("open loop needs a started (threaded) engine")
    if payloads is None:
        payloads = make_payloads(op_bytes)
    lock = threading.Lock()
    lat: list[float] = []
    rejected = 0
    before = _perf_snapshot(engine)

    def on_done(fut) -> None:
        with lock:
            lat.append(fut.t_done - fut.t_submit)

    period = 1.0 / rate_ops_s
    t0 = time.monotonic()
    offered = 0
    next_t = t0
    while True:
        now = time.monotonic()
        if now >= t0 + seconds:
            break
        if now < next_t:
            time.sleep(min(next_t - now, 0.01))
            continue
        try:
            fut = engine.submit_encode(payloads[offered % len(payloads)],
                                       op_class=op_class)
            fut.add_done_callback(on_done)
        except ThrottleFull:
            rejected += 1
        offered += 1
        next_t += period
    engine.flush(timeout)
    elapsed = time.monotonic() - t0
    done = len(lat)
    op_nbytes = int(payloads[0].nbytes)
    out = {
        "mode": "open", "offered_ops_s": rate_ops_s, "ops": done,
        "rejected": rejected, "op_bytes": op_nbytes,
        "elapsed_s": round(elapsed, 4),
        "ops_s": round(done / elapsed, 1) if elapsed else 0.0,
        "mb_s": round(done * op_nbytes / elapsed / 1e6, 2)
        if elapsed else 0.0,
    }
    out.update(_latency_stats(lat))
    out.update(_engine_deltas(engine, before))
    return out


def compare_batched_unbatched(ec_impl, sinfo, n_ops: int = 512,
                              concurrency: int = 64, op_bytes: int = 4096,
                              cct=None, warmup_ops: int = 64,
                              batch_max_ops: int | None = None,
                              timeout: float = 300.0) -> dict:
    """The acceptance-gate measurement: the SAME closed-loop workload on
    the SAME device through (a) a coalescing engine and (b) an
    op-at-a-time engine (``batch_max_ops=1`` — every op is its own device
    dispatch).  A warmup pass per engine takes shape compilation out of
    the measured window (the size buckets exist so steady state has a
    bounded shape set)."""
    results: dict = {"concurrency": concurrency, "op_bytes": op_bytes,
                     "n_ops": n_ops}
    payloads = make_payloads(op_bytes)
    for label, max_ops in (("batched",
                            batch_max_ops or min(concurrency, 64)),
                           ("unbatched", 1)):
        eng = ServingEngine(cct=cct, ec_impl=ec_impl, sinfo=sinfo,
                            name=f"bench.{label}",
                            max_ops=max(1024, concurrency * 2),
                            max_bytes=max(64 << 20,
                                          concurrency * op_bytes * 4),
                            batch_max_ops=max_ops,
                            batch_max_delay_ms=2.0).start()
        try:
            closed_loop(eng, warmup_ops, concurrency, payloads,
                        timeout=timeout)                       # warm shapes
            results[label] = closed_loop(eng, n_ops, concurrency, payloads,
                                         timeout=timeout)
        finally:
            eng.stop()
    b, u = results["batched"]["ops_s"], results["unbatched"]["ops_s"]
    results["speedup"] = round(b / u, 2) if u else 0.0
    return results
