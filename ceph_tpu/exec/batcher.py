"""Batch futures + the batch-forming math for the op coalescer.

The serving half of the TPU thesis: `ecutil.encode_many` can already fuse
MANY ops into ONE device dispatch, but only when a caller hands it an
explicit batch.  This module turns CONCURRENT single-op submissions into
those batches:

- :class:`BatchFuture` — the completion handle an async submitter gets
  back (the role the reference's ``Context``/``C_OSD_*`` completion
  callbacks play on ECBackend's write path), with
  ``result()/done()/add_done_callback()`` shaped like
  ``concurrent.futures``.
- :func:`group_ops` — partition a dequeued batch by codec identity
  (ops from different pools must not fuse: different k/m/chunk layout).
- :func:`bucket_pad_stripes` — round a batch's total stripe count up to
  the next power-of-two size bucket.  Dynamic batch totals would give
  the jitted device path a fresh shape (→ recompile) per batch; padding
  to geometric buckets keeps the shape set logarithmic, and RS parity is
  positionwise-linear so zero padding encodes to zero parity — sliced
  off exactly (the same trick inference servers use for dynamic
  batching).
- :func:`dispatch_batch` — run one formed batch through
  ``ecutil.encode_many`` / ``ecutil.decode_many`` under tracer spans.
"""
from __future__ import annotations

import threading

import numpy as np

from ..backend import ecutil
from ..common.tracer import trace_span

ENCODE = "encode"
DECODE = "decode"


class BatchFuture:
    """Completion handle for one submitted op (concurrent.futures shape)."""

    __slots__ = ("kind", "payload", "sinfo", "ec_impl", "op_class",
                 "cost_bytes", "t_submit", "t_submit_wall", "t_dispatch",
                 "t_done", "eager", "trace", "_event", "_result",
                 "_error", "_callbacks", "_lock")

    def __init__(self, kind: str, payload, sinfo, ec_impl, op_class: str,
                 cost_bytes: int, t_submit: float, t_submit_wall: float,
                 eager: bool = False, trace=None):
        self.kind = kind
        self.payload = payload
        self.sinfo = sinfo
        self.ec_impl = ec_impl
        self.op_class = op_class
        self.cost_bytes = cost_bytes
        self.t_submit = t_submit
        self.t_submit_wall = t_submit_wall
        self.t_dispatch = 0.0
        self.t_done = 0.0
        # eager: a submitter is BLOCKED on this op (sync encode()/
        # decode()); the coalescer dispatches what has arrived instead
        # of waiting out the deadline for hypothetical companions
        self.eager = eager
        # the submitter's TraceContext (if any): the engine stamps the
        # op's batch-formation wait into that trace at dispatch time,
        # so the critical-path ledger can attribute `batch_delay`
        self.trace = trace
        self._event = threading.Event()
        self._result = None
        self._error: BaseException | None = None
        self._callbacks: list = []
        self._lock = threading.Lock()

    # -- consumer side -------------------------------------------------------

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"serving op not complete within {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result

    def exception(self, timeout: float | None = None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"serving op not complete within {timeout}s")
        return self._error

    def add_done_callback(self, fn) -> None:
        """``fn(future)`` on completion; runs immediately when already
        done (concurrent.futures semantics), else on the finisher."""
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    # -- engine side ---------------------------------------------------------

    def _finish(self, result=None, error: BaseException | None = None):
        with self._lock:
            self._result = result
            self._error = error
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:
            fn(self)


def group_ops(ops: list[BatchFuture]) -> list[list[BatchFuture]]:
    """Partition by (codec, stripe geometry, kind) — only ops sharing the
    codec can share a device dispatch; decode ops additionally need the
    same available-chunk set to share a decode matrix, which
    ``ecutil.decode_many`` subdivides itself."""
    groups: dict[tuple, list[BatchFuture]] = {}
    for op in ops:
        key = (id(op.ec_impl), op.sinfo.k, op.sinfo.chunk_size, op.kind)
        groups.setdefault(key, []).append(op)
    return list(groups.values())


def bucket_pad_stripes(total_stripes: int) -> int:
    """Next power-of-two stripe count >= total (the size bucket)."""
    if total_stripes <= 1:
        return 1
    return 1 << (total_stripes - 1).bit_length()


def _land_results(ops: list[BatchFuture]):
    """A pipeline-future done-callback that copies the future's value (one
    result per op, in order) — or its error, shared — onto the ops."""
    def land(fut):
        if fut.error is not None:
            for op in ops:
                op._error = fut.error
        else:
            for op, result in zip(ops, fut.value):
                op._result = result
    return land


def _encode_group(group: list[BatchFuture], pad_to_bucket: bool,
                  pipeline=None) -> list[tuple[list[BatchFuture], object]]:
    sinfo, ec = group[0].sinfo, group[0].ec_impl
    bufs = [op.payload for op in group]
    total = sum(len(b) for b in bufs) // sinfo.stripe_width
    padded = bucket_pad_stripes(total) if pad_to_bucket else total
    if padded > total:
        bufs = bufs + [np.zeros((padded - total) * sinfo.stripe_width,
                                dtype=np.uint8)]
    if pipeline is not None:
        fut = ecutil.encode_many_pipelined(sinfo, ec, bufs, pipeline,
                                           owner="serving")
        if fut is not None:
            fut.add_done_callback(_land_results(group))
            return [(group, fut)]
    with trace_span("serving.batch_encode", owner="serving",
                    ops=len(group), stripes=total, padded_stripes=padded):
        encoded = ecutil.encode_many(sinfo, ec, bufs)
    for op, chunks in zip(group, encoded):
        op._result = chunks
    return [(group, None)]


def _decode_group(group: list[BatchFuture], pad_to_bucket: bool,
                  pipeline=None) -> list[tuple[list[BatchFuture], object]]:
    sinfo, ec = group[0].sinfo, group[0].ec_impl
    pad = bucket_pad_stripes if pad_to_bucket else None
    if pipeline is not None:
        pending = ecutil.decode_many_pipelined(
            sinfo, ec, [op.payload for op in group], pipeline,
            pad_chunks=pad, chunk_size=sinfo.chunk_size, owner="serving")
        if pending is not None:
            out = []
            for idxs, fut in pending:
                sub = [group[i] for i in idxs]
                fut.add_done_callback(_land_results(sub))
                out.append((sub, fut))
            return out
    with trace_span("serving.batch_decode", owner="serving",
                    ops=len(group)):
        decoded = ecutil.decode_many(
            sinfo, ec, [op.payload for op in group],
            pad_chunks=pad, chunk_size=sinfo.chunk_size)
    for op, data in zip(group, decoded):
        op._result = data
    return [(group, None)]


def dispatch_batch(ops: list[BatchFuture], pad_to_bucket: bool = True,
                   pipeline=None) -> list[tuple[list[BatchFuture], object]]:
    """Run one formed batch: fused per codec group; results (or a shared
    error) land on each future's ``_result``/``_error`` — the ENGINE
    completes them (throttle release + finisher callbacks stay with the
    component that owns those resources).

    Returns ``[(ops, pipeline_future | None), ...]``: None means the
    group ran synchronously and its results are already landed; a future
    means the group is IN FLIGHT on the device pipeline — results land
    via a done-callback at the pipeline's completion boundary, and the
    engine must defer each op's completion until then."""
    pending: list[tuple[list[BatchFuture], object]] = []
    for group in group_ops(ops):
        try:
            if group[0].kind == ENCODE:
                pending.extend(_encode_group(group, pad_to_bucket, pipeline))
            else:
                pending.extend(_decode_group(group, pad_to_bucket, pipeline))
        except BaseException as e:             # noqa: BLE001 — one bad op
            # (unaligned buffer, codec error) fails its GROUP, never the
            # coalescer thread; per-op granularity would re-dispatch the
            # good ops but a group shares one device call — fail together
            for op in group:
                op._error = e
            pending.append((group, None))
    return pending
