"""ServingEngine: admission throttles + mClock ordering + op coalescing.

The serving subsystem the north star needs between "fast codec" and "fast
service": the reference's ``Throttle`` / ``WorkQueue`` / ``Finisher`` trio
(src/common/Throttle.h, src/common/WorkQueue.h, src/common/Finisher.h)
fused with inference-style dynamic batching:

- **admission**: every submitted op takes from a byte throttle AND an op
  throttle first — backpressure blocks (FIFO) or fails fast
  (``osd_serving_fail_fast``) instead of growing queues unboundedly;
- **ordering**: admitted ops land in a dmClock queue keyed by op CLASS
  (client vs recovery vs scrub — :mod:`ceph_tpu.osd.mclock`), so QoS
  decides WHO batches first when the queue is contended;
- **coalescing**: one coalescer thread drains the queue into padded,
  size-bucketed device batches through ``ecutil.encode_many`` /
  ``decode_many`` under a deadline — an op waits at most
  ``osd_batch_max_delay_ms`` for companions, and a batch never exceeds
  ``osd_batch_max_ops``.  64 concurrent 1 MiB writes become a handful of
  fused dispatches instead of 64;
- **completion**: results come back as :class:`BatchFuture`; callbacks
  run on a :class:`Finisher`, never on the coalescer thread.

Deterministic single-thread mode for tests: leave ``start()`` uncalled
and drive with ``step()``/``flush()`` — same code path, no threads.

Every queue here is bounded: the throttles bound the mClock admission
queue (ops and bytes), the finisher bounds its callback queue.
``tests/test_no_unbounded_queue.py`` guards the discipline.
"""
from __future__ import annotations

import threading
import time
import weakref

import numpy as np

from ..backend import ecutil
from ..common import default_context
from ..common.perf_counters import PerfCountersBuilder
from ..common.tracer import LATENCY_BUCKETS_S, default_tracer
from ..ops.pipeline import CodecPipeline
from ..osd.mclock import CLIENT_OP, MClockOpClassQueue
from .batcher import BatchFuture, DECODE, ENCODE, dispatch_batch
from .finisher import Finisher
from .throttle import Throttle, ThrottleFull

# live engines, for the prometheus mclock-depth gauge export
_ENGINES: "weakref.WeakSet[ServingEngine]" = weakref.WeakSet()

BATCH_SIZE_BUCKETS = [1, 2, 4, 8, 16, 32, 64, 128, 256]


def live_engines() -> list["ServingEngine"]:
    return list(_ENGINES)


def _build_perf(name: str):
    return (PerfCountersBuilder(name)
            .add_u64("queue_depth", "ops waiting for a batch slot")
            .add_u64("queue_bytes", "bytes waiting for a batch slot")
            .add_u64_counter("ops_submitted", "ops admitted")
            .add_u64_counter("ops_rejected",
                             "fail-fast admissions refused (backpressure)")
            .add_u64_counter("ops_completed", "ops finished")
            .add_u64_counter("ops_failed", "ops finished with an error")
            .add_u64_counter("batches", "device batches dispatched")
            .add_u64_counter("ops_coalesced", "ops fused into batches")
            .add_u64_counter("bytes_in", "payload bytes through the engine")
            .add_histogram("batch_size", BATCH_SIZE_BUCKETS,
                           "ops per dispatched batch")
            .add_time_avg("queue_wait_time", "submit-to-dispatch wait")
            .add_time_avg("e2e_time", "submit-to-completion latency")
            .add_histogram("queue_wait_lat", list(LATENCY_BUCKETS_S),
                           "submit-to-dispatch wait histogram (s)")
            .add_histogram("op_e2e_lat", list(LATENCY_BUCKETS_S),
                           "submit-to-completion latency histogram (s)")
            .create_perf_counters())


class ServingEngine:
    """One serving pipeline: throttles -> dmClock queue -> coalescer ->
    fused device dispatch -> finisher completions."""

    def __init__(self, cct=None, ec_impl=None, sinfo=None,
                 name: str = "serving",
                 max_bytes: int | None = None, max_ops: int | None = None,
                 fail_fast: bool | None = None,
                 batch_max_delay_ms: float | None = None,
                 batch_max_ops: int | None = None,
                 class_info: dict | None = None,
                 pad_to_bucket: bool = True,
                 pipeline_depth: int | None = None):
        self.cct = cct if cct is not None else default_context()
        conf = self.cct.conf
        self.name = name
        self.ec_impl = ec_impl          # default codec (per-op override ok)
        self.sinfo = sinfo
        self.fail_fast = bool(conf.get("osd_serving_fail_fast")
                              if fail_fast is None else fail_fast)
        self.batch_max_delay_ms = float(
            conf.get("osd_batch_max_delay_ms")
            if batch_max_delay_ms is None else batch_max_delay_ms)
        self.batch_max_ops = int(conf.get("osd_batch_max_ops")
                                 if batch_max_ops is None else batch_max_ops)
        self.pad_to_bucket = pad_to_bucket
        self.byte_throttle = Throttle(
            f"{name}.bytes",
            conf.get("osd_serving_throttle_bytes")
            if max_bytes is None else max_bytes, cct=self.cct)
        self.op_throttle = Throttle(
            f"{name}.ops",
            conf.get("osd_serving_throttle_ops")
            if max_ops is None else max_ops, cct=self.cct)
        self.queue = MClockOpClassQueue(class_info)
        self.finisher = Finisher(name)
        # the device pipeline: coalesced batches dispatch async through it
        # (device-routed codecs only), so the NEXT batch's host pack
        # overlaps the in-flight device compute.  depth 0 = synchronous.
        depth = int(conf.get("jax_rs_pipeline_depth")
                    if pipeline_depth is None else pipeline_depth)
        self.pipeline = CodecPipeline(depth=depth, cct=self.cct,
                                      name=f"{name}.pipeline") \
            if depth > 0 else None
        self.perf = _build_perf(name)
        self.cct.perf.add(self.perf)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._idle = threading.Condition(self._lock)
        self._depth = 0
        self._qbytes = 0
        self._in_flight = 0
        self._eager = 0                 # queued ops with a blocked waiter
        self._first_t = 0.0             # oldest queued op's submit time
        self._stopping = False
        self._thread: threading.Thread | None = None
        # live-tunable batching knobs (md_config observer pattern); the
        # explicit ctor args pin a test's engine against global pokes.
        # Observers hold the engine WEAKLY: the config store outlives
        # engines and a strong closure would pin every engine forever.
        ref = weakref.ref(self)

        def _update(attr, cast):
            def obs(_name, value, _ref=ref):
                eng = _ref()
                if eng is not None:
                    setattr(eng, attr, cast(value))
            return obs
        if batch_max_delay_ms is None:
            conf.add_observer("osd_batch_max_delay_ms",
                              _update("batch_max_delay_ms", float))
        if batch_max_ops is None:
            conf.add_observer("osd_batch_max_ops",
                              _update("batch_max_ops", int))
        _ENGINES.add(self)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServingEngine":
        """Run threaded: coalescer + finisher threads."""
        if self._thread is None:
            self._stopping = False
            # re-register counters a previous stop() unhooked (restart),
            # and rejoin the live-engine registry stop() discarded from —
            # a restarted engine must keep exporting its queue gauges
            self.cct.perf.add(self.perf)
            self.cct.perf.add(self.byte_throttle.perf)
            self.cct.perf.add(self.op_throttle.perf)
            if self.pipeline is not None:
                self.pipeline.reopen()
            _ENGINES.add(self)
            self.finisher.start()
            self._thread = threading.Thread(
                target=self._loop, name=f"coalescer-{self.name}",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        """Drain everything queued, stop the threads, and unhook the
        perf collections from the Context (the repo's discipline: a
        discarded component must not leave frozen gauges in perf dump /
        prometheus forever — PGBackend.shutdown does the same)."""
        with self._lock:
            self._stopping = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self.finisher.stop()
        while self.step():              # anything submitted after join
            pass
        self._stopping = False
        for pc in (self.perf, self.byte_throttle.perf,
                   self.op_throttle.perf):
            self.cct.perf.remove(pc.name)
        if self.pipeline is not None:
            self.pipeline.close()       # drains + unhooks its perf
        _ENGINES.discard(self)

    @property
    def running(self) -> bool:
        return self._thread is not None

    def depths(self) -> dict:
        """mClock queue depth by op class (+ total/bytes gauges + the
        device breaker's state when a pipeline is attached)."""
        with self._lock:
            d = self.queue.depths()
            d["_total"] = self._depth
            d["_bytes"] = self._qbytes
        if self.pipeline is not None and self.pipeline.breaker is not None:
            d["_breaker"] = self.pipeline.breaker.state
        return d

    def pressure(self) -> float:
        """Admission occupancy in [0, 1]: the larger of the byte and op
        throttle fill fractions — the overload signal the sharded front
        end (msg/frontend.py) sheds on before work ever queues here."""
        b, o = self.byte_throttle, self.op_throttle
        return max(b.count / b.max if b.max else 0.0,
                   o.count / o.max if o.max else 0.0)

    def inject_device_faults(self, injector) -> None:
        """Route the device-plane fault injection (failure/) through this
        engine's codec pipeline — the chaos harness hook."""
        if self.pipeline is not None:
            self.pipeline.inject_faults(injector)

    # -- submission ----------------------------------------------------------

    # an admission wait shorter than this never emits a trace event: the
    # uncontended fast path would otherwise add one ring entry per op
    # for a phase whose whole point is "the throttle actually blocked"
    ADMISSION_TRACE_FLOOR_S = 5e-4

    def _admit_traced(self, cost_bytes: int):
        """Admit, and stamp a ``serving.admission`` event into the
        submitter's active trace when the throttles measurably blocked
        (the critical-path ledger's ``admission`` phase).  Returns the
        submitter's TraceContext so the op's BatchFuture rides the SAME
        context (one lookup; an ambient change between two lookups
        would split admission and batch_wait across traces)."""
        tr = default_tracer()
        ctx = tr.current_ctx()
        t0 = time.monotonic()
        self._admit(cost_bytes)
        wait = time.monotonic() - t0
        if ctx is not None and wait >= self.ADMISSION_TRACE_FLOOR_S:
            tr.complete("serving.admission", time.time() - wait, wait,
                        ctx=ctx, engine=self.name)
        return ctx

    def _admit(self, cost_bytes: int) -> None:
        if self.fail_fast:
            if not self.op_throttle.get_or_fail(1):
                self.perf.inc("ops_rejected")
                raise ThrottleFull(self.op_throttle.name, 1,
                                   self.op_throttle.count,
                                   self.op_throttle.max)
            if not self.byte_throttle.get_or_fail(cost_bytes):
                self.op_throttle.put(1)
                self.perf.inc("ops_rejected")
                raise ThrottleFull(self.byte_throttle.name, cost_bytes,
                                   self.byte_throttle.count,
                                   self.byte_throttle.max)
        else:
            self.op_throttle.get(1)
            self.byte_throttle.get(cost_bytes)

    def _enqueue(self, op: BatchFuture) -> BatchFuture:
        with self._lock:
            if self._depth == 0:
                self._first_t = op.t_submit
            self.queue.enqueue(op.op_class, op, now=op.t_submit, cost=1.0)
            self._depth += 1
            self._qbytes += op.cost_bytes
            if op.eager:
                self._eager += 1
            self.perf.set("queue_depth", self._depth)
            self.perf.set("queue_bytes", self._qbytes)
            self.perf.inc("ops_submitted")
            self.perf.inc("bytes_in", op.cost_bytes)
            self._cond.notify()
        return op

    # one bytes->uint8 conversion for the whole codebase (ecutil's)
    _as_u8 = staticmethod(ecutil._as_u8)

    def submit_encode(self, buf, op_class: str = CLIENT_OP,
                      sinfo=None, ec_impl=None,
                      eager: bool = False) -> BatchFuture:
        """Admit one encode op; returns a :class:`BatchFuture` resolving
        to ``{chunk: np.uint8 chunk bytes}`` for the (zero-padded to
        stripe width) buffer.  ``eager`` marks a submission whose caller
        blocks on the result: the coalescer then dispatches what has
        accumulated instead of waiting out the batching deadline."""
        sinfo = sinfo if sinfo is not None else self.sinfo
        ec = ec_impl if ec_impl is not None else self.ec_impl
        if sinfo is None or ec is None:
            raise ValueError("engine has no default codec: pass "
                             "sinfo/ec_impl per op or at construction")
        arr = self._as_u8(buf)
        pad = (-len(arr)) % sinfo.stripe_width
        if pad:
            arr = np.concatenate(
                [arr, np.zeros(pad, dtype=np.uint8)])
        cost = int(arr.nbytes)
        ctx = self._admit_traced(cost)
        op = BatchFuture(ENCODE, arr, sinfo, ec, op_class, cost,
                         time.monotonic(), time.time(), eager=eager,
                         trace=ctx)
        return self._enqueue(op)

    def submit_decode(self, chunks: dict, op_class: str = CLIENT_OP,
                      sinfo=None, ec_impl=None,
                      eager: bool = False) -> BatchFuture:
        """Admit one decode op (``{chunk_id: chunk bytes}``, >= k
        present); resolves to the logical bytes."""
        sinfo = sinfo if sinfo is not None else self.sinfo
        ec = ec_impl if ec_impl is not None else self.ec_impl
        if sinfo is None or ec is None:
            raise ValueError("engine has no default codec: pass "
                             "sinfo/ec_impl per op or at construction")
        payload = {c: self._as_u8(v) for c, v in chunks.items()}
        cost = int(sum(v.nbytes for v in payload.values()))
        ctx = self._admit_traced(cost)
        op = BatchFuture(DECODE, payload, sinfo, ec, op_class, cost,
                         time.monotonic(), time.time(), eager=eager,
                         trace=ctx)
        return self._enqueue(op)

    # sync conveniences (the ECBackend hook uses these) --------------------
    # eager=True: the caller blocks right here, so making it sit out the
    # full batching deadline buys nothing when it is alone — concurrent
    # sync submitters still fuse (whatever queued by dispatch time rides
    # the same batch), but a serial caller pays ~dispatch, not ~deadline.

    def encode(self, buf, op_class: str = CLIENT_OP, timeout: float = 60.0,
               **kw) -> dict:
        fut = self.submit_encode(buf, op_class, eager=True, **kw)
        if self._thread is None:
            self.flush()
        return fut.result(timeout)

    def decode(self, chunks: dict, op_class: str = CLIENT_OP,
               timeout: float = 60.0, **kw) -> bytes:
        fut = self.submit_decode(chunks, op_class, eager=True, **kw)
        if self._thread is None:
            self.flush()
        return fut.result(timeout)

    # -- the coalescer -------------------------------------------------------

    def _drain_locked(self, limit: int,
                      force: bool = False) -> list[BatchFuture]:
        """Pop up to ``limit`` ops in dmClock order (lock held).
        ``force`` serves QoS-over-limit items immediately (stop/step)."""
        ops: list[BatchFuture] = []
        while len(ops) < limit and self._depth:
            now = time.monotonic()
            item = self.queue.dequeue(now)
            if item is None:
                # everything queued is over its QoS limit.  A formed
                # batch dispatches now; an empty round waits for
                # eligibility (drains immediately on stop/step — limits
                # are rates, not suicide pacts)
                nxt = self.queue.next_eligible_time(now)
                if ops or nxt is None:
                    break
                if force or self._stopping:
                    item = self.queue.dequeue(nxt)
                    if item is None:
                        break
                else:
                    self._cond.wait(min(nxt - now, 0.05))
                    continue
            ops.append(item)
            self._depth -= 1
            self._qbytes -= item.cost_bytes
            if item.eager:
                self._eager -= 1
        self.perf.set("queue_depth", self._depth)
        self.perf.set("queue_bytes", self._qbytes)
        if self._depth:
            # leftover ops KEEP their original wait budget: the next
            # deadline derives from the oldest remaining submit time,
            # not from now (resetting would double an op's max wait
            # every partial drain)
            self._first_t = min(
                (rec.queue[0].item.t_submit
                 for rec in self.queue.clients.values() if rec.queue),
                default=time.monotonic())
        self._in_flight += len(ops)
        return ops

    def _gather(self) -> list[BatchFuture] | None:
        """Form one batch under the deadline; None = stopped and empty.
        An EMPTY list means: nothing to pack but the device pipeline has
        batches in flight — the loop completes the oldest instead of
        sleeping (the completion boundary on the idle edge)."""
        with self._lock:
            while self._depth == 0:
                if self.pipeline is not None and self.pipeline.in_flight:
                    return []
                if self._stopping:
                    return None
                self._cond.wait()
            deadline = self._first_t + self.batch_max_delay_ms / 1e3
            while (self._depth < self.batch_max_ops
                   and not self._eager      # a blocked sync waiter cuts
                   and not self._stopping):  # through the deadline
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cond.wait(left)
            return self._drain_locked(self.batch_max_ops)

    def _dispatch(self, ops: list[BatchFuture]) -> None:
        t = time.monotonic()
        tr = default_tracer()
        for op in ops:
            op.t_dispatch = t
            self.perf.tinc("queue_wait_time", t - op.t_submit)
            self.perf.hinc("queue_wait_lat", t - op.t_submit)
            if op.trace is not None:
                # the submit-to-dispatch wait IS the batch-formation
                # deadline the op paid: stamped into the op's trace so
                # the critical-path ledger attributes `batch_delay`
                tr.complete("serving.batch_wait", op.t_submit_wall,
                            t - op.t_submit, ctx=op.trace,
                            engine=self.name)
        self.perf.inc("batches")
        self.perf.inc("ops_coalesced", len(ops))
        self.perf.hinc("batch_size", len(ops))
        for group, fut in dispatch_batch(ops, self.pad_to_bucket,
                                         pipeline=self.pipeline):
            if fut is None:             # synchronous: results are landed
                self._queue_completions(group)
            else:                       # in flight on the device pipeline:
                # complete at the completion boundary (the result-landing
                # callback registered by the batcher runs first)
                fut.add_done_callback(
                    lambda _f, _g=tuple(group): self._queue_completions(_g))

    def _queue_completions(self, ops) -> None:
        for op in ops:
            self.finisher.queue(self._complete_op, op)

    def _complete_op(self, op: BatchFuture) -> None:
        op.t_done = time.monotonic()
        # release BEFORE the callbacks run: a callback that resubmits
        # (closed-loop generators) must find this op's units free
        self.byte_throttle.put(op.cost_bytes)
        self.op_throttle.put(1)
        e2e = op.t_done - op.t_submit
        self.perf.inc("ops_completed")
        if op._error is not None:
            self.perf.inc("ops_failed")
        self.perf.tinc("e2e_time", e2e)
        self.perf.hinc("op_e2e_lat", e2e)
        default_tracer().complete("serving.op", op.t_submit_wall, e2e,
                                  kind=op.kind, op_class=op.op_class)
        # finisher completion boundary: fold this thread's pending span
        # batch into the tracer ring once per retired op
        default_tracer().flush()
        with self._lock:
            self._in_flight -= 1
            if not self._in_flight and not self._depth:
                self._idle.notify_all()
        op._finish(op._result, op._error)

    def _loop(self) -> None:
        while True:
            ops = self._gather()
            if ops is None:
                return
            if ops:
                self._dispatch(ops)
            elif self.pipeline is not None:
                # idle edge: nothing to pack — retire the oldest in-flight
                # device batch (completions ride the finisher as usual)
                self.pipeline.complete_one()

    # -- deterministic driving (tests / inline mode) -----------------------

    def step(self) -> int:
        """One inline coalescer round: drain up to batch_max_ops NOW (no
        deadline wait), dispatch, run completions.  Single-thread mode
        only; returns ops dispatched."""
        assert self._thread is None, "step() is for the unstarted engine"
        with self._lock:
            ops = self._drain_locked(self.batch_max_ops, force=True)
        if ops:
            self._dispatch(ops)
        if self.pipeline is not None:
            self.pipeline.flush()
        self.finisher.drain()
        return len(ops)

    def flush(self, timeout: float | None = 60.0) -> None:
        """Complete everything submitted so far."""
        if self._thread is None:
            while self.step():
                pass
            return
        with self._lock:
            ok = self._idle.wait_for(
                lambda: not self._depth and not self._in_flight, timeout)
        if not ok:
            raise TimeoutError(f"serving flush timed out after {timeout}s")
        self.finisher.wait_for_empty(timeout)
