"""Admission throttles: bounded counting semaphores over bytes/ops.

Analog of the reference's ``Throttle`` (reference: src/common/Throttle.{h,cc}
— ``_wait`` FIFO condition queue :93-133, ``get``/``get_or_fail``/``put``
:134-221, per-throttle PerfCounters l_throttle_* :40-77).  Semantics
mirrored:

- ``get(c)`` blocks until ``count + c <= max`` **in FIFO order** (a large
  request cannot be starved by a stream of small ones slipping past it —
  the reference queues per-waiter condition variables for exactly this);
- ``get_or_fail(c)`` never blocks: False (and a perf tick) when the take
  would overshoot, also refusing while earlier waiters queue (fairness);
- ``put(c)`` releases and wakes the head waiter;
- a request larger than ``max`` itself is accepted once the throttle is
  EMPTY (the reference admits oversized singletons rather than deadlock).

The serving engine stacks two of these — bytes and op count — in front of
its admission queue; either limit hitting is backpressure (block or
fail-fast, option-controlled).
"""
from __future__ import annotations

import itertools
import threading
import time as _time

from ..common.perf_counters import PerfCountersBuilder


class ThrottleFull(IOError):
    """Fail-fast admission refusal: the throttle is at its limit."""

    def __init__(self, name: str, want: int, count: int, maximum: int):
        super().__init__(
            f"throttle {name!r} full: want {want}, {count}/{maximum} in use")
        self.throttle = name
        self.want = want
        self.count = count
        self.max = maximum


def _build_perf(name: str):
    return (PerfCountersBuilder(name)
            .add_u64("val", "currently taken units")
            .add_u64("max", "configured limit")
            .add_u64_counter("get", "successful blocking takes")
            .add_u64_counter("get_sum", "units taken by blocking takes")
            .add_u64_counter("get_or_fail_success",
                             "non-blocking takes that fit")
            .add_u64_counter("get_or_fail_fail",
                             "non-blocking takes refused (backpressure)")
            .add_u64_counter("put", "releases")
            .add_u64_counter("put_sum", "units released")
            .add_time_avg("wait", "blocking-take wait time")
            .create_perf_counters())


class Throttle:
    """FIFO bounded semaphore (src/common/Throttle.cc shape)."""

    def __init__(self, name: str, maximum: int, cct=None):
        if maximum <= 0:
            raise ValueError(f"throttle {name!r}: max must be > 0")
        self.name = name
        self._max = int(maximum)
        self._count = 0
        self._lock = threading.Lock()
        # FIFO waiters: ticket -> Condition; the head ticket is the only
        # one allowed to take (Throttle.cc queues cond-per-waiter)
        self._waiters: dict[int, threading.Condition] = {}
        self._tickets = itertools.count()
        self.perf = _build_perf(f"throttle.{name}")
        self.perf.set("max", self._max)
        if cct is not None:
            cct.perf.add(self.perf)

    # -- introspection -------------------------------------------------------

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def max(self) -> int:
        with self._lock:
            return self._max

    def set_max(self, maximum: int) -> None:
        with self._lock:
            self._max = int(maximum)
            self.perf.set("max", self._max)
            self._wake_head_locked()

    def waiters(self) -> int:
        with self._lock:
            return len(self._waiters)

    def _fits_locked(self, c: int) -> bool:
        # oversized singleton: admitted when empty (Throttle.cc:103-108
        # comment — blocking forever would deadlock the caller)
        if c > self._max:
            return self._count == 0
        return self._count + c <= self._max

    def _wake_head_locked(self) -> None:
        if self._waiters:
            head = next(iter(self._waiters))
            self._waiters[head].notify()

    # -- take / release ------------------------------------------------------

    def get(self, c: int = 1, timeout: float | None = None) -> bool:
        """Blocking take; returns True (or False on timeout, nothing
        taken).  FIFO: joins the waiter queue if anyone is ahead."""
        assert c >= 0
        with self._lock:
            if not self._waiters and self._fits_locked(c):
                self._count += c
                self.perf.set("val", self._count)
                self.perf.inc("get")
                self.perf.inc("get_sum", c)
                return True
            ticket = next(self._tickets)
            cond = threading.Condition(self._lock)
            self._waiters[ticket] = cond
            deadline = None if timeout is None else \
                threading.TIMEOUT_MAX if timeout < 0 else timeout
            t_end = None if deadline is None else \
                _time.monotonic() + deadline
            with self.perf.time("wait"):
                while True:
                    is_head = next(iter(self._waiters)) == ticket
                    if is_head and self._fits_locked(c):
                        break
                    left = None if t_end is None else \
                        t_end - _time.monotonic()
                    if left is not None and left <= 0 or \
                            not cond.wait(left):
                        del self._waiters[ticket]
                        self._wake_head_locked()
                        return False
            del self._waiters[ticket]
            self._count += c
            self.perf.set("val", self._count)
            self.perf.inc("get")
            self.perf.inc("get_sum", c)
            # the new head may also fit (e.g. after set_max growth)
            self._wake_head_locked()
            return True

    def get_or_fail(self, c: int = 1) -> bool:
        """Non-blocking take; False = backpressure (counted)."""
        assert c >= 0
        with self._lock:
            if self._waiters or not self._fits_locked(c):
                self.perf.inc("get_or_fail_fail")
                return False
            self._count += c
            self.perf.set("val", self._count)
            self.perf.inc("get_or_fail_success")
            return True

    def take(self, c: int = 1) -> int:
        """Unconditional take (the reference's ``take``: callers that
        already own the resource, e.g. requeues).  May overshoot max."""
        with self._lock:
            self._count += c
            self.perf.set("val", self._count)
            return self._count

    def put(self, c: int = 1) -> int:
        with self._lock:
            assert self._count >= c, \
                f"throttle {self.name!r}: put {c} > count {self._count}"
            self._count -= c
            self.perf.set("val", self._count)
            self.perf.inc("put")
            self.perf.inc("put_sum", c)
            self._wake_head_locked()
            return self._count
