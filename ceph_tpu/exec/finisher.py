"""Finisher: ordered completion-callback execution off the hot path.

Analog of the reference's ``Finisher`` (reference: src/common/Finisher.{h,cc}
— a dedicated thread draining ``finisher_queue`` in submission order, with
``queue_len``/``complete_latency`` perf counters :18-30).  The coalescer
thread must never run user completion callbacks inline: a slow callback
would stall every other op in the batch (and a callback that resubmits —
the closed-loop workload generator does exactly this — would deadlock
against a full admission throttle).

Runs threaded (``start``) or inline-on-demand (``drain`` — the
deterministic single-thread mode tests use).  The queue is explicitly
bounded; ``queue`` blocks when full (backpressure propagates to the
dispatcher rather than growing memory).
"""
from __future__ import annotations

import threading
from collections import deque

FINISHER_QUEUE_BOUND = 65536      # callbacks; far above any sane in-flight


class Finisher:
    def __init__(self, name: str = "fin", bound: int = FINISHER_QUEUE_BOUND):
        self.name = name
        self.bound = bound
        self._queue: deque = deque(maxlen=bound)   # guarded: never at maxlen
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._nonfull = threading.Condition(self._lock)
        self._idle = threading.Condition(self._lock)
        self._in_progress = 0
        self._stopping = False
        self._thread: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Finisher":
        if self._thread is None or not self._thread.is_alive():
            self._stopping = False
            self._thread = threading.Thread(
                target=self._loop, name=f"finisher-{self.name}", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        """Drain everything queued, then stop the thread (Finisher::stop
        waits for the queue to empty)."""
        with self._lock:
            self._stopping = True
            self._nonempty.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self.drain()          # anything queued after the thread exited

    # -- submission ----------------------------------------------------------

    def queue(self, fn, *args) -> None:
        with self._lock:
            while len(self._queue) >= self.bound and not self._stopping:
                self._nonfull.wait()
            if len(self._queue) >= self.bound:
                # stopping AND full: appending would make the bounded
                # deque silently EVICT the oldest pending completion
                # (hanging its future, leaking its throttle units) —
                # run this one inline on the submitter instead
                item = (fn, args)
            else:
                self._queue.append((fn, args))
                self._nonempty.notify()
                return
        self._run_one(item)

    def queue_len(self) -> int:
        with self._lock:
            return len(self._queue) + self._in_progress

    # -- execution -----------------------------------------------------------

    def _run_one(self, item) -> None:
        fn, args = item
        try:
            fn(*args)
        except Exception:                  # noqa: BLE001 — a callback
            # crashing must not take down the completion thread; the
            # reference asserts instead, but a serving loop has to keep
            # completing the other ops in flight
            import traceback
            traceback.print_exc()

    def _loop(self) -> None:
        while True:
            with self._lock:
                while not self._queue and not self._stopping:
                    self._nonempty.wait()
                if not self._queue and self._stopping:
                    return
                item = self._queue.popleft()
                self._in_progress += 1
                self._nonfull.notify()
            self._run_one(item)
            with self._lock:
                self._in_progress -= 1
                if not self._queue and not self._in_progress:
                    self._idle.notify_all()

    def drain(self) -> int:
        """Inline mode: run everything queued on the CALLING thread.
        Returns the number of callbacks executed."""
        ran = 0
        while True:
            with self._lock:
                if not self._queue:
                    return ran
                item = self._queue.popleft()
                self._nonfull.notify()
            self._run_one(item)
            ran += 1

    def wait_for_empty(self, timeout: float | None = None) -> bool:
        with self._lock:
            if self._thread is None:
                pass                        # inline mode: caller drains
            return self._idle.wait_for(
                lambda: not self._queue and not self._in_progress, timeout)
