"""The ten legacy guard tests, as declarative rules on the one engine.

Each rule keeps the exact semantics of the test file it replaces (the
test files stay as thin wrappers, so coverage never drops); the module
walkers they used to carry individually now all run off the shared
:class:`~ceph_tpu.analysis.engine.ProjectIndex`.

Rules that check against a RUNTIME registry (owner classes, critpath
phases, wire sizers) import those registries lazily inside the check,
keeping ``import ceph_tpu.analysis`` jax-free.
"""
from __future__ import annotations

import ast
import re
from collections.abc import Iterator

from .engine import Finding, ModuleInfo, ProjectIndex, rule

# ---------------------------------------------------------------- util

_DEF_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
              ast.ClassDef)


def _walk_scope(node: ast.AST,
                enter_classes: bool = False) -> Iterator[ast.AST]:
    """ast.walk without descending into nested defs (they are their
    own entries in the index)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        sub = stack.pop()
        if isinstance(sub, _DEF_NODES):
            if enter_classes and isinstance(sub, ast.ClassDef):
                stack.extend(ast.iter_child_nodes(sub))
            continue
        yield sub
        stack.extend(ast.iter_child_nodes(sub))


def _scoped_calls(mod: ModuleInfo) -> Iterator[tuple[str, str, ast.Call]]:
    """(enclosing function name, qualname, call) for every call site,
    attributed to its innermost def; module/class level calls get
    ``<module>``."""
    for fi in mod.functions.values():
        for sub in _walk_scope(fi.node):
            if isinstance(sub, ast.Call):
                yield fi.name, fi.qualname, sub
    for sub in _walk_scope(mod.tree, enter_classes=True):
        if isinstance(sub, ast.Call):
            yield "<module>", "<module>", sub


def _call_name(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


# ------------------------------------------------- 1. no-host-sync

_HOST_SYNC_SCOPE = ("ceph_tpu/exec", "ceph_tpu/recovery")
_FORBIDDEN_SYNC_CALLS = {"device_get", "block_until_ready"}


@rule("no-host-sync", severity="error", scope=_HOST_SYNC_SCOPE,
      description="serving/recovery hot paths touch the device "
                  "runtime (jax import, device_get, block_until_ready, "
                  "jnp.asarray) instead of ops/pipeline.py's "
                  "completion boundary")
def check_no_host_sync(index: ProjectIndex) -> list[Finding]:
    out: list[Finding] = []
    for mod in index.iter_modules(_HOST_SYNC_SCOPE):
        jnp_aliases = {"jnp"} | {
            a for a, dotted in mod.import_aliases.items()
            if dotted == "jax.numpy"}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.split(".")[0] == "jax":
                        out.append(Finding(
                            "no-host-sync", mod.rel, node.lineno,
                            "error", f"import {alias.name}"))
            elif isinstance(node, ast.ImportFrom):
                if (node.module or "").split(".")[0] == "jax":
                    out.append(Finding(
                        "no-host-sync", mod.rel, node.lineno, "error",
                        f"from {node.module} import ..."))
            elif isinstance(node, ast.Call):
                f = node.func
                name = _call_name(node)
                if isinstance(f, ast.Attribute) and \
                        f.attr == "asarray" and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id in jnp_aliases:
                    out.append(Finding(
                        "no-host-sync", mod.rel, node.lineno, "error",
                        f"{f.value.id}.asarray(...)"))
                if name in _FORBIDDEN_SYNC_CALLS:
                    out.append(Finding(
                        "no-host-sync", mod.rel, node.lineno, "error",
                        f"{name}(...)"))
    return out


# ------------------------------------------------- 2. unbounded-queue

_QUEUE_SCOPE = ("ceph_tpu/exec", "ceph_tpu/recovery",
                "ceph_tpu/tier")
_QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue"}


def _has_bound(node: ast.Call, kw_name: str, pos_index: int) -> bool:
    for kw in node.keywords:
        if kw.arg == kw_name:
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value in (None, 0))
    if len(node.args) > pos_index:
        arg = node.args[pos_index]
        return not (isinstance(arg, ast.Constant)
                    and arg.value in (None, 0))
    return False


@rule("unbounded-queue", severity="error", scope=_QUEUE_SCOPE,
      description="a queue constructed in the bounded subsystems "
                  "(exec/, recovery/) has no explicit bound — voids "
                  "the backpressure contract")
def check_unbounded_queue(index: ProjectIndex) -> list[Finding]:
    out: list[Finding] = []
    for mod in index.iter_modules(_QUEUE_SCOPE):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if name == "SimpleQueue":
                out.append(Finding(
                    "unbounded-queue", mod.rel, node.lineno, "error",
                    "SimpleQueue cannot be bounded — use "
                    "Queue(maxsize=...)"))
            elif name == "deque" and not _has_bound(node, "maxlen", 1):
                out.append(Finding(
                    "unbounded-queue", mod.rel, node.lineno, "error",
                    "deque without an explicit maxlen bound"))
            elif name in _QUEUE_CTORS and \
                    not _has_bound(node, "maxsize", 0):
                out.append(Finding(
                    "unbounded-queue", mod.rel, node.lineno, "error",
                    f"{name} without an explicit nonzero maxsize "
                    f"bound"))
    return out


# ------------------------------------------------- 3. blocking-socket

_MSG_SCOPE = ("ceph_tpu/msg",)
_BLOCKING_SOCKET_VERBS = {"recv", "recv_into", "sendall", "accept"}


@rule("blocking-socket", severity="error", scope=_MSG_SCOPE,
      description="a blocking socket verb (recv/recv_into/sendall/"
                  "accept) appears outside a reactor readiness "
                  "callback (on_*) in ceph_tpu/msg/")
def check_blocking_socket(index: ProjectIndex) -> list[Finding]:
    out: list[Finding] = []
    for mod in index.iter_modules(_MSG_SCOPE):
        for fn_name, qual, call in _scoped_calls(mod):
            f = call.func
            if isinstance(f, ast.Attribute) and \
                    f.attr in _BLOCKING_SOCKET_VERBS and \
                    not fn_name.startswith("on_"):
                out.append(Finding(
                    "blocking-socket", mod.rel, call.lineno, "error",
                    f"{qual} calls .{f.attr}() outside a readiness "
                    f"callback"))
    return out


# ---------------------------------------------- 4. thread-spawn-site

# the ONLY places a thread may be born in the async messenger: one
# reactor loop, the fixed dispatch pool, the single mux sender
THREAD_SPAWN_ALLOWLIST = {
    ("reactor.py", "Reactor.start"),
    ("server.py", "Dispatcher.start"),
    ("client.py", "MuxClient.__init__"),
}


def _is_thread_ctor(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr == "Thread" and isinstance(f.value, ast.Name) \
            and f.value.id == "threading"
    return isinstance(f, ast.Name) and f.id == "Thread"


@rule("thread-spawn-site", severity="error", scope=_MSG_SCOPE,
      description="threading.Thread constructed in ceph_tpu/msg/ "
                  "outside the three fixed spawn sites (thread count "
                  "must never scale with connections)")
def check_thread_spawn_site(index: ProjectIndex) -> list[Finding]:
    out: list[Finding] = []
    for mod in index.iter_modules(_MSG_SCOPE):
        fname = mod.rel.rsplit("/", 1)[-1]
        for _fn, qual, call in _scoped_calls(mod):
            if _is_thread_ctor(call) and \
                    (fname, qual) not in THREAD_SPAWN_ALLOWLIST:
                out.append(Finding(
                    "thread-spawn-site", mod.rel, call.lineno, "error",
                    f"threading.Thread constructed in {qual}, outside "
                    f"the fixed spawn sites"))
    return out


def blocking_socket_sites(index: ProjectIndex
                          ) -> set[tuple[str, str, str]]:
    """(file, qualname, verb) for EVERY blocking-verb call site in
    msg/, allowed or not — the wrapper test asserts the known
    readiness callbacks are still being scanned."""
    sites: set[tuple[str, str, str]] = set()
    for mod in index.iter_modules(_MSG_SCOPE):
        fname = mod.rel.rsplit("/", 1)[-1]
        for _fn, qual, call in _scoped_calls(mod):
            f = call.func
            if isinstance(f, ast.Attribute) and \
                    f.attr in _BLOCKING_SOCKET_VERBS:
                sites.add((fname, qual, f.attr))
    return sites


def msg_thread_spawn_sites(index: ProjectIndex
                           ) -> set[tuple[str, str]]:
    """(file, qualname) of every Thread construction in msg/ — the
    wrapper test asserts the allowlisted sites still exist."""
    sites: set[tuple[str, str]] = set()
    for mod in index.iter_modules(_MSG_SCOPE):
        fname = mod.rel.rsplit("/", 1)[-1]
        for _fn, qual, call in _scoped_calls(mod):
            if _is_thread_ctor(call):
                sites.add((fname, qual))
    return sites


# ------------------------------------------------- 5. bounded-retry

_RETRY_SCOPE = ("ceph_tpu/net.py", "ceph_tpu/client",
                "ceph_tpu/failure")
_RETRYABLE = {"ConnectionError", "OSError", "TimeoutError",
              "ConnectionResetError", "BrokenPipeError", "timeout",
              "Exception", "BaseException", "IOError", "error"}
_BOUND_NAME = re.compile(
    r"attempt|deadline|retries|tries|remaining|max|budget|stop",
    re.IGNORECASE)


def _handler_names(handler: ast.ExceptHandler) -> set[str]:
    t = handler.type
    if t is None:
        return {"BaseException"}
    parts = t.elts if isinstance(t, ast.Tuple) else [t]
    out = set()
    for p in parts:
        if isinstance(p, ast.Name):
            out.add(p.id)
        elif isinstance(p, ast.Attribute):
            out.add(p.attr)
    return out


def _swallows_retryable(node: ast.While) -> bool:
    for sub in _walk_scope(node):
        if not isinstance(sub, ast.Try):
            continue
        for h in sub.handlers:
            if not (_handler_names(h) & _RETRYABLE):
                continue
            if not any(isinstance(n, (ast.Raise, ast.Return))
                       for body in h.body for n in ast.walk(body)):
                return True
    return False


def _has_bound_reference(node: ast.While) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and _BOUND_NAME.search(sub.id):
            return True
        if isinstance(sub, ast.Attribute) and \
                _BOUND_NAME.search(sub.attr):
            return True
    return False


@rule("bounded-retry", severity="error", scope=_RETRY_SCOPE,
      description="a 'while True' loop swallows connection errors "
                  "with no attempt count or deadline in sight — a "
                  "dead server becomes a live-locked client")
def check_bounded_retry(index: ProjectIndex) -> list[Finding]:
    out: list[Finding] = []
    for mod in index.iter_modules(_RETRY_SCOPE):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.While):
                continue
            if not (isinstance(node.test, ast.Constant)
                    and bool(node.test.value)):
                continue
            if _swallows_retryable(node) and \
                    not _has_bound_reference(node):
                out.append(Finding(
                    "bounded-retry", mod.rel, node.lineno, "error",
                    "unbounded 'while True' retry loop swallowing "
                    "connection errors — bound it with an attempt "
                    "count or deadline "
                    "(failure/backoff.ExponentialBackoff)"))
    return out


# ------------------------------------------------- 6. span-owner

_SPAN_SCOPE = ("ceph_tpu/exec", "ceph_tpu/recovery",
               "ceph_tpu/tier")
_SPAN_CALLS = {"trace_span", "span"}


@rule("span-owner", severity="error", scope=_SPAN_SCOPE,
      description="a span opened in exec/ or recovery/ carries no "
                  "owner= (or a non-canonical one) — device-time "
                  "attribution misfiles it as client work")
def check_span_owner(index: ProjectIndex) -> list[Finding]:
    from ceph_tpu.common.device_attribution import OWNER_CLASSES
    out: list[Finding] = []
    for mod in index.iter_modules(_SPAN_SCOPE):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or \
                    _call_name(node) not in _SPAN_CALLS:
                continue
            owner = next((kw.value for kw in node.keywords
                          if kw.arg == "owner"), None)
            if owner is None:
                out.append(Finding(
                    "span-owner", mod.rel, node.lineno, "error",
                    "span without owner= (attribution would misfile "
                    "this as client work)"))
            elif isinstance(owner, ast.Constant) and \
                    owner.value not in OWNER_CLASSES:
                out.append(Finding(
                    "span-owner", mod.rel, node.lineno, "error",
                    f"owner={owner.value!r} is not a canonical owner "
                    f"class {OWNER_CLASSES}"))
    return out


# ------------------------------------------------- 7. span-phase

_PHASE_SCOPE = ("ceph_tpu/exec", "ceph_tpu/recovery",
                "ceph_tpu/ops/pipeline.py", "ceph_tpu/tier")
_PHASE_CALLS = {"trace_span", "span", "complete"}


@rule("span-phase", severity="error", scope=_PHASE_SCOPE,
      description="a span in exec/, recovery/ or ops/pipeline.py maps "
                  "to no declared critical-path phase — its self-time "
                  "files under 'other'")
def check_span_phase(index: ProjectIndex) -> list[Finding]:
    from ceph_tpu.common.critpath import PHASES, is_declared
    out: list[Finding] = []
    for mod in index.iter_modules(_PHASE_SCOPE):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or \
                    _call_name(node) not in _PHASE_CALLS or \
                    not node.args:
                continue
            first = node.args[0]
            if not (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)):
                continue
            name = first.value
            phase_kw = next((kw.value for kw in node.keywords
                             if kw.arg == "phase"), None)
            if isinstance(phase_kw, ast.Constant) and \
                    phase_kw.value in PHASES:
                continue
            if is_declared(name):
                continue
            out.append(Finding(
                "span-phase", mod.rel, node.lineno, "error",
                f"span {name!r} maps to no declared critical-path "
                f"phase — add it to critpath.SPAN_PHASES or pass "
                f"phase=<one of {PHASES}>"))
    return out


# ------------------------------------------- 8. profiler-confinement

_PROFILER_SCOPE = ("ceph_tpu", "tools", "bench.py")
# path -> why the profiler touch is legitimate there
PROFILER_ALLOWLIST = {
    "ceph_tpu/common/profiler_capture.py":
        "IS the capture-window manager (the only sanctioned owner of "
        "the process-global profiler session)",
}
_FORBIDDEN_PROFILER_CALLS = {"start_trace", "stop_trace"}


@rule("profiler-confinement", severity="error", scope=_PROFILER_SCOPE,
      description="a jax.profiler touch outside "
                  "common/profiler_capture.py — captures must go "
                  "through the managed windows")
def check_profiler_confinement(index: ProjectIndex) -> list[Finding]:
    out: list[Finding] = []
    for mod in index.iter_modules(_PROFILER_SCOPE):
        if mod.rel in PROFILER_ALLOWLIST:
            continue
        for node in ast.walk(mod.tree):
            what: str | None = None
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "jax.profiler" or \
                            alias.name.startswith("jax.profiler."):
                        what = f"import {alias.name}"
            elif isinstance(node, ast.ImportFrom):
                m = node.module or ""
                if m == "jax.profiler" or m.startswith("jax.profiler."):
                    what = f"from {m} import ..."
                elif m == "jax" and any(a.name == "profiler"
                                        for a in node.names):
                    what = "from jax import profiler"
            elif isinstance(node, ast.Attribute):
                if node.attr == "profiler" and \
                        isinstance(node.value, ast.Name) and \
                        node.value.id == "jax":
                    what = "jax.profiler"
            elif isinstance(node, ast.Call):
                name = _call_name(node)
                if name in _FORBIDDEN_PROFILER_CALLS:
                    what = f"{name}(...)"
            if what is not None:
                out.append(Finding(
                    "profiler-confinement", mod.rel, node.lineno,
                    "error", what))
    return out


# ------------------------------------------------- 9. bare-clock

_CLOCK_SCOPE = ("ceph_tpu/ops", "ceph_tpu/backend")
# path -> why the bare clock is legitimate there
CLOCK_ALLOWLIST = {
    "ceph_tpu/ops/traced_jit.py":
        "IS the timing wrapper (AOT fallback books compile wall time)",
}
_BARE_TIME = re.compile(r"time\.time\(\)|perf_counter\(\)")


@rule("bare-clock", severity="error", scope=_CLOCK_SCOPE,
      description="a bare time.time()/perf_counter() in the encode/"
                  "decode hot paths — route timing through "
                  "trace_span/PerfCounters/traced_jit")
def check_bare_clock(index: ProjectIndex) -> list[Finding]:
    out: list[Finding] = []
    for mod in index.iter_modules(_CLOCK_SCOPE):
        if mod.rel in CLOCK_ALLOWLIST:
            continue
        for lineno, line in enumerate(mod.text.splitlines(), start=1):
            if _BARE_TIME.search(line):
                out.append(Finding(
                    "bare-clock", mod.rel, lineno, "error",
                    f"bare timing call: {line.strip()}"))
    return out


# ------------------------------------------------- 10. counter-help

_COUNTER_SCOPE = ("ceph_tpu",)
# adder -> index of the description positional (after self)
COUNTER_ADDERS = {"add_u64": 1, "add_u64_counter": 1, "add_u64_avg": 1,
                  "add_time_avg": 1, "add_histogram": 2}


def _description_ok(node: ast.Call, pos_index: int) -> bool:
    for kw in node.keywords:
        if kw.arg == "description":
            return not (isinstance(kw.value, ast.Constant)
                        and not kw.value.value)
    if len(node.args) > pos_index:
        arg = node.args[pos_index]
        return not (isinstance(arg, ast.Constant) and not arg.value)
    return False


@rule("counter-help", severity="error", scope=_COUNTER_SCOPE,
      description="a perf-counter adder without a description — "
                  "prometheus # HELP renders as the bare metric name")
def check_counter_help(index: ProjectIndex) -> list[Finding]:
    out: list[Finding] = []
    for mod in index.iter_modules(_COUNTER_SCOPE):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute):
                continue
            pos = COUNTER_ADDERS.get(node.func.attr)
            if pos is not None and not _description_ok(node, pos):
                out.append(Finding(
                    "counter-help", mod.rel, node.lineno, "error",
                    f"{node.func.attr}(...) without a description "
                    f"(prometheus # HELP quality)"))
    return out


def count_counter_adders(index: ProjectIndex) -> int:
    """How many adder calls the index sees — the wrapper test uses
    this to prove the rule still scans something real (>= 20)."""
    hits = 0
    for mod in index.iter_modules(_COUNTER_SCOPE):
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in COUNTER_ADDERS:
                hits += 1
    return hits


# --------------------------------------------- 11. percentile-redef

_PCTL_SCOPE = ("ceph_tpu", "tools")
_PCTL_HOME = "ceph_tpu/common/percentile.py"
_PCTL_BANNED = {"percentile", "percentile_us", "nearest_rank"}


@rule("percentile-redef", severity="error", scope=_PCTL_SCOPE,
      description="a local percentile/nearest_rank redefinition "
                  "outside common/percentile.py — the drift that made "
                  "trace_report's copy silently diverge")
def check_percentile_redef(index: ProjectIndex) -> list[Finding]:
    out: list[Finding] = []
    for mod in index.iter_modules(_PCTL_SCOPE):
        if mod.rel == _PCTL_HOME:
            continue
        for node in ast.walk(mod.tree):
            if not (isinstance(node, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
                    and node.name in _PCTL_BANNED):
                continue
            # a thin delegating wrapper is fine — it must CALL the
            # shared helper, not re-derive the rank
            dump = ast.dump(node)
            if "nearest_rank" in dump or "_pctl" in dump:
                continue
            out.append(Finding(
                "percentile-redef", mod.rel, node.lineno, "error",
                f"def {node.name} redefines a percentile locally — "
                f"use ceph_tpu/common/percentile.py"))
    return out


# ------------------------------------------------- 12. wire-sizer

MESSAGE_MODULES = ("ceph_tpu/backend/messages.py", "ceph_tpu/net.py",
                   "ceph_tpu/msg/proto.py", "ceph_tpu/tier")
# message-shaped dataclasses that never ride a channel
NOT_WIRE_MESSAGES = {"FaultConfig"}


def _dataclass_names(mod: ModuleInfo) -> set[str]:
    names = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if isinstance(target, ast.Name) and \
                    target.id == "dataclass" or \
                    isinstance(target, ast.Attribute) and \
                    target.attr == "dataclass":
                names.add(node.name)
    return names


@rule("wire-sizer", severity="error", scope=MESSAGE_MODULES,
      description="a wire-message dataclass without a registered "
                  "payload sizer — its bytes get charged by an "
                  "unreviewed pickle estimate")
def check_wire_sizer(index: ProjectIndex) -> list[Finding]:
    # importing the modules runs their register_wire_sizes() blocks
    import ceph_tpu.backend.messages  # noqa: F401
    import ceph_tpu.msg.proto  # noqa: F401
    import ceph_tpu.net  # noqa: F401
    from ceph_tpu.common.wire_accounting import registered_wire_types
    registered = registered_wire_types()
    out: list[Finding] = []
    for mod in index.iter_modules(MESSAGE_MODULES):
        for name in sorted(_dataclass_names(mod)):
            if name.startswith("_") or name in NOT_WIRE_MESSAGES:
                continue
            if name not in registered:
                out.append(Finding(
                    "wire-sizer", mod.rel, 1, "error",
                    f"message class {name} has no wire-accounting "
                    f"sizer (register it in register_wire_sizes next "
                    f"to the definition)"))
    return out
