"""The ceph-lint engine: project index, rule registry, baseline.

One parse of the tree feeds every rule.  The index is deliberately
syntactic — no imports of the code under analysis are needed to build
it — but it is CROSS-MODULE: classes, methods, module functions,
import aliases, instance-attribute types and lock attributes are all
resolved project-wide, so a rule can follow ``self.reactor.call_soon``
from ``msg/connection.py`` into ``msg/reactor.py`` and ask what locks
the callee takes.

Call resolution is best-effort and documented per tier (exact →
class/attr-typed → unique-name fallback); deep rules are written to
tolerate the unresolved remainder and ship with a reviewed baseline
for the over-approximations that survive.
"""
from __future__ import annotations

import ast
import json
from dataclasses import dataclass, field
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[2]

# the production tree ceph-lint covers by default (tests/ excluded: the
# engine's own fixtures live there and must not self-trip)
DEFAULT_SCAN = ("ceph_tpu", "tools", "bench.py")

SEVERITIES = ("error", "warning")

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}


@dataclass(frozen=True)
class Finding:
    """One lint finding.  ``message`` must be line-free and stable so a
    baseline entry survives unrelated edits above it."""

    rule: str
    path: str                       # repo-relative posix path
    line: int
    severity: str
    message: str

    @property
    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.severity} " \
               f"[{self.rule}] {self.message}"


@dataclass
class FunctionInfo:
    """One function/method (incl. nested defs), project-qualified."""

    rel: str                        # module path
    qualname: str                   # "Class.method" / "outer.inner"
    name: str
    node: ast.AST                   # FunctionDef | AsyncFunctionDef
    class_name: str | None = None   # immediately enclosing class

    @property
    def ref(self) -> str:
        return f"{self.rel}:{self.qualname}"


@dataclass
class ClassInfo:
    rel: str
    name: str
    node: ast.ClassDef
    bases: list[str] = field(default_factory=list)
    methods: dict[str, FunctionInfo] = field(default_factory=dict)
    # attr -> threading ctor name ("Lock"/"RLock"/"Condition"/...)
    lock_attrs: dict[str, str] = field(default_factory=dict)
    # attr -> project class name (self.x = Foo(...) in a method body)
    attr_types: dict[str, str] = field(default_factory=dict)


@dataclass
class ModuleInfo:
    rel: str
    text: str
    tree: ast.Module
    dotted: str                     # "ceph_tpu.msg.client"
    classes: dict[str, ClassInfo] = field(default_factory=dict)
    functions: dict[str, FunctionInfo] = field(default_factory=dict)
    # import alias -> dotted module ("jnp" -> "jax.numpy")
    import_aliases: dict[str, str] = field(default_factory=dict)
    # from-import: local name -> (dotted module, original symbol)
    symbol_imports: dict[str, tuple[str, str]] = field(
        default_factory=dict)
    # module-level lock name -> ctor
    module_locks: dict[str, str] = field(default_factory=dict)


def _dotted_of(rel: str) -> str:
    p = rel[:-3] if rel.endswith(".py") else rel
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


class _Collector(ast.NodeVisitor):
    """One pass per module: classes, functions (nested included),
    imports, module-level locks."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self._class_stack: list[ClassInfo] = []
        self._fn_stack: list[str] = []

    # -- imports -------------------------------------------------------------

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.asname:
                self.mod.import_aliases[alias.asname] = alias.name
            else:
                root = alias.name.split(".")[0]
                self.mod.import_aliases[root] = root

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        target = self._resolve_from(node)
        if target is None:
            return
        for alias in node.names:
            self.mod.symbol_imports[alias.asname or alias.name] = \
                (target, alias.name)

    def _resolve_from(self, node: ast.ImportFrom) -> str | None:
        if node.level == 0:
            return node.module
        parts = self.mod.dotted.split(".")
        # for a module file, level 1 = its package
        parts = parts[: -node.level] if node.level <= len(parts) else []
        if node.module:
            parts = parts + node.module.split(".")
        return ".".join(parts) if parts else None

    # -- defs ----------------------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        ci = ClassInfo(self.mod.rel, node.name, node,
                       bases=[b.id if isinstance(b, ast.Name) else b.attr
                              for b in node.bases
                              if isinstance(b, (ast.Name, ast.Attribute))])
        # only top-level (and class-nested) classes are indexed by name
        if not self._fn_stack:
            self.mod.classes[node.name] = ci
        self._class_stack.append(ci)
        self.generic_visit(node)
        self._class_stack.pop()

    def _visit_fn(self, node) -> None:
        self._fn_stack.append(node.name)
        qual = ".".join(
            ([self._class_stack[-1].name] if self._class_stack else [])
            + self._fn_stack)
        fi = FunctionInfo(
            self.mod.rel, qual, node.name, node,
            class_name=self._class_stack[-1].name
            if self._class_stack else None)
        self.mod.functions[qual] = fi
        if self._class_stack and len(self._fn_stack) == 1:
            self._class_stack[-1].methods[node.name] = fi
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    # -- assignments: locks + attribute types --------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        ctor = self._lock_ctor(node.value)
        cls_name = self._attr_class(node.value)
        for t in node.targets:
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and \
                    t.value.id == "self" and self._class_stack:
                if ctor is not None:
                    self._class_stack[-1].lock_attrs[t.attr] = ctor
                elif cls_name is not None:
                    self._class_stack[-1].attr_types.setdefault(
                        t.attr, cls_name)
            elif isinstance(t, ast.Name) and not self._fn_stack and \
                    not self._class_stack and ctor is not None:
                self.mod.module_locks[t.id] = ctor
        self.generic_visit(node)

    @staticmethod
    def _lock_ctor(value: ast.expr) -> str | None:
        if not isinstance(value, ast.Call):
            return None
        fn = value.func
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id == "threading" and fn.attr in _LOCK_CTORS:
            return fn.attr
        if isinstance(fn, ast.Name) and fn.id in _LOCK_CTORS:
            return fn.id
        return None

    @staticmethod
    def _attr_class(value: ast.expr) -> str | None:
        if not isinstance(value, ast.Call):
            return None
        fn = value.func
        name = fn.id if isinstance(fn, ast.Name) else \
            fn.attr if isinstance(fn, ast.Attribute) else None
        return name if name and name[:1].isupper() else None


class ProjectIndex:
    """AST + cross-module symbol/call index over a set of sources."""

    def __init__(self, files: dict[str, str]):
        self.modules: dict[str, ModuleInfo] = {}
        self._dotted_to_rel: dict[str, str] = {}
        for rel in sorted(files):
            tree = ast.parse(files[rel], filename=rel)
            mod = ModuleInfo(rel, files[rel], tree, _dotted_of(rel))
            _Collector(mod).visit(tree)
            self.modules[rel] = mod
            self._dotted_to_rel[mod.dotted] = rel
        # global lookup tables for the fallback resolution tier
        self.classes_by_name: dict[str, list[ClassInfo]] = {}
        self.functions_by_name: dict[str, list[FunctionInfo]] = {}
        for mod in self.modules.values():
            for ci in mod.classes.values():
                self.classes_by_name.setdefault(ci.name, []).append(ci)
            for fi in mod.functions.values():
                self.functions_by_name.setdefault(fi.name, []).append(fi)
        # callback-kwarg bindings: Ctor(..., on_message=self._handler)
        # records (class name, kwarg) -> {handler refs}, so calling
        # ``self.on_message(...)`` later resolves to the real handlers
        self.callback_bindings: dict[tuple[str, str],
                                     set[str]] = {}
        self._fn_by_ref: dict[str, FunctionInfo] = {
            fi.ref: fi for mod in self.modules.values()
            for fi in mod.functions.values()}
        self._collect_callback_bindings()
        self._local_alias_cache: dict[str, dict[str, str]] = {}

    def _collect_callback_bindings(self) -> None:
        for mod in self.modules.values():
            for fi in mod.functions.values():
                for node in ast.walk(fi.node):
                    if not isinstance(node, ast.Call):
                        continue
                    cls = self._call_target_class(mod, node)
                    if cls is None:
                        continue
                    for kw in node.keywords:
                        handler = self._bound_handler(fi, kw.value)
                        if handler is not None and kw.arg:
                            self.callback_bindings.setdefault(
                                (cls, kw.arg), set()).add(handler.ref)

    def _call_target_class(self, mod: ModuleInfo,
                           call: ast.Call) -> str | None:
        fn = call.func
        name = fn.id if isinstance(fn, ast.Name) else \
            fn.attr if isinstance(fn, ast.Attribute) else None
        if name is None or name not in self.classes_by_name:
            return None
        return name

    def _bound_handler(self, fi: FunctionInfo,
                       value: ast.expr) -> FunctionInfo | None:
        if isinstance(value, ast.Attribute) and \
                isinstance(value.value, ast.Name) and \
                value.value.id == "self" and fi.class_name:
            ci = self.class_of(fi)
            return self.lookup_method(ci, value.attr) if ci else None
        if isinstance(value, ast.Name):
            return self.modules[fi.rel].functions.get(value.id)
        return None

    def local_aliases(self, fi: FunctionInfo) -> dict[str, str]:
        """{local name: self-attribute it aliases} — ``cb = self.on_x``
        (incl. the tuple-swap form ``cb, self.on_x = self.on_x, None``)."""
        cached = self._local_alias_cache.get(fi.ref)
        if cached is not None:
            return cached
        out: dict[str, str] = {}
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                pairs = []
                if isinstance(t, ast.Tuple) and \
                        isinstance(node.value, ast.Tuple) and \
                        len(t.elts) == len(node.value.elts):
                    pairs = list(zip(t.elts, node.value.elts))
                else:
                    pairs = [(t, node.value)]
                for tgt, val in pairs:
                    if isinstance(tgt, ast.Name) and \
                            isinstance(val, ast.Attribute) and \
                            isinstance(val.value, ast.Name) and \
                            val.value.id == "self":
                        out[tgt.id] = val.attr
        self._local_alias_cache[fi.ref] = out
        return out

    def param_type(self, fi: FunctionInfo,
                   name: str) -> ClassInfo | None:
        """The project class a parameter's annotation names, if any."""
        args = fi.node.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            if a.arg != name or a.annotation is None:
                continue
            ann = a.annotation
            # unwrap "X | None" / Optional-style strings conservatively
            if isinstance(ann, ast.Constant) and \
                    isinstance(ann.value, str):
                ann_name = ann.value.split("|")[0].strip().split(".")[-1]
            elif isinstance(ann, ast.BinOp):
                left = ann.left
                ann_name = left.id if isinstance(left, ast.Name) else \
                    left.attr if isinstance(left, ast.Attribute) else None
            elif isinstance(ann, ast.Name):
                ann_name = ann.id
            elif isinstance(ann, ast.Attribute):
                ann_name = ann.attr
            else:
                ann_name = None
            if not ann_name:
                return None
            mod = self.modules[fi.rel]
            target = mod.classes.get(ann_name)
            if target is None and ann_name in mod.symbol_imports:
                dotted, sym = mod.symbol_imports[ann_name]
                m = self.module_for(dotted)
                target = m.classes.get(sym) if m else None
            if target is None:
                cands = self.classes_by_name.get(ann_name, [])
                target = cands[0] if len(cands) == 1 else None
            return target
        return None

    def fn_by_ref(self, ref: str) -> FunctionInfo | None:
        return self._fn_by_ref.get(ref)

    # -- construction --------------------------------------------------------

    @classmethod
    def from_tree(cls, root: Path | str = REPO_ROOT,
                  scan: tuple[str, ...] = DEFAULT_SCAN) -> "ProjectIndex":
        root = Path(root)
        files: dict[str, str] = {}
        for entry in scan:
            p = root / entry
            paths = [p] if p.is_file() else sorted(p.rglob("*.py"))
            for path in paths:
                files[path.relative_to(root).as_posix()] = \
                    path.read_text()
        return cls(files)

    # -- lookups -------------------------------------------------------------

    def module_for(self, dotted: str) -> ModuleInfo | None:
        rel = self._dotted_to_rel.get(dotted)
        return self.modules.get(rel) if rel else None

    def iter_modules(self, scope: tuple[str, ...] = ()
                     ) -> list[ModuleInfo]:
        if not scope:
            return list(self.modules.values())
        return [m for rel, m in self.modules.items()
                if in_scope(rel, scope)]

    def class_of(self, fi: FunctionInfo) -> ClassInfo | None:
        if fi.class_name is None:
            return None
        return self.modules[fi.rel].classes.get(fi.class_name)

    def _bases_of(self, ci: ClassInfo) -> list[ClassInfo]:
        out = []
        mod = self.modules[ci.rel]
        for base in ci.bases:
            target = mod.classes.get(base)
            if target is None and base in mod.symbol_imports:
                dotted, sym = mod.symbol_imports[base]
                m = self.module_for(dotted)
                target = m.classes.get(sym) if m else None
            if target is None:
                cands = self.classes_by_name.get(base, [])
                target = cands[0] if len(cands) == 1 else None
            if target is not None:
                out.append(target)
        return out

    def lookup_method(self, ci: ClassInfo, name: str,
                      _depth: int = 0) -> FunctionInfo | None:
        if name in ci.methods:
            return ci.methods[name]
        if _depth > 4:
            return None
        for base in self._bases_of(ci):
            hit = self.lookup_method(base, name, _depth + 1)
            if hit is not None:
                return hit
        return None

    def lock_attr_owner(self, ci: ClassInfo, attr: str,
                        _depth: int = 0) -> tuple[str, str] | None:
        """(defining class name, ctor) for a lock attribute, following
        project base classes."""
        if attr in ci.lock_attrs:
            return (ci.name, ci.lock_attrs[attr])
        if _depth > 4:
            return None
        for base in self._bases_of(ci):
            hit = self.lock_attr_owner(base, attr, _depth + 1)
            if hit is not None:
                return hit
        return None

    def attr_type(self, ci: ClassInfo, attr: str,
                  _depth: int = 0) -> ClassInfo | None:
        name = ci.attr_types.get(attr)
        if name is None and _depth <= 4:
            for base in self._bases_of(ci):
                hit = self.attr_type(base, attr, _depth + 1)
                if hit is not None:
                    return hit
            return None
        if name is None:
            return None
        mod = self.modules[ci.rel]
        target = mod.classes.get(name)
        if target is None and name in mod.symbol_imports:
            dotted, sym = mod.symbol_imports[name]
            m = self.module_for(dotted)
            target = m.classes.get(sym) if m else None
        if target is None:
            cands = self.classes_by_name.get(name, [])
            target = cands[0] if len(cands) == 1 else None
        return target

    # -- call resolution -----------------------------------------------------

    def _resolve_self_method(self, fi: FunctionInfo,
                             meth: str) -> list[FunctionInfo]:
        """``self.<meth>(...)``: a real method of the class (+ bases),
        else the handlers bound to that attribute at construction
        sites (``Ctor(..., on_message=self._on_message)``), else the
        unique-name fallback."""
        ci = self.class_of(fi)
        if ci is not None:
            hit = self.lookup_method(ci, meth)
            if hit is not None:
                return [hit]
            names = [ci.name] + list(ci.bases)
            refs: set[str] = set()
            for n in names:
                refs |= self.callback_bindings.get((n, meth), set())
            if refs:
                return [self._fn_by_ref[r] for r in sorted(refs)
                        if r in self._fn_by_ref]
        return self._unique(meth, methods_only=True)

    def resolve_call(self, fi: FunctionInfo,
                     call: ast.Call) -> list[FunctionInfo]:
        """Best-effort callee resolution, tiered:

        1. ``self.m()``        → method of the enclosing class (+ bases);
        2. ``self.attr.m()``   → method of ``attr``'s known type;
        3. ``mod.f()`` / ``f()`` → module function via import aliases /
           same-module / from-imports;
        4. unique-name fallback: exactly ONE project function carries
           the name (cross-module edges like ``conn.update_interest`` →
           ``Reactor.update_interest`` resolve here).
        """
        fn = call.func
        mod = self.modules[fi.rel]
        if isinstance(fn, ast.Name):
            hit = mod.functions.get(fn.id)
            if hit is not None:
                return [hit]
            # a local alias of a stored self-callback:
            # ``cb = self.on_closed; ...; cb(self, exc)``
            aliased = self.local_aliases(fi).get(fn.id)
            if aliased is not None and fi.class_name is not None:
                return self._resolve_self_method(fi, aliased)
            if fn.id in mod.symbol_imports:
                dotted, sym = mod.symbol_imports[fn.id]
                m = self.module_for(dotted)
                if m and sym in m.functions:
                    return [m.functions[sym]]
            return self._unique(fn.id)
        if not isinstance(fn, ast.Attribute):
            return []
        recv, meth = fn.value, fn.attr
        if isinstance(recv, ast.Name):
            if recv.id == "self" and fi.class_name is not None:
                return self._resolve_self_method(fi, meth)
            if recv.id in mod.import_aliases:
                m = self.module_for(mod.import_aliases[recv.id])
                if m and meth in m.functions:
                    return [m.functions[meth]]
                return []
            if recv.id in mod.symbol_imports:
                # from .reactor import client_reactor; from . import net
                dotted, sym = mod.symbol_imports[recv.id]
                m = self.module_for(f"{dotted}.{sym}") or \
                    self.module_for(dotted)
                if m is not None:
                    if meth in m.functions:
                        return [m.functions[meth]]
                    if sym in m.classes:
                        hit = self.lookup_method(m.classes[sym], meth)
                        return [hit] if hit else []
                return self._unique(meth, methods_only=True)
            # an annotated parameter: ``def f(self, conn: AsyncConnection)``
            pt = self.param_type(fi, recv.id)
            if pt is not None:
                hit = self.lookup_method(pt, meth)
                if hit is not None:
                    return [hit]
                refs = self.callback_bindings.get((pt.name, meth))
                if refs:
                    return [self._fn_by_ref[r] for r in sorted(refs)
                            if r in self._fn_by_ref]
                return []
            return self._unique(meth, methods_only=True)
        if isinstance(recv, ast.Attribute) and \
                isinstance(recv.value, ast.Name) and \
                recv.value.id == "self" and fi.class_name is not None:
            ci = self.class_of(fi)
            target = self.attr_type(ci, recv.attr) if ci else None
            if target is not None:
                hit = self.lookup_method(target, meth)
                if hit is not None:
                    return [hit]
        return self._unique(meth, methods_only=True)

    def _unique(self, name: str,
                methods_only: bool = False) -> list[FunctionInfo]:
        cands = self.functions_by_name.get(name, [])
        if methods_only:
            cands = [c for c in cands if c.class_name is not None]
        # dunder/tiny-verb names are everywhere: never unique-resolve
        if name.startswith("__") or len(cands) != 1:
            return []
        return cands


def in_scope(rel: str, scope: tuple[str, ...]) -> bool:
    return any(rel == s or rel.startswith(s.rstrip("/") + "/")
               for s in scope)


# -- rule registry -----------------------------------------------------------

@dataclass(frozen=True)
class Rule:
    id: str
    severity: str
    description: str
    scope: tuple[str, ...]          # () = the whole index
    check: object                   # fn(index, rule) -> list[Finding]


_RULES: dict[str, Rule] = {}


def rule(rule_id: str, *, severity: str, description: str,
         scope: tuple[str, ...] = ()):
    """Declare a rule: the decorated fn(index) yields Findings."""
    assert severity in SEVERITIES, severity
    assert rule_id not in _RULES, f"duplicate rule id {rule_id}"

    def deco(fn):
        _RULES[rule_id] = Rule(rule_id, severity, description,
                               tuple(scope), fn)
        return fn
    return deco


def all_rules() -> dict[str, Rule]:
    return dict(_RULES)


def get_rule(rule_id: str) -> Rule:
    return _RULES[rule_id]


def make_finding(r: Rule, rel: str, line: int, message: str) -> Finding:
    return Finding(r.id, rel, int(line), r.severity, message)


def run_rules(index: ProjectIndex,
              rule_ids: tuple[str, ...] | None = None) -> list[Finding]:
    out: list[Finding] = []
    for rid in sorted(rule_ids if rule_ids is not None else _RULES):
        r = _RULES[rid]
        out.extend(r.check(index))
    # dedupe (reachability rules can report one site via two paths)
    return sorted(set(out),
                  key=lambda f: (f.path, f.line, f.rule, f.message))


_default_index: ProjectIndex | None = None


def default_index(refresh: bool = False) -> ProjectIndex:
    """The whole-tree index, built once per process (rules and wrapper
    tests share it; the CLI refreshes)."""
    global _default_index
    if _default_index is None or refresh:
        _default_index = ProjectIndex.from_tree()
    return _default_index


def run_rule_on_sources(rule_id: str, sources: dict[str, str]
                        ) -> list[Finding]:
    """Run ONE rule against synthetic sources (fixture testing).  A bare
    filename is placed inside the rule's first scope directory so the
    rule's own path filter admits it."""
    r = _RULES[rule_id]
    placed: dict[str, str] = {}
    for name, text in sources.items():
        if "/" not in name and r.scope:
            anchor = next((s for s in r.scope if not s.endswith(".py")),
                          r.scope[0])
            name = name if anchor.endswith(".py") else \
                f"{anchor.rstrip('/')}/{name}"
        placed[name] = text
    return r.check(ProjectIndex(placed))


# -- baseline ----------------------------------------------------------------

BASELINE_FILE = ".ceph_lint_baseline.json"


def load_baseline(path: Path | str | None = None) -> dict[tuple, str]:
    """{finding key: justification}.  Missing file = empty baseline."""
    p = Path(path) if path is not None else REPO_ROOT / BASELINE_FILE
    if not p.exists():
        return {}
    doc = json.loads(p.read_text())
    out: dict[tuple, str] = {}
    for e in doc.get("entries", []):
        out[(e["rule"], e["path"], e["message"])] = \
            e.get("justification", "")
    return out


def write_baseline(findings: list[Finding],
                   justification: str,
                   path: Path | str | None = None) -> None:
    p = Path(path) if path is not None else REPO_ROOT / BASELINE_FILE
    seen: set[tuple] = set()
    entries = []
    for f in findings:
        if f.key in seen:
            continue
        seen.add(f.key)
        entries.append({"rule": f.rule, "path": f.path,
                        "message": f.message,
                        "justification": justification})
    p.write_text(json.dumps({"version": 1, "entries": entries},
                            indent=1) + "\n")


def split_by_baseline(findings: list[Finding],
                      baseline: dict[tuple, str]
                      ) -> tuple[list[Finding], list[Finding], list[tuple]]:
    """(new, suppressed, stale baseline keys)."""
    new = [f for f in findings if f.key not in baseline]
    suppressed = [f for f in findings if f.key in baseline]
    live = {f.key for f in findings}
    stale = [k for k in baseline if k not in live]
    return new, suppressed, stale
