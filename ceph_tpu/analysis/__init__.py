"""ceph-lint: one static-analysis engine for the whole tree.

Every guard test used to carry its own ``ast`` walker; none of them
could see across module boundaries.  This package replaces the ten
parallel walkers with ONE engine (reference analog: the checks Ceph
ships as ``src/common/lockdep.cc`` + the mutex-debug layer, done ahead
of time instead of at runtime):

- :mod:`.engine`   — the project index (AST for every file + a
  cross-module symbol/call index) and the declarative rule registry;
- :mod:`.lockmodel` — the shared lock/acquisition walker (who holds
  what, where) both deep analyses build on;
- :mod:`.rules_locks`   — lock-order deadlock detection + callbacks/
  sends invoked under a held lock;
- :mod:`.rules_threads` — thread-context classification + cross-thread
  unlocked-mutation detection;
- :mod:`.rules_jax`     — JAX dispatch-purity (host syncs reachable
  under jit, recompile-prone signatures, donated-buffer reuse);
- :mod:`.rules_guards`  — the ten migrated legacy guards (host-sync,
  bounded queues/retries, blocking sockets, span owner/phase, profiler
  confinement, bare clocks, counter help, percentile redefinitions,
  wire-sizer registry).

Entry points: ``tools/ceph_lint.py`` (CLI with ``--baseline``) and
``tests/test_ceph_lint.py`` (tier-1).  Import stays jax-free; rules
that need runtime registries import them lazily inside their check.
"""
from .engine import (Finding, ProjectIndex, Rule, all_rules,  # noqa: F401
                     default_index, get_rule, load_baseline,
                     run_rule_on_sources, run_rules, split_by_baseline,
                     write_baseline)

# registering the rule modules populates the registry as a side effect
from . import rules_copy  # noqa: F401,E402
from . import rules_guards  # noqa: F401,E402
from . import rules_jax  # noqa: F401,E402
from . import rules_locks  # noqa: F401,E402
from . import rules_observability  # noqa: F401,E402
from . import rules_threads  # noqa: F401,E402
